"""Gradient accumulation: K-microbatch accumulation must equal the direct
full-batch step (same update, same metrics), across local, DP, and TP
step builders; plus the mode guards."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.synthetic import synthetic_digits
from distributed_tensorflow_tpu.models import DeepCNN
from distributed_tensorflow_tpu.training import (
    create_train_state,
    make_train_step,
    sgd,
)
from distributed_tensorflow_tpu.training.train_state import compute_grads


def _batch(n=32, seed=0):
    xs, labels = synthetic_digits(n, seed=seed)
    return jnp.asarray(xs), jax.nn.one_hot(jnp.asarray(labels), 10)


def _allclose_tree(a, b, **kw):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)


@pytest.mark.parametrize("k", [2, 4])
def test_accum_grads_equal_direct(k):
    """Mean of microbatch grads == full-batch grads (keep_prob=1 so
    dropout cannot differ)."""
    model = DeepCNN()
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(32)
    g1, m1, _ = compute_grads(model, params, batch, keep_prob=1.0,
                              rng=None, model_state=(), accum_steps=1)
    gk, mk, _ = compute_grads(model, params, batch, keep_prob=1.0,
                              rng=None, model_state=(), accum_steps=k)
    # f32 summation-order noise only: elements near zero show ~1e-4
    # relative at ~3e-7 absolute
    _allclose_tree(g1, gk, rtol=2e-4, atol=1e-6)
    assert float(m1["loss"]) == pytest.approx(float(mk["loss"]), rel=1e-5)
    assert float(m1["accuracy"]) == pytest.approx(float(mk["accuracy"]),
                                                  rel=1e-6)


def test_accum_step_equals_direct_step():
    model = DeepCNN()
    opt = sgd(0.05)
    batch = _batch(32)
    s_direct = create_train_state(model, opt, seed=0)
    s_accum = create_train_state(model, opt, seed=0)
    direct = make_train_step(model, opt, keep_prob=1.0, donate=False)
    accum = make_train_step(model, opt, keep_prob=1.0, donate=False,
                            accum_steps=4)
    s_direct, _ = direct(s_direct, batch)
    s_accum, _ = accum(s_accum, batch)
    assert int(s_accum.step) == 1  # ONE update for K microbatches
    _allclose_tree(s_direct.params, s_accum.params, rtol=2e-5, atol=1e-7)


def test_accum_indivisible_batch_is_loud():
    model = DeepCNN()
    params = model.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="does not split"):
        compute_grads(model, params, _batch(30), keep_prob=1.0, rng=None,
                      model_state=(), accum_steps=4)


def test_accum_dp_equals_direct_dp():
    from distributed_tensorflow_tpu.parallel import (
        MeshSpec,
        make_dp_train_step,
        make_mesh,
        shard_batch,
    )
    from distributed_tensorflow_tpu.parallel.data_parallel import replicate_state

    mesh = make_mesh(MeshSpec(data=8, model=1))
    model = DeepCNN()
    opt = sgd(0.05)
    batch = shard_batch(mesh, _batch(64))
    s_direct = replicate_state(mesh, create_train_state(model, opt, seed=0))
    s_accum = replicate_state(mesh, create_train_state(model, opt, seed=0))
    direct = make_dp_train_step(model, opt, mesh, keep_prob=1.0, donate=False)
    accum = make_dp_train_step(model, opt, mesh, keep_prob=1.0, donate=False,
                               accum_steps=2)
    s_direct, m1 = direct(s_direct, batch)
    s_accum, mk = accum(s_accum, batch)
    _allclose_tree(s_direct.params, s_accum.params, rtol=2e-5, atol=1e-7)
    assert float(m1["loss"]) == pytest.approx(float(mk["loss"]), rel=1e-5)


def test_accum_tp_runs():
    from distributed_tensorflow_tpu.parallel import MeshSpec, make_mesh
    from distributed_tensorflow_tpu.parallel.tensor_parallel import (
        make_tp_train_step,
        shard_state_tp,
        stage_batch_tp,
    )

    mesh = make_mesh(MeshSpec(data=4, model=2))
    model = DeepCNN()
    opt = sgd(0.05)
    state = shard_state_tp(create_train_state(model, opt, seed=0), mesh)
    step = make_tp_train_step(model, opt, mesh, keep_prob=1.0, donate=False,
                              accum_steps=2)
    state, m = step(state, stage_batch_tp(mesh, _batch(32)))
    assert int(state.step) == 1
    assert np.isfinite(float(m["loss"]))


def test_accum_stateful_model_threads_state():
    """Batch-norm state threads through the microbatches sequentially."""
    from distributed_tensorflow_tpu.models import get_model

    model = get_model("resnet20", image_size=8, channels=3, num_classes=10)
    opt = sgd(0.05)
    state = create_train_state(model, opt, seed=0)
    step = make_train_step(model, opt, keep_prob=1.0, donate=False,
                           accum_steps=2)
    x = jax.random.normal(jax.random.key(0), (8, 8 * 8 * 3))
    y = jax.nn.one_hot(jnp.arange(8) % 10, 10)
    before = jax.tree.leaves(state.model_state)[0].copy()
    state, m = step(state, (x, y))
    after = jax.tree.leaves(state.model_state)[0]
    assert np.isfinite(float(m["loss"]))
    assert not np.allclose(np.asarray(before), np.asarray(after))


def test_accum_rejected_with_device_data(tmp_path):
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()
    flags.FLAGS._reset()
    try:
        flags.FLAGS._parse([
            f"--logdir={tmp_path}/logs", f"--data_dir={tmp_path}/none",
            "--training_iter=2", "--batch_size=32",
            "--accum_steps=2", "--device_data",
        ])
        with pytest.raises(ValueError, match="incompatible with --device_data"):
            train(flags.FLAGS, mode="local")
    finally:
        flags.FLAGS._reset()
