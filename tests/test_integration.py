"""End-to-end integration: train() in both modes, resume, CLI, PS cluster."""

import glob
import json
import os
import re
import signal
import socket
import subprocess
import sys
import time

import pytest

from distributed_tensorflow_tpu import flags
from distributed_tensorflow_tpu.training.loop import train

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CPU_ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


@pytest.fixture(autouse=True)
def fresh_flags():
    flags.define_reference_flags()
    flags.FLAGS._reset()
    yield
    flags.FLAGS._reset()


def _parse(tmp_path, *extra):
    flags.FLAGS._parse([
        f"--logdir={tmp_path}/logs",
        f"--data_dir={tmp_path}/no-data",  # forces synthetic
        "--training_iter=30",
        "--batch_size=32",
        "--display_step=10",
        "--optimizer=adam",
        "--learning_rate=0.002",
        "--save_model_secs=100000",
        *extra,
    ])
    return flags.FLAGS


def test_train_local_end_to_end(tmp_path, capsys):
    F = _parse(tmp_path)
    res = train(F, mode="local")
    assert res.final_step == 30
    out = capsys.readouterr().out
    # reference stdout format (MNISTDist.py:183-186)
    assert re.search(r"job: worker/0 step: {2}\d+ mini_batch loss: ", out)
    assert "Optimization Finished!" in out
    assert res.test_metrics is not None
    # final checkpoint written by managed() exit
    assert os.path.exists(f"{tmp_path}/logs/checkpoint")
    # metrics jsonl written
    lines = open(f"{tmp_path}/logs/metrics.jsonl").read().splitlines()
    assert any("test_accuracy" in l for l in lines)
    assert all(json.loads(l) for l in lines)


def test_train_sync_mode_8_devices(tmp_path):
    F = _parse(tmp_path)
    res = train(F, mode="sync")
    assert res.n_chips == 8
    assert res.final_step == 30
    assert res.train_metrics["loss"] > 0


def test_sync_mode_rejects_indivisible_batch(tmp_path):
    F = _parse(tmp_path, "--batch_size=30")
    with pytest.raises(ValueError, match="divisible"):
        train(F, mode="sync")


def test_checkpoint_resume_continues_from_step(tmp_path):
    F = _parse(tmp_path, "--training_iter=10", "--save_model_secs=0")
    res1 = train(F, mode="local")
    assert res1.final_step == 10
    # managed() exit wrote ckpt-10; a second run to 20 resumes from 10
    flags.FLAGS._reset()
    F = _parse(tmp_path, "--training_iter=20", "--save_model_secs=0")
    res2 = train(F, mode="local")
    assert res2.final_step == 20


def test_training_iter_already_reached_noop(tmp_path):
    F = _parse(tmp_path, "--training_iter=10")
    train(F, mode="local")
    flags.FLAGS._reset()
    F = _parse(tmp_path, "--training_iter=5")
    res = train(F, mode="local")
    assert res.final_step == 10  # restored past target: loop body never runs


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_cli_local(tmp_path):
    out = subprocess.run(
        [sys.executable, "mnist_dist.py", "--training_iter=3",
         "--batch_size=16", "--display_step=1",
         f"--logdir={tmp_path}/logs", f"--data_dir={tmp_path}/none"],
        cwd=REPO, env=CPU_ENV, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "Optimization Finished!" in out.stdout
    assert "mini_batch loss" in out.stdout


def test_cli_bad_job_name(tmp_path):
    out = subprocess.run(
        [sys.executable, "mnist_dist.py", "--job_name=chief",
         "--ps_hosts=localhost:1", "--worker_hosts=localhost:2"],
        cwd=REPO, env=CPU_ENV, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 2
    assert "job_name" in out.stderr


def test_sigterm_graceful_stop_then_resume(tmp_path):
    """Supervisor recovery contract (MNISTDist.py:169-191): SIGTERM mid-run
    -> request_stop -> final checkpoint; a restart resumes from that step."""
    args = [
        sys.executable, "-u", "mnist_dist.py", "--mode=local",
        "--training_iter=1000000", "--batch_size=16", "--display_step=20",
        f"--logdir={tmp_path}/logs", f"--data_dir={tmp_path}/none",
        "--save_model_secs=100000", "--test_eval=false",
    ]
    p = subprocess.Popen(args, cwd=REPO, env=CPU_ENV, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    try:
        # wait until the training loop is demonstrably past compile; read
        # stdout from a thread so a silent hang can't block readline forever
        import queue as queue_mod
        import threading

        lines: queue_mod.Queue = queue_mod.Queue()
        threading.Thread(
            target=lambda: [lines.put(l) for l in p.stdout], daemon=True
        ).start()
        deadline = time.time() + 180
        seen = []
        progressed = False
        while time.time() < deadline and not progressed:
            try:
                line = lines.get(timeout=5)
            except queue_mod.Empty:
                continue
            seen.append(line)
            progressed = "mini_batch loss" in line and "step:  0" not in line
        if not progressed:
            pytest.fail(f"no progress before SIGTERM: {''.join(seen)[-2000:]}")
        p.send_signal(signal.SIGTERM)
        p.wait(timeout=180)
        time.sleep(0.5)  # let the reader thread drain the tail
        while not lines.empty():
            seen.append(lines.get_nowait())
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    full = "".join(seen)
    assert p.returncode == 0, full[-2000:]
    assert "stop requested" in full
    assert "Optimization Finished!" in full

    from distributed_tensorflow_tpu.checkpoint.checkpoint import latest_checkpoint

    found = latest_checkpoint(f"{tmp_path}/logs")
    assert found is not None
    _, saved_step = found
    assert saved_step > 0

    # restart for a few more steps: must resume from saved_step, not 0
    out2 = subprocess.run(
        [sys.executable, "mnist_dist.py", "--mode=local",
         f"--training_iter={saved_step + 5}", "--batch_size=16",
         "--display_step=1", f"--logdir={tmp_path}/logs",
         f"--data_dir={tmp_path}/none", "--save_model_secs=100000"],
        cwd=REPO, env=CPU_ENV, capture_output=True, text=True, timeout=300,
    )
    assert out2.returncode == 0, out2.stderr[-2000:]
    steps = [int(m) for m in re.findall(r"step: {2}(\d+)", out2.stdout)]
    assert steps and min(steps) >= saved_step
    found2 = latest_checkpoint(f"{tmp_path}/logs")
    assert found2 is not None and found2[1] == saved_step + 5


def test_profile_dir_writes_trace(tmp_path):
    """--profile_dir captures a jax.profiler trace of a post-compile step
    window (SURVEY.md §5 tracing obligation)."""
    F = _parse(tmp_path, f"--profile_dir={tmp_path}/prof",
               "--profile_steps=3", "--training_iter=8")
    train(F, mode="local")
    produced = [
        f for f in glob.glob(f"{tmp_path}/prof/**/*", recursive=True)
        if os.path.isfile(f)
    ]
    assert produced, "profiler produced no trace files"


def test_ps_cluster_multiprocess(tmp_path):
    """The reference's launch recipe: one ps + two workers, separate
    processes, shared global step terminates the job (MNISTDist.py §3.1)."""
    ps_port, = [_free_port()]
    ps_addr = f"localhost:{ps_port}"
    common = [
        f"--ps_hosts={ps_addr}", "--worker_hosts=localhost:1,localhost:2",
        "--training_iter=12", "--batch_size=16", "--display_step=4",
        f"--logdir={tmp_path}/logs", f"--data_dir={tmp_path}/none",
        "--learning_rate=0.01", "--save_model_secs=100000",
    ]
    ps = subprocess.Popen(
        [sys.executable, "mnist_dist.py", "--job_name=ps", "--task_index=0", *common],
        cwd=REPO, env=CPU_ENV, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        workers = [
            subprocess.Popen(
                [sys.executable, "mnist_dist.py", "--job_name=worker",
                 f"--task_index={i}", *common],
                cwd=REPO, env=CPU_ENV, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
            for i in range(2)
        ]
        outs = []
        for w in workers:
            so, se = w.communicate(timeout=300)
            outs.append((w.returncode, so, se))
        for rc, so, se in outs:
            assert rc == 0, se[-2000:]
            assert "Optimization Finished!" in so
        # chief printed test accuracy
        assert any("test accuracy" in so for _, so, _ in outs)
        # ps keeps serving (server.join parity) until killed
        assert ps.poll() is None
    finally:
        ps.kill()
        ps.wait()


def test_ps_cluster_bf16_wire_serial_cycle(tmp_path):
    """The same one-ps/two-worker cluster over the bf16 wire with the
    serial (mirror-off, prefetch-off) full-pull cycle: the half-width
    transport and the reference cycle ordering both train to completion.
    --ps_mirror=false is load-bearing — without it the default sgd run
    takes the mirror branch and the serial bf16 pull path goes untested."""
    ps_addr = f"localhost:{_free_port()}"
    common = [
        f"--ps_hosts={ps_addr}", "--worker_hosts=localhost:1,localhost:2",
        "--training_iter=12", "--batch_size=16", "--display_step=4",
        f"--logdir={tmp_path}/logs", f"--data_dir={tmp_path}/none",
        "--learning_rate=0.01", "--save_model_secs=100000",
        "--ps_wire=bf16", "--ps_prefetch=false", "--ps_mirror=false",
    ]
    ps = subprocess.Popen(
        [sys.executable, "mnist_dist.py", "--job_name=ps", "--task_index=0", *common],
        cwd=REPO, env=CPU_ENV, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        workers = [
            subprocess.Popen(
                [sys.executable, "mnist_dist.py", "--job_name=worker",
                 f"--task_index={i}", *common],
                cwd=REPO, env=CPU_ENV, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
            for i in range(2)
        ]
        for w in workers:
            so, se = w.communicate(timeout=300)
            assert w.returncode == 0, se[-2000:]
            assert "Optimization Finished!" in so
    finally:
        ps.kill()
        ps.wait()
