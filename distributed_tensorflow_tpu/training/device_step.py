"""Chunked train steps over device-resident data (see data/device_data.py).

Two builders mirroring the host-fed pair (``make_train_step`` /
``make_dp_train_step``) but with the input side moved INSIDE the compiled
program: each step draws its minibatch on device by PRNG gather from the
resident split, and ``lax.scan`` runs ``chunk`` steps per dispatch so the
host's per-step role shrinks to one function call per chunk. This is the
TPU-native answer to the reference's per-step feed_dict upload
(``MNISTDist.py:179,188``): nothing crosses the host boundary during
training at all.

Returned metrics are the LAST in-chunk step's training metrics (loss /
accuracy of the train pass, dropout on). The host-fed loop's display
semantics (dropout-off eval of the upcoming batch, ``MNISTDist.py:179-182``)
need the batch on the host, so this fast mode trades that for speed —
documented on the ``--device_data`` flag.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.parallel.mesh import DATA_AXIS
from distributed_tensorflow_tpu.training.train_state import (
    TrainState,
    apply_augment,
    apply_updates,
    loss_and_metrics,
)

_SAMPLE_SALT = 0x5EED  # folds the sampling stream away from the dropout stream


def _split_and_sample(state: TrainState, data, batch_size: int,
                      axis: str | None, augment_fn):
    """The ONE rng-evolution + on-device batch-draw rule every sampled
    step body shares (``_sampled_step_body`` and the ZeRO device step —
    their bit-identity contract is this function being common, not two
    copies kept in lockstep): returns ``(next_rng, dropout_sub, batch)``.
    ``state.rng`` advances every step, so the sampling key (a salted
    fold of it) yields a fresh batch each iteration of a scan."""
    rng, sub = jax.random.split(state.rng)
    samp = jax.random.fold_in(state.rng, _SAMPLE_SALT)
    if axis is not None:
        # distinct sample + dropout streams per data shard
        samp = jax.random.fold_in(samp, lax.axis_index(axis))
        sub = jax.random.fold_in(sub, lax.axis_index(axis))
    idx = jax.random.randint(samp, (batch_size,), 0, data.num_examples)
    batch = (data.images[idx], data.labels[idx])
    if augment_fn is not None:
        # samp is already per-shard (axis fold above), so the salted
        # augment stream decorrelates across shards too
        batch = apply_augment(augment_fn, batch, samp)
    return rng, sub, batch


def _sampled_step_body(model, optimizer, batch_size: int, keep_prob: float,
                       axis: str | None, grad_transform=None,
                       batch_sharding=None, augment_fn=None):
    """(state, data) -> (state, metrics): one full train step — on-device
    batch sample (``_split_and_sample``), forward, backward, (pmean over
    ``axis`` if set), update. ``batch_sharding`` (global-view/GSPMD
    callers only) constrains the sampled batch's layout so the
    partitioner splits the compute over the data axis."""

    def body(state: TrainState, data):
        rng, sub, batch = _split_and_sample(state, data, batch_size, axis,
                                            augment_fn)
        if batch_sharding is not None:
            batch = tuple(
                lax.with_sharding_constraint(b, s)
                for b, s in zip(batch, batch_sharding)
            )

        def loss_fn(params):
            return loss_and_metrics(model, params, batch, keep_prob=keep_prob,
                                    rng=sub, train=True,
                                    model_state=state.model_state)

        grads, aux = jax.grad(loss_fn, has_aux=True)(state.params)
        metrics, model_state = aux["metrics"], aux["model_state"]
        if axis is not None:
            grads = lax.pmean(grads, axis)
            metrics = lax.pmean(metrics, axis)
            if model_state:
                model_state = lax.pmean(model_state, axis)
        if grad_transform is not None:
            grads = grad_transform(grads)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params, state.step)
        params = apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1, rng, model_state), metrics

    return body


def _scan_chunk(body, chunk: int):
    def chunk_fn(state, data):
        state, metrics = lax.scan(
            lambda s, _: body(s, data), state, None, length=chunk
        )
        return state, jax.tree.map(lambda m: m[-1], metrics)

    return chunk_fn


def make_device_train_step(model, optimizer, batch_size: int, *,
                           keep_prob: float = 1.0, chunk: int = 1,
                           donate: bool = True, grad_transform=None,
                           augment_fn=None):
    """Single-device chunked step: (state, DeviceData) -> (state, metrics);
    advances ``state.step`` by ``chunk``."""
    body = _sampled_step_body(model, optimizer, batch_size, keep_prob, None,
                              grad_transform, augment_fn=augment_fn)
    fn = _scan_chunk(body, chunk)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def make_device_dp_train_step(model, optimizer, mesh, batch_size: int, *,
                              keep_prob: float = 1.0, chunk: int = 1,
                              donate: bool = True, grad_transform=None,
                              augment_fn=None):
    """Sync-DP chunked step over ``mesh``: state replicated, the resident
    split replicated, each shard samples ``batch_size // n_data`` examples
    locally and grads ``pmean`` over ICI — the input side costs no
    collective at all."""
    n_data = mesh.shape[DATA_AXIS]
    if batch_size % n_data:
        raise ValueError(
            f"batch_size={batch_size} not divisible by the {n_data}-way "
            f"data axis"
        )
    body = _sampled_step_body(model, optimizer, batch_size // n_data,
                              keep_prob, DATA_AXIS, grad_transform,
                              augment_fn=augment_fn)
    fn = jax.shard_map(
        _scan_chunk(body, chunk),
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def make_zero_device_train_step(model, optimizer, mesh, level: int,
                                batch_size: int, *,
                                keep_prob: float = 1.0, chunk: int = 1,
                                donate: bool = True, grad_transform=None,
                                augment_fn=None, overlap: bool = False,
                                bucket_mb: float | None = None):
    """ZeRO-sharded chunked step over device-resident data — the
    ``--zero`` composition of the headline input path. Sampling is the
    DP device step's verbatim (same salted PRNG folds, replicated
    split, ``batch_size // n_data`` rows per shard), so unclipped
    trajectories bit-match ``make_device_dp_train_step``; what changes
    is the update half (``parallel/zero._zero_step_core``): grads
    reduce-scatter over the data axis, the optimizer updates each
    rank's 1/D state shard, and — at level 1 — one all_gather rebuilds
    the replicated params. ``grad_transform`` arrives already
    axis-aware (``zero_clip_transform``).

    ``overlap=True`` (``--zero_overlap``) buckets the collectives and —
    at level 3 — DOUBLE-BUFFERS the param gather inside the scan body:
    each step ends by issuing the next step's all_gather, the scan
    carries the gathered full params, and the next iteration consumes
    them directly — XLA's async collectives hide the gather behind the
    step epilogue and the next step's on-device sampling. One warmup
    gather per dispatch primes the carry. Trajectories stay BITWISE
    identical to the serial ZeRO path (tests pin it)."""
    from distributed_tensorflow_tpu.parallel.zero import (
        DEFAULT_BUCKET_MB,
        _gather_bucketed,
        _zero_step_core,
        abstract_params,
        zero_state_specs,
    )

    n_data = mesh.shape[DATA_AXIS]
    if batch_size % n_data:
        raise ValueError(
            f"batch_size={batch_size} not divisible by the {n_data}-way "
            f"data axis")
    local_batch = batch_size // n_data
    bucket_mb = DEFAULT_BUCKET_MB if bucket_mb is None else float(bucket_mb)
    core = _zero_step_core(model, optimizer, mesh, level, keep_prob,
                           grad_transform, overlap=overlap,
                           bucket_bytes=int(bucket_mb * 2 ** 20))

    if overlap and level >= 3:
        meta = abstract_params(model)
        bucket_bytes = int(bucket_mb * 2 ** 20)

        def chunk_fn(state: TrainState, data):
            # warmup gather primes the double buffer once per dispatch
            full0 = _gather_bucketed(state.params, meta, n_data,
                                     bucket_bytes)

            def body(carry, _):
                st, full = carry
                rng, sub, batch = _split_and_sample(
                    st, data, local_batch, DATA_AXIS, augment_fn)
                st, metrics, nxt = core(st, batch, sub, rng,
                                        prefetched=full)
                return (st, nxt), metrics

            (state, _), metrics = lax.scan(body, (state, full0), None,
                                           length=chunk)
            return state, jax.tree.map(lambda mm: mm[-1], metrics)
    else:
        def body(state: TrainState, data):
            # _split_and_sample IS _sampled_step_body's sampler: every
            # shard draws the same rows a replicated-DP run would
            rng, sub, batch = _split_and_sample(state, data, local_batch,
                                                DATA_AXIS, augment_fn)
            st, metrics, _ = core(state, batch, sub, rng)
            return st, metrics

        chunk_fn = _scan_chunk(body, chunk)

    cache: dict = {}

    def call(state, data):
        fn = cache.get("fn")
        if fn is None:
            specs = zero_state_specs(state, level)
            sharded = jax.shard_map(
                chunk_fn, mesh=mesh,
                in_specs=(specs, P()),
                out_specs=(specs, P()),
                check_vma=False)
            fn = cache["fn"] = jax.jit(
                sharded, donate_argnums=(0,) if donate else ())
        return fn(state, data)

    return call


def make_device_sp_train_step(sp_model, optimizer, mesh, batch_size: int, *,
                              keep_prob: float = 1.0, chunk: int = 1,
                              donate: bool = True, grad_transform=None,
                              per_token_targets: bool = True):
    """Sequence-parallel chunked step over device-resident data — the
    composition of the two beyond-parity modes (--device_data +
    --seq_parallel). The split lives sharded over the token ("model")
    axis (data/device_data.put_device_data_sp); inside ``shard_map``
    each device samples example rows with a key folded on the DATA axis
    index ONLY — every token shard of a data row draws the SAME rows,
    so its local gather yields exactly its (B_local, S/P) tile of the
    batch, no collective on the input side. The rest is the SP train
    step verbatim: per-shard grads, ONE uniform pmean over the sequence
    axis then the data axis (both loss-family derivations in
    parallel/sequence_parallel.py), identical update everywhere.
    ``sp_model`` must carry ``seq_axis=MODEL_AXIS``."""
    from distributed_tensorflow_tpu.parallel.mesh import MODEL_AXIS
    from distributed_tensorflow_tpu.training.train_state import compute_grads

    if getattr(sp_model, "seq_axis", None) != MODEL_AXIS:
        raise ValueError(
            f"sp_model.seq_axis must be {MODEL_AXIS!r} (got "
            f"{getattr(sp_model, 'seq_axis', None)!r})")
    n_data = mesh.shape[DATA_AXIS]
    if batch_size % n_data:
        raise ValueError(
            f"batch_size={batch_size} not divisible by the {n_data}-way "
            f"data axis")
    local_batch = batch_size // n_data

    def body(state: TrainState, data):
        rng, sub = jax.random.split(state.rng)
        samp = jax.random.fold_in(state.rng, _SAMPLE_SALT)
        # DATA-axis fold only: token shards of one data row must draw
        # identical example rows (their tiles are slices of the same
        # sequences). The dropout key matches make_sp_train_step's: per
        # data shard here, and the LM folds the sequence index itself.
        samp = jax.random.fold_in(samp, lax.axis_index(DATA_AXIS))
        sub = jax.random.fold_in(sub, lax.axis_index(DATA_AXIS))
        idx = jax.random.randint(samp, (local_batch,), 0,
                                 data.num_examples)
        x = data.images[idx]
        y = data.labels[idx]
        if per_token_targets:
            # u8/u16 token storage -> int32 ids (image splits keep u8:
            # normalize_if_u8 in the model needs the original dtype)
            x = x.astype(jnp.int32)
            y = y.astype(jnp.int32)
        grads, metrics, model_state = compute_grads(
            sp_model, state.params, (x, y), keep_prob=keep_prob, rng=sub,
            model_state=state.model_state)
        grads = lax.pmean(grads, MODEL_AXIS)
        grads = lax.pmean(grads, DATA_AXIS)
        if grad_transform is not None:
            grads = grad_transform(grads)
        metrics = lax.pmean(metrics, MODEL_AXIS)
        metrics = lax.pmean(metrics, DATA_AXIS)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params, state.step)
        params = apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1, rng,
                          model_state), metrics

    from distributed_tensorflow_tpu.data.device_data import DeviceData

    y_spec = P(None, MODEL_AXIS) if per_token_targets else P(None)
    fn = jax.shard_map(
        _scan_chunk(body, chunk),
        mesh=mesh,
        # the data spec mirrors DeviceData's pytree type (shard_map's
        # spec matching is structural, a bare tuple prefix won't do)
        in_specs=(P(), DeviceData(P(None, MODEL_AXIS), y_spec)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def _make_resident_sharded_step(per_shard_step, state_specs_fn, mesh,
                                local_batch: int, chunk: int,
                                donate: bool):
    """Shared PP/EP resident-sampler wrapper: the DATA-axis-folded
    sample body lives HERE, once — every model-axis shard (stage or
    expert) of a data row folds the SAME (salt, data-index) key and so
    draws the SAME rows from its local 1/D of the split (the
    replicated-batch invariant both modes rest on); ``lax.scan`` runs
    ``chunk`` steps per dispatch, and the shard_map/jit pair is cached
    on first call (state specs need a concrete state)."""
    from distributed_tensorflow_tpu.data.device_data import DeviceData

    def body(state: TrainState, data):
        samp = jax.random.fold_in(state.rng, _SAMPLE_SALT)
        # DATA-axis fold only — the staged batch is replicated over the
        # model axis. The dropout stream is the wrapped step's own (it
        # folds DATA itself).
        samp = jax.random.fold_in(samp, lax.axis_index(DATA_AXIS))
        idx = jax.random.randint(samp, (local_batch,), 0,
                                 data.num_examples)
        batch = (data.images[idx].astype(jnp.int32),
                 data.labels[idx].astype(jnp.int32))
        return per_shard_step(state, batch)

    data_spec = DeviceData(P(DATA_AXIS, None), P(DATA_AXIS, None))
    cache: dict = {}

    def call(state, data):
        fn = cache.get("fn")
        if fn is None:
            specs = state_specs_fn(state)
            sharded = jax.shard_map(
                _scan_chunk(body, chunk), mesh=mesh,
                in_specs=(specs, data_spec),
                out_specs=(specs, P()),
                check_vma=False)
            fn = cache["fn"] = jax.jit(
                sharded, donate_argnums=(0,) if donate else ())
        return fn(state, data)

    return call


def make_pp_device_train_step(model, optimizer, mesh, batch_size: int,
                              microbatches: int, *, keep_prob: float = 1.0,
                              chunk: int = 1, donate: bool = True,
                              grad_transform=None,
                              virtual_stages: int = 1,
                              schedule: str = "auto"):
    """Pipeline-parallel chunked step over device-resident data — the
    GPipe schedule composed with the zero-host-bytes input path. The
    split lives DATA-SHARDED in HBM (``put_device_data(...,
    data_sharded=True)``: each data row of devices holds its 1/D of the
    examples, replicated over the stage axis); inside ``shard_map`` each
    device samples its local minibatch with a key folded on the DATA
    axis index ONLY — every stage of a data row draws the SAME rows, so
    its gather yields exactly its per-shard batch with no collective on
    the input side. The rest is the PP train step verbatim
    (parallel/pipeline_parallel._pp_step_fn: schedule-table tick scan +
    ppermute ring, psum'd replicated-leaf grads), and ``lax.scan`` runs
    ``chunk`` steps per dispatch. ``grad_transform`` composes inside the
    step — pass ``pp_clip_transform`` for an axis-correct --clip_norm.
    ``virtual_stages=V`` selects the interleaved schedule (state stacked
    by ``shard_state_pp(..., virtual_stages=V)``; bit-identical
    trajectories to V=1 with a ~V-fold smaller pipeline bubble).
    ``schedule="zb"`` runs the zero-bubble F/B/W table on the same
    layout — still bit-identical (parallel/pipeline_parallel)."""
    from distributed_tensorflow_tpu.parallel.pipeline_parallel import (
        _pp_step_fn,
        pp_state_specs,
    )

    n_data = mesh.shape[DATA_AXIS]
    if batch_size % n_data:
        raise ValueError(
            f"batch_size={batch_size} not divisible by the {n_data}-way "
            f"data axis")
    local_batch = batch_size // n_data
    if local_batch % int(microbatches):
        raise ValueError(
            f"per-shard batch {local_batch} must split into "
            f"{microbatches} microbatches")
    pp_step = _pp_step_fn(model, optimizer, mesh, microbatches, keep_prob,
                          grad_transform, virtual_stages, schedule)
    return _make_resident_sharded_step(pp_step, pp_state_specs, mesh,
                                       local_batch, chunk, donate)


def make_ep_device_train_step(model, optimizer, mesh, batch_size: int, *,
                              keep_prob: float = 1.0, chunk: int = 1,
                              donate: bool = True, grad_transform=None):
    """Expert-parallel chunked step over device-resident data — Switch
    MoE expert sharding composed with the zero-host-bytes input path.
    Same layout/sampling contract as the PP variant (data-sharded split,
    DATA-axis-folded sample key so every expert shard of a data row
    draws the SAME rows — the replicated-activation invariant the
    psum-combine rests on), with the EP gradient accounting verbatim
    (parallel/expert_parallel._ep_step_fn: 1/P loss seed, expert-shard
    grads as exact partials, psum'd replicated leaves). ``model`` must
    carry ``moe_axis=MODEL_AXIS``; pass ``ep_clip_transform`` as
    ``grad_transform`` for an axis-correct --clip_norm."""
    from distributed_tensorflow_tpu.parallel.expert_parallel import (
        _ep_step_fn,
        ep_state_specs,
    )

    n_data = mesh.shape[DATA_AXIS]
    if batch_size % n_data:
        raise ValueError(
            f"batch_size={batch_size} not divisible by the {n_data}-way "
            f"data axis")
    local_batch = batch_size // n_data
    ep_step = _ep_step_fn(model, optimizer, mesh, keep_prob,
                          grad_transform)
    return _make_resident_sharded_step(ep_step, ep_state_specs, mesh,
                                       local_batch, chunk, donate)


def make_device_tp_train_step(model, optimizer, mesh, batch_size: int, *,
                              keep_prob: float = 1.0, chunk: int = 1,
                              donate: bool = True, grad_transform=None,
                              augment_fn=None):
    """TP(+DP) chunked step over device-resident data: global-view GSPMD
    program — the state carries its TP layout (parallel/tensor_parallel),
    the split is replicated, the in-program sampled batch is constrained to
    the data axis, and XLA derives every collective. Composes the two
    beyond-parity modes (--device_data + --model_axis)."""
    from jax.sharding import NamedSharding

    batch_sharding = (
        NamedSharding(mesh, P(DATA_AXIS, None)),  # images [B, P]
        NamedSharding(mesh, P(DATA_AXIS)),        # int labels [B]
    )
    body = _sampled_step_body(model, optimizer, batch_size, keep_prob,
                              None, grad_transform, batch_sharding,
                              augment_fn=augment_fn)
    fn = _scan_chunk(body, chunk)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())
