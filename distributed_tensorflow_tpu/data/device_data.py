"""Device-resident datasets: the endpoint of the host-boundary elimination.

The reference uploads every batch from the client process per step (the
feed_dict at ``MNISTDist.py:179,188`` — ~3 kB/image over gRPC). The
thin-wire path (``DataSet.next_batch_raw`` + prefetch) cuts that 4x; this
module cuts it to ZERO: the full split (MNIST train = 60k x 784 uint8 ≈
47 MB) is staged into HBM once, and each compiled train step gathers its
minibatch on device from the step PRNG. Host↔device traffic per step is
nothing at all; combined with ``lax.scan`` chunking (training/device_step)
the dispatch overhead amortizes too.

Batches are sampled uniformly WITH replacement — statistically equivalent
to shuffled epochs for SGD but not the reference's exact epoch walk; the
host-fed paths keep exact reference semantics, this mode is the
TPU-native fast path (``--device_data``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class DeviceData(NamedTuple):
    """One split resident on device. ``images`` uint8 [N, ...] (models
    normalize on device — the thin-wire format), ``labels`` int32 [N]."""

    images: jnp.ndarray
    labels: jnp.ndarray

    @property
    def num_examples(self) -> int:
        return self.labels.shape[0]


def put_device_data_sp(split, mesh, per_token_targets: bool,
                       token_shape: tuple[int, int] | None = None
                       ) -> DeviceData:
    """Stage a split for the SEQUENCE-PARALLEL resident sampler: inputs
    sharded over the mesh's token ("model") axis, replicated over the
    data axis — each device holds (N, S/P[, token]) of the whole split,
    and the in-program gather draws the SAME example rows on every
    token shard of a data row (training/device_step's SP body), so a
    sampled batch IS the (B, S/P) tile ``stage_batch_sp`` would have
    uploaded. Token splits (``per_token_targets``): targets tiled like
    the inputs (next-token targets live with the tokens they score);
    image splits: inputs reshaped to (N, S, token_dim) host-side first
    (sequence_parallel.reshape_for_sp), labels replicated. Storage
    keeps the thin-wire dtypes (u8/u16 tokens, u8 pixels) — HBM cost
    is the split, tiny next to long-context activations."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.parallel.mesh import MODEL_AXIS

    x, y = split.images, split.labels
    if per_token_targets:
        # LM split: keep the native storage dtype (images/labels
        # materialize int32 copies of the whole split)
        toks = getattr(split, "_tokens", None)
        if toks is not None:
            x, y = toks[:, :-1], toks[:, 1:]
        x_spec, y_spec = P(None, MODEL_AXIS), P(None, MODEL_AXIS)
    else:
        if token_shape is None:
            raise ValueError("image splits need token_shape=(seq_len, "
                             "token_dim) to expose a token axis to shard")
        s, td = token_shape
        x = np.asarray(split._raw_u8()).reshape(-1, s, td)
        y = split.labels_int.astype(np.int32)
        x_spec, y_spec = P(None, MODEL_AXIS), P(None)
    arrays, specs = (np.asarray(x), np.asarray(y)), (x_spec, y_spec)
    out = []
    for arr, spec in zip(arrays, specs):
        sh = NamedSharding(mesh, spec)
        if jax.process_count() > 1:
            out.append(jax.make_array_from_process_local_data(sh, arr))
        else:
            out.append(jax.device_put(jnp.asarray(arr), sh))
    return DeviceData(*out)


def put_device_data(split, mesh=None, *, data_sharded: bool = False
                    ) -> DeviceData:
    """Stage a host ``DataSet`` split into HBM.

    With a mesh the arrays are replicated on every device (MNIST u8 is
    ~47 MB — cheap next to multi-GB HBM), so each data-parallel shard
    samples its sub-batch locally with no collective on the input side.
    Multi-process (one process per host, reference topology): every host
    already holds the full split (``MNISTDist.py:167`` semantics), so each
    supplies its own copy to the global replicated array — each host
    uploads only to its own chips.

    Token splits (LMDataSet) stage too: inputs/targets keep their u8/u16
    storage ((N, S) each — the x/y views of one (N, S+1) token table),
    and the sampled-gather step feeds them to the LM unchanged (ids are
    the thin-wire format; data/lm.py:121).

    ``data_sharded=True`` (requires a mesh) splits the example axis over
    the mesh's "data" axis instead, replicated over "model" — the layout
    the PP/EP resident samplers want: each data row of devices holds its
    1/D of the split and gathers minibatches from it with a
    DATA-axis-folded key, so every stage/expert shard of a row draws the
    SAME examples while rows sample disjoint pools (HBM cost per device
    drops 1/D too). A remainder of fewer than D examples is trimmed
    (sampling is with-replacement; the trim is below one batch of
    noise). Single-process only in this version — PP/EP are."""
    toks = getattr(split, "_tokens", None)
    if toks is not None:
        x, y = toks[:, :-1], toks[:, 1:]
    else:
        x = split._raw_u8()
        y = split.labels_int.astype(np.int32)
    if data_sharded:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_tensorflow_tpu.parallel.mesh import DATA_AXIS

        if mesh is None:
            raise ValueError("data_sharded staging needs a mesh")
        if jax.process_count() > 1:
            raise ValueError("data_sharded resident staging is "
                             "single-process in this version (PP/EP are)")
        n_data = mesh.shape[DATA_AXIS]
        x, y = np.asarray(x), np.asarray(y)
        n = len(y) - len(y) % n_data
        if n == 0:
            raise ValueError(
                f"split of {len(y)} examples cannot shard over the "
                f"{n_data}-way data axis (each row needs at least one "
                f"example)")
        x, y = x[:n], y[:n]
        out = []
        for arr in (x, y):
            spec = P(DATA_AXIS, *([None] * (arr.ndim - 1)))
            # numpy straight to the sharded layout: jnp.asarray first
            # would materialize the FULL split on the default device —
            # a transient HBM spike defeating the 1/D-per-device saving
            out.append(jax.device_put(arr, NamedSharding(mesh, spec)))
        return DeviceData(*out)
    if mesh is not None:
        from distributed_tensorflow_tpu.parallel.mesh import replicated_sharding

        sharding = replicated_sharding(mesh)
        if jax.process_count() > 1:
            return DeviceData(
                jax.make_array_from_process_local_data(sharding, np.asarray(x)),
                jax.make_array_from_process_local_data(sharding, np.asarray(y)),
            )
        return DeviceData(jax.device_put(jnp.asarray(x), sharding),
                          jax.device_put(jnp.asarray(y), sharding))
    return DeviceData(jax.device_put(jnp.asarray(x)),
                      jax.device_put(jnp.asarray(y)))
