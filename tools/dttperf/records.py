"""The measured side of the performance contract: bench-record
discovery plus the three DATA tables the passes check against.

- ``RATE_CHECKS`` (DTP001) — which measured record rates are BANDED
  against the predictor, at which (phase, mode, model) identity, and
  which are structurally EXEMPT because they are link-bound: PERF.md
  measured the host-fed tunnel wire varying 100x with load ("a
  measurement of the link first"), so no honest band exists for a
  rate the link dominates — exemption with the reason spelled out
  beats a band wide enough to be meaningless.
- ``PHASE_FACTS`` (DTP002) — for every host-only bench phase, the
  fact keys that must be NON-NULL in every record the phase appears
  in, including degraded/outage records (the established bench
  contract, now machine-enforced), plus the phase's error key: a
  record may carry null facts ONLY alongside the error key (the phase
  failed loudly and named why).
- ``PHASE_EXEMPT`` — bench phases with no dttperf-resolvable facts,
  each with the reason. dttlint DTT011 closes the loop: every public
  ``*_phase`` in bench.py must appear in exactly one of these two
  tables, so a new phase cannot ship outside the contract.

``MODEL_CONSUMES`` names the bench facts each predictor term has a
measured dual in — DTP002 proves the closure (every term's fact is
emitted by a covered phase), so the step-time model can never quietly
consume an analytic no record carries.
"""

from __future__ import annotations

import glob
import json
import os

from tools._analysis_common import REPO_ROOT


def load_records(root: str = REPO_ROOT) -> list[dict]:
    """Every ``BENCH_r*.json`` wrapper in ``root``, oldest first.
    ``parsed`` is normalized to a dict — a failed run's wrapper
    carries ``parsed: null`` (r04) and must not crash the scan."""
    out = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        stem = os.path.splitext(os.path.basename(path))[0]
        try:
            raw = json.load(open(path, encoding="utf-8"))
        except (OSError, ValueError):
            continue  # an unreadable wrapper has nothing to check
        if not isinstance(raw, dict):
            continue
        out.append({
            "stem": stem,
            "path": os.path.relpath(path, root),
            "rc": raw.get("rc"),
            "parsed": raw.get("parsed") or {},
        })
    return out


#: DTP001: one row per measured rate key. ``band`` is the allowed
#: measured/predicted ratio interval (the prediction is an efficiency-
#: 1.0 ceiling, so bands sit well below 1; the 1.05 roof catches a
#: measured rate beating the analytic ceiling — an accounting bug, not
#: a miracle). ``link_bound`` rows are exempt, with the reason.
#: Calibration: r02/r03 device-resident headline implies 0.31/0.30 of
#: ceiling; resnet20 implies 0.105/0.089 (bf16 convs fuse worse than
#: the dense stack). Band floors sit ~20% under the worst calibrated
#: point, so a >20% regression becomes a named finding.
RATE_CHECKS: tuple = (
    dict(key="value", metric="mnist_images_per_sec_per_chip",
         phase="device_resident", mode="dp", model="deep_cnn",
         per_chip_batch=2048, band=(0.25, 1.05)),
    dict(key="resnet20_cifar10_images_per_sec_per_chip",
         phase="resnet", mode="dp", model="resnet20",
         per_chip_batch=512, band=(0.07, 1.05)),
    dict(key="wire_images_per_sec_per_chip",
         phase="throughput", mode="dp", model="deep_cnn",
         link_bound="host-fed wire rate: the tunnel link varies 100x "
                    "with weather (PERF.md) — the number measures the "
                    "link, not the program; no honest band exists"),
    dict(key="feeddict_images_per_sec_per_chip",
         phase="feeddict_baseline", mode="dp", model="deep_cnn",
         link_bound="per-step host feed over the tunnel link (the "
                    "reference-parity baseline) — link-bound like the "
                    "wire rate"),
    dict(key="ps_emulation_images_per_sec",
         phase="ps_emulation", mode="ps", model="deep_cnn",
         link_bound="the PS pull/push cycle rides host TCP through "
                    "the tunnel — link-bound by design"),
    dict(key="ps_emulation_bf16_images_per_sec",
         phase="ps_emulation", mode="ps", model="deep_cnn",
         link_bound="bf16 wire variant of the PS cycle — link-bound "
                    "like its f32 twin"),
)


#: DTP002: host-only phases and the facts that stay non-null in EVERY
#: record the phase appears in (degraded/outage included). A phase
#: "appears" in a record when any of its keys or its error key is
#: present — records that predate a phase are out of scope.
PHASE_FACTS: dict = {
    "lint_phase": dict(
        keys=("lint_findings_total", "lint_baselined_total",
              "lint_stale_suppressions", "lint_rules", "lint_time_s"),
        error_key="lint_error"),
    "consan_phase": dict(
        keys=("consan_findings_total", "consan_baselined_total",
              "consan_threads_total", "consan_locks_total",
              "consan_shared_attrs", "consan_time_s"),
        error_key="consan_error"),
    "jaxprcheck_phase": dict(
        keys=("jaxprcheck_findings_total", "jaxprcheck_modes_proven",
              "jaxprcheck_collectives_total", "jaxprcheck_time_s"),
        error_key="jaxprcheck_error"),
    "perfcheck_phase": dict(
        keys=("perfcheck_findings_total", "perfcheck_scenarios_proven",
              "perfcheck_band_pct", "perfcheck_time_s"),
        error_key="perfcheck_error"),
    "efficiency_phase": dict(
        keys=("mfu", "flops_per_step", "goodput", "model_flops_per_sec",
              "mfu_peak_flops_per_sec", "mfu_peak_source",
              "efficiency_images_per_sec"),
        error_key="efficiency_error"),
    "resources_phase": dict(
        keys=("resources_hbm_live_bytes", "resources_hbm_source",
              "resources_hbm_analytic_state_bytes",
              "resources_live_vs_analytic",
              "resources_compiles_distinct_shapes",
              "resources_recompiles", "resources_compile_time_s",
              "resources_comm_bytes_dp", "resources_comm_bytes_zero1"),
        error_key="resources_error"),
    "telemetry_phase": dict(
        # telemetry_overhead_pct needs the chip A/B and is legitimately
        # null in host-only/degraded records — it is DTP003's budget
        # when measured, not a coverage fact here
        keys=("telemetry_span_overhead_ns", "telemetry_span_budget_ns",
              "telemetry_step_host_wait_s", "telemetry_step_dispatch_s",
              "telemetry_step_device_s", "telemetry_breakdown_source"),
        error_key="telemetry_error"),
    "reqtrace_phase": dict(
        keys=("reqtrace_requests_total", "reqtrace_complete_pct",
              "reqtrace_p99_phase", "reqtrace_slo_compliant_pct",
              "reqtrace_record_cost_ms", "reqtrace_overhead_pct"),
        error_key="reqtrace_error"),
    "recovery_phase": dict(
        keys=("recovery_restore_step", "recovery_fallback_depth",
              "recovery_quarantined", "recovery_time_s"),
        error_key="recovery_error"),
    "serving_phase": dict(
        keys=("serving_throughput_rps", "serving_p50_ms",
              "serving_p99_ms", "serving_reload_blip_ms",
              "serving_dropped"),
        error_key="serving_error"),
    "router_phase": dict(
        keys=("router_replicas", "router_healthy", "router_retries",
              "router_hedges", "router_ejections", "router_overhead_ms"),
        error_key="router_error"),
    "continuous_batching_phase": dict(
        # the knee A/B rates need wall-clock sweeps and stay null in
        # degraded records; the page-ledger facts are analytic
        keys=("kv_pages_allocated", "kv_pages_high_water",
              "kv_page_ledger_ok", "slot_occupancy",
              "tokens_per_iteration"),
        error_key="continuous_error"),
    "elastic_phase": dict(
        keys=("elastic_world", "elastic_drain_steps", "elastic_resize_s",
              "elastic_restore_step", "elastic_restore_fallback_depth",
              "elastic_epoch"),
        error_key="elastic_error"),
}


#: bench phases with nothing for dttperf to resolve — each with the
#: reason (DTT011 rejects a bare name; an unexplained exemption is an
#: unexplained hole in the contract).
PHASE_EXEMPT: dict = {
    "device_resident_phase":
        "the headline measured rate — DTP001 bands it against the "
        "predictor; it emits a rate, not analytic facts",
    "throughput_phase":
        "host-fed wire rate: link-bound (PERF.md tunnel weather), "
        "RATE_CHECKS exempts it explicitly",
    "resnet_phase":
        "chip-gated measured rate — DTP001 bands it via RATE_CHECKS",
    "convergence_phase":
        "accuracy trajectory (seconds/steps-to-target), not a step "
        "rate — no analytic dual in the step-time model",
    "feeddict_baseline_phase":
        "reference-parity baseline over the host link — link-bound, "
        "RATE_CHECKS exempts it explicitly",
    "ps_emulation_phase":
        "host-TCP PS cycle — link-bound, RATE_CHECKS exempts it",
    "lm_longctx_phase":
        "chip-gated LM sweep; its analytic duals (FLOPs, ledger "
        "bytes) ride efficiency_phase/resources_phase facts",
    "lm_largevocab_phase":
        "chip-gated LM sweep — see lm_longctx_phase",
    "pp_device_phase":
        "chip-gated PP A/B; the analytic schedule facts "
        "(pp_useful_tick_fraction) ride _pp_schedule_facts into every "
        "record including degraded ones",
    "ep_device_phase":
        "chip-gated EP A/B — rates need >=2 chips and stay null off",
    "dp_zero_phase":
        "chip-gated ZeRO A/B; the analytic memory facts ride "
        "_zero_mem_facts into every record",
    "overlap_phase":
        "chip-gated overlap A/B; the analytic fractions ride "
        "_overlap_analytic_facts into every record",
    "telemetry_ab_phase":
        "the chip half of the telemetry A/B — its product "
        "(telemetry_overhead_pct) is DTP003's budget when measured",
}


#: the closure DTP002 proves: every term of the step-time model names
#: the bench fact that carries its measured/analytic dual. ``phase``
#: None = the fact is emitted at record level by an analytic helper
#: (checked against bench.py source), else the fact must sit in that
#: phase's PHASE_FACTS row.
MODEL_CONSUMES: tuple = (
    ("compute", "efficiency_phase", "flops_per_step"),
    ("compute", "efficiency_phase", "mfu_peak_flops_per_sec"),
    ("exposed_comm", "resources_phase", "resources_comm_bytes_dp"),
    ("exposed_comm", "resources_phase", "resources_comm_bytes_zero1"),
    ("pp_useful_fraction", None, "pp_useful_tick_fraction"),
)
