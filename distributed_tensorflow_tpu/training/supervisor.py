"""Supervisor: the reference's training orchestration, re-built.

``tf.train.Supervisor`` (``MNISTDist.py:158-170``) owns: chief designation
(task 0), init-or-restore at session start, periodic chief-only
checkpointing, a should_stop signal, and cleanup. This Supervisor owns the
same responsibilities over a TrainState pytree; ``managed`` replaces
``managed_session`` — it yields the (possibly restored) state and
guarantees a final checkpoint + cleanup on the way out, including on error
(the "closing when done or an error occurs" contract, MNISTDist.py:169-191).
"""

from __future__ import annotations

import contextlib
import signal

from distributed_tensorflow_tpu.checkpoint import Checkpointer
from distributed_tensorflow_tpu.utils.faults import fault_point


class _CancelGate:
    """Cancel flag whose check and the guarded action are mutually
    excluded: ``cancel()`` blocks while a holder is inside ``guard()``,
    so a time-bounded caller that abandons a save either prevents the
    write entirely or waits for an already-started write to finish
    before closing the writer — never both racing."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._cancelled = False

    def cancel(self):
        with self._lock:
            self._cancelled = True

    @property
    def lock(self):
        return self._lock

    @property
    def cancelled(self) -> bool:
        return self._cancelled


class Supervisor:
    def __init__(
        self,
        is_chief: bool,
        logdir: str,
        save_model_secs: int = 600,
        max_to_keep: int = 5,
        background_save: bool = False,
        final_save_timeout_s: float = 300.0,
        exit_agreement_timeout_s: float = 60.0,
        sharded_spanning: bool = True,
    ):
        """``background_save`` moves the cadenced checkpoint writes off the
        training thread (the reference Supervisor's Saver ran in background
        service threads, MNISTDist.py:159-170); the final save on exit is
        always synchronous."""
        self.is_chief = is_chief
        self.logdir = logdir
        # bounds (pre-grace) on the exit path's two collectives when
        # state spans hosts; run_bounded extends each 4x with a progress
        # line before abandoning, so healthy-but-slow runs complete.
        # Both knobs sit on the same constructor so a slow-rendezvous
        # deployment tunes them together (an agreement that times out
        # while the save bound is raised reopens the asymmetric-skip
        # window).
        self.final_save_timeout_s = final_save_timeout_s
        self.exit_agreement_timeout_s = exit_agreement_timeout_s
        # cross-host-sharded state: per-process shard files (True,
        # default — no collective in the save) vs the monolithic
        # allgather-then-chief-writes path (False)
        self.sharded_spanning = sharded_spanning
        self.checkpointer = Checkpointer(
            logdir, is_chief=is_chief, save_model_secs=save_model_secs,
            max_to_keep=max_to_keep, background=background_save,
        )
        self._stop = False
        # recovery observability: the checkpoint.RestoreReport of the last
        # init_or_restore (None until then / on a fresh init)
        self.restore_report = None

    def should_stop(self) -> bool:
        return self._stop

    def request_stop(self):
        self._stop = True

    def stop(self):
        """MNISTDist.py:192 parity — idempotent shutdown signal."""
        self._stop = True

    def init_or_restore(self, init_state):
        """Chief restores latest checkpoint or keeps the fresh init
        (MNISTDist.py:169-170); returns (state, start_step).

        Cross-mode compatibility (SURVEY.md §7 hard part d): ps-mode
        checkpoints carry only {"params", "step"} — no optimizer slots or
        rng. Restoring one into a full-TrainState run adopts its params
        and step and keeps the fresh optimizer state. (The reverse needs
        nothing: full-state checkpoints are a superset of the ps layout,
        and restore ignores extra keys.)

        The restore runs through the VERIFIED fallback ladder
        (checkpoint.restore_with_fallback): the per-array CRC manifest
        is checked, a corrupt/torn/mixed newest set is quarantined to
        ``*.corrupt`` and the next-older complete set restores instead,
        and a set that vanishes mid-read under a racing peer's GC is
        re-scanned — loud failure only when the ladder is exhausted.
        ``self.restore_report`` (a checkpoint.RestoreReport, or None on
        a fresh init) records where the state actually came from; the
        loops emit it as the ``recovery_*`` scalars.

        The outer FileNotFoundError retry survives the one raiser the
        ladder does not cover: ``_latest_is_params_only``'s
        ``checkpoint_keys`` read on the ps-layout fallback path, where a
        racing peer's GC can delete the set between selection and the
        key scan. Bounded so a genuinely sick directory still fails
        loudly."""
        state = step = None
        for attempt in range(2):
            try:
                state, step = self._init_or_restore_once(init_state)
                break
            except FileNotFoundError as e:
                print(f"checkpoint vanished mid-restore (racing peer "
                      f"GC?): {e} — re-scanning (attempt "
                      f"{attempt + 1}/3)")
        else:
            # third and final attempt: an error here is the loud exit
            state, step = self._init_or_restore_once(init_state)
        self.restore_report = self.checkpointer.last_restore_report
        rep = self.restore_report
        if rep is not None:
            print(f"restored checkpoint step={rep.step} "
                  f"(fallback_depth={rep.fallback_depth}, "
                  f"quarantined={len(rep.quarantined)}, "
                  f"time={rep.time_s:.2f}s)")
        return state, step

    def _init_or_restore_once(self, init_state):
        try:
            restored = self.checkpointer.restore(init_state)
        except KeyError as e:
            # take the fallback ONLY for a genuine ps-layout file; any
            # other structural mismatch (renamed optimizer, new TrainState
            # field) must stay a loud error, not a silent params-only
            # restore that resets optimizer slots
            if not (hasattr(init_state, "params")
                    and self._latest_is_params_only()):
                if "opt_state" in str(e):
                    # the most common way to hit this: switching
                    # --optimizer between runs changes the slot layout
                    raise KeyError(
                        f"{e.args[0] if e.args else e} — note: the optimizer "
                        f"state layout depends on --optimizer; resume with "
                        f"the same optimizer the checkpoint was written with"
                    ) from e
                raise
            partial = self.checkpointer.restore(
                {"params": init_state.params, "step": 0})
            if partial is None:
                raise
            blob, step = partial
            print(f"restored a params-only (ps-mode) checkpoint at step "
                  f"{step}; optimizer state starts fresh")
            import jax.numpy as jnp

            state = init_state._replace(
                params=blob["params"],
                step=jnp.asarray(step, init_state.step.dtype),
            )
            return state, step
        if restored is None:
            return init_state, 0
        state, step = restored
        return state, step

    def maybe_checkpoint(self, state, step: int):
        return self.checkpointer.maybe_save(state, step)

    def checkpoint_coordinated(self, state, step: int,
                               attempt: str | None = None):
        """One coordinated checkpoint: EVERY process calls this together
        (the loop's cadenced vote agreed on the boundary step first;
        ``attempt`` is the per-save nonce that vote distributed — the
        sharded format stamps it so two save attempts at one step can
        never assemble into a mixed set).

        The fetch is the collective half — a state with leaves sharded
        across hosts (a model axis spanning processes) is gathered with
        ``process_allgather``, which only works if all processes
        participate; ``jax.device_get`` alone raises on such leaves (the
        round-2 latent crash). Only the chief writes the result. Processes
        whose state is locally fetchable and that aren't the chief skip
        the fetch entirely — single-host behavior is unchanged."""
        self._coordinated_save(state, step, final=False, attempt=attempt)

    def _coordinated_save(self, state, step: int, *, final: bool,
                          cancelled=None, attempt: str | None = None):
        """The ONE implementation of the symmetric fetch-then-chief-writes
        gate, shared by the cadenced vote path and the managed() exit so
        the two cannot drift apart (a gate that differs between them is a
        multi-host shutdown deadlock no single-host test catches).
        ``final`` picks the synchronous write over the background-capable
        one. Non-chief processes only join the cross-host collective —
        they never pay the full-model device->host copy the chief needs
        for the file. ``cancelled`` (a ``_CancelGate``) is consulted
        between the fetch and the write UNDER the gate's lock: a
        time-bounded caller that abandoned this save either flips the
        gate first (the late-completing fetch discards) or blocks in
        ``cancel()`` until an in-flight write finishes (so the
        checkpointer is never closed mid-write).

        Cross-host-sharded state defaults to the SHARDED format
        (``sharded_spanning``): every process writes its own shard file
        with its locally-owned slices — NO collective, no O(model)
        allgather to every host (r3 verdict item 6); restore reassembles
        from the complete set. ``sharded_spanning=False`` keeps the
        monolithic allgather-then-chief-writes path."""
        import contextlib as _ctx

        from distributed_tensorflow_tpu.utils.pytree import (
            flatten_pytree,
            join_collective_fetch,
            needs_collective_fetch,
        )

        fault_point("collective_fetch", step=step)
        if self.sharded_spanning and needs_collective_fetch(state):
            self.checkpointer.save_sharded(state, step, attempt=attempt)
            return
        if self.is_chief:
            flat = flatten_pytree(state, tag_bf16=True)
            # injection seam between the fetch and the gated write: a
            # mode=delay rule here forces the fetch to complete AFTER a
            # bounded caller abandoned it — the discard path below
            fault_point("cancel_gate", step=step)
            with (cancelled.lock if cancelled is not None
                  else _ctx.nullcontext()):
                if cancelled is not None and cancelled.cancelled:
                    print(f"final checkpoint fetch completed after its "
                          f"bound expired; discarding (step {step})")
                    return
                if final:
                    self.checkpointer.save_fetched(flat, step)
                else:
                    self.checkpointer.submit_fetched(flat, step)
        elif needs_collective_fetch(state):
            join_collective_fetch(state)

    def _latest_is_params_only(self) -> bool:
        """True when the newest checkpoint holds exactly the ps-mode
        {"params", "step"} layout (utils/pytree path keys)."""
        from distributed_tensorflow_tpu.checkpoint.checkpoint import (
            latest_checkpoint,
        )

        found = latest_checkpoint(self.checkpointer.directory)
        if found is None:
            return False
        from distributed_tensorflow_tpu.checkpoint.checkpoint import (
            checkpoint_keys,
        )

        from distributed_tensorflow_tpu.utils.pytree import _BF16_TAG

        keys = {k[len(_BF16_TAG):] if k.startswith(_BF16_TAG) else k
                for k in checkpoint_keys(found[0])}
        return bool(keys) and all(
            k == "step" or k.startswith("params/") for k in keys
        )

    def _install_signal_handlers(self):
        """SIGTERM/SIGINT -> request_stop, so the loop exits cleanly and
        ``managed`` writes the final checkpoint (the Supervisor recovery
        contract, MNISTDist.py:169-191: close cleanly 'when done or an
        error occurs'). Returns a restore callable; no-op off the main
        thread (signal.signal is main-thread-only)."""
        previous = {}

        def _handler(signum, frame):
            print(f"signal {signum}: stop requested, checkpointing... "
                  f"(repeat to force-quit)", flush=True)
            self.request_stop()
            # escalation path: restore the original dispositions so a
            # second signal (e.g. repeated Ctrl-C on a wedged run) is not
            # swallowed by this handler
            for sig, old in previous.items():
                signal.signal(sig, old)

        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                previous[sig] = signal.signal(sig, _handler)
        except ValueError:  # not the main thread
            previous = {}

        def _restore():
            for sig, old in previous.items():
                signal.signal(sig, old)

        return _restore

    @contextlib.contextmanager
    def managed(self, init_state, handle_signals: bool = True):
        """Context manager over a training run: restore-or-init on entry,
        final checkpoint + stop on exit (normal, error, or SIGTERM/SIGINT
        — the signal path requests a stop, the loop drains, and the final
        save lands here).

        An elastic ``ResizeRequired`` unwinding through here is a CLEAN
        exit: every participant raises it at the same agreed boundary
        (the vote invariant), and the final save below IS the drain
        checkpoint the re-formed world restores from. The one exception
        is ``lost_step`` (an immediate preemption — the capacity died
        with the step): the state is dropped so NO save happens, and the
        re-form falls back to the newest cadenced checkpoint or the
        sentinel's adopted emergency snapshot."""
        state_box = _StateBox(*self.init_or_restore(init_state))
        restore_signals = (
            self._install_signal_handlers() if handle_signals else lambda: None
        )
        clean_exit = False
        try:
            yield state_box
            clean_exit = True
        except Exception as e:
            from distributed_tensorflow_tpu.training.elastic import (
                Departed,
                ResizeRequired,
            )

            if isinstance(e, ResizeRequired):
                if e.lost_step:
                    state_box.state = None  # lost with the capacity
                else:
                    clean_exit = True  # the final save is the drain
            elif isinstance(e, Departed):
                # the preempted process leaves at the AGREED boundary —
                # a clean exit: it must vote clean in the exit agreement
                # and join the final collective fetch, or cross-host-
                # sharded survivors would skip the drain save
                clean_exit = True
            raise
        finally:
            restore_signals()
            abandoned = None  # set => raise after cleanup (clean exits)
            if state_box.state is not None:
                from distributed_tensorflow_tpu.utils.pytree import (
                    agree_clean_exit,
                    needs_collective_fetch,
                )

                # cross-host-sharded state: EVERY process participates in
                # the collective fetch (they all exit the loop at the same
                # agreed step — the stop-vote invariant); only the chief
                # writes. Locally-fetchable state keeps the chief-only
                # path. Ahead of the collective, ALL processes — clean or
                # unwinding an exception — join one bounded agreement
                # allgather of their clean flags: the save proceeds only
                # when every process is clean, so a mixed exit skips
                # SYMMETRICALLY instead of stranding clean peers in a
                # process_allgather the failed process never joins (r3
                # ADVICE: the unbounded-hang mixed-exit hole).
                needs = needs_collective_fetch(state_box.state)
                proceed = True
                attempt = None
                if needs:
                    # the agreement allgather also carries the sharded
                    # save's attempt nonce — the save itself stays
                    # collective-free (its load-bearing contract: it
                    # runs UNBOUNDED below)
                    verdict, attempt = agree_clean_exit(
                        clean_exit, timeout_s=self.exit_agreement_timeout_s,
                        return_token=True)
                    if verdict is None:
                        proceed = False
                        abandoned = ("a peer process never reached the "
                                     "exit agreement (died hard?); final "
                                     "checkpoint skipped")
                        print(f"final checkpoint skipped: {abandoned} — "
                              "dying loudly instead of hanging in the "
                              "collective fetch")
                    elif not verdict:
                        proceed = False
                        print("final checkpoint skipped: a process exited "
                              "on an error with cross-host-sharded state "
                              "(the collective fetch needs every process "
                              "at the same point; all peers skip "
                              "symmetrically)")
                if proceed and (self.is_chief or needs):
                    if needs and not self.sharded_spanning:
                        # the save's collective fetch gets its own bound
                        # (run_bounded's timeout + grace): even if the
                        # agreement resolved asymmetrically (a peer
                        # abandoned it right as it completed — the
                        # two-generals residue), this process blocks a
                        # bounded time, then dies loudly instead of
                        # hanging forever in process_allgather. The
                        # cancel gate (event + lock, mutually excluded
                        # with the write) keeps an ABANDONED fetch that
                        # completes late from writing through the
                        # checkpointer we are about to close.
                        from distributed_tensorflow_tpu.utils.pytree import (
                            run_bounded,
                        )

                        gate = _CancelGate()
                        done, err = run_bounded(
                            lambda: self._coordinated_save(
                                state_box.state, state_box.step,
                                final=True, cancelled=gate),
                            self.final_save_timeout_s,
                            what="final collective checkpoint")
                        if not done:
                            gate.cancel()
                            abandoned = ("final checkpoint abandoned: a "
                                         "peer never joined the "
                                         "collective fetch")
                            print(f"{abandoned} — exiting loudly")
                        elif isinstance(err, Exception):
                            print(f"final checkpoint failed: {err}")
                    else:
                        try:
                            self._coordinated_save(state_box.state,
                                                   state_box.step,
                                                   final=True,
                                                   attempt=attempt)
                        except Exception as e:  # noqa: BLE001 best-effort
                            print(f"final checkpoint failed: {e}")
            self.checkpointer.close()
            self.stop()
            # an otherwise-clean run whose exit protocol was ABANDONED
            # (peer died hard) must not report success: raise so the
            # process exits nonzero and the orchestrator sees the job
            # failed. When an exception is already unwinding (not
            # clean_exit), raising here would mask it — the in-flight
            # error is the loud exit.
            if abandoned and clean_exit:
                raise RuntimeError(abandoned)


class _StateBox:
    """Mutable holder so the loop can publish progress to the supervisor."""

    def __init__(self, state, step: int):
        self.state = state
        self.step = step

    def update(self, state, step: int):
        self.state = state
        self.step = step
