"""MiniTransformer: an attention model family for the long-context path.

The reference framework has no attention model — this is the build's
extension exercising the sequence-parallel machinery
(ops/attention.ring_attention + parallel/sequence_parallel) on the same
datasets: an image is read as a SEQUENCE of rows (MNIST: 28 tokens of 28
pixels; CIFAR-10: 32 tokens of 96), embedded, run through pre-LN
transformer blocks, mean-pooled and classified. Pure pytree-of-arrays +
``apply`` like every model here — jits, shards, grads as a function.

Sequence parallelism: constructed with ``seq_axis="model"`` the model is
SPMD-aware — called inside shard_map with the token dimension sharded
over that mesh axis it slices its own positional embeddings by
``lax.axis_index``, runs RING attention over the axis, and mean-pools
with a ``psum``. Everything before the pool is per-token compute whose
parameter gradients arrive as P-scaled partials per shard while the
post-pool head's arrive replicated — one uniform pmean over the
sequence axis reduces both exactly (see
parallel/sequence_parallel.py for the derivation).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_tpu.models.cnn import truncated_normal_init
from distributed_tensorflow_tpu.models.registry import register_model
from distributed_tensorflow_tpu.ops import nn
from distributed_tensorflow_tpu.ops.attention import (
    multi_head_attention,
    ring_attention,
)


def _layernorm(x, gain, bias, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = xf.var(axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * gain + bias).astype(x.dtype)


@register_model("transformer")
class MiniTransformer:
    """Row-sequence transformer classifier.

    ``seq_axis=None`` (default): dense attention, runs anywhere a
    DeepCNN runs. ``seq_axis="model"``: ring attention + sharded
    positional slices + psum pooling — must then be applied inside
    shard_map with tokens sharded over that axis (the sequence-parallel
    step builder does this).
    """

    stateful = False

    def __init__(
        self,
        image_size: int = 28,
        channels: int = 1,
        num_classes: int = 10,
        d_model: int = 128,
        num_heads: int = 4,
        num_blocks: int = 2,
        mlp_ratio: int = 4,
        compute_dtype: Any = None,
        seq_axis: str | None = None,
        **_unused,  # registry passes hidden_units etc. to every model
    ):
        if d_model % num_heads:
            raise ValueError(f"d_model={d_model} % num_heads={num_heads} != 0")
        self.image_size = image_size
        self.channels = channels
        self.num_classes = num_classes
        self.d_model = d_model
        self.num_heads = num_heads
        self.num_blocks = num_blocks
        self.mlp_dim = mlp_ratio * d_model
        self.compute_dtype = compute_dtype
        self.seq_axis = seq_axis
        self.seq_len = image_size           # one token per image row
        self.token_dim = image_size * channels

    def init(self, key, dtype=jnp.float32):
        d, h = self.d_model, self.num_heads
        dh = d // h
        keys = iter(jax.random.split(key, 4 + 7 * self.num_blocks))

        def w(shape, stddev=0.02):
            return truncated_normal_init(next(keys), shape, stddev, dtype)

        params = {
            "embed": {"w": w((self.token_dim, d)), "b": jnp.zeros((d,), dtype)},
            "pos": w((self.seq_len, d)),
            "blocks": [],
            "ln_f": {"g": jnp.ones((d,), dtype), "b": jnp.zeros((d,), dtype)},
            "head": {
                "w": w((d, self.num_classes)),
                "b": jnp.zeros((self.num_classes,), dtype),
            },
        }
        for _ in range(self.num_blocks):
            params["blocks"].append({
                "ln1_g": jnp.ones((d,), dtype),
                "ln1_b": jnp.zeros((d,), dtype),
                "qkv": w((d, 3, h, dh)),
                "proj": w((h * dh, d)),
                "ln2_g": jnp.ones((d,), dtype),
                "ln2_b": jnp.zeros((d,), dtype),
                "mlp_in": {"w": w((d, self.mlp_dim)), "b": jnp.zeros((self.mlp_dim,), dtype)},
                "mlp_out": {"w": w((self.mlp_dim, d)), "b": jnp.zeros((d,), dtype)},
            })
        return params

    # ---- forward -------------------------------------------------------
    def apply(self, params, x, *, keep_prob=1.0, rng=None, train: bool = False):
        cd = self.compute_dtype
        x = nn.normalize_if_u8(x, cd)
        # (B, 784[*C]) or (B, S, token): accept both layouts. In SP mode
        # x is the LOCAL token block (B, S/P, token) handed in by the
        # shard_map step.
        if x.ndim == 2:
            x = x.reshape(-1, self.seq_len, self.token_dim)
        if cd is not None:
            x = x.astype(cd)

        d = self.d_model
        h = nn.dense(x, params["embed"]["w"], params["embed"]["b"],
                     compute_dtype=cd)
        pos = params["pos"]
        if self.seq_axis is not None:
            # my shard's slice of the positional table
            s_local = x.shape[1]
            start = lax.axis_index(self.seq_axis) * s_local
            pos = lax.dynamic_slice_in_dim(pos, start, s_local, axis=0)
        h = h + pos.astype(h.dtype)

        for blk in params["blocks"]:
            y = _layernorm(h, blk["ln1_g"], blk["ln1_b"])
            qkv = jnp.einsum("bsd,dthe->tbshe",
                             y, blk["qkv"].astype(y.dtype))
            q, k, v = qkv[0], qkv[1], qkv[2]
            if self.seq_axis is not None:
                a = ring_attention(q, k, v, self.seq_axis)
            else:
                a = multi_head_attention(q, k, v)
            a = a.reshape(*a.shape[:2], -1)  # (B, S, H*Dh)
            h = h + nn.dense(a, blk["proj"], compute_dtype=cd)
            y = _layernorm(h, blk["ln2_g"], blk["ln2_b"])
            y = jax.nn.relu(nn.dense(y, blk["mlp_in"]["w"],
                                     blk["mlp_in"]["b"], compute_dtype=cd))
            h = h + nn.dense(y, blk["mlp_out"]["w"], blk["mlp_out"]["b"],
                             compute_dtype=cd)

        h = _layernorm(h, params["ln_f"]["g"], params["ln_f"]["b"])
        # mean-pool over the FULL sequence: local sum, psum across the
        # sequence shards, divide by the global length
        pooled = h.sum(axis=1)
        if self.seq_axis is not None:
            pooled = lax.psum(pooled, self.seq_axis)
        pooled = pooled / jnp.asarray(self.seq_len, pooled.dtype)
        pooled = nn.dropout(pooled, keep_prob, rng, deterministic=not train)
        logits = nn.dense(pooled, params["head"]["w"], params["head"]["b"],
                          compute_dtype=cd)
        return logits.astype(jnp.float32)

    def num_params(self, params=None):
        if params is None:
            params = jax.eval_shape(lambda: self.init(jax.random.key(0)))
        return sum(int(jnp.size(p)) for p in jax.tree.leaves(params))
