"""CLI: ``python -m tools.dttperf [--json] [--mode M] [--model M]
[--baseline PATH] [--matrix]``.

Exit status is the shared analyzer contract (dttlint/dttcheck/dttsan):
0 when every cell prices clean, every banded record rate sits in its
band, the fact-coverage and budget closures hold, and no suppression
is stale; 1 otherwise.

``--mode`` / ``--model`` filter the cell matrix for bring-up (a
filtered run prices cells only — the record/budget passes need the
whole corpus). ``--matrix`` prints the per-cell prediction table
(step time, bound term, predicted ceiling) — the human-readable view
of what the contract promises.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# tools/ convention: runnable as a script too
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from tools.dttperf import DEFAULT_BASELINE, run_perf  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dttperf",
        description="dttperf — the performance-contract analyzer "
                    "(passes DTP000-DTP003; see docs/ARCHITECTURE.md "
                    "'Performance contracts')")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON object")
    ap.add_argument("--mode", action="append", default=None,
                    help="restrict to one parallel mode (repeatable): "
                         "dp zero1 zero3 pp tp ep sp ps")
    ap.add_argument("--model", action="append", default=None,
                    help="restrict to one model (repeatable): "
                         "deep_cnn mlp lm lm_moe")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="suppression file (default: the checked-in "
                         "tools/dttperf/baseline.json)")
    ap.add_argument("--matrix", action="store_true",
                    help="print the per-cell prediction table")
    args = ap.parse_args(argv)

    result = run_perf(args.baseline, modes=args.mode, models=args.model)

    if args.json:
        print(json.dumps(result.to_json()))
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.format())
    for key in result.stale:
        print(f"{args.baseline}: STALE suppression {key} — the finding "
              f"no longer exists; delete the entry (the baseline only "
              f"shrinks)")
    rep = result.report
    if args.matrix:
        print(f"{'cell':<24} {'chips':>5} {'batch':>6} "
              f"{'step ms':>9} {'ex/s/chip':>11} {'bound':<8} "
              f"{'useful':>6}")
        for r in rep.get("cells", []):
            print(f"{r['cell']:<24} {r['chips']:>5} "
                  f"{r['global_batch']:>6} {r['step_time_ms']:>9.3f} "
                  f"{r['examples_per_sec_per_chip']:>11,.0f} "
                  f"{r['bound']:<8} {r['useful_fraction']:>6.3f}")
    n_budget_ok = sum(1 for b in rep.get("budgets", [])
                      if b["status"] == "ok")
    print(f"dttperf: {len(result.findings)} finding(s), "
          f"{len(result.baselined)} baselined, "
          f"{len(result.stale)} stale suppression(s); "
          f"{rep.get('scenarios_proven')} cell(s) priced, "
          f"modes: {rep.get('modes_priced')}, "
          f"records in-band: {rep.get('in_band_pct')}%, "
          f"budgets ok: {n_budget_ok}/{len(rep.get('budgets', []))}, "
          f"{rep.get('time_s')}s")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
