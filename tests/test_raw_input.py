"""Thin-wire input path: uint8 pixels + int32 labels end to end.

The raw path exists because the host->device link, not the MXU, bounds
throughput for small models (PERF.md); these tests pin its semantics:
int-label loss/accuracy == one-hot loss/accuracy, u8 model inputs ==
normalized f32 inputs, and the raw batch stream draws the same shuffled
indices as the reference-parity float stream.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.datasets import DataSet
from distributed_tensorflow_tpu.models import DeepCNN
from distributed_tensorflow_tpu.ops import nn


@pytest.fixture(scope="module")
def logits_labels():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(64, 10)), jnp.float32)
    ints = rng.integers(0, 10, 64)
    onehot = np.zeros((64, 10), np.float32)
    onehot[np.arange(64), ints] = 1.0
    return logits, jnp.asarray(ints, jnp.int32), jnp.asarray(onehot)


def test_cross_entropy_int_equals_onehot(logits_labels):
    logits, ints, onehot = logits_labels
    a = float(nn.softmax_cross_entropy(logits, onehot))
    b = float(nn.softmax_cross_entropy(logits, ints))
    assert a == pytest.approx(b, rel=1e-6)


def test_accuracy_int_equals_onehot(logits_labels):
    logits, ints, onehot = logits_labels
    assert float(nn.accuracy(logits, onehot)) == float(nn.accuracy(logits, ints))


def test_next_batch_raw_same_index_stream():
    """raw and float streams draw identical shuffled epochs from the same
    seed; u8-sourced images match exactly (f32 = u8/255)."""
    rng = np.random.default_rng(1)
    images = rng.integers(0, 256, (50, 784), np.uint8)
    labels = rng.integers(0, 10, 50).astype(np.int64)
    a = DataSet(images.copy(), labels.copy(), one_hot=True, seed=7)
    b = DataSet(images.copy(), labels.copy(), one_hot=True, seed=7)
    for _ in range(4):  # crosses an epoch boundary (50 examples, bs 16)
        xf, yf = a.next_batch(16)
        xu, yu = b.next_batch_raw(16)
        # f32 path may scale by the reciprocal (native gather); 1-ulp-level
        # agreement with u8/255 is the contract
        np.testing.assert_allclose(xf, xu.astype(np.float32) / 255.0,
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_array_equal(np.argmax(yf, axis=1), yu)
        assert xu.dtype == np.uint8 and yu.dtype == np.int32


def test_next_batch_raw_float_source_quantizes_without_side_effects():
    rng = np.random.default_rng(2)
    images = rng.random((20, 784)).astype(np.float32)
    labels = rng.integers(0, 10, 20).astype(np.int64)
    ds = DataSet(images, labels, one_hot=True, seed=0)
    xu, yu = ds.next_batch_raw(8)
    assert xu.dtype == np.uint8
    # the float path must still serve the ORIGINAL float values afterwards
    xf, _ = ds.next_batch(8)
    assert xf.dtype == np.float32
    assert np.isin(xf, images).all()


def test_model_accepts_uint8_equals_normalized_float():
    model = DeepCNN()
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(3)
    xu = rng.integers(0, 256, (4, 784), np.uint8)
    xf = xu.astype(np.float32) / 255.0
    lu = model.apply(params, jnp.asarray(xu))
    lf = model.apply(params, jnp.asarray(xf))
    np.testing.assert_allclose(np.asarray(lu), np.asarray(lf), rtol=1e-6, atol=1e-6)


def test_train_step_raw_batch_reduces_loss():
    from distributed_tensorflow_tpu.training import adam, create_train_state, make_train_step

    model = DeepCNN()
    opt = adam(2e-3)
    state = create_train_state(model, opt, seed=0)
    step = make_train_step(model, opt, keep_prob=1.0)
    rng = np.random.default_rng(4)
    x = rng.integers(0, 256, (64, 784), np.uint8)
    y = rng.integers(0, 10, 64).astype(np.int32)
    first = None
    for _ in range(40):
        state, m = step(state, (x, y))
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first * 0.5


def test_train_loop_raw_input_flag(tmp_path):
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()
    flags.FLAGS._reset()
    flags.FLAGS._parse([
        f"--logdir={tmp_path}/logs",
        f"--data_dir={tmp_path}/no-data",
        "--training_iter=12",
        "--batch_size=32",
        "--display_step=4",
        "--optimizer=adam",
        "--raw_input=true",
        "--save_model_secs=100000",
    ])
    res = train(flags.FLAGS, mode="sync")
    assert res.final_step == 12
    assert res.train_metrics["loss"] > 0
    flags.FLAGS._reset()
