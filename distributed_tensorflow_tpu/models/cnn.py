"""The reference's deep CNN, rebuilt as a pure-JAX functional model.

Architecture parity with ``conv_net`` (``/root/reference/.idea/MNISTDist.py:66-90``)
and its parameter dicts (``:117-141``):

    reshape [B,784] -> [B,28,28,1]
    conv 5x5x1x32  + bias + relu -> maxpool 2x2  -> [B,14,14,32]
    conv 5x5x32x64 + bias + relu -> maxpool 2x2  -> [B,7,7,64]
    flatten 3136 -> dense 1024 + relu -> dropout -> dense 10 logits

≈3.27 M parameters (wd1 = 3136x1024 dominates). Init parity with
``weight_variable``/``bias_variable`` (``MNISTDist.py:42-49``): truncated
normal σ=0.1, biases constant 0.1.

The model is a pytree-of-arrays + pure ``apply`` — no layers/objects — so it
jits, shards, vmaps and grads like any JAX function. Params keep the
reference's exact names (wc1, wc2, wd1, out / bc1, bc2, bd1, out) so
checkpoints are self-describing against the reference.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.registry import register_model
from distributed_tensorflow_tpu.ops import nn


def truncated_normal_init(key, shape, stddev=0.1, dtype=jnp.float32):
    """TF ``tf.truncated_normal`` parity (MNISTDist.py:43): normal truncated
    to ±2σ. jax.random.truncated_normal samples the truncated distribution
    directly (TF redraws, same distribution)."""
    return stddev * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)


def constant_init(shape, value=0.1, dtype=jnp.float32):
    """TF ``bias_variable`` parity (MNISTDist.py:47-49)."""
    return jnp.full(shape, value, dtype)


@register_model("deep_cnn")
class DeepCNN:
    """2×conv + 2×dense MNIST classifier (the reference's only model).

    Generalised just enough for the Fashion-MNIST drop-in (identical graph)
    and other square grayscale inputs: image_size and num_classes are
    parameters with reference defaults (MNISTDist.py:33-39).
    """

    def __init__(
        self,
        image_size: int = 28,
        channels: int = 1,
        num_classes: int = 10,
        hidden_units: int = 1024,
        compute_dtype: Any = None,
        use_pallas: bool = False,
    ):
        self.image_size = image_size
        self.channels = channels
        self.num_classes = num_classes
        self.hidden_units = hidden_units
        self.compute_dtype = compute_dtype
        self.use_pallas = use_pallas
        # two 2x2 stride-2 SAME pools => ceil(size/4)
        self.pooled = math.ceil(math.ceil(image_size / 2) / 2)
        self.flat_dim = self.pooled * self.pooled * 64

    def init(self, key, dtype=jnp.float32):
        """Parameter pytree with the reference's names/shapes (MNISTDist.py:117-141)."""
        ks = jax.random.split(key, 4)
        weights = {
            "wc1": truncated_normal_init(ks[0], (5, 5, self.channels, 32), dtype=dtype),
            "wc2": truncated_normal_init(ks[1], (5, 5, 32, 64), dtype=dtype),
            "wd1": truncated_normal_init(ks[2], (self.flat_dim, self.hidden_units), dtype=dtype),
            "out": truncated_normal_init(ks[3], (self.hidden_units, self.num_classes), dtype=dtype),
        }
        biases = {
            "bc1": constant_init((32,), dtype=dtype),
            "bc2": constant_init((64,), dtype=dtype),
            "bd1": constant_init((self.hidden_units,), dtype=dtype),
            "out": constant_init((self.num_classes,), dtype=dtype),
        }
        return {"weights": weights, "biases": biases}

    def apply(self, params, x, *, keep_prob=1.0, rng=None, train: bool = False):
        """Forward pass -> logits (reference ``conv_net``, MNISTDist.py:66-90).

        ``keep_prob`` mirrors the reference's dropout placeholder
        (MNISTDist.py:115). Note the reference *disables* dropout by feeding
        1.0 during training (MNISTDist.py:179, a known defect); here dropout
        is actually applied when ``train=True`` and an rng is given.
        """
        w, b = params["weights"], params["biases"]
        cd = self.compute_dtype
        x = nn.normalize_if_u8(x, cd)
        x = x.reshape(-1, self.image_size, self.image_size, self.channels)

        x = nn.conv2d(x, w["wc1"], b["bc1"], compute_dtype=cd)
        x = nn.maxpool2d(x, k=2)
        x = nn.conv2d(x, w["wc2"], b["bc2"], compute_dtype=cd)
        x = nn.maxpool2d(x, k=2)

        x = x.reshape(-1, self.flat_dim)
        if self.use_pallas:
            # fused matmul+bias+relu Pallas kernel on the dominant FC layer
            from distributed_tensorflow_tpu.ops import pallas_ops

            interpret = jax.default_backend() == "cpu"
            if cd is not None:
                x = pallas_ops.fused_dense_relu(
                    x.astype(cd), w["wd1"].astype(cd), b["bd1"].astype(cd),
                    interpret,
                ).astype(jnp.float32)
            else:
                x = pallas_ops.fused_dense_relu(x, w["wd1"], b["bd1"], interpret)
        else:
            x = jax.nn.relu(nn.dense(x, w["wd1"], b["bd1"], compute_dtype=cd))
        x = nn.dropout(x, keep_prob, rng, deterministic=not train)
        logits = nn.dense(x, w["out"], b["out"], compute_dtype=cd)
        return logits

    def num_params(self, params=None):
        if params is None:
            params = jax.eval_shape(lambda: self.init(jax.random.key(0)))
        return sum(int(jnp.size(p)) for p in jax.tree.leaves(params))
