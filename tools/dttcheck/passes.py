"""The four dttcheck passes — each one turns a jaxpr-level fact into a
named finding (rules DTC001-DTC004; DTC000 is reserved for a scenario
that fails to build or trace, which is itself a finding: a step the
verifier cannot even trace is a step nobody has proven anything about).

DTC001 ledger-proof        every ``comm_ledger`` row corresponds to
                           collectives actually present in the traced
                           computation and the summed wire bytes match
                           EXACTLY (both directions: an unpriced
                           collective is a finding, a phantom row is a
                           finding)
DTC002 spmd-deadlock       ``lax.cond``/``switch`` branches carry
                           identical collective signatures; collective
                           axis names exist on the mesh the function
                           is lowered for; no collective hides inside
                           a ``while`` (unbounded trip count)
DTC003 donation-audit      every donated input buffer has a same-
                           shape/dtype output to alias (the jaxpr's
                           actual aliasing opportunity) — the runtime
                           complement of dttlint's AST-level DTT008
DTC004 replication-drift   every leaf the ParallelismPlan declares
                           sharded is actually split by the lowered
                           shard_map (and vice versa) — a leaf whose
                           jaxpr shape shows full replication while
                           the plan claims a shard is silent HBM waste
                           and a wrong memory budget

Finding keys are stable (scenario name + symbol, never line numbers);
paths point at the module that owns the violated fact (the mode's
``parallel/`` module for ledger rows, the builder for the rest).
"""

from __future__ import annotations

from collections import Counter

from tools._analysis_common import Finding
from tools.dttcheck.inventory import Inventory

#: ledger row "collective" name prefix -> inventory family
ROW_FAMILY = {
    "all_reduce": "psum", "psum": "psum", "pmean": "psum",
    "psum_scatter": "reduce_scatter", "reduce_scatter": "reduce_scatter",
    "all_gather": "all_gather", "ppermute": "ppermute",
    "all_to_all": "all_to_all",
    "pull": "host", "push": "host",
}

#: which parallel/ module owns each mode's row builders (finding paths)
MODE_PATH = {
    "dp": "distributed_tensorflow_tpu/parallel/data_parallel.py",
    "zero1": "distributed_tensorflow_tpu/parallel/zero.py",
    "zero3": "distributed_tensorflow_tpu/parallel/zero.py",
    "pp": "distributed_tensorflow_tpu/parallel/pipeline_parallel.py",
    "tp": "distributed_tensorflow_tpu/parallel/tensor_parallel.py",
    "ep": "distributed_tensorflow_tpu/parallel/expert_parallel.py",
    "sp": "distributed_tensorflow_tpu/parallel/sequence_parallel.py",
    "ps": "distributed_tensorflow_tpu/parallel/ps_emulation.py",
}


def row_family(row: dict) -> str:
    name = row.get("collective", "").split("(", 1)[0].strip()
    return ROW_FAMILY.get(name, name or "?")


def _fmt(n: int) -> str:
    return f"{n:,} B"


def pass_ledger(target, inv: Inventory, ledger: dict) -> list:
    """DTC001: rows <-> traced collectives, byte-exact per
    (family, axis) group. Host-wire rows (the ps topology's pull/push)
    are exempt from jaxpr matching by design — they price TCP + PCI
    traffic the device program never sees — but then the device
    program must be collective-free, which the generic both-direction
    check enforces (a device collective would have no matching row)."""
    out = []
    expected: dict = {}
    for row in ledger.get("rows", ()):
        fam = row_family(row)
        if fam == "host" or row.get("axis") == "host":
            continue
        key = (fam, (row["axis"],))
        expected[key] = expected.get(key, 0) + int(row["bytes"])
    actual = inv.grouped()
    for key in sorted(set(expected) | set(actual)):
        fam, axes = key
        exp, act = expected.get(key, 0), actual.get(key, 0)
        if exp == act:
            continue
        sites = sorted({e.site for e in inv.priced()
                        if (e.family, e.axes) == key})
        rows = [r["collective"] for r in ledger.get("rows", ())
                if (row_family(r), (r.get("axis"),)) == key]
        if exp == 0:
            what = (f"UNPRICED collective: the traced step moves "
                    f"{_fmt(act)} of {fam} over axis {axes[0]!r} "
                    f"(sites: {', '.join(sites) or '?'}) but the "
                    f"comm_ledger has no row for it")
        elif act == 0:
            what = (f"PHANTOM row(s) {rows}: the ledger prices "
                    f"{_fmt(exp)} of {fam} over axis {axes[0]!r} but "
                    f"the traced step contains no such collective")
        else:
            what = (f"ledger drift: rows {rows} price {_fmt(exp)} of "
                    f"{fam} over axis {axes[0]!r}, the traced step "
                    f"moves {_fmt(act)} "
                    f"(sites: {', '.join(sites) or '?'})")
        out.append(Finding(
            "DTC001", f"ledger:{target.name}:{fam}:{axes[0]}",
            MODE_PATH.get(target.mode, "tools/dttcheck"), 0,
            f"[{target.name}] {what}"))
    return out


def pass_deadlock(target, inv: Inventory, ledger: dict | None) -> list:
    """DTC002: the static twin of the r11 watchdog's two documented
    deadlock classes — divergent collective sequences across cond
    branches, and collectives over axis names the lowered mesh does
    not carry (plus the unprovable case: a collective under `while`)."""
    out = []
    path = MODE_PATH.get(target.mode, "tools/dttcheck")
    for site, sigs in inv.cond_mismatches:
        short = [tuple((f, a) for f, a, _ in s) for s in sigs]
        out.append(Finding(
            "DTC002", f"cond:{target.name}:{site}", path, 0,
            f"[{target.name}] divergent cond/switch branches at {site}: "
            f"collective signatures differ across branches "
            f"({short}) — ranks taking different branches rendezvous "
            f"on different collectives and deadlock"))
    for site, axes, env in inv.bad_axes:
        out.append(Finding(
            "DTC002", f"axis:{target.name}:{site}:{','.join(axes)}",
            path, 0,
            f"[{target.name}] collective at {site} names axis(es) "
            f"{axes} not bound by the enclosing mesh {tuple(env)}"))
    for site in inv.unbounded:
        out.append(Finding(
            "DTC002", f"while:{target.name}:{site}", path, 0,
            f"[{target.name}] collective inside a while loop at {site}: "
            f"trip count is not static, wire bytes are unprovable "
            f"(the entry is excluded from the byte proof)"))
    for i, (op, line) in enumerate(getattr(inv, "unparsed", ())):
        out.append(Finding(
            "DTC002", f"unparsed:{target.name}:{op}:{i}", path, 0,
            f"[{target.name}] compiled HLO contains a {op} the "
            f"inventory parser could not read ({line!r}) — its wire "
            f"bytes are uncounted, so nothing about this step is "
            f"proven; extend tools/dttcheck/inventory.hlo_inventory"))
    mesh_axes = (set(target.mesh.axis_names)
                 if target.mesh is not None else set())
    for row in (ledger or {}).get("rows", ()):
        axis = row.get("axis")
        if axis in (None, "host") or row_family(row) == "host":
            continue
        if mesh_axes and axis not in mesh_axes:
            out.append(Finding(
                "DTC002", f"row-axis:{target.name}:{axis}", path, 0,
                f"[{target.name}] ledger row {row.get('collective')!r} "
                f"claims axis {axis!r}, which does not exist on the "
                f"mesh {sorted(mesh_axes)} this step lowers for"))
    return out


def _pjit_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pjit":
            yield eqn


def pass_donation(target, closed) -> list:
    """DTC003: donated inputs verified against the jaxpr's actual
    aliasing opportunity. XLA aliases a donated buffer only to an
    output of identical shape/dtype; a donated invar with no matching
    output is a wasted donation (the buffer dies for nothing), and a
    builder that promises donation but lowers none has silently lost
    the in-place update path."""
    out = []
    path = MODE_PATH.get(target.mode, "tools/dttcheck")
    if not target.donate:
        return out
    donated_any = False
    for eqn in _pjit_eqns(closed.jaxpr):
        donated = eqn.params.get("donated_invars", ())
        if not any(donated):
            continue
        donated_any = True
        outs = Counter((tuple(v.aval.shape), str(v.aval.dtype))
                       for v in eqn.outvars)
        for i, (don, var) in enumerate(zip(donated, eqn.invars)):
            if not don:
                continue
            sig = (tuple(var.aval.shape), str(var.aval.dtype))
            if outs[sig] > 0:
                outs[sig] -= 1
            else:
                out.append(Finding(
                    "DTC003",
                    f"donate:{target.name}:arg{i}:"
                    f"{sig[1]}{list(sig[0])}",
                    path, 0,
                    f"[{target.name}] donated input {i} "
                    f"({sig[1]}{list(sig[0])}) has no same-shape/dtype "
                    f"output to alias — the buffer is freed for "
                    f"nothing (XLA will warn and copy)"))
    if not donated_any:
        out.append(Finding(
            "DTC003", f"donate:{target.name}:none", path, 0,
            f"[{target.name}] the builder promises donation "
            f"(donate=True) but the lowered jaxpr donates no input — "
            f"the in-place state update was silently lost"))
    return out


def pass_replication(target, closed) -> list:
    """DTC004: the declared plan vs the lowered split. For shard_map
    modes the jaxpr records, per input, exactly which dims split over
    which axes (``in_names``); a leaf the plan declares sharded but the
    jaxpr replicates (or vice versa) is layout drift the memory budget
    and checkpoint layouts silently inherit. GSPMD (TP) targets carry
    no plan here — their commitment check is placement-based
    (pass_replication_gspmd)."""
    out = []
    path = MODE_PATH.get(target.mode, "tools/dttcheck")
    if target.plan is None:
        return out
    for eqn in _pjit_eqns(closed.jaxpr):
        inner = eqn.params["jaxpr"].jaxpr
        sm = next((e for e in inner.eqns
                   if e.primitive.name == "shard_map"), None)
        if sm is None:
            continue
        in_names = sm.params.get("in_names", ())
        pos_of = {id(v): j for j, v in enumerate(sm.invars)}
        import jax

        from distributed_tensorflow_tpu.utils.pytree import path_key

        flat_paths = [
            path_key(kp)
            for kp, _ in jax.tree_util.tree_flatten_with_path(
                target.args)[0]]
        for i, expected in enumerate(target.plan):
            if i >= len(inner.invars):
                break
            j = pos_of.get(id(inner.invars[i]))
            if j is None or j >= len(in_names):
                continue  # leaf transformed before entering shard_map
            actual = tuple(
                a for axes in in_names[j].values()
                for a in (axes if isinstance(axes, tuple) else (axes,)))
            leaf = flat_paths[i] if i < len(flat_paths) else f"leaf{i}"
            if set(expected) - set(actual):
                out.append(Finding(
                    "DTC004", f"replication:{target.name}:{leaf}", path,
                    0,
                    f"[{target.name}] plan declares leaf {leaf!r} "
                    f"sharded over {tuple(expected)} but the lowered "
                    f"shard_map replicates it (in_names="
                    f"{dict(in_names[j])}) — a full copy per device "
                    f"where the budget prices a shard"))
            elif set(actual) - set(expected):
                out.append(Finding(
                    "DTC004", f"replication:{target.name}:{leaf}", path,
                    0,
                    f"[{target.name}] plan declares leaf {leaf!r} "
                    f"replicated but the lowered shard_map splits it "
                    f"over {tuple(actual)} — the standard-layout "
                    f"contract (checkpoints, budgets) is broken"))
        break  # one shard_map per step — the repo's builders' shape
    return out


def pass_replication_gspmd(target) -> list:
    """DTC004 for GSPMD targets: every leaf ``tp_param_specs`` declares
    split must be COMMITTED split on the mesh (the partitioner derives
    all collectives from these placements — a silently replicated leaf
    voids the whole sharding story)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_tpu.parallel.tensor_parallel import (
        tp_param_specs,
    )

    from distributed_tensorflow_tpu.utils.pytree import path_key

    out = []
    state = target.args[0]
    specs = tp_param_specs(state.params)
    flat_specs = jax.tree.leaves(specs,
                                 is_leaf=lambda v: isinstance(v, P))
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    declared_split = 0
    for (kp, leaf), spec in zip(flat, flat_specs):
        name = path_key(kp)
        if spec == P():
            continue
        declared_split += 1
        if isinstance(leaf, jax.Array) and leaf.is_fully_replicated:
            out.append(Finding(
                "DTC004", f"replication:{target.name}:{name}",
                MODE_PATH["tp"], 0,
                f"[{target.name}] tp_param_specs declares {name!r} "
                f"split {spec} but the committed placement is fully "
                f"replicated — GSPMD will derive no collective and "
                f"every chip holds the full leaf"))
    if declared_split == 0:
        out.append(Finding(
            "DTC004", f"replication:{target.name}:no-split",
            MODE_PATH["tp"], 0,
            f"[{target.name}] tp_param_specs declares NO split leaf "
            f"for this model — tensor parallelism would shard nothing "
            f"(the has_tp_specs guard class)"))
    return out
