"""serving/router.py (r22) — the health-driven fleet router: p2c
dispatch with id echo, budgeted retries and hedging, the
drain/eject/half-open state machine, rolling reload under the
min-healthy invariant, the HTTP front end + loadgen attribution, and
the chaos contract (a SIGKILLed replica costs zero client requests)."""

import json
import os
import queue as queue_mod
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from distributed_tensorflow_tpu.checkpoint import save_checkpoint
from distributed_tensorflow_tpu.serving import reqtrace
from distributed_tensorflow_tpu.serving.batcher import DynamicBatcher
from distributed_tensorflow_tpu.serving.engine import InferenceEngine
from distributed_tensorflow_tpu.serving.replica import (
    HttpTransport,
    LocalTransport,
    Replica,
    TransportError,
)
from distributed_tensorflow_tpu.serving.router import (
    HealthPoller,
    Router,
    RouterServer,
)
from distributed_tensorflow_tpu.serving.server import (
    InferenceServer,
    InProcessClient,
    make_predict_runner,
)
from distributed_tensorflow_tpu.utils import faults, telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPU_ENV = {**os.environ, "JAX_PLATFORMS": "cpu"}


@pytest.fixture(autouse=True)
def _clean_spine():
    """Quiet spine per test: faults disarmed, tracer ring cleared, the
    process-global request plane saved/restored (the hedge test arms
    it; nothing may leak into neighbors)."""
    faults.reset()
    telemetry.configure(logdir=None, enabled=True)
    telemetry.get_tracer().clear()
    prev = reqtrace.get_plane()
    reqtrace._PLANE = None
    yield
    faults.reset()
    telemetry.configure(logdir=None, enabled=True)
    telemetry.get_tracer().clear()
    reqtrace._PLANE = prev


class _HostModel:
    """Minimal host model (no jit): logits = x @ w + b."""

    @staticmethod
    def apply(params, x):
        return np.asarray(x) @ params["w"] + params["b"]


class _Flaky:
    """Transport wrapper: switchable connect-fail + optional per-call
    delay — the unreachable-replica and slow-replica stand-ins."""

    def __init__(self, inner):
        self.inner = inner
        self.fail = False
        self.delay_s = 0.0

    def get(self, path):
        if self.fail:
            raise TransportError("test: injected connect-fail")
        return self.inner.get(path)

    def post(self, path, obj):
        if self.fail:
            raise TransportError("test: injected connect-fail")
        if self.delay_s:
            time.sleep(self.delay_s)
        return self.inner.post(path, obj)


class _Fleet:
    """N in-process replicas over ONE logdir (checkpoint step 10),
    dispatched through LocalTransport — no sockets unless a test
    starts the servers itself."""

    def __init__(self, tmpdir, n=2, **rep_kw):
        self.dir = str(tmpdir)
        rng = np.random.default_rng(0)
        self.params = {
            "w": rng.standard_normal((64, 16)).astype(np.float32),
            "b": np.zeros(16, np.float32)}
        save_checkpoint(self.dir, {"params": self.params}, 10)
        kw = dict(breaker_fails=2, eject_s=0.2)
        kw.update(rep_kw)
        self.batchers, self.servers, self.replicas = [], [], []
        for i in range(n):
            eng = InferenceEngine(_HostModel(), self.dir, jit=False,
                                  params_template=self.params,
                                  max_batch=8)
            b = DynamicBatcher(make_predict_runner(eng), max_batch=8,
                               max_delay_ms=1.0, queue_depth=64,
                               name=f"router-test-{i}")
            self.batchers.append(b)
            srv = InferenceServer(
                eng, InProcessClient(predict_batcher=b), port=0)
            self.servers.append(srv)
            self.replicas.append(
                Replica(f"r{i}", _Flaky(LocalTransport(srv)), **kw))
        self.payload = {
            "inputs": rng.standard_normal(64).astype(np.float32).tolist()}

    def save(self, step):
        save_checkpoint(self.dir, {"params": self.params}, step)

    def close(self):
        for b in self.batchers:
            b.close(drain=False)
        for s in self.servers:
            if s._thread is not None:  # started: full shutdown
                s.close()
            else:  # never started: shutdown() would wait forever
                s.httpd.server_close()


def _recs():
    return telemetry.last_spans(10 ** 6)


# ------------------------------------------------------------ dispatch


def test_p2c_spread_id_echo_and_served_step(tmp_path):
    reqtrace.configure(enabled=True)  # served_step rides the traces
    f = _Fleet(tmp_path, 2)
    try:
        router = Router(f.replicas, retries=2, backoff_ms=2.0,
                        min_healthy=1, seed=0)
        for i in range(30):
            status, body, name = router.dispatch(
                "/v1/predict", dict(f.payload), request_id=f"req-{i}")
            assert status == 200, body
            assert body["request_id"] == f"req-{i}"  # echo, always
            assert name in ("r0", "r1")
            assert body["served_step"] == 10  # the wire names the params
        spread = [r.snapshot()["dispatches"] for r in f.replicas]
        assert min(spread) > 0, f"p2c starved a replica: {spread}"
        assert router.requests_total == 30
    finally:
        f.close()


def test_retry_absorbs_connect_fail_and_names_the_ejection(tmp_path):
    f = _Fleet(tmp_path, 2)
    try:
        router = Router(f.replicas, retries=2, backoff_ms=1.0,
                        retry_budget_pct=100.0, min_healthy=1, seed=0)
        f.replicas[0].transport.fail = True  # r0 unreachable
        for i in range(8):
            status, _body, name = router.dispatch(
                "/v1/predict", dict(f.payload))
            assert status == 200, "retry must absorb the outage"
            assert name == "r1"
        assert router.retries_total > 0
        recs = _recs()
        assert any(r.get("name") == "route_retry" for r in recs)
        # breaker_fails=2: the outage is NAMED in the span ring
        assert any(r.get("name") == "route_state"
                   and r.get("transition") == "eject"
                   and r.get("replica") == "r0" for r in recs)
        assert f.replicas[0].snapshot()["ejections"] >= 1
    finally:
        f.close()


def test_fault_point_router_dispatch_is_one_retry(tmp_path):
    f = _Fleet(tmp_path, 2)
    try:
        faults.configure("router_dispatch:mode=error:times=1")
        router = Router(f.replicas, retries=2, backoff_ms=1.0,
                        min_healthy=1, seed=0)
        status, body, _name = router.dispatch("/v1/predict",
                                              dict(f.payload))
        assert status == 200, body
        assert router.retries_total == 1  # the injected fail, absorbed
    finally:
        f.close()


def test_retry_budget_denies_past_the_floor(tmp_path):
    f = _Fleet(tmp_path, 2)
    try:
        for rep in f.replicas:
            rep.transport.fail = True  # total outage
        router = Router(f.replicas, retries=10, backoff_ms=0.5,
                        retry_budget_pct=0.0, min_healthy=1, seed=0)
        status, body, name = router.dispatch("/v1/predict",
                                             dict(f.payload))
        assert status == 503 and name is None
        assert body["request_id"]  # even the failure carries the id
        # pct=0: only the burst floor's retries spent, then DENIED —
        # a dead fleet degrades to honest errors, not a retry storm
        from distributed_tensorflow_tpu.serving.router import (
            RETRY_BURST_FLOOR,
        )
        assert router.retries_total == RETRY_BURST_FLOOR
        assert router.retries_denied >= 1
    finally:
        f.close()


# ------------------------------------------------- replica state machine


class _Scripted:
    """Healthz answers from a script; posts always succeed."""

    def __init__(self):
        self.healthz = (200, {"ok": True, "queue_depth": 0})

    def get(self, path):
        if path == "/healthz":
            return self.healthz
        return 200, {}

    def post(self, path, obj):
        return 200, {"ok": True}


def test_drain_on_503_poll_and_undrain_on_recovery():
    rep = Replica("s0", _Scripted(), breaker_fails=2, eject_s=0.1)
    poller = HealthPoller([rep], interval_s=60)  # manual ticks only
    poller.poll_once()
    assert rep.is_healthy()
    rep.transport.healthz = (503, {"ok": False, "reason": "hbm_low"})
    poller.poll_once()
    assert rep.state_name() == "draining"
    assert not rep.dispatchable(time.monotonic())  # no NEW dispatch
    rep.transport.healthz = (200, {"ok": True})
    poller.poll_once()
    assert rep.is_healthy()  # drain is reversible, poll-driven
    names = [(r.get("name"), r.get("transition")) for r in _recs()]
    assert ("route_state", "drain") in names
    assert ("route_state", "undrain") in names


def test_breaker_eject_half_open_probe_and_backoff():
    rep = Replica("d0", _Scripted(), breaker_fails=3, eject_s=0.05)
    for _ in range(3):
        assert rep.begin_dispatch(time.monotonic())
        rep.end_dispatch(False, time.monotonic())
    assert rep.state_name() == "ejected"
    assert not rep.dispatchable(time.monotonic())  # cooldown holds
    time.sleep(0.06)
    now = time.monotonic()
    assert rep.dispatchable(now)  # half-open window opened
    assert rep.begin_dispatch(now)  # claims THE probe slot
    assert not rep.dispatchable(time.monotonic())  # exactly one probe
    rep.end_dispatch(False, time.monotonic())  # probe fails: re-eject
    snap = rep.snapshot()
    assert snap["ejections"] == 2
    assert snap["eject_cooldown_s"] > 0.05 * 1.5  # cooldown doubled
    time.sleep(snap["eject_cooldown_s"] + 0.02)
    assert rep.begin_dispatch(time.monotonic())
    assert rep.end_dispatch(True, time.monotonic()) == "heal"
    assert rep.is_healthy()


def test_poll_connect_fail_feeds_the_breaker():
    rep = Replica("p0", _Scripted(), breaker_fails=2, eject_s=0.1)
    down = _Flaky(rep.transport)
    rep.transport = down
    down.fail = True
    poller = HealthPoller([rep], interval_s=60)
    poller.poll_once()
    poller.poll_once()
    assert rep.state_name() == "ejected"
    assert any(r.get("name") == "route_state"
               and r.get("transition") == "eject"
               and r.get("source") == "poll" for r in _recs())


# -------------------------------------------------------------- hedging


def test_hedge_wins_and_slo_books_exactly_one_outcome(tmp_path):
    plane = reqtrace.configure(enabled=True, slo_p99_ms=60_000.0)
    f = _Fleet(tmp_path, 2)
    try:
        # the FIRST post (the primary, whichever replica it picked) is
        # slow; the hedge's post runs clean — so the hedge wins the
        # race while the primary still completes server-side
        calls = [0]
        lock = threading.Lock()
        real_posts = {r.name: r.transport.post for r in f.replicas}

        def _slow_first(name):
            def post(path, obj):
                with lock:
                    first = calls[0] == 0
                    calls[0] += 1
                if first:
                    time.sleep(0.15)
                return real_posts[name](path, obj)
            return post

        for r in f.replicas:
            r.transport.post = _slow_first(r.name)
        router = Router(f.replicas, retries=1, backoff_ms=1.0,
                        hedge_ms=20.0, hedge_budget_pct=100.0,
                        min_healthy=1, seed=0)
        status, body, _name = router.dispatch(
            "/v1/predict", dict(f.payload), request_id="hedge-1")
        assert status == 200
        assert body["request_id"] == "hedge-1"
        assert router.hedges_total == 1
        assert router.hedge_wins == 1
        # BOTH arms reached an engine with the same id, yet the SLO
        # ledger booked exactly ONE outcome (reqtrace's r22 dedupe)
        assert plane.slo.total == 1
        assert plane.slo_deduped == 1
        assert any(r.get("name") == "route_hedge" for r in _recs())
    finally:
        f.close()


def test_hedge_stays_home_when_primary_already_resolved(tmp_path):
    f = _Fleet(tmp_path, 2)
    try:
        router = Router(f.replicas, retries=1, backoff_ms=1.0,
                        hedge_ms=5_000.0, hedge_budget_pct=100.0,
                        min_healthy=1, seed=0)
        status, _body, _name = router.dispatch("/v1/predict",
                                               dict(f.payload))
        assert status == 200
        assert router.hedges_total == 0  # timer cancelled, no join
    finally:
        f.close()


def test_reqtrace_slo_dedupe_window_books_once_then_expires():
    plane = reqtrace.RequestPlane(slo_p99_ms=60_000.0,
                                  dedupe_window_s=0.05)
    for _ in range(2):  # a hedged/retried pair reusing the id
        tr = plane.begin("dup-1", "predict", np.zeros(4, np.float32))
        plane.finish(tr, "ok")
    assert plane.slo.total == 1  # first finish books...
    assert plane.slo_deduped == 1  # ...the duplicate only counts here
    time.sleep(0.06)  # past the window: the id is a NEW request now
    tr = plane.begin("dup-1", "predict", np.zeros(4, np.float32))
    plane.finish(tr, "ok")
    assert plane.slo.total == 2
    assert plane.slo_deduped == 1


# ------------------------------------------------------- rolling reload


def test_rolling_reload_min_healthy_and_monotonic_served_step(tmp_path):
    reqtrace.configure(enabled=True)  # served_step rides the traces
    f = _Fleet(tmp_path, 3)
    try:
        router = Router(f.replicas, retries=2, backoff_ms=1.0,
                        min_healthy=2, seed=0)
        poller = HealthPoller(f.replicas, interval_s=60)
        poller.poll_once()
        served = {}

        def hit(n):
            for _ in range(n):
                status, body, name = router.dispatch(
                    "/v1/predict", dict(f.payload))
                assert status == 200, body
                served.setdefault(name, []).append(body["served_step"])

        hit(9)
        f.save(20)
        report = router.rolling_reload(poller, timeout_s=30.0)
        assert report["ok"], report
        # the invariant: the fleet NEVER dropped below min_healthy
        assert report["min_healthy_observed"] >= 2
        assert len(report["replicas"]) == 3
        for entry in report["replicas"]:
            assert entry["reloaded"], entry
            assert entry["params_step"] == 20
        hit(9)
        # per-replica: steps only move forward, and every response is
        # whole — one step per batch, never a mixed-step answer
        for name, seq in served.items():
            assert seq == sorted(seq), f"{name} served {seq}"
            assert set(seq) <= {10, 20}
        assert {10, 20} <= {s for seq in served.values() for s in seq}
        # nobody left admin-drained, everyone dispatchable again
        assert router.healthy_count() == 3
        reloads = [r for r in _recs()
                   if r.get("name") == "route_state"
                   and r.get("transition") == "reload"]
        assert {r.get("replica") for r in reloads} == {"r0", "r1", "r2"}
    finally:
        f.close()


# ---------------------------------------------- HTTP front end + loadgen


def test_router_server_loadgen_attribution_and_fleet_table(tmp_path):
    f = _Fleet(tmp_path, 2)
    router = Router(f.replicas, retries=2, backoff_ms=1.0,
                    min_healthy=1, seed=0)
    rs = RouterServer(router, HealthPoller(f.replicas, interval_s=0.05),
                      port=0).start_background()
    try:
        from tools.serve_loadgen import http_request_fn, run_closed_loop

        fn = http_request_fn(rs.address, "predict", input_dim=64)
        rep = run_closed_loop(fn, n_requests=24, concurrency=3)
        assert rep["errors"] == 0 and rep["rejected"] == 0
        assert rep["id_echo_failures"] == 0
        assert rep["ok"] == 24
        # X-DTT-Replica attribution -> the per_replica columns
        per = rep["per_replica"]
        assert per and set(per) <= {"r0", "r1"}
        assert sum(e["ok"] for e in per.values()) == 24
        for e in per.values():
            assert e["p99_ms"] >= e["p50_ms"] >= 0

        t = HttpTransport(rs.address)
        st, hz = t.get("/healthz")
        assert st == 200 and hz["ok"] and hz["healthy"] == 2

        from tools.router_report import load_fleet, render

        table = render(load_fleet(rs.address))
        assert "r0" in table and "r1" in table
        assert "fleet: 2/2 healthy" in table
    finally:
        rs.close()
        f.close()


def test_router_healthz_503_below_min_healthy(tmp_path):
    f = _Fleet(tmp_path, 2)
    router = Router(f.replicas, retries=1, backoff_ms=1.0,
                    min_healthy=2, seed=0)
    rs = RouterServer(router, HealthPoller(f.replicas, interval_s=60),
                      port=0).start_background()
    try:
        f.replicas[1].set_admin_drain(True)  # healthy 1 < floor 2
        st, body = HttpTransport(rs.address).get("/healthz")
        assert st == 503 and not body["ok"]
        assert body["healthy"] == 1 and body["min_healthy"] == 2
    finally:
        rs.close()
        f.close()


def test_loadgen_multi_target_attributes_by_url(tmp_path):
    f = _Fleet(tmp_path, 2)
    try:
        for srv in f.servers:
            srv.start_background()
        from tools.serve_loadgen import multi_target_fn, run_closed_loop

        urls = [srv.address for srv in f.servers]
        fn = multi_target_fn(urls, "predict", input_dim=64)
        rep = run_closed_loop(fn, n_requests=12, concurrency=2)
        assert rep["errors"] == 0 and rep["ok"] == 12
        per = rep["per_replica"]
        assert per and len(per) == 2  # one column per target URL
        assert sum(e["ok"] for e in per.values()) == 12
    finally:
        f.close()


def test_router_report_json_file_and_exit_codes(tmp_path, capsys):
    from tools import router_report

    fleet = {"replicas": [
        {"name": "a:1", "state": "healthy", "dispatches": 30,
         "inflight": 0, "ejections": 0},
        {"name": "b:2", "state": "ejected", "dispatches": 10,
         "inflight": 0, "ejections": 2, "eject_cooldown_s": 1.5},
    ], "healthy": 1, "min_healthy": 1, "requests_total": 40,
        "retries_total": 3, "retries_denied": 0, "hedges_total": 0,
        "hedges_denied": 0, "hedge_wins": 0}
    path = tmp_path / "fleet.json"
    path.write_text(json.dumps(fleet))
    assert router_report.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "a:1" in out and "ejected" in out
    fleet["healthy"] = 0  # below the floor: scriptable exit 1
    path.write_text(json.dumps(fleet))
    assert router_report.main([str(path)]) == 1
    assert router_report.main([str(tmp_path / "missing.json")]) == 2


# ----------------------------------------------------------------- chaos


def _spawn_replica(logdir):
    p = subprocess.Popen(
        [sys.executable, "-u", "-m", "distributed_tensorflow_tpu.serving",
         f"--logdir={logdir}", "--model=mlp", "--dataset=mnist",
         "--serve_port=0", "--serve_reload_secs=0",
         "--serve_max_delay_ms=2"],
        cwd=REPO, env=CPU_ENV, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    lines: queue_mod.Queue = queue_mod.Queue()
    threading.Thread(target=lambda: [lines.put(l) for l in p.stdout],
                     daemon=True).start()
    return p, lines


def _wait_url(p, lines, deadline_s=240):
    seen = []
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if p.poll() is not None:
            break
        try:
            line = lines.get(timeout=5)
        except queue_mod.Empty:
            continue
        seen.append(line)
        m = re.search(r"serving on (http://\S+)", line)
        if m:
            return m.group(1)
    pytest.fail(f"replica never came up: {''.join(seen)[-2000:]}")


@pytest.mark.slow  # chaos: two full serving subprocesses + a SIGKILL
def test_chaos_sigkill_replica_mid_sweep_zero_failed_requests(tmp_path):
    """The r22 acceptance drill: SIGKILL one of two live replicas in
    the middle of a loadgen sweep. The router's retries absorb the
    outage onto the survivor — zero failed client requests, every id
    echo-verified, no SLO fast-burn on the survivor, and the ejection
    NAMED in the span ring and the flight recorder."""
    from distributed_tensorflow_tpu.models.mlp import MLP
    from distributed_tensorflow_tpu.training import create_train_state, sgd
    from tools.serve_loadgen import http_request_fn, run_closed_loop

    logdir = str(tmp_path / "logs")
    state = create_train_state(MLP(), sgd(0.1), seed=0)
    save_checkpoint(logdir, state, 10)

    telemetry.configure(logdir=str(tmp_path), enabled=True)
    procs = []
    rs = None
    try:
        procs = [_spawn_replica(logdir) for _ in range(2)]
        urls = [_wait_url(p, lines) for p, lines in procs]
        replicas = [Replica(f"r{i}", HttpTransport(u),
                            breaker_fails=2, eject_s=0.3)
                    for i, u in enumerate(urls)]
        router = Router(replicas, retries=3, backoff_ms=10.0,
                        retry_budget_pct=100.0, min_healthy=1, seed=0)
        poller = HealthPoller(replicas, interval_s=0.1)
        rs = RouterServer(router, poller, port=0).start_background()

        n_requests = 120
        fn = http_request_fn(rs.address, "predict", input_dim=784)
        holder = []
        sweep = threading.Thread(
            target=lambda: holder.append(
                run_closed_loop(fn, n_requests=n_requests,
                                concurrency=4)),
            daemon=True)
        sweep.start()
        deadline = time.time() + 120
        while router.requests_total < n_requests // 4:
            assert time.time() < deadline, "sweep never progressed"
            time.sleep(0.02)
        procs[1][0].kill()  # SIGKILL, mid-sweep
        sweep.join(timeout=240)
        assert not sweep.is_alive(), "loadgen sweep hung after the kill"

        rep = holder[0]
        assert rep["ok"] == n_requests  # ZERO failed client requests
        assert rep["errors"] == 0
        assert rep["rejected"] == 0
        assert rep["id_echo_failures"] == 0
        # the survivor took the traffic and is not burning its budget
        st, hz = HttpTransport(urls[0]).get("/healthz")
        assert st == 200 and hz["ok"]
        assert not hz.get("slo_fast_burn")
        # the ejection is NAMED: span ring + flight recorder
        deadline = time.time() + 10
        while (replicas[1].state_name() != "ejected"
               and time.time() < deadline):
            poller.poll_once()
            time.sleep(0.05)
        assert replicas[1].state_name() == "ejected"
        assert any(r.get("name") == "route_state"
                   and r.get("transition") == "eject"
                   and r.get("replica") == "r1" for r in _recs())
        fr = telemetry.flight_recorder().dump("router-chaos-test")
        assert fr is not None
        with open(fr) as fh:
            recs = [json.loads(line) for line in fh]
        assert any(r.get("kind") == "router"
                   and r.get("transition") == "eject"
                   and r.get("replica") == "r1" for r in recs)
    finally:
        if rs is not None:
            rs.close()
        for p, _lines in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
