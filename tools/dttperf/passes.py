"""The four dttperf passes. Each returns (findings, report_rows);
the runner in ``__init__`` assembles them into one AnalysisResult.

  DTP000 cell-pricing       a perf cell that fails to compose its
                            prediction is itself a finding (emitted by
                            scenarios.build_matrix — a cell nobody can
                            price is a cell no record can be banded
                            against)
  DTP001 record-conformance every banded measured rate must sit inside
                            the prediction's declared band; the finding
                            key is (record, phase, mode, model), so a
                            NEW out-of-band record is a fresh finding
                            even when an older one is baselined
  DTP002 fact-coverage      every covered bench phase's facts are
                            non-null in every record the phase appears
                            in (null allowed only next to the phase's
                            error key), the phase is wired into BOTH
                            _run_phases and degraded_record, and the
                            step-time model's term->fact closure holds
  DTP003 budget-conformance declared wall-time/overhead budgets are
                            checked against measured values — pinned,
                            live-measured this run, or read from the
                            newest record that carries them
"""

from __future__ import annotations

import ast
import os
import time

from tools._analysis_common import REPO_ROOT, Finding

from tools.dttperf import records as rec_mod


# ------------------------------------------------ DTP001 conformance


def pass_conformance(records: list, hardware="v5lite") -> tuple:
    """Band every measured rate in every record against the predictor's
    ceiling for that rate's (phase, mode, model) identity."""
    from tools.dttperf.model import predict_step_time
    from tools.dttperf.scenarios import flagship_model

    findings: list = []
    rows: list = []
    pred_cache: dict = {}
    for rec in records:
        parsed = rec["parsed"]
        for chk in rec_mod.RATE_CHECKS:
            val = parsed.get(chk["key"])
            if val is None:
                continue  # absent or null: DTP002's beat, not DTP001's
            if "metric" in chk and parsed.get("metric") != chk["metric"]:
                continue
            ident = f"{chk['phase']}:{chk['mode']}:{chk['model']}"
            if chk.get("link_bound"):
                rows.append({"record": rec["stem"], "check": ident,
                             "key": chk["key"], "measured": val,
                             "status": "exempt",
                             "why": chk["link_bound"]})
                continue
            n_chips = int(parsed.get("n_chips") or 1)
            cache_key = (ident, n_chips)
            if cache_key not in pred_cache:
                try:
                    pred_cache[cache_key] = predict_step_time(
                        dict(mode=chk["mode"], data_ways=n_chips),
                        flagship_model(chk["model"]), n_chips,
                        global_batch=chk["per_chip_batch"] * n_chips,
                        hardware=hardware)
                except Exception as e:  # noqa: BLE001
                    findings.append(Finding(
                        "DTP000", f"build:{ident}", "tools/dttperf", 0,
                        f"[{ident}] conformance prediction failed to "
                        f"PRICE: {type(e).__name__}: {e}"))
                    pred_cache[cache_key] = None
            pred = pred_cache[cache_key]
            if pred is None:
                continue
            ceiling = pred["examples_per_sec_per_chip"]
            ratio = float(val) / ceiling if ceiling > 0 else float("inf")
            lo, hi = chk["band"]
            in_band = lo <= ratio <= hi
            rows.append({"record": rec["stem"], "check": ident,
                         "key": chk["key"], "measured": val,
                         "predicted_ceiling": round(ceiling, 1),
                         "ratio": round(ratio, 4),
                         "band": [lo, hi],
                         "status": "in_band" if in_band else "OUT"})
            if not in_band:
                why = ("faster than the analytic roof: accounting bug"
                       if ratio > hi
                       else "a performance regression or a "
                            "mis-declared band")
                findings.append(Finding(
                    "DTP001", f"band:{rec['stem']}:{ident}",
                    rec["path"], 0,
                    f"[{ident}] measured {chk['key']}={val:,.1f} is "
                    f"{ratio:.3f} of the predicted ceiling "
                    f"{ceiling:,.1f} ex/s/chip — outside the declared "
                    f"band [{lo}, {hi}] ({why})"))
    return findings, rows


# ---------------------------------------------- DTP002 fact-coverage


def _bench_tree(bench_path: str):
    with open(bench_path, encoding="utf-8") as f:
        return ast.parse(f.read())


def _called_names(fn_node) -> set:
    out = set()
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute):
                out.add(f.attr)
    return out


def _str_constants(node) -> set:
    """Every string literal in the AST subtree — the fact keys a
    phase can actually emit (dict keys, subscript assignments)."""
    return {sub.value for sub in ast.walk(node)
            if isinstance(sub, ast.Constant)
            and isinstance(sub.value, str)}


def pass_fact_coverage(records: list,
                       bench_path: str | None = None) -> tuple:
    """Three closures: (a) every PHASE_FACTS phase exists in bench.py,
    is wired into BOTH ``_run_phases`` and ``degraded_record``, and
    every fact key it owes appears as a string literal inside that
    phase's OWN body (a mention elsewhere — a comment, another
    phase's dict — does not emit the fact); (b) in every
    record where a phase appears, its facts are non-null unless the
    phase's error key is present; (c) MODEL_CONSUMES — each predictor
    term's measured dual is emitted by a covered phase."""
    bench_path = bench_path or os.path.join(REPO_ROOT, "bench.py")
    findings: list = []
    rows: list = []
    try:
        tree = _bench_tree(bench_path)
    except (OSError, SyntaxError) as e:
        return [Finding("DTP002", "bench:unreadable", "bench.py", 0,
                        f"bench.py cannot be parsed for fact-coverage: "
                        f"{type(e).__name__}: {e}")], rows
    defs = {n.name: n for n in tree.body
            if isinstance(n, ast.FunctionDef)}
    wiring = {name: _called_names(defs[name]) for name in
              ("_run_phases", "degraded_record") if name in defs}

    for phase, spec in sorted(rec_mod.PHASE_FACTS.items()):
        if phase not in defs:
            findings.append(Finding(
                "DTP002", f"phase:{phase}:missing", "bench.py", 0,
                f"PHASE_FACTS covers {phase}() but bench.py defines no "
                f"such phase — the coverage table drifted from the "
                f"tree"))
            continue
        for where, called in wiring.items():
            if phase not in called:
                kind = ("degraded/outage"
                        if where == "degraded_record" else "healthy")
                findings.append(Finding(
                    "DTP002", f"phase:{phase}:unwired:{where}",
                    "bench.py", defs[phase].lineno,
                    f"{phase}() is fact-covered but not invoked from "
                    f"{where}() — its facts would go null in {kind} "
                    f"records, breaking the non-null contract DTP002 "
                    f"enforces"))
        emitted = _str_constants(defs[phase])
        for key in spec["keys"]:
            if key not in emitted:
                findings.append(Finding(
                    "DTP002", f"phase:{phase}:unemitted:{key}",
                    "bench.py", defs[phase].lineno,
                    f"{phase}() owes fact {key!r} but no string "
                    f"literal in its body emits that key — the fact "
                    f"cannot reach any record from the phase that "
                    f"owns it"))

    for rec in records:
        parsed = rec["parsed"]
        for phase, spec in sorted(rec_mod.PHASE_FACTS.items()):
            present = [k for k in spec["keys"] if k in parsed]
            has_err = spec["error_key"] in parsed
            if not present and not has_err:
                continue  # the record predates the phase
            nulls = [k for k in spec["keys"] if parsed.get(k) is None]
            status = "ok"
            if nulls and not has_err:
                status = "VIOLATION"
                for k in nulls:
                    findings.append(Finding(
                        "DTP002", f"facts:{rec['stem']}:{phase}:{k}",
                        rec["path"], 0,
                        f"record {rec['stem']} carries {phase}() facts "
                        f"but {k!r} is "
                        f"{'null' if k in parsed else 'missing'} with "
                        f"no {spec['error_key']!r} — the phase broke "
                        f"the non-null-even-degraded contract "
                        f"silently"))
            elif nulls:
                status = "errored"  # nulls excused by the error key
            rows.append({"record": rec["stem"], "phase": phase,
                         "facts": len(spec["keys"]),
                         "null": len(nulls), "status": status})

    module_emits = _str_constants(tree)
    for term, phase, key in rec_mod.MODEL_CONSUMES:
        if phase is not None:
            spec = rec_mod.PHASE_FACTS.get(phase)
            if spec is None or key not in spec["keys"]:
                findings.append(Finding(
                    "DTP002", f"consumes:{term}:{key}",
                    "tools/dttperf/records.py", 0,
                    f"the step-time model's {term!r} term consumes "
                    f"{key!r} but {phase}() does not emit it under "
                    f"PHASE_FACTS — the prediction would rest on a "
                    f"fact no record carries"))
        elif key not in module_emits:
            findings.append(Finding(
                "DTP002", f"consumes:{term}:{key}", "bench.py", 0,
                f"the step-time model's {term!r} term consumes "
                f"record-level fact {key!r} but bench.py never emits "
                f"it"))
    return findings, rows


# -------------------------------------------------- DTP003 budgets


def load_budgets(path: str | None = None) -> list[dict]:
    import json

    path = path or os.path.join(os.path.dirname(os.path.abspath(
        __file__)), "budgets.json")
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return data.get("budgets", [])


def measure_live() -> dict:
    """The live half of DTP003: wall-clock the analyzers cheap enough
    to run inside this process (dttlint is pure ast, ~2s). dttcheck's
    full trace matrix costs ~10s of subprocess and stays PINNED."""
    out = {}
    t0 = time.perf_counter()
    try:
        from tools.dttlint import run_lint

        run_lint()
        out["live:dttlint"] = time.perf_counter() - t0
    except Exception as e:  # noqa: BLE001 — report as unmeasured
        out["live:dttlint"] = None
        out["live:dttlint:error"] = f"{type(e).__name__}: {e}"
    return out


def pass_budgets(budgets: list, records: list, live: dict) -> tuple:
    """Every declared budget must have a measurement and sit under its
    limit. Measurement sources: ``pinned`` (the checked-in measured
    value — re-pinned whenever the quantity is re-measured),
    ``live:*`` (wall-clocked during THIS run), ``record:<key>`` (the
    newest bench record carrying the key; a key no record carries yet
    is reported, not failed — the fact was born after the last chip
    run)."""
    findings: list = []
    rows: list = []
    for b in budgets:
        name, limit, source = b["name"], float(b["limit"]), b["source"]
        measured = None
        note = ""
        if source == "pinned":
            measured = b.get("measured")
            if measured is None:
                findings.append(Finding(
                    "DTP003", f"budget:{name}:unmeasured",
                    "tools/dttperf/budgets.json", 0,
                    f"budget {name} (limit {limit}) is declared pinned "
                    f"but carries no measured value — an unmeasured "
                    f"budget is an unenforced one"))
        elif source.startswith("live:"):
            measured = live.get(source)
            if measured is None:
                findings.append(Finding(
                    "DTP003", f"budget:{name}:unmeasured",
                    "tools/dttperf/budgets.json", 0,
                    f"budget {name} (limit {limit}) wants live "
                    f"measurement {source!r} but none was taken: "
                    f"{live.get(source + ':error', 'not measured')}"))
        elif source.startswith("record:"):
            key = source.split(":", 1)[1]
            for rec in reversed(records):
                if rec["parsed"].get(key) is not None:
                    measured = rec["parsed"][key]
                    note = f"from {rec['stem']}"
                    break
            if measured is None:
                note = ("no record carries this yet (born after the "
                        "last chip run)")
        else:
            findings.append(Finding(
                "DTP003", f"budget:{name}:bad-source",
                "tools/dttperf/budgets.json", 0,
                f"budget {name} has unknown measurement source "
                f"{source!r}"))
        if measured is not None and float(measured) > limit:
            findings.append(Finding(
                "DTP003", f"budget:{name}", "tools/dttperf/budgets.json",
                0,
                f"budget {name} BLOWN: measured {float(measured):g} > "
                f"declared limit {limit:g} ({source}"
                f"{', ' + note if note else ''}) — either the "
                f"regression goes or the budget is re-justified"))
        rows.append({"budget": name, "limit": limit,
                     "measured": measured, "source": source,
                     "note": note,
                     "status": ("unmeasured" if measured is None
                                else ("BLOWN" if float(measured) > limit
                                      else "ok"))})
    return findings, rows
