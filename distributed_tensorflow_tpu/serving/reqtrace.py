"""The request plane (r19): end-to-end per-request tracing, tail-latency
attribution, and SLO accounting for serving.

The serving stack until now reported only aggregate histograms: a p99
number with no way to say WHICH requests were slow or WHERE their time
went. This module is the per-request answer — the Dapper-style pattern
vLLM-class serving stacks and SRE practice standardize on:

- **Request ids.** Every request owns a ``request_id`` minted at
  admission (or supplied by the client and echoed back on the wire), so
  a slow request named by ``/metrics`` is findable in the span sink, the
  audit ring, and the client's own logs by ONE string.
- **Phase timelines.** A request's life decomposes into the phases
  ``admit`` (admission bookkeeping), ``queue_wait`` (admitted → taken by
  the batch worker), ``batch_assembly`` (taken → model execution,
  including the stack/unstack glue), ``prefill`` (the forward / prompt
  pass, engine-attributed), ``decode`` (the autoregressive loop, with a
  per-token tick count), and ``respond`` (results → futures). The sum of
  a finished request's phases equals its wall time by construction (the
  execute residual not claimed by prefill/decode folds into
  batch_assembly — that IS the assembly glue's time).
- **Dispositions.** Every request terminates with exactly one of
  ``ok`` / ``rejected_full`` / ``rejected_closed`` / ``rejected_fault``
  / ``expired`` / ``failed`` — rejections and expiries get the same
  audit-ring record and ``req:*`` spans a success does (previously they
  vanished from any per-request story), and the reason rides along.
- **Emission.** At finish, each phase lands as a backdated completed
  ``req:<phase>`` span (plus a ``req:done`` instant with the summary) in
  the EXISTING telemetry spine — the serving replica's
  ``spans-serve-N.jsonl`` sink — and the summary dict joins a bounded
  audit ring. ``tools/req_report.py`` reconstructs waterfalls, exemplar
  tables, and SLO compliance offline from the span file alone.
- **Tail attribution.** Per (route, shape-bucket) streaming histograms
  per phase decompose p50-vs-p99, and the N worst live exemplars
  (request_id + phase breakdown) make "p99 is queue-dominated at bucket
  64" a served fact (the ``tail`` block in ``/metrics``), not a
  log-dive.
- **SLO accounting.** ``--slo_p99_ms`` / ``--slo_target_pct`` drive an
  error-budget ledger with fast/slow burn-rate windows (the
  multiwindow-multi-burn-rate alerting pattern); ``/metrics`` serves a
  ``slo`` block (compliant_pct, budget_remaining, burn rates) and
  ``/healthz`` flips to 503 on a fast-burn breach — joining the
  HBM-headroom drain floor as a router-facing signal.

Import cost: utils/telemetry (stdlib) + utils/metrics'
``StreamingHistogram`` — no jax, so the plane works chip-less (bench's
host-only ``reqtrace_phase`` drives it through the real batcher/engine).
``--telemetry=false`` leaves the plane unconfigured: ids still mint and
echo (the wire contract), but no spans, ring, or ledger.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

from distributed_tensorflow_tpu.utils import telemetry
from distributed_tensorflow_tpu.utils.metrics import StreamingHistogram

PHASES = ("admit", "queue_wait", "batch_assembly", "prefill", "decode",
          "respond")
DISPOSITIONS = ("ok", "rejected_full", "rejected_closed",
                "rejected_fault", "expired", "failed")

RING_DEFAULT = 512
EXEMPLARS_DEFAULT = 5

_SALT = os.urandom(3).hex()
_COUNTER = itertools.count(1)


def new_request_id() -> str:
    """Mint a process-unique request id (``req-<salt>-<n>``): a random
    per-process salt plus a counter — collision-free across replicas
    without coordination, readable in a log line."""
    return f"req-{_SALT}-{next(_COUNTER):06x}"


def pow2_ceil(n: int) -> int:
    """Smallest power of two >= ``n`` (n >= 1) — THE rounding rule for
    batch/shape buckets; ``batcher.pow2_bucket`` wraps it with the
    batch cap, so tail-attribution bucket keys and the engine's
    compiled-shape cache can never round differently."""
    b = 1
    while b < n:
        b <<= 1
    return b


def shape_bucket(payload) -> int:
    """The tail-attribution shape key: the power-of-two bucket of the
    payload's leading dimension (a generate request's prompt length,
    a predict request's example length) — the same rounding the
    engine's executable cache uses, so "slow at bucket 64" names a
    compiled shape, not a raw size."""
    try:
        n = len(payload)
    except TypeError:
        return 0
    return pow2_ceil(n) if n >= 1 else 1


class RequestTrace:
    """One request's in-flight timeline: monotonic marks set by the
    batcher as the request moves through its life, phase durations noted
    by the engine/decoder mid-execution. Cheap by construction — a
    handful of perf_counter reads per request; all derived accounting
    happens once, at finish."""

    __slots__ = ("plane", "request_id", "route", "bucket", "wall0", "t0",
                 "t_admitted", "t_taken", "t_run0", "t_run1", "noted",
                 "decode_ticks", "summary", "slot", "iter_admit",
                 "iter_retire", "served_step")

    def __init__(self, plane, request_id: str, route: str, bucket: int):
        self.plane = plane
        self.request_id = request_id
        self.route = route
        self.bucket = bucket
        self.wall0 = time.time()
        self.t0 = time.monotonic()
        self.t_admitted = None
        self.t_taken = None
        self.t_run0 = None
        self.t_run1 = None
        self.noted: dict = {}
        self.decode_ticks = 0
        self.summary = None
        # continuous batching (r21): which batch slot served the
        # request and at which scheduler iterations it entered/left —
        # None under the whole-batch scheduler
        self.slot = None
        self.iter_admit = None
        self.iter_retire = None
        # fleet router (r22): the checkpoint step of the params snapshot
        # that served this request — the per-replica monotonicity fact
        # the rolling-reload test pins
        self.served_step = None

    def admitted(self) -> None:
        self.t_admitted = time.monotonic()

    def taken(self) -> None:
        self.t_taken = time.monotonic()

    def run_start(self) -> None:
        self.t_run0 = time.monotonic()

    def run_end(self) -> None:
        self.t_run1 = time.monotonic()

    def note(self, phase: str, dur_s: float, ticks: int | None = None) \
            -> None:
        """Attribute ``dur_s`` of the current batch execution to
        ``phase`` (prefill/decode — engine-side measurement). Additive:
        a retried prefill accumulates."""
        self.noted[phase] = self.noted.get(phase, 0.0) + float(dur_s)
        if ticks:
            self.decode_ticks += int(ticks)

    def _phases(self, now: float) -> dict:
        """Phase durations (seconds). Exhaustive by construction: every
        monotonic interval of the request's life lands in exactly one
        phase, so the sum equals the wall time."""
        p: dict = {}
        admitted = self.t_admitted
        p["admit"] = (admitted if admitted is not None else now) - self.t0
        if admitted is None:
            return p
        if self.t_taken is not None:
            p["queue_wait"] = self.t_taken - admitted
        elif self.t_run0 is None:
            # never taken (expired in queue / rejected at close): the
            # whole wait is queue time
            p["queue_wait"] = now - admitted
            return p
        run0, run1 = self.t_run0, self.t_run1
        if run0 is None:
            return p
        assembly = run0 - self.t_taken
        exec_end = run1 if run1 is not None else now
        noted_sum = 0.0
        for phase in ("prefill", "decode"):
            if phase in self.noted:
                p[phase] = self.noted[phase]
                noted_sum += self.noted[phase]
        # the execute residual the engine didn't claim (np.stack /
        # unstack glue, runner overhead) is assembly-and-response glue;
        # folding it here keeps sum(phases) == wall exactly
        p["batch_assembly"] = assembly + max(
            (exec_end - run0) - noted_sum, 0.0)
        if run1 is not None:
            p["respond"] = now - run1
        return p


class SLOLedger:
    """Error-budget accounting over a latency SLO: a request is
    COMPLIANT when it completed ok within ``p99_ms``; ``target_pct`` of
    requests are promised compliant, and the remainder is the error
    budget. Burn rate = (observed non-compliance rate) / (budgeted
    rate), measured over a fast and a slow window (the SRE
    multiwindow-multi-burn-rate pattern: the fast window catches an
    outage in minutes, the slow window a simmering regression).
    ``fast_burn_breach`` — the /healthz 503 condition — requires both
    the threshold and a minimum window population, so one slow request
    on an idle replica cannot drain it."""

    FAST_WINDOW_S = 60.0
    SLOW_WINDOW_S = 600.0
    FAST_BURN_THRESHOLD = 14.0  # the SRE-book page-now multiple
    MIN_WINDOW_COUNT = 10

    def __init__(self, p99_ms: float, target_pct: float = 99.0):
        if p99_ms <= 0:
            raise ValueError(f"slo p99_ms must be > 0, got {p99_ms}")
        if not (50.0 < target_pct <= 100.0):
            raise ValueError(f"slo target_pct must be in (50, 100], "
                             f"got {target_pct}")
        self.p99_ms = float(p99_ms)
        self.target_pct = float(target_pct)
        self._allowed = max(1.0 - self.target_pct / 100.0, 1e-9)
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=65536)  # (mono_t, compliant)
        self.total = 0
        self.bad = 0

    def observe(self, latency_ms: float, ok: bool) -> bool:
        compliant = bool(ok) and float(latency_ms) <= self.p99_ms
        with self._lock:
            self._events.append((time.monotonic(), compliant))
            self.total += 1
            if not compliant:
                self.bad += 1
        return compliant

    def _window_counts(self, now: float, window_s: float) -> tuple:
        total = bad = 0
        for t, good in reversed(self._events):
            if t < now - window_s:
                break
            total += 1
            if not good:
                bad += 1
        return total, bad

    def _burn(self, total: int, bad: int) -> float:
        if not total:
            return 0.0
        return (bad / total) / self._allowed

    def report(self) -> dict:
        now = time.monotonic()
        with self._lock:
            total, bad = self.total, self.bad
            ft, fb = self._window_counts(now, self.FAST_WINDOW_S)
            st, sb = self._window_counts(now, self.SLOW_WINDOW_S)
        compliant_pct = (100.0 * (1.0 - bad / total) if total else 100.0)
        spent = self._burn(total, bad)  # lifetime burn = budget spent
        fast = self._burn(ft, fb)
        return {
            "slo_p99_ms": self.p99_ms,
            "slo_target_pct": self.target_pct,
            "requests": total,
            "compliant_pct": round(compliant_pct, 4),
            "budget_remaining_pct": round(
                max(0.0, 1.0 - spent) * 100.0, 4),
            "burn_rate_fast": round(fast, 4),
            "burn_rate_slow": round(self._burn(st, sb), 4),
            "fast_window_s": self.FAST_WINDOW_S,
            "slow_window_s": self.SLOW_WINDOW_S,
            "fast_burn_threshold": self.FAST_BURN_THRESHOLD,
            "fast_burn_breach": bool(
                ft >= self.MIN_WINDOW_COUNT
                and fast >= self.FAST_BURN_THRESHOLD),
        }

    def fast_burn_breach(self) -> bool:
        now = time.monotonic()
        with self._lock:
            ft, fb = self._window_counts(now, self.FAST_WINDOW_S)
        return (ft >= self.MIN_WINDOW_COUNT
                and self._burn(ft, fb) >= self.FAST_BURN_THRESHOLD)


class RequestPlane:
    """The per-process request-plane state: the bounded audit ring of
    finished request summaries, per-(route, bucket) phase histograms
    for tail attribution, the optional SLO ledger, and the ``req:*``
    span emission into the telemetry spine."""

    SLO_SEEN_CAP = 65536

    def __init__(self, ring: int = RING_DEFAULT,
                 exemplars: int = EXEMPLARS_DEFAULT,
                 slo_p99_ms: float = 0.0,
                 slo_target_pct: float = 99.0,
                 dedupe_window_s: float = 120.0):
        self.audit: deque = deque(maxlen=max(int(ring), 1))
        self.exemplars = max(int(exemplars), 1)
        self.slo = (SLOLedger(slo_p99_ms, slo_target_pct)
                    if slo_p99_ms and slo_p99_ms > 0 else None)
        self._lock = threading.Lock()
        self._hists: dict = {}  # (route, bucket) -> {phase|"total": hist}
        self.requests_total = 0
        self.by_disposition = dict.fromkeys(DISPOSITIONS, 0)
        # r22 bugfix: a client/router retry reuses its request_id, and
        # each attempt's finish() used to book an SLO outcome — a hedged
        # or retried request burned the error budget twice. Terminal
        # dispositions now dedupe by id within a window: the FIRST
        # finish for an id books; later finishes for the same id within
        # ``dedupe_window_s`` count only in ``slo_deduped``. Insertion-
        # ordered dict, evicted from the front by age and a hard cap.
        self.dedupe_window_s = float(dedupe_window_s)
        self._slo_seen: dict = {}  # request_id -> mono_t of first book
        self.slo_deduped = 0

    # ------------------------------------------------------- lifecycle

    def begin(self, request_id: str, route: str, payload) -> RequestTrace:
        return RequestTrace(self, request_id, route,
                            shape_bucket(payload))

    def finish(self, tr: RequestTrace, disposition: str,
               reason: str | None = None) -> dict:
        """Terminate a request's timeline: compute its phases, record
        the audit/tail/SLO accounting, emit its ``req:*`` spans.
        Idempotent — the first disposition wins (a request cannot both
        expire and complete)."""
        if disposition not in DISPOSITIONS:
            raise ValueError(f"unknown disposition {disposition!r}")
        if tr.summary is not None:
            return tr.summary
        now = time.monotonic()
        phases = tr._phases(now)
        total_s = now - tr.t0
        summary = {
            "request_id": tr.request_id,
            "route": tr.route,
            "bucket": tr.bucket,
            "disposition": disposition,
            "reason": reason,
            "total_ms": round(total_s * 1e3, 4),
            "phases_ms": {k: round(v * 1e3, 4)
                          for k, v in phases.items()},
            "decode_ticks": tr.decode_ticks,
            "t_wall": tr.wall0,
        }
        if tr.slot is not None:
            summary["slot"] = tr.slot
            summary["iter_admit"] = tr.iter_admit
            summary["iter_retire"] = tr.iter_retire
        if tr.served_step is not None:
            summary["served_step"] = tr.served_step
        tr.summary = summary
        ok = disposition == "ok"
        with self._lock:
            self.requests_total += 1
            self.by_disposition[disposition] += 1
            self.audit.append(summary)
            hists = self._hists.setdefault((tr.route, tr.bucket), {})
            for name, dur in phases.items():
                h = hists.get(name)
                if h is None:
                    h = hists[name] = StreamingHistogram()
                h.record(dur * 1e3)
            th = hists.get("total")
            if th is None:
                th = hists["total"] = StreamingHistogram()
            th.record(total_s * 1e3)
            first_outcome = self._slo_first_outcome(tr.request_id, now)
        if self.slo is not None and first_outcome:
            self.slo.observe(total_s * 1e3, ok)
        self._emit(tr, summary, phases)
        return summary

    def _slo_first_outcome(self, request_id: str, now: float) -> bool:
        """Under ``self._lock``: True iff this id has NOT booked an SLO
        outcome within the dedupe window (and record that it now has).
        Front-evicts expired/overflow ids — the dict is insertion-
        ordered, so the oldest entries are always first."""
        seen = self._slo_seen
        cutoff = now - self.dedupe_window_s
        while seen:
            rid, t = next(iter(seen.items()))
            if t >= cutoff and len(seen) < self.SLO_SEEN_CAP:
                break
            del seen[rid]
        prior = seen.get(request_id)
        if prior is not None and prior >= cutoff:
            self.slo_deduped += 1
            return False
        seen.pop(request_id, None)  # re-insert at the back if expired
        seen[request_id] = now
        return True

    def _emit(self, tr: RequestTrace, summary: dict,
              phases: dict) -> None:
        """One backdated completed span per phase plus a ``req:done``
        instant carrying the summary — into the telemetry spine's ring
        and JSONL sink (``spans-serve-N.jsonl`` on a replica), so
        ``tools/req_report.py`` reconstructs the whole story offline."""
        tracer = telemetry.get_tracer()
        if not tracer.enabled:
            return
        # phase start offsets on the request's own monotonic clock
        starts = {"admit": 0.0}
        cursor = phases.get("admit", 0.0)
        for phase in ("queue_wait", "batch_assembly", "prefill",
                      "decode", "respond"):
            if phase in phases:
                starts[phase] = cursor
                cursor += phases[phase]
        for phase in PHASES:
            if phase not in phases:
                continue
            attrs = {"request_id": tr.request_id, "route": tr.route,
                     "bucket": tr.bucket,
                     "disposition": summary["disposition"]}
            if phase == "decode" and tr.decode_ticks:
                attrs["ticks"] = tr.decode_ticks
            telemetry.record_span(f"req:{phase}",
                                  ts=tr.wall0 + starts[phase],
                                  dur_s=phases[phase], **attrs)
        # continuous batching (r21): the slot story rides the summary so
        # the offline report can tell which slot served the request and
        # how many scheduler iterations it was resident
        slot_attrs = ({"slot": tr.slot, "iter_admit": tr.iter_admit,
                       "iter_retire": tr.iter_retire}
                      if tr.slot is not None else {})
        tracer.record_instant(
            "req:done", request_id=tr.request_id, route=tr.route,
            bucket=tr.bucket, disposition=summary["disposition"],
            reason=summary["reason"], total_ms=summary["total_ms"],
            decode_ticks=tr.decode_ticks, **slot_attrs,
            **{f"{k}_ms": v for k, v in summary["phases_ms"].items()})

    # --------------------------------------------------------- reports

    def tail_report(self) -> dict:
        """The ``/metrics`` tail block: per route and shape-bucket, the
        p50-vs-p99 decomposition by phase (which phase GREW between the
        median and the tail), plus the worst live exemplars by total
        latency — request_id + phase breakdown, so the slow requests
        are named, not just counted."""
        with self._lock:
            # snapshot the inner dicts too: finish() inserts new phase
            # keys under the lock, and an unlocked items() walk would
            # race it ("dict changed size during iteration" mid-scrape)
            snapshot = {key: dict(hists)
                        for key, hists in self._hists.items()}
            ring = list(self.audit)
            requests_total = self.requests_total
            by_disposition = dict(self.by_disposition)
        routes: dict = {}
        for (route, bucket), hists in sorted(snapshot.items()):
            entry: dict = {"phases": {}}
            p99s = {}
            for name, h in hists.items():
                s = {"p50_ms": round(h.quantile(0.5), 3),
                     "p99_ms": round(h.quantile(0.99), 3),
                     "count": h.count}
                if name == "total":
                    entry["total"] = s
                else:
                    entry["phases"][name] = s
                    p99s[name] = s["p99_ms"]
            entry["p99_dominant_phase"] = (
                max(p99s, key=p99s.get) if p99s else None)
            routes.setdefault(route, {})[str(bucket)] = entry
        worst = sorted(ring, key=lambda s: s["total_ms"],
                       reverse=True)[:self.exemplars]
        exemplars = []
        for s in worst:
            pm = s["phases_ms"]
            exemplars.append({
                "request_id": s["request_id"], "route": s["route"],
                "bucket": s["bucket"], "disposition": s["disposition"],
                "total_ms": s["total_ms"],
                "dominant_phase": (max(pm, key=pm.get) if pm else None),
                "phases_ms": pm,
            })
        return {"routes": routes, "exemplars": exemplars,
                "requests_total": requests_total,
                "by_disposition": by_disposition}

    def audit_snapshot(self) -> list[dict]:
        """One consistent copy of the audit ring — what offline readers
        (bench, req_report via the span files' sibling) iterate while
        batcher/expiry threads keep finishing requests; iterating the
        live deque would race their appends."""
        with self._lock:
            return list(self.audit)

    def slo_report(self) -> dict | None:
        return self.slo.report() if self.slo is not None else None

    def fast_burn_breach(self) -> bool:
        return self.slo is not None and self.slo.fast_burn_breach()


# ------------------------------------------------ batch execution context

_CTX = threading.local()


class batch_context:
    """Bracket one microbatch execution with the traces of the requests
    in it: marks run start/end on every trace, and makes them the
    target of ``note_phase`` calls from the engine/decoder below (which
    cannot see request ids — they see tensors)."""

    def __init__(self, traces):
        self._traces = [t for t in traces if t is not None]

    def __enter__(self):
        _CTX.traces = self._traces
        for t in self._traces:
            t.run_start()
        return self

    def __exit__(self, *exc):
        _CTX.traces = []
        for t in self._traces:
            t.run_end()
        return False


def note_phase(phase: str, dur_s: float, ticks: int | None = None) -> None:
    """Attribute ``dur_s`` of the current microbatch's execution to
    ``phase`` on every request in the batch (each request WAITED that
    long, whatever its share of the math was). No-op outside a
    ``batch_context`` (direct engine calls, tests)."""
    for t in getattr(_CTX, "traces", ()):
        t.note(phase, dur_s, ticks)


def note_served_step(step) -> None:
    """Fleet router (r22): stamp the checkpoint step of the params
    snapshot serving the current microbatch on every request in it.
    The engine reads ``(params, step)`` ONCE per microbatch under its
    swap lock, so every request in a batch shares one step — the
    "never a mixed-step batch" fact the rolling-reload test pins rides
    this stamp into the summary and the wire meta. No-op outside a
    ``batch_context``."""
    if step is None:
        return
    for t in getattr(_CTX, "traces", ()):
        t.served_step = int(step)


def note_slot_admit(tr, iteration: int, slot: int) -> None:
    """Continuous batching (r21): mark the iteration-level admission of
    a request into batch slot ``slot``. Emits a LIVE ``req:slot_admit``
    instant (unlike the backdated phase spans, slot events are visible
    while the request is still decoding) and stamps the trace so the
    finish summary carries the slot story. ``tr`` is the request's
    ``RequestTrace`` or None; the stamp is lock-free by the same
    lifecycle sequencing as ``taken``/``run_start`` (submit hands the
    request to exactly one scheduler thread through the batcher cv)."""
    if tr is not None:
        tr.slot = int(slot)
        tr.iter_admit = int(iteration)
    tracer = telemetry.get_tracer()
    if tr is not None and tracer.enabled:
        tracer.record_instant("req:slot_admit", request_id=tr.request_id,
                              route=tr.route, iteration=int(iteration),
                              slot=int(slot))


def note_slot_retire(tr, iteration: int) -> None:
    """Continuous batching (r21): mark the iteration-level retirement of
    a request from its batch slot (generation complete or the request
    failed mid-flight). Live instant + trace stamp, mirror of
    ``note_slot_admit`` (same lifecycle-sequenced ``tr``)."""
    if tr is not None:
        tr.iter_retire = int(iteration)
    tracer = telemetry.get_tracer()
    if tr is not None and tracer.enabled:
        tracer.record_instant("req:slot_retire", request_id=tr.request_id,
                              route=tr.route, iteration=int(iteration),
                              slot=tr.slot)


def finish(tr: RequestTrace | None, disposition: str,
           reason: str | None = None) -> dict | None:
    """Finish a trace through the plane that began it (None-safe: the
    batcher calls this unconditionally; with the plane unconfigured
    there is no trace)."""
    if tr is None:
        return None
    return tr.plane.finish(tr, disposition, reason)


# --------------------------------------------------------- configuration

_PLANE: RequestPlane | None = None


def get_plane() -> RequestPlane | None:
    return _PLANE


def configure(enabled: bool = True, ring: int = RING_DEFAULT,
              exemplars: int = EXEMPLARS_DEFAULT,
              slo_p99_ms: float = 0.0,
              slo_target_pct: float = 99.0) -> RequestPlane | None:
    """Install (or with ``enabled=False`` remove) the process request
    plane. Returns the new plane (or None). Ids mint and echo
    regardless — the plane gates the accounting, not the wire
    contract."""
    global _PLANE
    _PLANE = (RequestPlane(ring=ring, exemplars=exemplars,
                           slo_p99_ms=slo_p99_ms,
                           slo_target_pct=slo_target_pct)
              if enabled else None)
    return _PLANE


def configure_from_flags(FLAGS) -> RequestPlane | None:
    """The one flag->feature mapping for ``--reqtrace_*`` / ``--slo_*``,
    called by the serving entry point next to
    ``telemetry.configure_from_flags``. The plane rides the telemetry
    spine: ``--telemetry=false`` leaves it unconfigured."""
    return configure(
        enabled=bool(getattr(FLAGS, "telemetry", True)),
        ring=int(getattr(FLAGS, "reqtrace_ring", RING_DEFAULT)
                 or RING_DEFAULT),
        exemplars=int(getattr(FLAGS, "reqtrace_exemplars",
                              EXEMPLARS_DEFAULT) or EXEMPLARS_DEFAULT),
        slo_p99_ms=float(getattr(FLAGS, "slo_p99_ms", 0.0) or 0.0),
        slo_target_pct=float(getattr(FLAGS, "slo_target_pct", 99.0)
                             or 99.0),
    )
