"""Multi-worker async PS measurement: fan-in, cycle scaling, staleness.

The reference's deployment is N workers hammering the ps
(MNISTDist.py:94-95,188); this measures how this build's PS emulation
behaves as worker count grows. Compute runs on CPU (forced — the
object of measurement is the ps fan-in, dedup table, and the mirror
desync/resync protocol under contention, not chip throughput; CPU also
keeps the shared TPU chip clean). Workers are threads, each with its
own PSClient (own sockets + client id), all driving MirrorCycle in the
documented multi-worker degraded mode: every foreign push desyncs the
mirror, forcing a resync pull — the reference's staleness model.

Per N in {1, 2, 4}: aggregate pushes/s, per-worker cycle rate, and the
observed STALENESS distribution (per push: how many foreign pushes
landed since this worker's mirror state — ``new_step - my_step - 1``).
Prints one JSON line per N.

Usage: python tools/ps_multiworker_bench.py [cycles_per_worker]
"""

from __future__ import annotations

import json
import sys
import threading
import time


def main(cycles: int = 60):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from distributed_tensorflow_tpu.data import read_data_sets
    from distributed_tensorflow_tpu.models import get_model
    from distributed_tensorflow_tpu.parallel.ps_emulation import (
        MirrorCycle,
        PSClient,
        PSServer,
        assign_shards,
        flatten_params,
        make_grad_fn,
    )

    ds = read_data_sets("", dataset="mnist")
    model = get_model("mlp", hidden_units=100)
    template = model.init(jax.random.PRNGKey(0))
    flat = flatten_params(template)
    batch = 64

    for n_workers in (1, 2, 4):
        server = PSServer(0, "127.0.0.1:0")
        server.start_background()
        init_client = PSClient([server.address])
        assignment = assign_shards(list(flat), 1)
        init_client.init_params(flat, assignment, optimizer="sgd",
                                learning_rate=0.01,
                                num_workers=n_workers)

        grad_fn = make_grad_fn(model, keep_prob=1.0,
                               devices=jax.devices()[:1])
        results = [None] * n_workers
        barrier = threading.Barrier(n_workers)

        errors: list = []

        def worker(widx: int):
            try:
                client = PSClient([server.address])
                data = ds.train.shard(widx, n_workers)
                cyc = MirrorCycle(client, grad_fn, template, assignment,
                                  learning_rate=0.01, resync_steps=10**9)
                cyc.maybe_sync()
                rng = jax.random.PRNGKey(widx)
                staleness: list[int] = []
                desyncs = 0
                barrier.wait()
                t0 = time.perf_counter()
                for i in range(cycles):
                    before = cyc.step
                    cyc.run_cycle(data.next_batch(batch),
                                  jax.random.fold_in(rng, i))
                    if cyc.step > before:  # a push happened this cycle
                        staleness.append(cyc.step - before - 1)
                    if cyc.needs_resync:
                        desyncs += 1
                        cyc.maybe_sync()
                cyc.drain()
                dt = time.perf_counter() - t0
                client.close()
                results[widx] = {"dt": dt, "staleness": staleness,
                                 "desyncs": desyncs}
            except Exception as e:  # noqa: BLE001 — reported by main
                errors.append((widx, repr(e)))

        try:
            threads = [threading.Thread(target=worker, args=(w,),
                                        daemon=True)
                       for w in range(n_workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors or any(r is None for r in results):
                print(json.dumps({"n_workers": n_workers,
                                  "errors": errors}), flush=True)
                continue

            total = server.dispatch({"op": "get_step"})["global_step"]
            st = np.array(sum((r["staleness"] for r in results), []))
            wall = max(r["dt"] for r in results)
            rec = {
                "n_workers": n_workers,
                "global_step_total": int(total),
                "aggregate_pushes_per_sec": round(total / wall, 2),
                "per_worker_cycles_per_sec": [
                    round(cycles / r["dt"], 2) for r in results],
                "desyncs_total": int(sum(r["desyncs"] for r in results)),
                "staleness_mean": (round(float(st.mean()), 3)
                                   if len(st) else 0),
                "staleness_p95": (int(np.percentile(st, 95))
                                  if len(st) else 0),
                "staleness_max": int(st.max()) if len(st) else 0,
            }
            print(json.dumps(rec), flush=True)
        finally:
            init_client.close()
            server.close()


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 60)
