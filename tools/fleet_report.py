#!/usr/bin/env python
"""Merge a fleet's per-host telemetry into ONE clock-aligned timeline,
with per-step skew histograms and straggler attribution.

Every host writes its own ``spans-<host>.jsonl`` (PR-6 spine); this tool
answers the question none of them can alone: WHICH HOST made the step
slow. Three stages:

1. **Clock alignment.** Each host drops a ``coord_clock`` instant marker
   immediately after the multi-host coordinator's vote allgather
   completes (training/loop._HostCoordinator) — a shared barrier all
   hosts leave within network-jitter of each other. Matching markers by
   boundary id gives per-host wall-clock offsets against the reference
   host (median over all shared boundaries, robust to jittery
   boundaries); every event's timestamp is shifted onto the reference
   clock. Hosts with no shared markers align at offset 0 (single-host
   files still merge).

2. **Per-step / per-boundary skew.** Two attribution sources, same
   semantics (work = time a host spent PRODUCING its step rather than
   waiting in a collective — in synchronous training every host's wall
   time per step is equal by construction, so work is the only column
   that differs):

   - each ``coord_clock`` marker carries its host's mean work-per-step
     since the previous vote (``work_us`` — StepTimer.cumulative_work's
     host_wait + dispatch, the exact numerator behind the live
     ``step_skew_s``/``straggler_host`` scalars in metrics.jsonl).
     This is the PRIMARY source: a slow input pipeline's lost time
     hides in host_wait, which no per-step span covers.
   - per step, the summed duration of a host's step-dispatch spans
     (train_step / device_chunk / pp_step / pp_chunk / zero_step /
     zero_chunk) — the fallback when no vote markers exist (span files
     from single-host runs, hand-rolled harnesses).

3. **Attribution.** Per-host straggler counts (boundary-based when vote
   markers carry work, else span-based); the report's
   ``straggler_host`` is the host that was slowest most often (None
   when under 2 hosts). A skew histogram (p50/p90/max) says whether
   that host is chronically slow or one bad step.

Usage:
    python tools/fleet_report.py LOGDIR                # all spans-*.jsonl
    python tools/fleet_report.py spans-a.jsonl spans-b.jsonl
    python tools/fleet_report.py LOGDIR --chrome fleet.json
    python tools/fleet_report.py LOGDIR --json        # machine-readable

stdlib-only beyond utils/telemetry (via tools/trace_view's loaders) —
run it anywhere the JSONL files land, no jax, no chip.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from statistics import median as _median

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from tools.trace_view import (  # noqa: E402
    fleet_chrome_trace,
    load_records,
)

# the per-step dispatch spans (one per training step or scan chunk);
# their summed duration is a host's work time for the step
STEP_SPANS = ("train_step", "device_chunk", "pp_step", "pp_chunk",
              "zero_step", "zero_chunk")
CLOCK_SPAN = "coord_clock"


def discover_span_files(target: str) -> list[str]:
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, "spans-*.jsonl")))
    return [target] if os.path.exists(target) else []


def clock_offsets(by_host: dict[str, list[dict]],
                  reference: str | None = None) -> dict[str, float]:
    """Per-host wall-clock offset (seconds to SUBTRACT from a host's
    timestamps to land on the reference host's clock), from matching
    ``coord_clock`` boundary markers. Hosts sharing no boundary with
    the reference get 0.0."""
    marks: dict[str, dict[int, float]] = {}
    for host, recs in by_host.items():
        marks[host] = {}
        for r in recs:
            if r.get("name") == CLOCK_SPAN and "boundary" in r:
                # last marker per boundary wins (re-votes overwrite)
                marks[host][int(r["boundary"])] = float(r.get("ts", 0.0))
    hosts = sorted(by_host)
    if reference is None:
        # prefer worker-0/chief-looking names, else the first
        reference = next((h for h in hosts if h.endswith("-0")), hosts[0])
    ref_marks = marks.get(reference, {})
    out = {}
    for host in hosts:
        if host == reference:
            out[host] = 0.0
            continue
        shared = sorted(set(marks[host]) & set(ref_marks))
        if not shared:
            out[host] = 0.0
            continue
        out[host] = _median([marks[host][b] - ref_marks[b]
                             for b in shared])
    return out


def align(by_host: dict[str, list[dict]],
          offsets: dict[str, float]) -> list[dict]:
    """One merged, clock-aligned, time-sorted record list."""
    merged = []
    for host, recs in by_host.items():
        off = offsets.get(host, 0.0)
        for r in recs:
            r = dict(r)
            r["ts"] = float(r.get("ts", 0.0)) - off
            merged.append(r)
    merged.sort(key=lambda r: r["ts"])
    return merged


def step_skews(by_host: dict[str, list[dict]]) -> list[dict]:
    """[{step, skew_s, straggler, work: {host: s}}] for every step seen
    on >= 2 hosts (per-host work = summed step-dispatch span durations
    at that step; chunked loops tag the chunk's START step)."""
    work: dict[int, dict[str, float]] = {}
    for host, recs in by_host.items():
        for r in recs:
            if r.get("name") in STEP_SPANS and isinstance(
                    r.get("step"), int):
                w = work.setdefault(int(r["step"]), {})
                w[host] = w.get(host, 0.0) + float(r.get("dur_s", 0.0))
    out = []
    for step in sorted(work):
        w = work[step]
        if len(w) < 2:
            continue
        hi = max(w, key=w.get)
        out.append({"step": step,
                    "skew_s": max(w.values()) - min(w.values()),
                    "straggler": hi,
                    "work": {h: round(s, 6) for h, s in w.items()}})
    return out


def boundary_skews(by_host: dict[str, list[dict]]) -> list[dict]:
    """[{boundary, step, skew_s, straggler, work_us: {host: us}}] from
    the coord_clock markers' work_us payload (the live vote's numerator
    persisted into the span stream), for boundaries seen on >= 2 hosts
    with nonzero work. Skew here is per-STEP work skew in seconds."""
    marks: dict[int, dict[str, tuple[float, int]]] = {}
    for host, recs in by_host.items():
        for r in recs:
            if r.get("name") == CLOCK_SPAN and "boundary" in r \
                    and "work_us" in r:
                b = int(r["boundary"])
                marks.setdefault(b, {})[host] = (
                    float(r["work_us"]), int(r.get("step", 0)))
    out = []
    for b in sorted(marks):
        w = {h: us for h, (us, _step) in marks[b].items()}
        if len(w) < 2 or max(w.values()) <= 0:
            continue
        hi = max(w, key=w.get)
        out.append({"boundary": b,
                    "step": max(s for _us, s in marks[b].values()),
                    "skew_s": (max(w.values()) - min(w.values())) / 1e6,
                    "straggler": hi,
                    "work_us": {h: int(us) for h, us in w.items()}})
    return out


def load_by_host(paths: list[str]) -> dict[str, list[dict]]:
    """Span files -> {host: records} (one parse; analyze and the chrome
    export share the result)."""
    by_host: dict[str, list[dict]] = {}
    for p in paths:
        recs = load_records(p)
        if recs:
            by_host.setdefault(recs[0].get("host", p), []).extend(recs)
    return by_host


def analyze(paths: list[str],
            by_host: dict[str, list[dict]] | None = None) -> dict:
    """The full fleet report as a dict (the CLI prints it; tests and
    dashboards consume it directly). Attribution prefers the
    boundary/work_us source (``attribution: "vote_work"``), falling
    back to step-span durations (``"step_spans"``)."""
    if by_host is None:
        by_host = load_by_host(paths)
    offsets = clock_offsets(by_host)
    span_skews = step_skews(by_host)
    vote_skews = boundary_skews(by_host)
    chosen = vote_skews if vote_skews else span_skews
    attribution = "vote_work" if vote_skews else "step_spans"
    counts: dict[str, int] = {}
    excess: dict[str, float] = {}  # skew-weighted: µs-level ties on
    for s in chosen:               # healthy steps can't out-vote a real
        counts[s["straggler"]] = counts.get(s["straggler"], 0) + 1
        excess[s["straggler"]] = (excess.get(s["straggler"], 0.0)
                                  + s["skew_s"])
    skew_vals = sorted(s["skew_s"] for s in chosen)

    def pct(q):
        if not skew_vals:
            return None
        return skew_vals[min(len(skew_vals) - 1,
                             int(q * (len(skew_vals) - 1)))]

    hosts = {}
    for host, recs in sorted(by_host.items()):
        steps = [r["step"] for r in recs
                 if r.get("name") in STEP_SPANS
                 and isinstance(r.get("step"), int)]
        # resource plane (r13): each fresh MemoryMeter sample rides the
        # span stream as an hbm_sample instant, and the loop drops one
        # comm_ledger marker at startup — the per-host memory/wire
        # columns come for free from the files already being merged
        hbm_peaks = [int(r["peak"]) for r in recs
                     if r.get("name") == "hbm_sample" and "peak" in r]
        comm = next((r for r in recs if r.get("name") == "comm_ledger"
                     and "comm_bytes_per_step" in r), None)
        # elastic plane (r15): each completed resize drops a `resize`
        # instant carrying its measured downtime (drain+reinit+restore)
        # and a `membership_change` instant at the change itself — the
        # per-host resize accounting comes from the same merged files
        resizes = [float(r["resize_s"]) for r in recs
                   if r.get("name") == "resize" and "resize_s" in r]
        n_changes = sum(1 for r in recs
                        if r.get("name") == "membership_change")
        hosts[host] = {
            "spans": len(recs),
            "steps": len(steps),
            "step_range": [min(steps), max(steps)] if steps else None,
            "work_s": round(sum(float(r.get("dur_s", 0.0)) for r in recs
                                if r.get("name") in STEP_SPANS), 6),
            "clock_offset_s": round(offsets.get(host, 0.0), 6),
            "straggler_steps": counts.get(host, 0),
            "hbm_peak_bytes": max(hbm_peaks) if hbm_peaks else None,
            "comm_bytes_per_step": (int(comm["comm_bytes_per_step"])
                                    if comm is not None else None),
            "resize_s": round(sum(resizes), 4) if resizes else None,
            "membership_changes": n_changes or None,
        }
    straggler = (max(excess, key=excess.get)
                 if excess and len(by_host) > 1 else None)
    return {
        "hosts": hosts,
        "n_hosts": len(by_host),
        "attribution": attribution,
        "steps_compared": len(chosen),
        "skew_p50_s": pct(0.50),
        "skew_p90_s": pct(0.90),
        "skew_max_s": skew_vals[-1] if skew_vals else None,
        "straggler_host": straggler,
        "straggler_share": (round(counts[straggler] / len(chosen), 4)
                            if straggler and chosen else None),
        "per_step": span_skews,
        "per_boundary": vote_skews,
    }


def print_report(report: dict, out=None) -> None:
    out = out if out is not None else sys.stdout
    def _mb(n):
        return f"{n / 2 ** 20:.1f}M" if n is not None else "-"

    print(f"fleet report — {report['n_hosts']} host(s), "
          f"{report['steps_compared']} steps compared", file=out)
    print(f"{'host':<16} {'spans':>7} {'steps':>6} {'work_s':>10} "
          f"{'clock_off_s':>12} {'straggled':>9} {'hbm_peak':>9} "
          f"{'comm/step':>10} {'resize_s':>9}", file=out)
    for host, h in report["hosts"].items():
        rs = h.get("resize_s")
        print(f"{host:<16} {h['spans']:>7} {h['steps']:>6} "
              f"{h['work_s']:>10.3f} {h['clock_offset_s']:>12.6f} "
              f"{h['straggler_steps']:>9} "
              f"{_mb(h.get('hbm_peak_bytes')):>9} "
              f"{_mb(h.get('comm_bytes_per_step')):>10} "
              f"{(f'{rs:.2f}' if rs is not None else '-'):>9}", file=out)
    if report["steps_compared"]:
        print(f"step skew: p50={report['skew_p50_s'] * 1e3:.3f}ms "
              f"p90={report['skew_p90_s'] * 1e3:.3f}ms "
              f"max={report['skew_max_s'] * 1e3:.3f}ms", file=out)
    if report["straggler_host"] is not None:
        print(f"straggler: {report['straggler_host']} (slowest on "
              f"{report['straggler_share']:.0%} of compared steps; "
              f"attribution: {report['attribution']})",
              file=out)
    else:
        print("straggler: n/a (need step spans from >= 2 hosts)",
              file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Clock-aligned fleet timeline + straggler "
                    "attribution from per-host spans-*.jsonl")
    ap.add_argument("targets", nargs="+",
                    help="a logdir (all its spans-*.jsonl) or explicit "
                         "span files")
    ap.add_argument("--chrome", metavar="OUT.json", default=None,
                    help="also write the clock-aligned Chrome trace, "
                         "one track per host")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON instead of text")
    args = ap.parse_args(argv)

    paths = []
    for t in args.targets:
        paths.extend(discover_span_files(t))
    if not paths:
        print(f"no spans-*.jsonl under {args.targets}", file=sys.stderr)
        return 2
    by_host = load_by_host(paths)
    report = analyze(paths, by_host=by_host)
    if args.chrome:
        merged = align(by_host, clock_offsets(by_host))
        with open(args.chrome, "w") as f:
            json.dump(fleet_chrome_trace(merged), f)
        print(f"wrote clock-aligned fleet trace ({len(merged)} events, "
              f"{len(by_host)} host tracks) to {args.chrome}")
    if args.json:
        print(json.dumps(report))
    else:
        print_report(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
