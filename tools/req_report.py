#!/usr/bin/env python
"""Merge serving span files into per-request waterfalls, tail-latency
exemplar tables, and SLO compliance over time — the request plane's
offline report (tools/fleet_report.py's serving sibling).

Every serving replica writes ``spans-serve-N.jsonl`` (PR-6 spine); the
request plane (serving/reqtrace.py) emits each finished request into it
as backdated ``req:<phase>`` spans plus one ``req:done`` instant
carrying the summary (disposition, reason, total, phase breakdown).
This tool reconstructs the whole per-request story from the files alone
— including rejections and deadline expiries, which never produced a
response body anyone kept:

- **Waterfalls.** Requests grouped by ``request_id``; each renders as
  its ordered phase segments (admit / queue_wait / batch_assembly /
  prefill / decode / respond) with offsets — ``--request ID`` shows one
  in detail. Under the continuous scheduler (r21) the live
  ``req:slot_admit`` / ``req:slot_retire`` instants render as point
  marks on the waterfall (slot + scheduler iteration), never as phases.
- **Completeness.** A finished request must have a ``req:done`` record
  and the phase spans its disposition implies (an "ok" without a
  ``respond`` span is a hole in the plane). Incomplete timelines are
  listed and set the exit code.
- **Tail attribution.** Per (route, shape-bucket): p50-vs-p99 by phase
  recomputed offline — the same decomposition the live ``/metrics``
  tail block serves — plus the worst-N exemplar table (request_id,
  disposition, dominant phase, per-phase ms).
- **SLO over time.** With ``--slo_p99_ms``: per-window compliance
  (``--window_s`` buckets on the req:done wall clock), overall
  compliant_pct, and the budget spent against ``--slo_target_pct``.
- **Chrome export.** ``--chrome out.json`` gives every request its own
  named track (one tid per request) — load in chrome://tracing /
  ui.perfetto.dev and read the fleet of waterfalls on one clock.

Exit codes: 0 = every request timeline complete; 1 = incomplete
timelines found; 2 = no request-plane records in the input.

Usage:
    python tools/req_report.py LOGDIR                # all spans-*.jsonl
    python tools/req_report.py spans-serve-0.jsonl [more.jsonl ...]
    python tools/req_report.py LOGDIR --slo_p99_ms 50 --window_s 10
    python tools/req_report.py LOGDIR --json
    python tools/req_report.py LOGDIR --chrome requests.json
    python tools/req_report.py LOGDIR --request req-ab12cd-000007

stdlib-only beyond utils/telemetry (via tools/trace_view's loaders) —
run it anywhere the JSONL files land, no jax, no chip.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from tools.trace_view import load_records  # noqa: E402

PHASE_ORDER = ("admit", "queue_wait", "batch_assembly", "prefill",
               "decode", "respond")

# continuous batching (r21): iteration-level scheduler events — these
# are INSTANTS (slot admission/retirement marks), not phase segments,
# so they join the waterfall as point annotations, never the phase math
MARKS = ("req:slot_admit", "req:slot_retire")

# the phases a disposition's timeline must include to count complete
# (beyond them, what a request has depends on where it died)
REQUIRED_PHASES = {
    "ok": ("admit", "queue_wait", "batch_assembly", "respond"),
    "expired": ("admit", "queue_wait"),
    "failed": ("admit",),
    "rejected_full": ("admit",),
    "rejected_closed": ("admit",),
    "rejected_fault": ("admit",),
}


def discover_span_files(target: str) -> list[str]:
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, "spans-*.jsonl")))
    return [target] if os.path.exists(target) else []


def collect_requests(records: list[dict]) -> dict[str, dict]:
    """Group the req:* records by request_id ->
    {id, route, bucket, disposition, reason, total_ms, decode_ticks,
    t_wall, phases: {name: {dur_ms, ts}}, done: bool}."""
    out: dict[str, dict] = {}
    for r in records:
        name = r.get("name", "")
        if not name.startswith("req:"):
            continue
        rid = r.get("request_id")
        if not rid:
            continue
        req = out.setdefault(rid, {
            "request_id": rid, "route": r.get("route"),
            "bucket": r.get("bucket"), "disposition": None,
            "reason": None, "total_ms": None, "decode_ticks": 0,
            "t_wall": None, "phases": {}, "marks": [], "done": False,
            "slot": None, "iter_admit": None, "iter_retire": None})
        if name == "req:done":
            req["done"] = True
            req["disposition"] = r.get("disposition")
            req["reason"] = r.get("reason")
            req["total_ms"] = r.get("total_ms")
            req["decode_ticks"] = r.get("decode_ticks", 0)
            req["t_wall"] = float(r.get("ts", 0.0))
            req["slot"] = r.get("slot")
            req["iter_admit"] = r.get("iter_admit")
            req["iter_retire"] = r.get("iter_retire")
        elif name in MARKS:
            req["marks"].append({
                "mark": name[len("req:"):],
                "ts": float(r.get("ts", 0.0)),
                "iteration": r.get("iteration"),
                "slot": r.get("slot")})
        else:
            phase = name[len("req:"):]
            req["phases"][phase] = {
                "dur_ms": float(r.get("dur_s", 0.0)) * 1e3,
                "ts": float(r.get("ts", 0.0))}
            if req["t_wall"] is None or float(r.get("ts", 0.0)) \
                    < req["t_wall"]:
                req["t_wall"] = float(r.get("ts", 0.0))
    return out


def incomplete_requests(requests: dict[str, dict]) -> list[dict]:
    """Requests whose timeline cannot be reconstructed: no req:done
    summary, or missing the phase spans their disposition implies."""
    bad = []
    for rid, req in sorted(requests.items()):
        if not req["done"]:
            bad.append({"request_id": rid, "missing": ["req:done"]})
            continue
        need = REQUIRED_PHASES.get(req["disposition"], ("admit",))
        missing = [p for p in need if p not in req["phases"]]
        if missing:
            bad.append({"request_id": rid,
                        "disposition": req["disposition"],
                        "missing": missing})
    return bad


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def tail_attribution(requests: dict[str, dict]) -> dict:
    """Offline recomputation of the /metrics tail block: per (route,
    bucket), p50/p99 total and by phase, with the dominant phase at
    the tail named."""
    groups: dict = {}
    for req in requests.values():
        if req["total_ms"] is None:
            continue
        key = (str(req["route"]), str(req["bucket"]))
        g = groups.setdefault(key, {"total": [], "phases": {}})
        g["total"].append(float(req["total_ms"]))
        for phase, seg in req["phases"].items():
            g["phases"].setdefault(phase, []).append(seg["dur_ms"])
    out: dict = {}
    for (route, bucket), g in sorted(groups.items()):
        totals = sorted(g["total"])
        entry = {"count": len(totals),
                 "total": {"p50_ms": round(_quantile(totals, 0.5), 3),
                           "p99_ms": round(_quantile(totals, 0.99), 3)},
                 "phases": {}}
        p99s = {}
        for phase, vals in g["phases"].items():
            vals = sorted(vals)
            s = {"p50_ms": round(_quantile(vals, 0.5), 3),
                 "p99_ms": round(_quantile(vals, 0.99), 3)}
            entry["phases"][phase] = s
            p99s[phase] = s["p99_ms"]
        entry["p99_dominant_phase"] = (max(p99s, key=p99s.get)
                                       if p99s else None)
        out.setdefault(route, {})[bucket] = entry
    return out


def exemplar_table(requests: dict[str, dict], top: int) -> list[dict]:
    done = [r for r in requests.values() if r["total_ms"] is not None]
    worst = sorted(done, key=lambda r: r["total_ms"], reverse=True)[:top]
    out = []
    for r in worst:
        durs = {p: seg["dur_ms"] for p, seg in r["phases"].items()}
        out.append({
            "request_id": r["request_id"], "route": r["route"],
            "bucket": r["bucket"], "disposition": r["disposition"],
            "reason": r["reason"],
            "total_ms": round(float(r["total_ms"]), 3),
            "dominant_phase": (max(durs, key=durs.get) if durs else None),
            "phases_ms": {p: round(v, 3) for p, v in durs.items()},
        })
    return out


def slo_over_time(requests: dict[str, dict], slo_p99_ms: float,
                  target_pct: float, window_s: float) -> dict | None:
    """Compliance bucketed on the req:done wall clock: per-window
    compliant percentage plus the overall budget story — the offline
    twin of the live ledger (windowed on wall time here; the live
    ledger windows on arrival)."""
    if not slo_p99_ms or slo_p99_ms <= 0:
        return None
    done = [r for r in requests.values()
            if r["done"] and r["t_wall"] is not None]
    if not done:
        return None
    t0 = min(r["t_wall"] for r in done)
    windows: dict[int, list] = {}
    total = bad = 0
    for r in done:
        ok = (r["disposition"] == "ok" and r["total_ms"] is not None
              and float(r["total_ms"]) <= slo_p99_ms)
        total += 1
        bad += not ok
        windows.setdefault(int((r["t_wall"] - t0) / window_s),
                           []).append(ok)
    allowed = max(1.0 - target_pct / 100.0, 1e-9)
    series = [{"window": w, "t_offset_s": round(w * window_s, 3),
               "requests": len(oks),
               "compliant_pct": round(100.0 * sum(oks) / len(oks), 4)}
              for w, oks in sorted(windows.items())]
    return {
        "slo_p99_ms": slo_p99_ms, "slo_target_pct": target_pct,
        "requests": total,
        "compliant_pct": round(100.0 * (1 - bad / total), 4),
        "budget_spent": round((bad / total) / allowed, 4),
        "window_s": window_s,
        "windows": series,
    }


def chrome_trace_per_request(requests: dict[str, dict]) -> dict:
    """Chrome-trace JSON with ONE TRACK PER REQUEST: every request gets
    its own tid (thread_name = request_id), so the exemplars read as
    parallel waterfalls on one clock."""
    events = []
    order = sorted(requests.values(),
                   key=lambda r: r["t_wall"] if r["t_wall"] is not None
                   else 0.0)
    for i, req in enumerate(order):
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": i,
                       "args": {"name": f"{req['request_id']} "
                                        f"[{req['disposition']}]"}})
        for phase, seg in sorted(req["phases"].items(),
                                 key=lambda kv: kv[1]["ts"]):
            events.append({
                "name": f"req:{phase}", "ph": "X", "pid": 1, "tid": i,
                "ts": seg["ts"] * 1e6, "dur": seg["dur_ms"] * 1e3,
                "cat": "reqtrace",
                "args": {"request_id": req["request_id"],
                         "route": req["route"],
                         "bucket": req["bucket"],
                         "disposition": req["disposition"]}})
        for m in req.get("marks", ()):
            events.append({
                "name": f"req:{m['mark']}", "ph": "i", "s": "t",
                "pid": 1, "tid": i, "ts": m["ts"] * 1e6,
                "cat": "reqtrace",
                "args": {"request_id": req["request_id"],
                         "iteration": m["iteration"],
                         "slot": m["slot"]}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def waterfall_lines(req: dict) -> list[str]:
    t0 = min((seg["ts"] for seg in req["phases"].values()),
             default=req["t_wall"] or 0.0)
    head = (f"request {req['request_id']}  route={req['route']} "
            f"bucket={req['bucket']} disposition={req['disposition']}"
            + (f" reason={req['reason']!r}" if req["reason"] else "")
            + (f" total={req['total_ms']:.3f}ms"
               if req["total_ms"] is not None else ""))
    if req.get("slot") is not None:
        # continuous scheduler: which slot + how many iterations resident
        head += (f"  slot={req['slot']} "
                 f"iters={req['iter_admit']}..{req['iter_retire']}")
    lines = [head]
    for phase in PHASE_ORDER:
        seg = req["phases"].get(phase)
        if seg is None:
            continue
        off = (seg["ts"] - t0) * 1e3
        extra = (f"  ticks={req['decode_ticks']}"
                 if phase == "decode" and req["decode_ticks"] else "")
        lines.append(f"  +{off:9.3f}ms  {phase:<15} "
                     f"{seg['dur_ms']:9.3f}ms{extra}")
    for m in sorted(req.get("marks", ()), key=lambda m: m["ts"]):
        # iteration-level marks are instants: offset + annotation, no dur
        off = (m["ts"] - t0) * 1e3
        lines.append(f"  +{off:9.3f}ms  * {m['mark']:<13} "
                     f"iteration={m['iteration']} slot={m['slot']}")
    return lines


def build_report(requests: dict[str, dict], *, top: int,
                 slo_p99_ms: float, slo_target_pct: float,
                 window_s: float) -> dict:
    by_disp: dict[str, int] = {}
    for r in requests.values():
        d = r["disposition"] or "(no req:done)"
        by_disp[d] = by_disp.get(d, 0) + 1
    incomplete = incomplete_requests(requests)
    return {
        "requests_total": len(requests),
        "by_disposition": by_disp,
        "incomplete": incomplete,
        "complete_pct": round(
            100.0 * (1 - len(incomplete) / len(requests)), 4)
        if requests else None,
        "tail": tail_attribution(requests),
        "exemplars": exemplar_table(requests, top),
        "slo": slo_over_time(requests, slo_p99_ms, slo_target_pct,
                             window_s),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("targets", nargs="+",
                    help="a logdir (all spans-*.jsonl) or span files")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--chrome", metavar="OUT",
                    help="write Chrome-trace JSON, one track per request")
    ap.add_argument("--request", metavar="ID",
                    help="show one request's waterfall in detail")
    ap.add_argument("--top", type=int, default=10,
                    help="exemplar-table size (worst by total latency)")
    ap.add_argument("--slo_p99_ms", type=float, default=0.0,
                    help="latency SLO for offline compliance (0 = skip)")
    ap.add_argument("--slo_target_pct", type=float, default=99.0)
    ap.add_argument("--window_s", type=float, default=10.0,
                    help="SLO-over-time window width (seconds)")
    args = ap.parse_args(argv)

    files: list[str] = []
    for t in args.targets:
        files += discover_span_files(t)
    if not files:
        print(f"no span files found under {args.targets}",
              file=sys.stderr)
        return 2
    records: list[dict] = []
    for path in files:
        records += load_records(path)
    requests = collect_requests(records)
    if not requests:
        print(f"no request-plane (req:*) records in {len(files)} span "
              f"file(s) — is the plane configured (--telemetry and "
              f"serving/reqtrace)?", file=sys.stderr)
        return 2

    if args.request:
        req = requests.get(args.request)
        if req is None:
            print(f"request {args.request!r} not found "
                  f"({len(requests)} requests in input)",
                  file=sys.stderr)
            return 2
        print("\n".join(waterfall_lines(req)))
        return 0

    report = build_report(requests, top=args.top,
                          slo_p99_ms=args.slo_p99_ms,
                          slo_target_pct=args.slo_target_pct,
                          window_s=args.window_s)

    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(chrome_trace_per_request(requests), f)
        print(f"wrote {args.chrome} ({len(requests)} request tracks)",
              file=sys.stderr)

    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(f"{report['requests_total']} requests from {len(files)} "
              f"span file(s); by disposition: "
              f"{json.dumps(report['by_disposition'])}")
        if report["incomplete"]:
            print(f"INCOMPLETE timelines: {len(report['incomplete'])}")
            for bad in report["incomplete"][:10]:
                print(f"  {bad['request_id']}: missing "
                      f"{','.join(bad['missing'])}")
        print("\ntail attribution (p50 / p99 ms by phase):")
        for route, buckets in report["tail"].items():
            for bucket, entry in buckets.items():
                dom = entry["p99_dominant_phase"]
                print(f"  {route} @ bucket {bucket}  "
                      f"n={entry['count']}  total "
                      f"{entry['total']['p50_ms']}/"
                      f"{entry['total']['p99_ms']}  "
                      f"p99-dominant: {dom}")
                for phase in PHASE_ORDER:
                    s = entry["phases"].get(phase)
                    if s:
                        print(f"      {phase:<15} {s['p50_ms']:9.3f} / "
                              f"{s['p99_ms']:9.3f}")
        print("\nworst exemplars:")
        for ex in report["exemplars"]:
            print(f"  {ex['request_id']}  {ex['route']}@"
                  f"{ex['bucket']}  {ex['total_ms']:9.3f}ms  "
                  f"[{ex['disposition']}] dominant: "
                  f"{ex['dominant_phase']}")
        if report["slo"]:
            s = report["slo"]
            print(f"\nSLO {s['slo_p99_ms']}ms @ {s['slo_target_pct']}%:"
                  f" compliant {s['compliant_pct']}% over "
                  f"{s['requests']} requests (budget spent "
                  f"{s['budget_spent']}x)")
            for w in s["windows"]:
                print(f"  t+{w['t_offset_s']:8.1f}s  "
                      f"n={w['requests']:<6} "
                      f"compliant {w['compliant_pct']}%")
    return 1 if report["incomplete"] else 0


if __name__ == "__main__":
    sys.exit(main())
