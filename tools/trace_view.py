#!/usr/bin/env python
"""Render telemetry span files as a per-step text timeline, and export
Chrome-trace JSON.

Reads the JSONL the telemetry spine writes — ``<logdir>/spans-<host>.jsonl``
(raw span records) or ``<logdir>/flightrec-<host>.jsonl`` (a crash
postmortem: meta/scalars/note records are carried along, spans render) —
no jax, no framework import beyond utils/telemetry.

Accepts MULTIPLE files: each record is tagged with the host parsed from
its filename (``spans-worker-1.jsonl`` -> ``worker-1``), the timeline
shows the host column, and the Chrome-trace export gives every host its
own named track (one pid per host) — load a whole fleet's span files
and see all hosts on one clock. ``tools/fleet_report.py`` builds on the
same loaders to ALIGN the clocks and attribute stragglers.

    python tools/trace_view.py /tmp/train_logs/spans-worker-0.jsonl
    python tools/trace_view.py /tmp/train_logs/spans-*.jsonl
    python tools/trace_view.py spans.jsonl --last 50
    python tools/trace_view.py spans.jsonl --step 100 200   # step range
    python tools/trace_view.py spans-*.jsonl --chrome trace.json
        # then load trace.json in chrome://tracing or ui.perfetto.dev
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

# sys.path[0] is tools/ when run as a script; the package root is one up
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from distributed_tensorflow_tpu.utils.telemetry import chrome_trace  # noqa: E402

_HOST_RE = re.compile(r"^(?:spans|flightrec)-(.+)\.jsonl$")


def host_from_path(path: str) -> str:
    """``.../spans-worker-1.jsonl`` -> ``worker-1`` (filename convention
    of telemetry.configure); the bare filename stem otherwise."""
    name = os.path.basename(path)
    m = _HOST_RE.match(name)
    if m:
        return m.group(1)
    return os.path.splitext(name)[0]


def load_records(path: str, host: str | None = None) -> list[dict]:
    """Span records from a spans-*.jsonl or flightrec-*.jsonl file.
    Flight-recorder events are enveloped ``{"kind": ..., ...}``; only
    span events carry a timeline, the rest are dropped here (``--raw``
    in a pager shows them). ``host`` tags every record (defaults to the
    filename's host)."""
    host = host_from_path(path) if host is None else host
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            kind = rec.get("kind")
            if kind is None and "name" in rec:  # raw span record
                span = rec
            elif kind == "span":  # flight-recorder envelope
                span = {k: v for k, v in rec.items()
                        if k not in ("kind", "t")}
                if "name" not in span:
                    continue
            else:
                continue
            span.setdefault("host", host)
            out.append(span)
    return out


def load_many(paths: list[str]) -> list[dict]:
    """All files' span records, host-tagged, time-sorted."""
    records: list[dict] = []
    for p in paths:
        records.extend(load_records(p))
    records.sort(key=lambda r: float(r.get("ts", 0.0)))
    return records


def fleet_chrome_trace(records: list[dict]) -> dict:
    """Chrome-trace JSON with ONE TRACK PER HOST: records are bucketed
    by their ``host`` tag, each host gets its own pid plus a
    ``process_name`` metadata event, so a fleet export renders as
    side-by-side per-host lanes instead of one interleaved soup."""
    hosts = sorted({r.get("host", "?") for r in records})
    pid_of = {h: i for i, h in enumerate(hosts)}
    tagged = [dict(r, pid=pid_of.get(r.get("host", "?"), 0))
              for r in records]
    out = chrome_trace(tagged)
    out["traceEvents"] = [
        {"ph": "M", "name": "process_name", "pid": pid_of[h],
         "args": {"name": h}} for h in hosts
    ] + out["traceEvents"]
    return out


def render_timeline(records: list[dict], out=None) -> None:
    """Per-step text timeline: wall-clock offset from the first span,
    duration, host (when several), thread, nesting by depth, step/attr
    tags."""
    out = out if out is not None else sys.stdout
    if not records:
        print("(no span records)", file=out)
        return
    t0 = min(float(r.get("ts", 0.0)) for r in records)
    records = sorted(records, key=lambda r: float(r.get("ts", 0.0)))
    multi_host = len({r.get("host") for r in records}) > 1
    last_step = object()
    core = ("name", "ts", "dur_s", "tid", "thread", "depth", "instant",
            "host")
    for r in records:
        step = r.get("step")
        if step != last_step and step is not None:
            print(f"--- step {step} ---", file=out)
            last_step = step
        off = float(r.get("ts", 0.0)) - t0
        dur = float(r.get("dur_s", 0.0))
        extras = {k: v for k, v in r.items() if k not in core
                  and k != "step"}
        mark = "!" if r.get("instant") else " "
        host_col = (f"<{r.get('host', '?')}> " if multi_host else "")
        print(f"{off:12.6f}s {mark}{dur * 1e3:10.3f}ms "
              f"{host_col}[{r.get('thread', '?')}] "
              f"{'  ' * int(r.get('depth', 0))}{r.get('name', '?')}"
              f"{'  ' + str(extras) if extras else ''}", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Render telemetry span JSONL as a text timeline / "
                    "Chrome trace (multiple spans-*.jsonl = one track "
                    "per host)")
    ap.add_argument("files", nargs="+",
                    help="spans-<host>.jsonl and/or "
                         "flightrec-<host>.jsonl (several = fleet view)")
    ap.add_argument("--last", type=int, default=0,
                    help="only the newest N spans")
    ap.add_argument("--step", type=int, nargs=2, metavar=("LO", "HI"),
                    default=None,
                    help="only spans whose step tag is in [LO, HI]")
    ap.add_argument("--chrome", metavar="OUT.json", default=None,
                    help="write Chrome-trace/Perfetto JSON and exit")
    args = ap.parse_args(argv)

    records = load_many(args.files)
    if args.step is not None:
        lo, hi = args.step
        records = [r for r in records
                   if isinstance(r.get("step"), int) and
                   lo <= r["step"] <= hi]
    if args.last:
        records = records[-args.last:]
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(fleet_chrome_trace(records), f)
        hosts = sorted({r.get("host", "?") for r in records})
        print(f"wrote {len(records)} spans from {len(hosts)} host(s) to "
              f"{args.chrome} (load in chrome://tracing or "
              f"https://ui.perfetto.dev)")
        return 0
    render_timeline(records)
    return 0


if __name__ == "__main__":
    sys.exit(main())
