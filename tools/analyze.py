"""``python -m tools.analyze`` — the one-command static-analysis gate:
dttlint (AST invariants) + dttcheck (jaxpr proofs) + dttsan (host-plane
concurrency) + dttperf (performance contracts), one merged exit code.

The four analyzers prove four layers of the same tree — what the
source SAYS (dttlint, rules DTT001-DTT011), what the compiler LOWERS
(dttcheck, passes DTC001-DTC004), what the host THREADS do (dttsan,
passes SAN001-SAN004), and what the program COSTS in time (dttperf,
passes DTP000-DTP003: predicted step time per canonical cell banded
against the measured bench records) — and they share one suppression
discipline (``tools/_analysis_common``: baseline by stable key,
mandatory reasons, stale entries fail loudly). This runner is the
verify-pipeline entry: exit 0 only when ALL FOUR are clean, ``--json``
merges the four reports into one object keyed by analyzer.

dttcheck needs an 8-device mesh that must exist BEFORE jax initializes;
like bench's jaxprcheck_phase it runs in a subprocess with a forced CPU
mesh, so this command is chip-free end to end. dttperf is chip-free by
construction (pure Python + ``jax.eval_shape``). The acceptance budget
is < 45 s for all four (DTP003 budget ``analyze_umbrella_wall_s``).

Usage: python -m tools.analyze [--json] [--skip dttcheck] ...
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._analysis_common import REPO_ROOT  # noqa: E402

ANALYZERS = ("dttlint", "dttcheck", "dttsan", "dttperf")


def _run_dttlint() -> dict:
    from tools.dttlint import run_lint

    return run_lint().to_json()


def _run_dttsan() -> dict:
    from tools.dttsan import run_san

    return run_san().to_json()


def _run_dttperf() -> dict:
    """In-process like dttlint/dttsan: predictions are pure Python +
    ``jax.eval_shape`` — no mesh, no devices, so no subprocess."""
    from tools.dttperf import run_perf

    return run_perf().to_json()


def _run_dttcheck() -> dict:
    """Subprocess with its own forced 8-device CPU mesh (the bench
    jaxprcheck_phase pattern): this process's jax may already be bound
    to real chips or a 1-device CPU fallback, and the verifier's mesh
    must exist before jax initializes."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dttcheck", "--json"],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env,
        timeout=300)
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"ok": False,
                "error": f"dttcheck subprocess failed (rc={proc.returncode}): "
                         f"{proc.stderr.strip()[-400:]}"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="run dttlint + dttcheck + dttsan + dttperf with "
                    "one merged exit code")
    ap.add_argument("--json", action="store_true",
                    help="emit one merged machine-readable JSON object")
    ap.add_argument("--skip", action="append", default=[],
                    choices=ANALYZERS,
                    help="skip one analyzer (repeatable; bring-up "
                         "ergonomics)")
    args = ap.parse_args(argv)

    runners = {"dttlint": _run_dttlint, "dttcheck": _run_dttcheck,
               "dttsan": _run_dttsan, "dttperf": _run_dttperf}
    merged: dict = {}
    ok = True
    for name in ANALYZERS:
        if name in args.skip:
            continue
        t0 = time.perf_counter()
        try:
            res = runners[name]()
        except Exception as e:  # a crashed analyzer is a failed gate
            res = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        res["time_s"] = round(time.perf_counter() - t0, 3)
        merged[name] = res
        ok = ok and bool(res.get("ok"))
        if not args.json:
            n_find = len(res.get("findings", []))
            n_base = len(res.get("baselined", []))
            n_stale = len(res.get("stale_suppressions", []))
            status = "clean" if res.get("ok") else "FAILED"
            extra = (f" ({res['error'][:120]})" if "error" in res
                     else "")
            print(f"{name:8} {status:7} {n_find} finding(s), {n_base} "
                  f"baselined, {n_stale} stale — {res['time_s']}s"
                  f"{extra}")
    merged["ok"] = ok
    if args.json:
        print(json.dumps(merged))
    else:
        print(f"analyze: {'ALL CLEAN' if ok else 'GATE FAILED'} "
              f"({', '.join(n for n in ANALYZERS if n not in args.skip)})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
