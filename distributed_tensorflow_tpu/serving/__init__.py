"""serving/ — checkpoint-to-traffic inference.

The training stack ends at a verified checkpoint; this package turns one
into answered requests: ``engine`` (restore-with-fallback + placement +
per-bucket jitted apply + hot-reload), ``batcher`` (dynamic microbatch
assembly with deadline-aware admission), ``decode`` (KV-cache
autoregressive decode, bitwise-consistent with full recompute),
``server`` (stdlib JSON-over-HTTP + in-process client), ``reqtrace``
(the request plane: per-request phase timelines, tail attribution, SLO
accounting), ``router``/``replica`` (the fleet front-end: health-driven
power-of-two-choices dispatch with retries, hedging, circuit breaking,
and rolling reload over N replicas). Run it:

    python -m distributed_tensorflow_tpu.serving --logdir /tmp/train_logs
"""

from distributed_tensorflow_tpu.serving import reqtrace
from distributed_tensorflow_tpu.serving.batcher import (
    DynamicBatcher,
    Future,
    RejectedError,
    pow2_bucket,
)
from distributed_tensorflow_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousScheduler,
    EngineSlotBackend,
    HostSlotBackend,
)
from distributed_tensorflow_tpu.serving.kvpage import (
    PageAllocator,
    pages_needed,
)
from distributed_tensorflow_tpu.serving.reqtrace import (
    RequestPlane,
    SLOLedger,
    new_request_id,
)
from distributed_tensorflow_tpu.serving.engine import (
    CheckpointWatcher,
    InferenceEngine,
    NoCheckpointError,
)
from distributed_tensorflow_tpu.serving.replica import (
    HttpTransport,
    LocalTransport,
    Replica,
    ReplicaState,
    TransportError,
)
from distributed_tensorflow_tpu.serving.router import (
    HealthPoller,
    Router,
    RouterServer,
)
from distributed_tensorflow_tpu.serving.server import (
    InferenceServer,
    InProcessClient,
    ServingMetrics,
    generate_group_key,
    make_generate_runner,
    make_predict_runner,
    predict_group_key,
)

__all__ = [
    "CheckpointWatcher",
    "ContinuousBatcher",
    "ContinuousScheduler",
    "DynamicBatcher",
    "EngineSlotBackend",
    "Future",
    "HealthPoller",
    "HostSlotBackend",
    "HttpTransport",
    "InferenceEngine",
    "InferenceServer",
    "InProcessClient",
    "LocalTransport",
    "NoCheckpointError",
    "PageAllocator",
    "RejectedError",
    "Replica",
    "ReplicaState",
    "RequestPlane",
    "Router",
    "RouterServer",
    "SLOLedger",
    "ServingMetrics",
    "TransportError",
    "generate_group_key",
    "make_generate_runner",
    "make_predict_runner",
    "new_request_id",
    "pages_needed",
    "pow2_bucket",
    "predict_group_key",
    "reqtrace",
]
