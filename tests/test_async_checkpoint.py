"""Background (async) checkpointing: donation safety of the device-side
snapshot, latest-wins coalescing, final-save ordering, error propagation,
and the Supervisor integration."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.checkpoint.checkpoint import (
    Checkpointer,
    latest_checkpoint,
    restore_latest,
)
from distributed_tensorflow_tpu.models import DeepCNN
from distributed_tensorflow_tpu.training import create_train_state, sgd
from distributed_tensorflow_tpu.training.supervisor import Supervisor


def _due(ckpt):
    """Make the next maybe_save consider the cadence elapsed."""
    ckpt._last_save = time.time() - 10 * max(1, ckpt.save_model_secs)


def _state(seed=0):
    return create_train_state(DeepCNN(), sgd(0.1), seed=seed)


def test_background_save_writes_and_restores(tmp_path):
    ckpt = Checkpointer(str(tmp_path), save_model_secs=1, background=True)
    state = _state()
    _due(ckpt)
    # background mode promises no path (the write is async and latest-wins)
    assert ckpt.maybe_save(state, 3) is None
    ckpt.wait()
    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 3 and os.path.exists(found[0])
    restored, step = restore_latest(str(tmp_path), _state(seed=1))
    assert step == 3
    np.testing.assert_array_equal(
        np.asarray(restored.params["biases"]["out"]),
        np.asarray(state.params["biases"]["out"]))
    ckpt.close()


def test_background_save_is_donation_safe(tmp_path):
    """The snapshot must survive the state being donated to the next step
    immediately after maybe_save returns — the exact hot-loop pattern."""
    ckpt = Checkpointer(str(tmp_path), save_model_secs=1, background=True)
    state = _state()
    before = np.asarray(state.params["weights"]["wd1"]).copy()

    clobber_d = jax.jit(lambda s: jax.tree.map(
        lambda x: x * 0.0 if x.dtype.kind == "f" else x, s),
        donate_argnums=(0,))

    _due(ckpt)
    ckpt.maybe_save(state, 5)
    state = clobber_d(state)  # donation invalidates the original buffers
    jax.block_until_ready(state.params)
    ckpt.wait()
    restored, step = restore_latest(str(tmp_path), _state(seed=1))
    assert step == 5
    np.testing.assert_array_equal(
        np.asarray(restored.params["weights"]["wd1"]), before)
    ckpt.close()


def test_background_coalesces_latest_wins(tmp_path):
    """Many quick submissions: no unbounded queue; the newest step's
    checkpoint exists and the index points at it after draining."""
    ckpt = Checkpointer(str(tmp_path), save_model_secs=1, background=True)
    for step in range(1, 8):
        _due(ckpt)
        ckpt.maybe_save(_state(), step)
    ckpt.wait()
    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 7
    ckpt.close()


def test_forced_save_drains_pending_first(tmp_path):
    """A background save of an older step must not land in the index after
    the forced (shutdown) save of a newer one."""
    ckpt = Checkpointer(str(tmp_path), save_model_secs=1, background=True)
    _due(ckpt)
    ckpt.maybe_save(_state(), 10)
    ckpt.save(_state(), 20)  # drains, then writes synchronously
    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 20
    ckpt.close()


def test_background_write_failure_is_loud(tmp_path):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("x")  # makedirs inside save_checkpoint will fail
    ckpt = Checkpointer(str(blocker), save_model_secs=1, background=True)
    _due(ckpt)
    ckpt.maybe_save(_state(), 1)
    with pytest.raises(RuntimeError, match="background checkpoint write failed"):
        # wait() drains and surfaces the writer's exception
        ckpt.wait()
    ckpt.close()


def test_supervisor_background_final_checkpoint(tmp_path):
    """Supervisor(background_save=True): cadenced saves run off-thread, the
    managed-exit save is synchronous, and a fresh Supervisor restores it."""
    sv = Supervisor(is_chief=True, logdir=str(tmp_path), save_model_secs=1,
                    background_save=True)
    with sv.managed(_state(), handle_signals=False) as box:
        state = box.state
        state = state._replace(step=jnp.asarray(42, jnp.int32))
        _due(sv.checkpointer)
        sv.maybe_checkpoint(state, 42)
        box.update(state, 42)
    # managed exit: drained + final sync save at step 42
    sv2 = Supervisor(is_chief=True, logdir=str(tmp_path), save_model_secs=1)
    restored, step = sv2.init_or_restore(_state(seed=9))
    assert step == 42 and int(restored.step) == 42
