"""JSON-over-HTTP front end + in-process client for the serving stack.

Stdlib only (``http.server``): the container bakes no web framework, and
the protocol is four routes —

    POST /v1/predict   {"inputs": [...]}  ONE example       -> {"outputs"}
    POST /v1/generate  {"prompt": [ids], "max_new_tokens",
                        "temperature", "seed"}              -> {"tokens"}
    GET  /healthz                                           -> {"ok", "step"}
    GET  /stats                                             -> counters + quantiles

(one example per request BY DESIGN — batching is the server's job,
across requests, not the client's)

Backpressure maps to status codes a load balancer understands: a
``RejectedError`` (queue full / deadline / closed) is 429, bad JSON is
400, anything else 500 — a client is always answered, never hung
(the batcher's contract carried to the wire).

``InProcessClient`` speaks the same request surface directly against the
batcher — the test/bench path, and what ``tools/serve_loadgen.py``
drives when no URL is given.

``ServingMetrics`` is the observability cadence: every ``emit_every``
microbatches the queue depth, p50/p99 latency, throughput, and reload
counters land in the SAME JSONL + TensorBoard sinks training scalars use
(``MetricsLogger``/``utils/events.py``), stepped by batch count.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from distributed_tensorflow_tpu.serving import reqtrace
from distributed_tensorflow_tpu.serving.batcher import (
    DynamicBatcher,
    RejectedError,
)
from distributed_tensorflow_tpu.serving.engine import InferenceEngine


def _result_with_id(fut, wait_s: float):
    """``fut.result`` that stamps the request_id onto a TimeoutError:
    a timed-out request is still running server-side and WILL land in
    the audit ring/span sink — the 504 must carry the id that joins
    the client's log line to that record."""
    try:
        return fut.result(wait_s)
    except TimeoutError as e:
        e.request_id = fut.request_id
        raise


def _future_meta(fut) -> dict:
    """The wire-facing request metadata from a completed Future: the
    echoed request_id always; the phase breakdown and disposition when
    the request plane is configured (serving/reqtrace.py)."""
    meta = {"request_id": fut.request_id}
    if fut.meta is not None:
        meta["disposition"] = fut.meta["disposition"]
        meta["phases_ms"] = fut.meta["phases_ms"]
        meta["total_ms"] = fut.meta["total_ms"]
        meta["bucket"] = fut.meta["bucket"]
        # fleet router (r22): which checkpoint step served the request
        # — the router's rolling-reload test pins per-replica
        # monotonicity of this field
        if "served_step" in fut.meta:
            meta["served_step"] = fut.meta["served_step"]
    return meta


class InProcessClient:
    """Typed request surface over a predict and/or generate batcher —
    the engine-side twin of the HTTP routes. Owns the serving-side
    request policy: the default new-token budget/temperature for
    requests that omit them (``--serve_max_new_tokens`` /
    ``--serve_temperature``) and the budget CAP — a request asking for
    more than ``max_new_tokens_cap`` is rejected loudly (400 on the
    wire) instead of monopolizing the batch worker."""

    def __init__(self, predict_batcher: DynamicBatcher | None = None,
                 generate_batcher=None, *,  # Dynamic- or ContinuousBatcher
                 default_max_new_tokens: int = 16,
                 max_new_tokens_cap: int | None = None,
                 default_temperature: float = 0.0):
        self.predict_batcher = predict_batcher
        self.generate_batcher = generate_batcher
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.max_new_tokens_cap = (None if max_new_tokens_cap is None
                                   else int(max_new_tokens_cap))
        self.default_temperature = float(default_temperature)

    def predict(self, x, timeout_ms: float | None = None,
                wait_s: float = 30.0):
        return self.predict_ex(x, timeout_ms=timeout_ms,
                               wait_s=wait_s)[0]

    def predict_ex(self, x, timeout_ms: float | None = None,
                   wait_s: float = 30.0,
                   request_id: str | None = None):
        """``(outputs, meta)`` — meta carries the echoed request_id and,
        with the request plane configured, the phase breakdown +
        disposition (what the HTTP routes put on the wire)."""
        if self.predict_batcher is None:
            raise ValueError(
                "this server is not configured for predict")
        fut = self.predict_batcher.submit(np.asarray(x),
                                          timeout_ms=timeout_ms,
                                          request_id=request_id)
        out = _result_with_id(fut, wait_s)
        return out, _future_meta(fut)

    def generate(self, prompt, max_new_tokens: int | None = None,
                 temperature: float | None = None,
                 seed: int | None = None,
                 timeout_ms: float | None = None, wait_s: float = 60.0):
        return self.generate_ex(prompt, max_new_tokens=max_new_tokens,
                                temperature=temperature, seed=seed,
                                timeout_ms=timeout_ms,
                                wait_s=wait_s)[0]

    def generate_ex(self, prompt, max_new_tokens: int | None = None,
                    temperature: float | None = None,
                    seed: int | None = None,
                    timeout_ms: float | None = None,
                    wait_s: float = 60.0,
                    request_id: str | None = None):
        """``(tokens, meta)`` — the generate twin of ``predict_ex``."""
        if self.generate_batcher is None:
            raise ValueError(
                "this server's model does not support generate "
                "(token decode serves --model lm only)")
        n = (self.default_max_new_tokens if max_new_tokens is None
             else int(max_new_tokens))
        if self.max_new_tokens_cap is not None \
                and n > self.max_new_tokens_cap:
            raise ValueError(
                f"max_new_tokens={n} exceeds the server cap "
                f"({self.max_new_tokens_cap})")
        t = (self.default_temperature if temperature is None
             else float(temperature))
        fut = self.generate_batcher.submit(
            np.asarray(prompt, dtype=np.int32), timeout_ms=timeout_ms,
            request_id=request_id,
            max_new_tokens=n, temperature=t,
            seed=None if seed is None else int(seed))
        out = _result_with_id(fut, wait_s)
        return out, _future_meta(fut)


def make_predict_runner(engine: InferenceEngine):
    """Batcher runner for the predict route: stack the per-request
    examples, one engine call, unstack."""

    def runner(payloads, opts_list):
        del opts_list
        out = engine.predict(np.stack(payloads))
        return [out[i] for i in range(len(payloads))]

    return runner


def make_generate_runner(engine: InferenceEngine):
    """Batcher runner for the generate route. Requests are grouped by
    (prompt length, max_new_tokens, temperature) — see
    ``generate_group_key`` — so one engine call serves the whole
    microbatch through one compiled bucket."""

    def runner(payloads, opts_list):
        o = opts_list[0]
        out = engine.generate(
            np.stack(payloads),
            max_new_tokens=o.get("max_new_tokens", 16),
            temperature=o.get("temperature", 0.0),
            seed=o.get("seed"))
        return [out["tokens"][i] for i in range(len(payloads))]

    return runner


def generate_group_key(payload, opts):
    """Decode requests batch together only when shape-compatible: same
    prompt length (one prefill bucket) and same decode opts (one loop).

    An explicitly-seeded request gets a UNIQUE group (batches alone):
    sampling draws one noise tensor per batch, so co-batched rows — and
    even the bucket size — would change a seeded request's tokens with
    its batchmates. Solo it reproduces exactly (the engine pads a solo
    row deterministically); the batching loss only hits requests that
    opted into reproducibility."""
    if opts.get("seed") is not None:
        return object()  # equal only to itself
    return (len(payload), opts.get("max_new_tokens", 16),
            opts.get("temperature", 0.0))


def predict_group_key(payload, opts):
    """Predict requests batch together only when their example shapes
    stack — one malformed request must fail alone, not 500 the whole
    microbatch it landed in."""
    del opts
    return np.asarray(payload).shape


class ServingMetrics:
    """Cadenced scalar emission through MetricsLogger — the serving
    counters land next to the training scalars. Installed as the
    batchers' ``on_batch`` hook; also drives the optional profiler-trace
    capture (``--serve_profile_batches``)."""

    def __init__(self, logger, engine: InferenceEngine, *,
                 emit_every: int = 50,
                 profiler=None, name: str = ""):
        self.logger = logger
        self.engine = engine
        self.emit_every = int(emit_every)
        self.profiler = profiler
        # one ServingMetrics per batcher: _t0/_last_count track ONE
        # completed-counter; `name` keys the scalars per route so two
        # batchers sharing a logger don't collide tag-for-tag
        self.prefix = f"serve_{name}_" if name else "serve_"
        self._t0 = time.monotonic()
        self._last_count = 0
        self._calls = 0
        self._lock = threading.Lock()

    def on_batch(self, batcher) -> None:
        if self.profiler is not None:
            self.profiler.on_batch()
        # cadence on OUR call count, not stats.batches: the hook only
        # runs on success, and a failed batch on the modulo boundary
        # would silently skip a whole emission window
        with self._lock:
            self._calls += 1
            calls = self._calls
        # the span-sink flush must NOT depend on scalars being on
        # (--serve_metrics_every=0): without a cadenced flush the
        # tracer's pending buffer grows unbounded in a long-running
        # replica and spans-<host>.jsonl stays empty until shutdown
        flush_every = self.emit_every if self.emit_every > 0 else 50
        if calls % flush_every == 0:
            from distributed_tensorflow_tpu.utils import telemetry

            telemetry.get_tracer().flush()
        if self.emit_every <= 0:  # 0 = scalars off (profiler still runs)
            return
        if calls % self.emit_every:
            return
        stats = batcher.stats.as_dict()
        n = stats["batches"]
        with self._lock:
            dt = time.monotonic() - self._t0
            done = stats["completed"]
            rps = (done - self._last_count) / dt if dt > 0 else 0.0
            self._t0 = time.monotonic()
            self._last_count = done
        p = self.prefix
        reloads = self.engine.counters_snapshot()
        scalars = {
            f"{p}queue_depth": float(stats["queue_depth"]),
            f"{p}throughput_rps": rps,
            f"{p}rejected_full": float(stats["rejected_full"]),
            f"{p}rejected_deadline": float(stats["rejected_deadline"]),
            f"{p}reloads": float(reloads["reloads"]),
            f"{p}reload_failures": float(reloads["reload_failures"]),
        }
        if batcher.latency is not None:
            scalars.update(batcher.latency.summary(f"{p}latency_ms_"))
        # resource plane (r13): the serving cadence emits the same
        # hbm_*/compiles_* family the training loops do (the monitor is
        # stashed on the engine by the serving entry point)
        rm = getattr(self.engine, "resources", None)
        if rm is not None:
            scalars.update({f"{p}{k}": v for k, v in rm.scalars().items()})
        # request plane (r19): the SLO story rides the scalar cadence
        # too, so compliance/burn trend lines land in serve_metrics
        # .jsonl + TB next to the latency quantiles
        plane = reqtrace.get_plane()
        if plane is not None and plane.slo is not None:
            slo = plane.slo.report()
            scalars[f"{p}slo_compliant_pct"] = slo["compliant_pct"]
            scalars[f"{p}slo_budget_remaining_pct"] = \
                slo["budget_remaining_pct"]
            scalars[f"{p}slo_burn_rate_fast"] = slo["burn_rate_fast"]
        if self.logger is not None:
            self.logger.scalars(n, scalars)
            # the serving cadence is this logger's display step: push
            # the buffered tails so a crash keeps the latest window
            self.logger.flush()


class _Handler(BaseHTTPRequestHandler):
    server_version = "dtt-serving/1.0"

    def _send(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # quiet: metrics carry the story
        pass

    def do_GET(self):
        srv: InferenceServer = self.server.serving  # type: ignore[attr-defined]
        if self.path == "/healthz":
            health = srv.healthz()
            self._send(200 if health["ok"] else 503, health)
        elif self.path == "/metrics":
            self._send(200, srv.metrics())
        elif self.path == "/stats":
            self._send(200, srv.stats())
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        srv: InferenceServer = self.server.serving  # type: ignore[attr-defined]
        try:
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            self._send(400, {"error": f"bad JSON: {e}"})
            return
        # client-suppliable request id, echoed on EVERY response shape
        # (success, backpressure, error) so the client's log line and
        # the replica's audit ring/span sink name the same request
        rid = req.get("request_id") if isinstance(req, dict) else None
        try:
            if self.path == "/v1/predict":
                out, meta = srv.client.predict_ex(
                    np.asarray(req["inputs"]),
                    timeout_ms=req.get("timeout_ms"),
                    request_id=rid)
                self._send(200, {"outputs": np.asarray(out).tolist(),
                                 **meta})
            elif self.path == "/v1/generate":
                toks, meta = srv.client.generate_ex(
                    req["prompt"],
                    max_new_tokens=req.get("max_new_tokens"),
                    temperature=req.get("temperature"),
                    seed=req.get("seed"),
                    timeout_ms=req.get("timeout_ms"),
                    request_id=rid)
                self._send(200, {"tokens": np.asarray(toks).tolist(),
                                 **meta})
            elif self.path == "/admin/reload":
                # fleet router (r22): the rolling-reload orchestration
                # asks each drained replica to pick up a newer
                # checkpoint NOW instead of waiting for its watcher
                # tick. Safe under traffic (engine.reload_if_newer is
                # serialized and swaps atomically between microbatches).
                report = srv.engine.reload_if_newer()
                self._send(200, {"reloaded": report is not None,
                                 "report": report,
                                 "params_step": srv.engine.step})
            else:
                self._send(404, {"error": f"no route {self.path}"})
        except RejectedError as e:
            self._send(429, {"error": e.reason, "rejected": True,
                             "request_id": getattr(e, "request_id",
                                                   None) or rid})
        except (KeyError, ValueError) as e:
            self._send(400, {"error": f"{type(e).__name__}: {e}",
                             "request_id": rid})
        except TimeoutError as e:
            # the id matters MOST here: the request is still running
            # server-side and will land in the audit ring/span sink —
            # the client's log line must be joinable to it
            self._send(504, {"error": "request timed out in flight",
                             "request_id": getattr(e, "request_id",
                                                   None) or rid})
        except Exception as e:  # noqa: BLE001 — the wire must answer
            self._send(500, {"error": f"{type(e).__name__}: {e}",
                             "request_id": rid})


class InferenceServer:
    """ThreadingHTTPServer wrapper owning the route -> batcher wiring."""

    def __init__(self, engine: InferenceEngine,
                 client: InProcessClient,
                 host: str = "127.0.0.1", port: int = 8000,
                 resources_monitor=None,
                 hbm_headroom_floor_pct: float = 0.0):
        self.engine = engine
        self.client = client
        # resource plane (r13): the replica's memory meter + compile
        # sentry (utils/resources.ResourceMonitor, usually built by
        # __main__ via monitor_from_flags and also stashed on the
        # engine). --serve_hbm_headroom_pct: /healthz flips to 503
        # below the floor so a router can drain a leaking replica
        # BEFORE the allocator kills it mid-request.
        self.resources = (resources_monitor
                          if resources_monitor is not None
                          else getattr(engine, "resources", None))
        self.hbm_headroom_floor_pct = float(hbm_headroom_floor_pct or 0.0)
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.serving = self  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None
        self._t0 = time.monotonic()
        # replica-health accounting (r12): serving goodput (uptime not
        # spent unhealthy) + per-batcher p99 trend and saturation
        # streaks, integrated lazily at poll time — the fields ROADMAP
        # item 2's front-end router reads to weight replicas. Poll-
        # driven by design: the router's own cadence is the sampler.
        self._health_lock = threading.Lock()
        self._health_last_t = self._t0
        self._health_was_ok = True
        self._down_s = 0.0
        self._p99_prev: dict[str, float] = {}
        self._sat_streak: dict[str, int] = {}

    @property
    def address(self) -> str:
        h, p = self.httpd.server_address[:2]
        return f"http://{h}:{p}"

    def _batchers(self):
        for name in ("predict", "generate"):
            b = getattr(self.client, f"{name}_batcher")
            if b is not None:
                yield name, b

    def _hbm_block(self) -> dict | None:
        """The replica's live memory story for /metrics and /healthz:
        aggregate in_use/peak/headroom plus per-device detail where the
        backend reports it. Rate-limited sampling (``sample_if_stale``)
        so a hot health-poll loop can't turn into a span flood. None
        when no meter is armed (--telemetry=false or no monitor)."""
        from distributed_tensorflow_tpu.utils import resources as _res

        rm = self.resources
        if rm is None or rm.meter is None:
            return None
        s = rm.meter.sample_if_stale(max_age_s=1.0, tag="serve_poll")
        if s is None:
            return None
        per_device = [
            {"device": d["device"],
             "in_use_bytes": d["in_use"],
             "peak_bytes": d["peak"],
             "headroom_pct": _res.headroom_pct(d["in_use"],
                                               d.get("limit", 0))}
            for d in s.get("per_device", ())]
        known = [d["headroom_pct"] for d in per_device
                 if d["headroom_pct"] >= 0]
        agg = _res.headroom_pct(s["in_use"], s.get("limit", 0))
        return {
            "in_use_bytes": int(s["in_use"]),
            "peak_bytes": int(s["peak"]),
            "limit_bytes": int(s.get("limit", 0)),
            "headroom_pct": agg,
            # the drain floor's number: ONE device near its limit must
            # not hide behind idle peers in the aggregate ratio
            "min_device_headroom_pct": min(known) if known else agg,
            "source": s.get("source", "?"),
            "per_device": per_device,
        }

    def _kv_block(self) -> dict | None:
        """Paged KV-cache occupancy (r21): the free-list allocator's
        snapshot from any continuous-mode batcher. None under the
        whole-batch scheduler (dense cache — nothing page-allocated)."""
        for _name, b in self._batchers():
            sched = getattr(b, "scheduler", None)
            if sched is not None:
                return sched.allocator.occupancy()
        return None

    def healthz(self) -> dict:
        """The per-replica health signal a router/load-balancer polls:
        liveness (every configured batcher still has a worker), the
        served params version, the current backpressure headline, and —
        with ``--serve_hbm_headroom_pct`` — the memory-drain floor
        (headroom below it flips ok, so a leaking replica drains before
        the allocator kills it). ``ok: false`` maps to HTTP 503 so an
        upstream health check can act without parsing."""
        closed = [name for name, b in self._batchers() if b.closed]
        depth = sum(b.stats.as_dict()["queue_depth"]
                    for _, b in self._batchers())
        hbm = self._hbm_block()
        # headroom -1 means "backend reports no limit" — unknown never
        # trips the floor (a CPU-mesh replica is not 'leaking'). The
        # floor judges the WORST device, not the aggregate: one chip
        # near its limit must not hide behind idle peers.
        low = bool(hbm is not None and self.hbm_headroom_floor_pct > 0
                   and 0 <= hbm["min_device_headroom_pct"]
                   < self.hbm_headroom_floor_pct)
        # SLO layer (r19): a fast-burn breach of the error budget flips
        # the replica unhealthy — the router drains it like the HBM
        # floor. Unarmed (no --slo_p99_ms, or telemetry off) never
        # trips.
        plane = reqtrace.get_plane()
        slo_burn = bool(plane is not None and plane.fast_burn_breach())
        # paged KV cache (r21): the same drain floor judges the page
        # pool — a replica whose uncommitted pages fall below the floor
        # is about to refuse admissions, so drain it first
        kv = self._kv_block()
        kv_low = bool(kv is not None and self.hbm_headroom_floor_pct > 0
                      and kv["free_pct"] < self.hbm_headroom_floor_pct)
        return {"ok": not closed and not low and not slo_burn
                and not kv_low,
                "step": self.engine.step,
                "params_step": self.engine.step,
                "closed_batchers": closed,
                "queue_depth": depth,
                "hbm_headroom_pct": (hbm["headroom_pct"]
                                     if hbm is not None else None),
                "hbm_low_headroom": low,
                "kv_page_free_pct": (kv["free_pct"] if kv is not None
                                     else None),
                "kv_low_pages": kv_low,
                "slo_fast_burn": slo_burn,
                "uptime_s": round(time.monotonic() - self._t0, 3)}

    def _goodput_uptime_pct(self) -> float:
        """Serving goodput: percent of this replica's uptime NOT spent
        in an unhealthy state (a closed batcher — the /healthz 503
        condition). Integrated lazily: each poll attributes the time
        since the previous poll to the state observed THEN, so a
        replica that went down between polls is billed from the poll
        that last saw it healthy."""
        now = time.monotonic()
        ok_now = not any(b.closed for _, b in self._batchers())
        with self._health_lock:
            dt = max(0.0, now - self._health_last_t)
            if not self._health_was_ok:
                self._down_s += dt
            self._health_last_t = now
            self._health_was_ok = ok_now
            uptime = max(now - self._t0, 1e-9)
            return round(100.0 * (1.0 - min(self._down_s / uptime, 1.0)),
                         4)

    def _health_block(self, name: str, stats: dict, b) -> dict:
        """Per-batcher health trend for the router: current p99 vs the
        previous poll's (rising/flat/falling at +-25%/-20%), and the
        saturation streak (consecutive polls with the queue at its
        limit — one hot poll is a blip, a streak is a shed signal)."""
        p99 = (b.latency.quantile(0.99) if b.latency is not None
               else None)
        saturated = stats["queue_depth"] >= b.queue_depth
        with self._health_lock:
            prev = self._p99_prev.get(name)
            if p99 is not None:
                self._p99_prev[name] = p99
            streak = (self._sat_streak.get(name, 0) + 1) if saturated \
                else 0
            self._sat_streak[name] = streak
        if p99 is None or prev is None or prev <= 0:
            trend = "flat"
        elif p99 > prev * 1.25:
            trend = "rising"
        elif p99 < prev * 0.8:
            trend = "falling"
        else:
            trend = "flat"
        return {
            "p99_ms": p99,
            "p99_prev_ms": prev,
            "p99_trend": trend,
            "saturation_streak": streak,
            "closed": b.closed,
        }

    def metrics(self) -> dict:
        """The full serving-metrics JSON (the ServingMetrics counters +
        histogram summaries, per batcher): admission/rejection/failure
        counters, latency quantiles from one consistent histogram
        snapshot, explicit backpressure state (queue depth vs limit,
        saturation, closed), the params-version/reload story the
        continuous-deployment loop reads (params_step, reload counts,
        last reload wall time and fallback depth), and the r12
        replica-health fields a front-end router consumes:
        ``goodput_uptime_pct`` plus a per-batcher ``health`` block
        (p99 trend between polls, saturation streak)."""
        eng = self.engine
        reloads = eng.counters_snapshot()
        out = {
            "params_step": eng.step,
            "reloads": reloads["reloads"],
            "reload_failures": reloads["reload_failures"],
            "reload_fallbacks": reloads["reload_fallbacks"],
            "last_reload_ms": reloads["last_reload_ms"],
            "last_fallback_depth": reloads["last_fallback_depth"],
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "goodput_uptime_pct": self._goodput_uptime_pct(),
        }
        # resource plane (r13): the replica's memory block + compile
        # counters — what the router reads next to the health trend
        out["hbm"] = self._hbm_block()
        # paged KV cache (r21): page-pool occupancy rides the hbm block
        # — it IS device memory accounting, just allocator-grained (a
        # meterless CPU replica still gets a dict with the kv story)
        kv = self._kv_block()
        if kv is not None:
            out["hbm"] = {**(out["hbm"] or {}), "kv_pages": kv}
        snt = (self.resources.sentry if self.resources is not None
               else None)
        out["compiles_total"] = (float(snt.compiles_total)
                                 if snt is not None else None)
        out["recompiles_total"] = (float(snt.recompiles_total)
                                   if snt is not None else None)
        # request plane (r19): the tail block — p50-vs-p99 decomposed
        # by phase per route and shape-bucket, with the worst live
        # exemplars NAMED (request_id + phase breakdown) — and the SLO
        # ledger (compliant_pct, budget remaining, burn rates). None
        # when the plane is unconfigured (--telemetry=false).
        plane = reqtrace.get_plane()
        out["tail"] = (plane.tail_report() if plane is not None
                       else None)
        out["slo"] = (plane.slo_report() if plane is not None
                      else None)
        for name, b in self._batchers():
            stats = b.stats.as_dict()
            entry = dict(stats)
            if b.latency is not None:
                entry["latency_ms"] = b.latency.summary()
            entry["backpressure"] = {
                "queue_depth": stats["queue_depth"],
                "queue_limit": b.queue_depth,
                "saturated": stats["queue_depth"] >= b.queue_depth,
                "closed": b.closed,
                "rejected_full": stats["rejected_full"],
            }
            entry["health"] = self._health_block(name, stats, b)
            sched = getattr(b, "scheduler", None)
            if sched is not None:
                # continuous mode (r21): iteration-level counters —
                # slot occupancy, tokens/iteration, page ledger
                entry["continuous"] = sched.snapshot()
            out[name] = entry
        return out

    def stats(self) -> dict:
        out = {"engine": self.engine.stats()}
        for name in ("predict_batcher", "generate_batcher"):
            b = getattr(self.client, name)
            if b is not None:
                out[name] = b.stats.as_dict()
                if b.latency is not None:
                    out[name].update(b.latency.summary("latency_ms_"))
        return out

    def start_background(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="serve-http", daemon=True)
        self._thread.start()
        return self

    def serve_forever(self):
        self.httpd.serve_forever()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
