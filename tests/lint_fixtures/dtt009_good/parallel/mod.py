"""DTT009 bad fixture: one traced collective path, one ORPHAN."""
from jax import lax

DATA_AXIS = "data"


def make_traced_step():
    """Referenced by the fixture's tools/dttcheck — covered."""

    def per_shard(x):
        return lax.pmean(_helper_collective(x), DATA_AXIS)

    return per_shard


def _helper_collective(x):
    return lax.all_gather(x, DATA_AXIS, tiled=True)


def orphan_collective_path(x):
    """A new comm path NO dttcheck scenario traces — the finding."""
    return lax.psum(x, DATA_AXIS)
