"""Async PS emulation: sharding policy, protocol, stale-gradient semantics,
multi-worker global-step termination — all in-process on localhost."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.models import DeepCNN
from distributed_tensorflow_tpu.parallel.ps_emulation import (
    PSClient,
    PSServer,
    assign_shards,
    flatten_params,
    make_grad_fn,
    unflatten_params,
)


@pytest.fixture()
def ps_pair():
    servers = [PSServer(i, "127.0.0.1:0") for i in range(2)]
    for s in servers:
        s.start_background()
    client = PSClient([s.address for s in servers])
    yield servers, client
    client.close()
    for s in servers:
        s.close()


def test_assign_shards_round_robin():
    keys = ["b", "a", "d", "c"]
    a = assign_shards(keys, 2)
    # sorted order: a,b,c,d -> 0,1,0,1
    assert a == {"a": 0, "b": 1, "c": 0, "d": 1}


def test_flatten_unflatten_roundtrip():
    model = DeepCNN()
    params = model.init(jax.random.PRNGKey(0))
    flat = flatten_params(params)
    assert "weights/wd1" in flat
    back = unflatten_params(params, flat)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pull_before_init_reports_uninitialized(ps_pair):
    _, client = ps_pair
    r = client.call(0, {"op": "pull"})
    assert r == {"ok": False, "uninitialized": True}


def test_init_pull_push_cycle(ps_pair):
    _, client = ps_pair
    flat = {"a": np.ones(4, np.float32), "b": np.full(3, 2.0, np.float32)}
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment)
    got, step = client.pull_all()
    assert step == 0
    np.testing.assert_allclose(got["a"], 1.0)
    np.testing.assert_allclose(got["b"], 2.0)

    # SGD on the ps: p -= lr*g, global step counted once on ps0
    grads = {"a": np.ones(4, np.float32), "b": np.ones(3, np.float32)}
    new_step = client.push_grads(grads, assignment, lr=0.5)
    assert new_step == 1
    got, _ = client.pull_all()
    np.testing.assert_allclose(got["a"], 0.5)
    np.testing.assert_allclose(got["b"], 1.5)


def test_global_step_counts_total_pushes_across_workers(ps_pair):
    """training_iter bounds TOTAL steps across workers (MNISTDist.py:173)."""
    servers, client = ps_pair
    flat = {"a": np.zeros(2, np.float32)}
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment)

    second = PSClient([s.address for s in servers])
    try:
        for _ in range(3):
            client.push_grads({"a": np.ones(2, np.float32)}, assignment, lr=0.1)
        for _ in range(2):
            second.push_grads({"a": np.ones(2, np.float32)}, assignment, lr=0.1)
        assert client.get_step() == 5
    finally:
        second.close()


def test_concurrent_pushes_are_all_applied(ps_pair):
    """Async semantics: racy but lossless — N pushes => N applied updates."""
    servers, client = ps_pair
    flat = {"a": np.zeros(1, np.float32)}
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment)

    n_workers, n_pushes = 4, 25
    def worker():
        c = PSClient([s.address for s in servers])
        try:
            for _ in range(n_pushes):
                c.push_grads({"a": np.full(1, -1.0, np.float32)}, assignment, lr=1.0)
        finally:
            c.close()

    threads = [threading.Thread(target=worker) for _ in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    got, step = client.pull_all()
    assert step == n_workers * n_pushes
    np.testing.assert_allclose(got["a"], n_workers * n_pushes)  # -= 1.0 * -1.0 each


def test_grad_fn_end_to_end_with_ps(ps_pair):
    """A miniature async training loop drives the loss down."""
    _, client = ps_pair
    model = DeepCNN()
    params = model.init(jax.random.PRNGKey(0))
    flat = flatten_params(params)
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment)

    grad_fn = make_grad_fn(model, keep_prob=1.0)
    from distributed_tensorflow_tpu.data.synthetic import synthetic_digits

    xs, labels = synthetic_digits(16, seed=0)
    x, y = jnp.asarray(xs), jax.nn.one_hot(jnp.asarray(labels), 10)

    losses = []
    rng = jax.random.PRNGKey(1)
    for _ in range(10):
        cur, _ = client.pull_all()
        p = unflatten_params(params, cur)
        rng, sub = jax.random.split(rng)
        grads, metrics = grad_fn(p, (x, y), sub)
        losses.append(float(metrics["loss"]))
        client.push_grads(flatten_params(grads), assignment, lr=0.05)
    assert min(losses[1:]) < losses[0], losses


def test_shutdown_op(ps_pair):
    servers, client = ps_pair
    client.call(0, {"op": "shutdown"})
    assert servers[0]._shutdown.is_set()
