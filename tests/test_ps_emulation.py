"""Async PS emulation: sharding policy, wire protocol, ps-side optimizers,
stale-gradient semantics, multi-worker global-step termination, multi-chip
worker compute — all in-process on localhost."""

import socket
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.models import DeepCNN
from distributed_tensorflow_tpu.parallel.ps_emulation import (
    PSClient,
    PSServer,
    _encode_msg,
    _recv_msg,
    assign_shards,
    flatten_params,
    make_grad_fn,
    unflatten_params,
)


@pytest.fixture()
def ps_pair():
    servers = [PSServer(i, "127.0.0.1:0") for i in range(2)]
    for s in servers:
        s.start_background()
    client = PSClient([s.address for s in servers])
    yield servers, client
    client.close()
    for s in servers:
        s.close()


@pytest.fixture()
def ps_pair_bf16():
    servers = [PSServer(i, "127.0.0.1:0") for i in range(2)]
    for s in servers:
        s.start_background()
    client = PSClient([s.address for s in servers], wire="bf16")
    yield servers, client
    client.close()
    for s in servers:
        s.close()


def test_assign_shards_round_robin():
    keys = ["b", "a", "d", "c"]
    a = assign_shards(keys, 2)
    # sorted order: a,b,c,d -> 0,1,0,1
    assert a == {"a": 0, "b": 1, "c": 0, "d": 1}


def test_flatten_unflatten_roundtrip():
    model = DeepCNN()
    params = model.init(jax.random.PRNGKey(0))
    flat = flatten_params(params)
    assert "weights/wd1" in flat
    back = unflatten_params(params, flat)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------- protocol


def test_wire_roundtrip_preserves_dtypes_shapes_and_meta():
    """The transport is a typed frame (JSON header + raw tensor bytes) —
    no object deserialization anywhere (the reference's gRPC/protobuf
    transport likewise cannot execute code on receive)."""
    msg = {
        "op": "push_grads",
        "count_step": True,
        "grads": {
            "a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": np.array(7, dtype=np.int32),  # 0-d
            "c": np.arange(4, dtype=np.float64),
        },
    }
    a, b = socket.socketpair()
    try:
        a.sendall(_encode_msg(msg))
        got = _recv_msg(b)
    finally:
        a.close()
        b.close()
    assert got["op"] == "push_grads" and got["count_step"] is True
    for k, v in msg["grads"].items():
        assert got["grads"][k].dtype == v.dtype
        assert got["grads"][k].shape == v.shape
        np.testing.assert_array_equal(got["grads"][k], v)


def test_encode_msg_contains_no_pickle_opcodes():
    frame = _encode_msg({"op": "pull", "params": {"w": np.ones(3, np.float32)}})
    # a pickle stream starts with PROTO (0x80); the frame is u64 | JSON | raw
    assert frame[8:9] == b"{"
    assert b"\x80\x04" not in frame[:64]


def test_ping_carries_initialized_flag(ps_pair):
    _, client = ps_pair
    r = client.call(0, {"op": "ping"})
    assert r["ok"] and r["initialized"] is False
    flat = {"a": np.zeros(2, np.float32)}
    client.init_params(flat, assign_shards(list(flat), 2))
    assert client.call(0, {"op": "ping"})["initialized"] is True
    # wait_initialized consumes the same lightweight status (no shard pull)
    client.wait_initialized(poll_s=0.01)


def test_pull_before_init_reports_uninitialized(ps_pair):
    _, client = ps_pair
    r = client.call(0, {"op": "pull"})
    assert r == {"ok": False, "uninitialized": True}


# ------------------------------------------------------- ps-side optimizer


def test_init_pull_push_cycle(ps_pair):
    _, client = ps_pair
    flat = {"a": np.ones(4, np.float32), "b": np.full(3, 2.0, np.float32)}
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment, optimizer="sgd", learning_rate=0.5)
    got, step = client.pull_all()
    assert step == 0
    np.testing.assert_allclose(got["a"], 1.0)
    np.testing.assert_allclose(got["b"], 2.0)

    # SGD applied ON the ps (ApplyGradientDescent parity, MNISTDist.py:149):
    # p -= lr*g, global step counted once on ps0
    grads = {"a": np.ones(4, np.float32), "b": np.ones(3, np.float32)}
    new_step = client.push_grads(grads, assignment)
    assert new_step == 1
    got, _ = client.pull_all()
    np.testing.assert_allclose(got["a"], 0.5)
    np.testing.assert_allclose(got["b"], 1.5)


def test_unknown_optimizer_rejected_loudly(ps_pair):
    """--mode=ps with an optimizer the ps cannot apply must fail at init,
    not silently train with SGD."""
    _, client = ps_pair
    flat = {"a": np.zeros(2, np.float32)}
    with pytest.raises(ValueError, match="unknown optimizer"):
        client.init_params(flat, assign_shards(list(flat), 2),
                           optimizer="adagrad")


@pytest.mark.parametrize("name", ["momentum", "adam"])
def test_ps_optimizer_matches_device_optimizer(name, ps_pair):
    """The host-side ps apply must track the in-jit optimizer exactly: run
    the same grad sequence through both and compare trajectories."""
    from distributed_tensorflow_tpu.training.train_state import (
        apply_updates,
        get_optimizer,
    )

    _, client = ps_pair
    rng = np.random.default_rng(0)
    flat = {
        "a": rng.normal(size=(3, 2)).astype(np.float32),
        "b": rng.normal(size=(4,)).astype(np.float32),
    }
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment, optimizer=name, learning_rate=0.1)

    opt = get_optimizer(name, 0.1)
    ref_params = {k: jnp.asarray(v) for k, v in flat.items()}
    opt_state = opt.init(ref_params)

    for i in range(5):
        grads = {k: rng.normal(size=v.shape).astype(np.float32)
                 for k, v in flat.items()}
        client.push_grads(grads, assignment)
        updates, opt_state = opt.update(
            {k: jnp.asarray(g) for k, g in grads.items()}, opt_state, ref_params)
        ref_params = apply_updates(ref_params, updates)

    got, step = client.pull_all()
    assert step == 5
    for k in flat:
        np.testing.assert_allclose(got[k], np.asarray(ref_params[k]),
                                   rtol=1e-5, atol=1e-6)


def test_global_step_counts_total_pushes_across_workers(ps_pair):
    """training_iter bounds TOTAL steps across workers (MNISTDist.py:173)."""
    servers, client = ps_pair
    flat = {"a": np.zeros(2, np.float32)}
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment, learning_rate=0.1)

    second = PSClient([s.address for s in servers])
    try:
        for _ in range(3):
            client.push_grads({"a": np.ones(2, np.float32)}, assignment)
        for _ in range(2):
            second.push_grads({"a": np.ones(2, np.float32)}, assignment)
        assert client.get_step() == 5
    finally:
        second.close()


def test_concurrent_pushes_are_all_applied(ps_pair):
    """Async semantics: racy but lossless — N pushes => N applied updates."""
    servers, client = ps_pair
    flat = {"a": np.zeros(1, np.float32)}
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment, optimizer="sgd", learning_rate=1.0)

    n_workers, n_pushes = 4, 25
    def worker():
        c = PSClient([s.address for s in servers])
        try:
            for _ in range(n_pushes):
                c.push_grads({"a": np.full(1, -1.0, np.float32)}, assignment)
        finally:
            c.close()

    threads = [threading.Thread(target=worker) for _ in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    got, step = client.pull_all()
    assert step == n_workers * n_pushes
    np.testing.assert_allclose(got["a"], n_workers * n_pushes)  # -= 1.0 * -1.0 each


# ------------------------------------------------------- worker compute


def test_grad_fn_end_to_end_with_ps(ps_pair):
    """A miniature async training loop drives the loss down."""
    _, client = ps_pair
    model = DeepCNN()
    params = model.init(jax.random.PRNGKey(0))
    flat = flatten_params(params)
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment, optimizer="sgd", learning_rate=0.05)

    grad_fn = make_grad_fn(model, keep_prob=1.0, devices=jax.devices()[:1])
    from distributed_tensorflow_tpu.data.synthetic import synthetic_digits

    xs, labels = synthetic_digits(16, seed=0)
    x, y = jnp.asarray(xs), jax.nn.one_hot(jnp.asarray(labels), 10)

    losses = []
    rng = jax.random.PRNGKey(1)
    for _ in range(10):
        cur, _ = client.pull_all()
        p = unflatten_params(params, cur)
        rng, sub = jax.random.split(rng)
        grads, metrics = grad_fn(p, (x, y), sub)
        losses.append(float(metrics["loss"]))
        client.push_grads(flatten_params(grads), assignment)
    assert min(losses[1:]) < losses[0], losses


def test_multichip_worker_grads_match_single_chip():
    """A worker host with N local chips shards the batch over a local mesh
    and pmeans grads before the push (VERDICT r1 #10): the pushed grads must
    equal the single-chip grads on the same batch. keep_prob=1 so the
    per-shard dropout fold_in has no effect on the comparison."""
    from distributed_tensorflow_tpu.data.synthetic import synthetic_digits

    model = DeepCNN()
    params = model.init(jax.random.PRNGKey(0))
    xs, labels = synthetic_digits(32, seed=3)
    x, y = jnp.asarray(xs), jax.nn.one_hot(jnp.asarray(labels), 10)
    rng = jax.random.PRNGKey(7)

    g1, m1 = make_grad_fn(model, 1.0, devices=jax.devices()[:1])(params, (x, y), rng)
    g4, m4 = make_grad_fn(model, 1.0, devices=jax.devices()[:4])(params, (x, y), rng)

    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]), rtol=1e-5)


def test_stateful_model_rejected_by_grad_fn():
    from distributed_tensorflow_tpu.models import ResNet20

    with pytest.raises(NotImplementedError, match="sync mode"):
        make_grad_fn(ResNet20(), keep_prob=1.0)


def test_shutdown_op(ps_pair):
    servers, client = ps_pair
    client.call(0, {"op": "shutdown"})
    assert servers[0]._shutdown.is_set()


def test_idempotent_call_survives_broken_connection(ps_pair):
    """A dropped TCP connection (worker hiccup, ps restart behind the same
    address) must not kill the worker on a read op: call() reconnects and
    retries idempotent ops."""
    servers, client = ps_pair
    model = DeepCNN()
    flat = flatten_params(model.init(jax.random.PRNGKey(0)))
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment)

    # sever the established connections out from under the client
    for i in range(2):
        client.debug_break_connections(i)
    pulled, step = client.pull_all()  # reconnects + retries
    assert step == 0 and set(pulled) == set(flat)

    client.debug_break_connections(0)
    assert client.call(0, {"op": "ping"})["initialized"]


def test_push_survives_broken_connection(ps_pair):
    """A connection severed BEFORE the push reaches the ps: the retry
    resends on a fresh connection and the gradient applies exactly once
    (seq dedup makes the resend safe; round 2 excluded push_grads from
    retry entirely)."""
    servers, client = ps_pair
    model = DeepCNN()
    flat = flatten_params(model.init(jax.random.PRNGKey(0)))
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment, optimizer="sgd", learning_rate=0.5)

    client.debug_break_connections(0)
    grads = {k: np.ones_like(v) for k, v in flat.items()}
    step = client.push_grads(grads, assignment)
    assert step == 1
    pulled, _ = client.pull_all()
    for k in flat:
        np.testing.assert_allclose(pulled[k], flat[k] - 0.5, rtol=1e-6,
                                   err_msg=k)


def test_push_retries_exactly_once_when_reply_lost(ps_pair):
    """The hard failure mode the round-2 verdict named: the ps APPLIES the
    push but the reply is lost on the wire. The worker must survive (retry)
    and the gradient must apply EXACTLY once — the resend is recognized by
    its (worker, seq) and no-ops."""
    servers, client = ps_pair
    model = DeepCNN()
    flat = flatten_params(model.init(jax.random.PRNGKey(0)))
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment, optimizer="sgd", learning_rate=0.5)

    servers[0].drop_reply_once.add("push_grads")  # apply, then sever
    grads = {k: np.ones_like(v) for k, v in flat.items()}
    step = client.push_grads(grads, assignment)
    # step counted once (ps 0 owns the counter and got the duplicate)
    assert step == 1
    pulled, _ = client.pull_all()
    for k in flat:
        # exactly one -0.5 update; a double-apply would give -1.0
        np.testing.assert_allclose(pulled[k], flat[k] - 0.5, rtol=1e-6,
                                   err_msg=k)

    # a FRESH client incarnation must not be treated as a duplicate
    step = client.push_grads(grads, assignment)
    assert step == 2


def test_pull_prefetch_and_bf16_wire(ps_pair_bf16):
    """wire='bf16': pulls arrive as bf16 (half width), pushes are applied
    on the f32 master within bf16 truncation error; pull_all_async
    overlaps and returns the same data."""
    import ml_dtypes

    servers, client = ps_pair_bf16
    model = DeepCNN()
    flat = flatten_params(model.init(jax.random.PRNGKey(0)))
    assignment = assign_shards(list(flat), 2)
    client.init_params(flat, assignment, optimizer="sgd", learning_rate=0.5)

    pulled, step = client.pull_all()
    assert step == 0
    for k in flat:
        assert pulled[k].dtype == ml_dtypes.bfloat16, k
        np.testing.assert_allclose(np.asarray(pulled[k], np.float32),
                                   flat[k], rtol=8e-3, atol=1e-3)

    grads = {k: np.full_like(v, 0.25) for k, v in flat.items()}  # bf16-exact
    assert client.push_grads(grads, assignment) == 1
    fut = client.pull_all_async()
    pulled2, step2 = fut.result()
    assert step2 == 1
    for k in flat:
        np.testing.assert_allclose(np.asarray(pulled2[k], np.float32),
                                   flat[k] - 0.125, rtol=8e-3, atol=2e-3,
                                   err_msg=k)


def test_ps_mode_rejects_augment_and_eval_step():
    """--augment / --eval_step are compiled into (or drive) the sync/local
    loops only; a ps-mode run must refuse them loudly, not silently train
    unaugmented / skip the evals (round-2 advisor finding)."""
    from distributed_tensorflow_tpu.parallel.ps_emulation import run_worker

    class F:
        lr_schedule = "constant"
        warmup_steps = 0
        accum_steps = 1
        weight_decay = 0.0
        augment = True
        eval_step = 0

    with pytest.raises(ValueError, match="--augment is not supported in ps"):
        run_worker(None, F)

    F.augment = False
    F.eval_step = 10
    with pytest.raises(ValueError, match="--eval_step is not supported in ps"):
        run_worker(None, F)


def _run_worker_once(tmp_path, tag, extra=()):
    """Drive run_worker in-process against a fresh in-process ps."""
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.cluster import ClusterSpec
    from distributed_tensorflow_tpu.parallel.ps_emulation import run_worker

    server = PSServer(0, "127.0.0.1:0")
    server.start_background()
    try:
        flags.define_reference_flags()
        flags.FLAGS._reset()
        flags.FLAGS._parse([
            f"--ps_hosts={server.address}", "--worker_hosts=localhost:1",
            "--job_name=worker", "--task_index=0", "--training_iter=8",
            "--batch_size=16", "--display_step=4",
            f"--logdir={tmp_path}/logs-{tag}", f"--data_dir={tmp_path}/none",
            "--learning_rate=0.05", "--save_model_secs=100000",
            "--test_eval=false", *extra,
        ])
        cluster = ClusterSpec.from_flags(flags.FLAGS)
        assert run_worker(cluster, flags.FLAGS) == 0
        client = PSClient([server.address])
        final, step = client.pull_all()
        client.close()
        return final, step
    finally:
        server.close()
        flags.FLAGS._reset()


def test_mirror_trajectory_matches_full_pull(tmp_path):
    """--ps_mirror (device-resident params, on-chip sgd replay of the
    ps-side apply) must land the PS on the same trajectory as the
    full-pull cycle: same seed, same batches, same pushes — the mirror
    only changes WHERE the worker's copy of the params lives."""
    mirror, s1 = _run_worker_once(tmp_path, "mirror")  # default: mirror on
    # serial full-pull is the semantics the mirror replays (prefetch's
    # double-buffered pull is one own-push staler by design)
    full, s2 = _run_worker_once(
        tmp_path, "fullpull", ("--ps_mirror=false", "--ps_prefetch=false"))
    assert s1 == s2 == 8
    assert mirror.keys() == full.keys()
    for k in mirror:
        np.testing.assert_allclose(mirror[k], full[k], rtol=2e-5, atol=1e-6,
                                   err_msg=k)


def test_mirror_resync_cadence(tmp_path):
    """A 2-step resync cadence forces mid-run pulls; the run completes and
    the ps state is still the trajectory authority."""
    resync, s = _run_worker_once(tmp_path, "resync", ("--ps_resync_steps=2",))
    baseline, _ = _run_worker_once(
        tmp_path, "base2", ("--ps_mirror=false", "--ps_prefetch=false"))
    assert s == 8
    for k in resync:
        np.testing.assert_allclose(resync[k], baseline[k], rtol=2e-5,
                                   atol=1e-6, err_msg=k)


def test_mirror_cycle_desyncs_on_foreign_push():
    """MirrorCycle must detect another worker's interleaved push (the
    global step skips ahead) and resync from the ps instead of trusting
    its on-chip replay."""
    from distributed_tensorflow_tpu.parallel.ps_emulation import MirrorCycle

    server = PSServer(0, "127.0.0.1:0")
    server.start_background()
    client = PSClient([server.address])
    rogue = PSClient([server.address])
    try:
        model = DeepCNN()
        template = model.init(jax.random.PRNGKey(0))
        flat = flatten_params(template)
        assignment = assign_shards(list(flat), 1)
        client.init_params(flat, assignment, optimizer="sgd",
                           learning_rate=0.1)
        grad_fn = make_grad_fn(model, keep_prob=1.0,
                               devices=jax.devices()[:1])
        cyc = MirrorCycle(client, grad_fn, template, assignment,
                          learning_rate=0.1, resync_steps=10**6)
        assert cyc.maybe_sync()

        rng = jax.random.PRNGKey(1)
        x = np.random.default_rng(0).random((8, 784)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[np.arange(8) % 10]
        cyc.run_cycle((x, y), rng)          # pending grad, no push yet
        cyc.run_cycle((x, y), rng)          # pushes cycle 1 -> step 1
        assert cyc.step == 1 and not cyc.needs_resync

        # another worker's push lands between our cycles
        rogue.push_grads({k: np.zeros_like(v) for k, v in flat.items()},
                         assignment)
        cyc.run_cycle((x, y), rng)          # our push sees step jump 1->3
        assert cyc.step == 3
        assert cyc.needs_resync             # foreign update detected
        # resync drains the trailing grad (step -> 4), then pulls the
        # fresh authority
        assert cyc.maybe_sync()
        assert not cyc.needs_resync and cyc.mirror_step == cyc.step == 4
    finally:
        client.close()
        rogue.close()
        server.close()


def test_dedup_survives_eviction_pressure_for_active_worker():
    """An active worker's retry must still dedupe even when the table is
    at capacity with churning one-shot incarnations (ADVICE r3: the old
    insertion-order eviction could evict a live-but-slow worker). Both a
    successful apply AND a dedup hit refresh recency, so churn evicts
    idle incarnations, never the active worker."""
    server = PSServer(0, "127.0.0.1:0")
    try:
        server.dispatch({"op": "init_shard", "params": {"w": [1.0]},
                         "optimizer": "sgd", "learning_rate": 0.5,
                         "num_workers": 3})
        assert server.dedup_cap == 1024  # floor holds for small clusters
        server.dedup_cap = 4             # shrink to make churn cheap
        push = {"op": "push_grads", "grads": {"w": [1.0]},
                "count_step": True}

        r = server.dispatch(dict(push, worker="slow", seq=0))
        assert r["ok"] and not r.get("duplicate")
        # fill the table around it with one-shot incarnations
        for i in range(3):
            server.dispatch(dict(push, worker=f"churn{i}", seq=0))
        # a RETRY (dedup hit) is proof of life: it must refresh recency
        r = server.dispatch(dict(push, worker="slow", seq=0))
        assert r["duplicate"]
        # churn past the cap: every churn incarnation is now older than
        # the refreshed entry, so they are the eviction victims. (Under
        # the old insertion-order scheme "slow" was oldest and the very
        # next new worker would have evicted it.)
        for i in range(3, 6):
            server.dispatch(dict(push, worker=f"churn{i}", seq=0))
        assert "slow" in server._applied_seq
        assert "churn0" not in server._applied_seq  # idle ones evicted
        # ...but the active worker's entry survived: retry still no-ops
        before = server.params["w"].copy()
        r = server.dispatch(dict(push, worker="slow", seq=0))
        assert r["duplicate"]
        np.testing.assert_array_equal(server.params["w"], before)
    finally:
        server.close()


def test_dedup_cap_scales_with_declared_cluster():
    """init_shard's num_workers raises the dedup cap to 4x the declared
    deployment so large clusters can never evict a live worker."""
    server = PSServer(0, "127.0.0.1:0")
    try:
        server.dispatch({"op": "init_shard", "params": {"w": [0.0]},
                         "optimizer": "sgd", "learning_rate": 0.1,
                         "num_workers": 1000})
        assert server.dedup_cap == 4000
    finally:
        server.close()


def test_negative_seq_for_unknown_worker_is_benign():
    """A malformed push with seq=-1 for a worker the table has never seen
    matches the -1 dedup default; the reply must be a duplicate no-op,
    not a crashed handler (the refresh must not KeyError on a missing
    entry)."""
    server = PSServer(0, "127.0.0.1:0")
    try:
        server.dispatch({"op": "init_shard", "params": {"w": [1.0]},
                         "optimizer": "sgd", "learning_rate": 0.5})
        r = server.dispatch({"op": "push_grads", "grads": {"w": [1.0]},
                             "worker": "ghost", "seq": -1})
        assert r["ok"] and r["duplicate"]
        assert "ghost" not in server._applied_seq
        np.testing.assert_array_equal(server.params["w"], [1.0])  # no apply
    finally:
        server.close()


@pytest.mark.parametrize("opt", ["momentum", "adam"])
def test_mirror_trajectory_matches_full_pull_slot_optimizers(tmp_path, opt):
    """r3 verdict item 3: momentum/adam now run the device-mirror cycle
    (on-chip slot replay of the ps-side apply + slot adoption at
    resync) and must land the ps on the SAME trajectory as the full-pull
    cycle. The grads feed back through the mirror params, so any replay
    or slot error compounds — trajectory equality is the strong test."""
    mirror, s1 = _run_worker_once(
        tmp_path, f"m-{opt}", ("--model=mlp", f"--optimizer={opt}"))
    full, s2 = _run_worker_once(
        tmp_path, f"f-{opt}",
        ("--model=mlp", f"--optimizer={opt}", "--ps_mirror=false",
         "--ps_prefetch=false"))
    assert s1 == s2 == 8
    # tolerance: the two cycles run the same f32 math but through
    # different evaluators (numpy on the ps vs XLA on the mirror); ulp
    # differences feed back through the params. A real replay/slot bug
    # shows up at the update scale (~lr = 1e-2+), 10x above this.
    for k in mirror:
        np.testing.assert_allclose(mirror[k], full[k], rtol=1e-3,
                                   atol=1e-3, err_msg=k)


def test_mirror_adam_desync_adopts_ps_slots():
    """A foreign push under adam advances ps-side slots the mirror did
    not replay; the desync resync must adopt the ps's authoritative
    params AND slots, then keep cycling."""
    from distributed_tensorflow_tpu.parallel.ps_emulation import MirrorCycle

    server = PSServer(0, "127.0.0.1:0")
    server.start_background()
    client = PSClient([server.address])
    rogue = PSClient([server.address])
    try:
        from distributed_tensorflow_tpu.models import get_model

        model = get_model("mlp", hidden_units=16)
        template = model.init(jax.random.PRNGKey(0))
        flat = flatten_params(template)
        assignment = assign_shards(list(flat), 1)
        client.init_params(flat, assignment, optimizer="adam",
                           learning_rate=0.01)
        grad_fn = make_grad_fn(model, keep_prob=1.0,
                               devices=jax.devices()[:1])
        cyc = MirrorCycle(client, grad_fn, template, assignment,
                          learning_rate=0.01, resync_steps=10**6,
                          optimizer="adam")
        assert cyc.maybe_sync()

        rng = jax.random.PRNGKey(1)
        x = np.random.default_rng(0).random((8, 784)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[np.arange(8) % 10]
        cyc.run_cycle((x, y), rng)
        cyc.run_cycle((x, y), rng)  # pushes cycle 1 -> step 1
        assert cyc.step == 1 and not cyc.needs_resync

        rogue.push_grads({k: np.ones_like(v) * 0.1 for k, v in flat.items()},
                         assignment)
        cyc.run_cycle((x, y), rng)  # sees the step jump -> desync
        assert cyc.needs_resync
        assert cyc.maybe_sync()     # drains + adopts params AND slots
        # after adoption the mirror equals the ps bitwise (params) and
        # its next replayed update starts from the ps's slot state
        pulled, step = client.pull_all(with_slots=False)
        for k, v in flatten_params(cyc.dparams).items():
            np.testing.assert_allclose(np.asarray(v), pulled[k],
                                       rtol=1e-6, atol=1e-7, err_msg=k)
        assert cyc.mirror_step == cyc.step == step
        cyc.run_cycle((x, y), rng)  # keeps cycling after adoption
        leaf = jax.tree.leaves(cyc.dparams)[0]
        assert np.isfinite(float(np.asarray(leaf).sum()))
    finally:
        client.close()
        rogue.close()
        server.close()


def test_concurrent_mirror_workers_stay_live_and_converge_steps():
    """The reference's deployment shape: N workers concurrently driving
    the SAME ps through mirror cycles (every foreign push desyncs the
    mirror -> resync pull; the documented multi-worker degraded mode).
    Both workers must complete their budget, every push must count
    exactly once (global step == total pushes), and desyncs must
    actually occur and be recovered from (not deadlock or double-apply).
    The measurement twin of this test is tools/ps_multiworker_bench.py."""
    import threading

    from distributed_tensorflow_tpu.models import get_model
    from distributed_tensorflow_tpu.parallel.ps_emulation import MirrorCycle

    server = PSServer(0, "127.0.0.1:0")
    server.start_background()
    init_client = PSClient([server.address])
    try:
        model = get_model("mlp", hidden_units=16)
        template = model.init(jax.random.PRNGKey(0))
        flat = flatten_params(template)
        assignment = assign_shards(list(flat), 1)
        init_client.init_params(flat, assignment, optimizer="sgd",
                                learning_rate=0.05, num_workers=2)
        grad_fn = make_grad_fn(model, keep_prob=1.0,
                               devices=jax.devices()[:1])
        x = np.random.default_rng(0).random((8, 784)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[np.arange(8) % 10]
        cycles = 8
        desyncs = [0, 0]
        errors = []
        start = threading.Barrier(2)  # force interleaving -> desyncs

        def worker(widx):
            try:
                client = PSClient([server.address])
                cyc = MirrorCycle(client, grad_fn, template, assignment,
                                  learning_rate=0.05,
                                  resync_steps=10**9)
                cyc.maybe_sync()
                rng = jax.random.PRNGKey(widx)
                start.wait()
                for i in range(cycles):
                    cyc.run_cycle((x, y), jax.random.fold_in(rng, i))
                    if cyc.needs_resync:
                        desyncs[widx] += 1
                        cyc.maybe_sync()
                cyc.drain()
                client.close()
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append((widx, e))

        # daemon: a deadlocked worker must FAIL the test in ~2 min, not
        # hang the pytest process forever at interpreter exit
        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "worker hung"
        assert not errors, errors
        # exactly-once accounting under concurrency: every one of the
        # 2 x cycles pushes counted exactly once on the shared step
        assert server.dispatch({"op": "get_step"})["global_step"] == 2 * cycles
        # the barrier-forced interleaving means each worker saw foreign
        # pushes: the desync/resync recovery path actually ran
        assert sum(desyncs) > 0, desyncs
    finally:
        init_client.close()
        server.close()
