"""CLI: ``python -m tools.dttcheck [--json] [--mode M] [--model M]
[--baseline PATH] [--inventory]``.

Exit status is the tier-1 contract (dttlint's): 0 when every scenario
traces clean — ledger bytes proven equal to the jaxpr-derived bytes,
no divergent cond branches, no wasted donation, no replication drift —
and no stale suppressions; 1 otherwise.

``--mode`` / ``--model`` filter the scenario matrix for bring-up
(``--mode zero1 --mode zero3``); stale-suppression accounting still
only charges the passes that ran. ``--inventory`` prints the per-
scenario collective inventory table (family, axes, trips, wire bytes)
instead of just the verdict — the human-readable view of what the
proof measured.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# tools/ convention: runnable as a script too
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from tools.dttcheck import DEFAULT_BASELINE, run_check  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dttcheck",
        description="dttcheck — the jaxpr-level ledger/SPMD verifier "
                    "(passes DTC001-DTC004; see docs/ARCHITECTURE.md "
                    "'Jaxpr verification')")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON object")
    ap.add_argument("--mode", action="append", default=None,
                    help="restrict to one parallel mode (repeatable): "
                         "dp zero1 zero3 pp tp ep sp ps")
    ap.add_argument("--model", action="append", default=None,
                    help="restrict to one model (repeatable): "
                         "deep_cnn mlp lm lm_moe")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="suppression file (default: the checked-in "
                         "tools/dttcheck/baseline.json)")
    ap.add_argument("--inventory", action="store_true",
                    help="print the per-scenario collective inventory")
    args = ap.parse_args(argv)

    # the 8-device CPU mesh must exist BEFORE jax spins up — run_check
    # handles it, but fail early with the real message if jax snuck in
    result = run_check(args.baseline, modes=args.mode, models=args.model)

    if args.json:
        print(json.dumps(result.to_json()))
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.format())
    for key in result.stale:
        print(f"{args.baseline}: STALE suppression {key} — the finding "
              f"no longer exists; delete the entry (the baseline only "
              f"shrinks)")
    rows = result.report.get("scenarios", [])
    if args.inventory:
        print(f"{'scenario':<26} {'src':<6} {'colls':>5} "
              f"{'wire bytes':>14} {'ctrl':>4} {'ledger':>7} "
              f"{'time':>7}")
        for r in rows:
            print(f"{r['scenario']:<26} {r['source']:<6} "
                  f"{r['collectives']:>5} {r['wire_bytes']:>14,} "
                  f"{r['control']:>4} "
                  f"{'proven' if r['ledger_proven'] else '-':>7} "
                  f"{r['time_s']:>6.2f}s")
    print(f"dttcheck: {len(result.findings)} finding(s), "
          f"{len(result.baselined)} baselined, "
          f"{len(result.stale)} stale suppression(s); "
          f"{len(rows)} scenario(s), "
          f"modes proven: {result.report.get('modes_proven')}, "
          f"{result.report.get('collectives_total')} collectives, "
          f"{result.report.get('wire_bytes_total', 0):,} wire bytes")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
