"""Ring attention + MiniTransformer + sequence parallelism.

The long-context extension's correctness pins: ring attention must equal
dense attention (it is the same math, blockwise), and the full
sequence-parallel train step must reproduce the dense single-device
trajectory exactly — including the subtle gradient reduction (pmean over
the sequence axis for per-token params; the pooled psum's transpose
scales every pre-pool cotangent by the axis size)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.models import MiniTransformer, get_model
from distributed_tensorflow_tpu.ops.attention import (
    multi_head_attention,
    ring_attention,
)
from distributed_tensorflow_tpu.parallel import MeshSpec, make_mesh
from distributed_tensorflow_tpu.parallel.mesh import MODEL_AXIS
from distributed_tensorflow_tpu.parallel.sequence_parallel import (
    make_sp_eval_step,
    make_sp_train_step,
    reshape_for_sp,
    stage_batch_sp,
)
from distributed_tensorflow_tpu.training import (
    create_train_state,
    make_train_step,
    sgd,
)

KW = dict(d_model=32, num_heads=2, num_blocks=2)


def _qkv(key, b=2, s=16, h=2, dh=8):
    ks = jax.random.split(key, 3)
    shape = (b, s, h, dh)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_equals_dense_attention(causal):
    """Ring attention over a sharded sequence == dense attention on the
    gathered sequence (forward), bidirectional and causal."""
    q, k, v = _qkv(jax.random.PRNGKey(0))
    dense = multi_head_attention(q, k, v, causal=causal)

    mesh = make_mesh(MeshSpec(data=1, model=8))
    ring = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, MODEL_AXIS, causal=causal),
        mesh=mesh,
        in_specs=(P(None, MODEL_AXIS), P(None, MODEL_AXIS), P(None, MODEL_AXIS)),
        out_specs=P(None, MODEL_AXIS),
        check_vma=False,
    ))(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grads_match_dense(causal):
    """Gradients THROUGH the ring (ppermute transpose chain) equal the
    dense gradients; per-shard q/k/v grads are per-token partials, so
    they compare directly after the same sharding."""
    q, k, v = _qkv(jax.random.PRNGKey(1))
    w = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.float32)

    def dense_loss(qkv):
        return (multi_head_attention(*qkv, causal=causal) * w).sum()

    g_dense = jax.grad(dense_loss)((q, k, v))

    mesh = make_mesh(MeshSpec(data=1, model=8))

    def shard_loss(qkv, w):
        # LOCAL loss per shard: the global objective is the sum of shard
        # losses, so each q grad is shard-local and the k/v grads flow
        # back through the ppermute transpose chain — both exactly the
        # dense partials. (A psum'd replicated loss would scale every
        # grad by the axis size: each shard differentiates its own copy.)
        out = ring_attention(*qkv, MODEL_AXIS, causal=causal)
        return (out * w).sum()

    g_ring = jax.jit(jax.shard_map(
        lambda qkv, w: jax.grad(shard_loss)(qkv, w),
        mesh=mesh,
        in_specs=((P(None, MODEL_AXIS),) * 3, P(None, MODEL_AXIS)),
        out_specs=(P(None, MODEL_AXIS),) * 3,
        check_vma=False,
    ))((q, k, v), w)
    for a, b in zip(g_dense, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-5, atol=2e-6)


def test_sp_step_matches_dense_trajectory():
    """The COMPLETE sequence-parallel train step (ring attention, sharded
    positional slices, psum pooling, pmean/identity grad reduction)
    reproduces the dense single-device sgd trajectory."""
    mesh = make_mesh(MeshSpec(data=2, model=4))
    sp_model = MiniTransformer(seq_axis=MODEL_AXIS, **KW)
    dense_model = MiniTransformer(**KW)
    opt = sgd(0.1)
    s_sp = create_train_state(sp_model, opt, seed=0)
    s_d = create_train_state(dense_model, opt, seed=0)
    x = jax.random.uniform(jax.random.PRNGKey(1), (8, 784))
    y = jax.nn.one_hot(jnp.arange(8) % 10, 10)

    sp_step = make_sp_train_step(sp_model, opt, mesh, keep_prob=1.0,
                                 donate=False)
    d_step = make_train_step(dense_model, opt, keep_prob=1.0, donate=False)
    batch_sp = stage_batch_sp(mesh, (reshape_for_sp(sp_model, x), y))
    for _ in range(3):
        s_sp, m1 = sp_step(s_sp, batch_sp)
        s_d, m2 = d_step(s_d, (x, y))
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-6)
    np.testing.assert_allclose(float(m1["accuracy"]), float(m2["accuracy"]))
    for (path, p1), p2 in zip(
        jax.tree_util.tree_leaves_with_path(jax.device_get(s_sp.params)),
        jax.tree.leaves(jax.device_get(s_d.params)),
    ):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                                   rtol=1e-5, atol=1e-7, err_msg=str(path))

    # eval over the SP layout agrees with the state it trained
    ev = make_sp_eval_step(sp_model, mesh)
    m = ev(s_sp.params, batch_sp)
    assert 0.0 <= float(m["accuracy"]) <= 1.0


def test_sp_step_rejects_dense_model():
    mesh = make_mesh(MeshSpec(data=2, model=4))
    with pytest.raises(ValueError, match="seq_axis"):
        make_sp_train_step(MiniTransformer(**KW), sgd(0.1), mesh)


def test_transformer_registry_and_local_training():
    """--model transformer trains through the ordinary local machinery
    (the dense path needs no mesh at all) and the loss falls."""
    model = get_model("transformer", image_size=28, channels=1,
                      num_classes=10, **KW)
    assert isinstance(model, MiniTransformer)
    from distributed_tensorflow_tpu.training import adam

    opt = adam(1e-3)
    state = create_train_state(model, opt, seed=0)
    step = make_train_step(model, opt, keep_prob=0.9)
    x = jax.random.uniform(jax.random.PRNGKey(0), (32, 784))
    y = jax.nn.one_hot(jnp.arange(32) % 10, 10)
    first = None
    for _ in range(30):
        state, m = step(state, (x, y))
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first


def test_transformer_composes_with_dp_and_device_steps():
    """The dense transformer is a pure model function, so the existing
    sync-DP shard_map step and the device-resident chunked step must both
    drive it unchanged (the composition the mode matrix promises)."""
    from distributed_tensorflow_tpu.data.device_data import DeviceData
    from distributed_tensorflow_tpu.parallel import make_dp_train_step, shard_batch
    from distributed_tensorflow_tpu.parallel.data_parallel import replicate_state
    from distributed_tensorflow_tpu.training import adam
    from distributed_tensorflow_tpu.training.device_step import (
        make_device_train_step,
    )

    model = MiniTransformer(**KW)
    opt = adam(1e-3)

    mesh = make_mesh(MeshSpec(data=8, model=1))
    state = replicate_state(mesh, create_train_state(model, opt, seed=0))
    step = make_dp_train_step(model, opt, mesh, keep_prob=0.9, donate=False)
    x = jax.random.uniform(jax.random.PRNGKey(0), (16, 784))
    y = jax.nn.one_hot(jnp.arange(16) % 10, 10)
    state, m = step(state, shard_batch(mesh, (x, y)))
    assert np.isfinite(float(m["loss"])) and int(state.step) == 1

    # the production pairing: single-device builder with plain arrays
    # (loop.py hands mesh-replicated data to make_device_dp_train_step)
    data = DeviceData(jnp.zeros((64, 784), jnp.uint8),
                      jnp.arange(64, dtype=jnp.int32) % 10)
    dstate = create_train_state(model, opt, seed=0)
    dstep = make_device_train_step(model, opt, 16, keep_prob=0.9, chunk=2,
                                   donate=False)
    dstate, dm = dstep(dstate, data)
    assert np.isfinite(float(dm["loss"])) and int(dstate.step) == 2


def test_seq_parallel_cli_mode(tmp_path, capsys):
    """--seq_parallel as a full training MODE: the production loop trains
    a transformer with the token axis sharded over the mesh, display
    evals run on the SP layout, host-side final test eval runs on the
    dense twin, and the checkpoint round-trips through --eval_only."""
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import evaluate_only, train

    flags.define_reference_flags()
    flags.FLAGS._reset()
    try:
        flags.FLAGS._parse([
            f"--logdir={tmp_path}/logs", f"--data_dir={tmp_path}/none",
            "--model=transformer", "--seq_parallel", "--model_axis=4",
            "--training_iter=12", "--batch_size=32", "--display_step=4",
            "--optimizer=adam", "--save_model_secs=100000",
        ])
        res = train(flags.FLAGS, mode="sync")
        out = capsys.readouterr().out
        assert res.final_step == 12
        assert res.n_chips == 8  # data=2 x model(seq)=4
        assert res.test_metrics is not None
        assert "mini_batch loss" in out

        # the saved (replicated -> locally fetchable) checkpoint restores
        # through the dense path
        m = evaluate_only(flags.FLAGS)
        assert 0.0 <= m["accuracy"] <= 1.0
    finally:
        flags.FLAGS._reset()


def test_seq_parallel_mode_rejections(tmp_path):
    """--seq_parallel refuses incompatible configurations loudly."""
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()

    def parse(*extra):
        flags.FLAGS._reset()
        flags.FLAGS._parse([
            f"--logdir={tmp_path}/logs", f"--data_dir={tmp_path}/none",
            "--training_iter=4", "--batch_size=32", "--seq_parallel",
            *extra,
        ])
        return flags.FLAGS

    try:
        with pytest.raises(ValueError, match="requires --model transformer"):
            train(parse("--model=deep_cnn", "--model_axis=4"), mode="sync")
        with pytest.raises(ValueError, match="shards nothing"):
            train(parse("--model=transformer"), mode="sync")
        with pytest.raises(ValueError, match="must divide"):
            train(parse("--model=transformer", "--model_axis=8"),
                  mode="sync")
        # --device_data now COMPOSES with --seq_parallel (r5; see
        # tests/test_device_step.py's composition tests) — the one
        # remaining rejection is --augment
        with pytest.raises(ValueError, match="not supported with"):
            train(parse("--model=transformer", "--model_axis=4",
                        "--augment"), mode="sync")
    finally:
        flags.FLAGS._reset()


def test_seq_parallel_composes_accum_clip_eval(tmp_path, capsys):
    """The round-3 fence is down: --accum_steps, --clip_norm,
    --eval_step and --validation_size all compose with --seq_parallel
    (pre-/post-reduction gradient transforms and the sharded full-split
    evaluator — no dense-twin forward in the periodic/final evals)."""
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()
    flags.FLAGS._reset()
    flags.FLAGS._parse([
        f"--logdir={tmp_path}/logs", f"--data_dir={tmp_path}/none",
        "--model=transformer", "--seq_parallel", "--model_axis=4",
        "--training_iter=6", "--batch_size=16", "--display_step=3",
        "--accum_steps=2", "--clip_norm=1.0", "--eval_step=3",
        "--validation_size=64", "--optimizer=adam",
        "--save_model_secs=100000",
    ])
    try:
        res = train(flags.FLAGS, mode="sync")
        out = capsys.readouterr().out
        assert res.final_step == 6
        assert "validation accuracy" in out  # periodic evals ran, on val
        assert res.test_metrics is not None  # final eval on test
    finally:
        flags.FLAGS._reset()
