"""dttperf — the performance-contract analyzer (tools/dttperf/).

Four layers: (1) the step-time predictor's term composition,
hand-pinned for the flagship CNN and LM across dp/zero/pp/tp against
the HARDWARE table; (2) the passes on SYNTHETIC corpora — a slowed
record trips DTP001 at the band edge, silent nulls trip DTP002, blown
and unmeasured budgets trip DTP003; (3) the REPO-WIDE gate: the full
matrix prices clean against the checked-in records/budgets inside the
<15s acceptance, stale suppressions fail loudly; (4) the CLI surface
(--json, --mode filtering, exit codes)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.dttperf import predict_step_time, run_perf  # noqa: E402
from tools.dttperf.model import HARDWARE  # noqa: E402
from tools.dttperf.passes import (  # noqa: E402
    pass_budgets,
    pass_conformance,
    pass_fact_coverage,
)
from tools.dttperf.records import (  # noqa: E402
    MODEL_CONSUMES,
    PHASE_EXEMPT,
    PHASE_FACTS,
    RATE_CHECKS,
)
from tools.dttperf.scenarios import flagship_model  # noqa: E402

HW = HARDWARE["v5lite"]

#: the flagship DeepCNN's analytic train FLOPs/example — the
#: hand-computed pin (utils.efficiency.flops_budget, 3x fwd) every
#: composition below rests on. If the model or the accounting changes,
#: this NUMBER must be re-derived by hand, not copied from the code.
CNN_TRAIN_FLOPS_PER_EXAMPLE = 83_303_424


def _empty_baseline(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"version": 1, "entries": []}))
    return str(p)


def _rec(stem="SYNTH", **parsed):
    return {"stem": stem, "path": f"{stem}.json", "rc": 0,
            "parsed": parsed}


# ------------------------------------------- the step-time composition


def test_predict_cnn_dp_composition_hand_pinned():
    """The flagship CNN, 8-way DP at the bench per-chip batch: every
    term re-derived by hand from the HARDWARE row and the analytic
    FLOPs pin — compute-bound, so the step IS the FLOPs term plus the
    fixed host share."""
    model = flagship_model("deep_cnn")
    pred = predict_step_time(dict(mode="dp", data_ways=8), model, 8,
                             global_batch=16384)
    assert pred["train_flops_per_example"] == CNN_TRAIN_FLOPS_PER_EXAMPLE
    assert pred["flops_per_step"] == CNN_TRAIN_FLOPS_PER_EXAMPLE * 16384
    compute = (CNN_TRAIN_FLOPS_PER_EXAMPLE * 16384
               / (HW["peak_flops_per_chip"] * 8))
    assert pred["compute_s"] == pytest.approx(compute)
    assert pred["comm_s"] == pytest.approx(
        pred["comm_exposed_bytes_per_step"] / HW["ici_bytes_per_sec"])
    assert pred["bound"] == "compute"
    assert pred["useful_fraction"] == 1.0
    assert pred["step_time_s"] == pytest.approx(
        compute + HW["host_fixed_s"])
    # the implied DTP001 ceiling, end to end: ~2.31M images/s/chip
    assert pred["examples_per_sec_per_chip"] == pytest.approx(
        16384 / (compute + HW["host_fixed_s"]) / 8)
    assert pred["examples_per_sec_per_chip"] == pytest.approx(
        2_311_467, rel=1e-3)


def test_predict_cnn_zero_shares_compute_changes_wire():
    """ZeRO-1 re-prices the WIRE (reduce-scatter+all-gather vs
    all-reduce), never the FLOPs: same compute term as DP, different
    ledger bytes."""
    model = flagship_model("deep_cnn")
    dp = predict_step_time(dict(mode="dp", data_ways=8), model, 8,
                           global_batch=16384)
    z1 = predict_step_time(dict(mode="zero1", data_ways=8,
                                zero_level=1), model, 8,
                           global_batch=16384)
    assert z1["compute_s"] == pytest.approx(dp["compute_s"])
    assert z1["comm_bytes_per_step"] != dp["comm_bytes_per_step"]
    assert z1["step_time_s"] == pytest.approx(
        max(z1["compute_s"], z1["comm_s"]) + HW["host_fixed_s"])


def test_predict_lm_pp_stretches_compute_by_the_bubble():
    """The LM pipelined 4 stages x 8 microbatches under GPipe: the
    useful-tick fraction is the hand-computed M/(M+K-1) = 8/11, and
    the compute term is the flat-DP term divided by exactly that —
    bubbles stretch compute, they add no wire bytes."""
    model = flagship_model("lm")
    flat = predict_step_time(dict(mode="dp", data_ways=2), model, 8,
                             global_batch=64)
    pp = predict_step_time(
        dict(mode="pp", data_ways=2, model_axis=4, microbatches=8,
             pp_schedule="gpipe"), model, 8, global_batch=64)
    assert pp["useful_fraction"] == pytest.approx(8 / 11)
    assert pp["flops_per_step"] == flat["flops_per_step"]
    # flat compute uses the same 8 chips, so the bubble is the ONLY
    # difference between the two compute terms
    assert pp["compute_s"] == pytest.approx(
        flat["compute_s"] / (8 / 11))
    assert pp["step_time_s"] == pytest.approx(
        max(pp["compute_s"], pp["comm_s"]) + HW["host_fixed_s"])


def test_predict_lm_tp_composition():
    """The LM tensor-parallel 4 x 2: activation psums on the wire,
    the full max(compute, comm) + host composition, and a nonzero
    exposed-comm term."""
    model = flagship_model("lm")
    pred = predict_step_time(
        dict(mode="tp", data_ways=4, model_axis=2), model, 8,
        global_batch=128)
    assert pred["comm_exposed_bytes_per_step"] > 0
    assert pred["comm_s"] == pytest.approx(
        pred["comm_exposed_bytes_per_step"] / HW["ici_bytes_per_sec"])
    assert pred["step_time_s"] == pytest.approx(
        max(pred["compute_s"], pred["comm_s"]) + HW["host_fixed_s"])
    assert pred["examples_per_sec_per_chip"] == pytest.approx(
        128 / pred["step_time_s"] / 8)


def test_predict_ps_prices_the_host_wire():
    """The PS-emulation topology pays the HOST wire, not ICI — the
    comm term divides by the tunnel figure and dominates (the
    reference's own bottleneck, predicted)."""
    model = flagship_model("deep_cnn")
    pred = predict_step_time(dict(mode="ps", data_ways=1), model, 1,
                             global_batch=2048)
    assert pred["comm_s"] == pytest.approx(
        pred["comm_exposed_bytes_per_step"]
        / HW["host_wire_bytes_per_sec"])
    assert pred["bound"] == "comm"


# --------------------------------------------- DTP001 on synthetic data


def test_band_edge_findings_on_slowed_record():
    """A record whose headline rate sits below the band floor is a
    DTP001 finding keyed (record, phase, mode, model); the same record
    at an in-band rate is clean. The ceiling is re-derived by hand
    from the FLOPs pin (1 chip, batch 2048, no collectives)."""
    step = (CNN_TRAIN_FLOPS_PER_EXAMPLE * 2048
            / HW["peak_flops_per_chip"] + HW["host_fixed_s"])
    ceiling = 2048 / step
    lo, hi = next(c["band"] for c in RATE_CHECKS
                  if c["phase"] == "device_resident")
    slowed = _rec(metric="mnist_images_per_sec_per_chip",
                  value=round(0.5 * lo * ceiling, 1), n_chips=1)
    f, rows = pass_conformance([slowed])
    assert [x.key for x in f] == [
        "band:SYNTH:device_resident:dp:deep_cnn"]
    assert f[0].rule == "DTP001"
    assert "regression" in f[0].message
    assert rows[0]["status"] == "OUT"
    healthy = _rec(metric="mnist_images_per_sec_per_chip",
                   value=round(0.5 * (lo + hi) * ceiling, 1), n_chips=1)
    f2, rows2 = pass_conformance([healthy])
    assert f2 == []
    assert rows2[0]["status"] == "in_band"


def test_faster_than_the_roof_is_also_a_finding():
    """A measured rate ABOVE the analytic ceiling band is an
    accounting bug, not a win — DTP001 names it as such."""
    fast = _rec(metric="mnist_images_per_sec_per_chip",
                value=9e9, n_chips=1)
    f, _ = pass_conformance([fast])
    assert len(f) == 1 and "accounting bug" in f[0].message


def test_link_bound_rates_are_exempt_not_banded():
    """The tunnel-weather rates (host-fed wire, feed_dict, PS cycle)
    are structurally exempt — reported, never banded (PERF.md: the
    link varies 100x under load)."""
    rec = _rec(metric="mnist_images_per_sec_per_chip",
               wire_images_per_sec_per_chip=123.4,
               feeddict_images_per_sec_per_chip=56.7, n_chips=1)
    f, rows = pass_conformance([rec])
    assert f == []
    assert {r["status"] for r in rows} == {"exempt"}


# --------------------------------------------- DTP002 on synthetic data


def test_fact_coverage_flags_silent_nulls():
    """A record carrying a phase's facts with one silently null (no
    error key) is a DTP002 finding; the same null WITH the phase's
    error key is excused (the phase failed loudly)."""
    silent = _rec(lint_findings_total=None, lint_baselined_total=1,
                  lint_stale_suppressions=0, lint_rules=11,
                  lint_time_s=0.5)
    f, rows = pass_fact_coverage([silent])
    keys = [x.key for x in f]
    assert "facts:SYNTH:lint_phase:lint_findings_total" in keys
    assert any(r["phase"] == "lint_phase" and r["status"] == "VIOLATION"
               for r in rows)
    excused = _rec(lint_findings_total=None, lint_baselined_total=None,
                   lint_stale_suppressions=None, lint_rules=None,
                   lint_time_s=None, lint_error="RuntimeError: boom")
    f2, rows2 = pass_fact_coverage([excused])
    assert [x for x in f2 if x.key.startswith("facts:")] == []
    assert any(r["phase"] == "lint_phase" and r["status"] == "errored"
               for r in rows2)


def test_fact_coverage_catches_unwired_phase(tmp_path):
    """A bench.py that defines a covered phase but never calls it from
    _run_phases/degraded_record is a DTP002 finding for EACH missing
    wiring — the degraded-record contract is enforced statically."""
    stub = tmp_path / "bench.py"
    stub.write_text(
        "def lint_phase():\n"
        "    return {'lint_findings_total': 0}\n"
        "def _run_phases(out):\n"
        "    out.update(lint_phase())\n"
        "def degraded_record(e, i):\n"
        "    return {}\n")
    f, _ = pass_fact_coverage([], bench_path=str(stub))
    keys = {x.key for x in f}
    assert "phase:lint_phase:unwired:degraded_record" in keys
    assert "phase:lint_phase:unwired:_run_phases" not in keys
    # every OTHER covered phase is missing from this stub entirely
    assert "phase:perfcheck_phase:missing" in keys


# --------------------------------------------- DTP003 on synthetic data


def test_budgets_blown_unmeasured_and_record_sourced():
    """The three measurement sources: a pinned budget over its limit
    is BLOWN, a pinned budget with no measurement is unmeasured (both
    findings), a record-sourced budget reads the newest record
    carrying the key — and one no record carries yet is a note, not a
    failure (the fact was born after the last chip run)."""
    budgets = [
        {"name": "a_wall_s", "limit": 10.0, "source": "pinned",
         "measured": 12.0},
        {"name": "b_wall_s", "limit": 10.0, "source": "pinned",
         "measured": None},
        {"name": "c_pct", "limit": 2.0, "source": "record:ov_pct"},
        {"name": "d_pct", "limit": 2.0, "source": "record:unborn"},
        {"name": "e_wall_s", "limit": 5.0, "source": "live:e"},
    ]
    recs = [_rec("OLD", ov_pct=0.5), _rec("NEW", ov_pct=1.5)]
    f, rows = pass_budgets(budgets, recs, {"live:e": 1.0})
    by = {r["budget"]: r for r in rows}
    assert by["a_wall_s"]["status"] == "BLOWN"
    assert by["b_wall_s"]["status"] == "unmeasured"
    assert by["c_pct"]["status"] == "ok"
    assert by["c_pct"]["measured"] == 1.5 and "NEW" in by["c_pct"]["note"]
    assert by["d_pct"]["status"] == "unmeasured"
    assert by["e_wall_s"]["status"] == "ok"
    keys = {x.key for x in f}
    assert keys == {"budget:a_wall_s", "budget:b_wall_s:unmeasured"}


# ------------------------------------------------------- repo-wide gate


@pytest.fixture(scope="module")
def gate():
    return run_perf()


def test_repo_gate_prices_clean_inside_the_budget(gate):
    """THE gate: the full (mode x model) matrix prices chip-free with
    zero non-baselined findings, zero stale suppressions, every mode
    covered, inside the <15s matrix acceptance — and the suppressed
    set is exactly the checked-in baseline (which can only shrink)."""
    assert gate.findings == [], \
        "new findings:\n" + "\n".join(f.format() for f in gate.findings)
    assert gate.stale == [], gate.stale
    rep = gate.report
    assert rep["scenarios_proven"] == 13
    assert rep["modes_priced"] == ["dp", "ep", "pp", "ps", "sp", "tp",
                                   "zero1", "zero3"]
    assert rep["matrix_time_s"] < 15.0, rep["matrix_time_s"]
    assert rep["in_band_pct"] >= 50.0
    from tools.dttperf import load_baseline

    assert {(f.rule, f.key) for f in gate.baselined} == \
        {(e["rule"], e["key"]) for e in load_baseline()}


def test_repo_gate_covers_the_fact_and_budget_closures(gate):
    """The unfiltered run exercises all four passes: conformance rows
    for the real records, fact-coverage rows for every covered phase
    the corpus carries (the checked-in r01-r05 corpus is degraded
    TPU-unavailable records predating every analyzer phase, so the
    static closure — phases wired and emitting — carries the proof
    here; the synthetic tests above exercise the row side), and a
    status for every declared budget."""
    rep = gate.report
    assert any(r["status"] == "in_band" for r in rep["rate_checks"])
    assert any(r["status"] == "exempt" for r in rep["rate_checks"])
    covered = {r["phase"] for r in rep["fact_coverage"]}
    assert covered <= set(PHASE_FACTS)
    assert not any(r["status"] == "VIOLATION"
                   for r in rep["fact_coverage"])
    # every pinned budget carries a real measurement (a BLOWN one is
    # allowed only because the gate fixture already proved it
    # baselined with a reason — findings == [])
    assert all(b["status"] != "unmeasured" for b in rep["budgets"]
               if b["source"] == "pinned"), rep["budgets"]


def test_stale_suppression_fails_loudly(tmp_path):
    """A baseline entry whose finding no longer exists FAILS the run
    (the baseline only shrinks) — exercised with synthetic records so
    the dead DTP001 key is provably dead."""
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "DTP001", "key": "band:GONE:device_resident:dp:deep_cnn",
         "reason": "left over from a deleted record"},
    ]}))
    res = run_perf(str(base), records=[])
    assert not res.ok
    assert any("GONE" in s for s in res.stale)


def test_model_consumes_closure_holds():
    """Every predictor term's measured dual is really declared: the
    MODEL_CONSUMES rows with a phase point at keys that phase's
    PHASE_FACTS row owns (the repo gate then proves bench.py emits
    them)."""
    for term, phase, key in MODEL_CONSUMES:
        if phase is not None:
            assert key in PHASE_FACTS[phase]["keys"], (term, phase, key)


def test_rate_checks_and_exemptions_are_well_formed():
    """Table sanity the passes rest on: every banded check declares a
    real band and a full identity; every exemption states a reason;
    no phase sits in both PHASE_FACTS and PHASE_EXEMPT."""
    for chk in RATE_CHECKS:
        if chk.get("link_bound"):
            assert isinstance(chk["link_bound"], str) and chk["link_bound"]
        else:
            lo, hi = chk["band"]
            assert 0 < lo < hi
            assert chk["phase"] and chk["mode"] and chk["model"]
            assert chk["per_chip_batch"] > 0
    assert not set(PHASE_FACTS) & set(PHASE_EXEMPT)
    for phase, why in PHASE_EXEMPT.items():
        assert isinstance(why, str) and why.strip(), phase


# ------------------------------------------------------------------ CLI


def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.dttperf", *args],
        capture_output=True, text=True, cwd=cwd)


def test_cli_json_exits_zero_and_carries_the_report():
    p = _cli("--json")
    assert p.returncode == 0, p.stdout + p.stderr
    out = json.loads(p.stdout)
    assert out["ok"] and out["findings"] == []
    assert out["report"]["scenarios_proven"] == 13
    assert out["report"]["budgets"]


def test_cli_filtered_run_prices_the_subset():
    """--mode dp prices only the dp cells (bring-up ergonomics) and
    must not charge the whole-corpus passes' stale entries."""
    p = _cli("--mode", "dp", "--json")
    assert p.returncode == 0, p.stdout + p.stderr
    out = json.loads(p.stdout)
    assert out["report"]["modes_priced"] == ["dp"]
    assert out["report"]["rate_checks"] == []


def test_cli_exits_nonzero_on_stale_entry(tmp_path):
    """A dead suppression flips the exit code — scoped to a filtered
    run so the check stays cheap: the DTP000 entry names a cell that
    RAN clean, so the entry is provably stale."""
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "DTP000", "key": "build:dp/deep_cnn",
         "reason": "pretend this cell cannot price"},
    ]}))
    p = _cli("--mode", "dp", "--baseline", str(base))
    assert p.returncode == 1
    assert "STALE" in p.stdout
