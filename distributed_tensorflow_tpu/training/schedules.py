"""Learning-rate schedules for the compiled train step.

The reference trains with a fixed learning rate (``MNISTDist.py:30,149``);
schedules are a build extension (selected with ``--lr_schedule``). A
schedule is a plain callable ``step -> learning_rate`` evaluated INSIDE the
jitted step on the optimizer's own step count, so it traces once and
compiles into the same XLA executable as the update itself — no host-side
re-jitting per learning-rate change, which is the TPU-native reason
schedules live here rather than in the loop (a Python-side lr would make
every step a new compile).

All math uses ``jnp`` on a traced int32 step; every schedule is total
(defined for any step >= 0) and clamps rather than extrapolating past its
decay horizon.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def constant(learning_rate: float) -> Schedule:
    """The reference's behavior: one fixed rate (MNISTDist.py:30)."""
    lr = float(learning_rate)

    def schedule(step):
        return jnp.asarray(lr, jnp.float32)

    return schedule


def cosine_decay(learning_rate: float, decay_steps: int,
                 alpha: float = 0.0) -> Schedule:
    """Cosine annealing from ``learning_rate`` to ``alpha*learning_rate``
    over ``decay_steps``, then held at the floor."""
    lr = float(learning_rate)
    decay_steps = max(1, int(decay_steps))
    alpha = float(alpha)

    def schedule(step):
        frac = jnp.clip(step.astype(jnp.float32) / decay_steps, 0.0, 1.0)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        return lr * ((1.0 - alpha) * cos + alpha)

    return schedule


def linear_decay(learning_rate: float, decay_steps: int,
                 end_factor: float = 0.0) -> Schedule:
    """Linear ramp from ``learning_rate`` to ``end_factor*learning_rate``
    over ``decay_steps``, then held."""
    lr = float(learning_rate)
    decay_steps = max(1, int(decay_steps))
    end_factor = float(end_factor)

    def schedule(step):
        frac = jnp.clip(step.astype(jnp.float32) / decay_steps, 0.0, 1.0)
        return lr * (1.0 + (end_factor - 1.0) * frac)

    return schedule


def exponential_decay(learning_rate: float, decay_steps: int,
                      decay_rate: float, staircase: bool = False) -> Schedule:
    """``lr * decay_rate ** (step / decay_steps)`` — TF's classic
    ``tf.train.exponential_decay`` semantics, including the ``staircase``
    integer-division variant."""
    lr = float(learning_rate)
    decay_steps = max(1, int(decay_steps))
    decay_rate = float(decay_rate)

    def schedule(step):
        exp = step.astype(jnp.float32) / decay_steps
        if staircase:
            exp = jnp.floor(exp)
        return lr * decay_rate**exp

    return schedule


def with_warmup(schedule: Schedule, warmup_steps: int) -> Schedule:
    """Linear warmup from 0 to the base schedule over ``warmup_steps``; the
    wrapped schedule then continues, evaluated on the post-warmup step so
    its decay horizon starts where the ramp ends."""
    warmup_steps = int(warmup_steps)
    if warmup_steps <= 0:
        return schedule

    def warmed(step):
        ramp = (step.astype(jnp.float32) + 1.0) / warmup_steps
        after = schedule(jnp.maximum(step - warmup_steps, 0))
        return jnp.where(step < warmup_steps, ramp * schedule(jnp.zeros_like(step)), after)

    return warmed


_SCHEDULES = ("constant", "cosine", "linear", "exponential")


def get_schedule(name: str, learning_rate: float, decay_steps: int, *,
                 warmup_steps: int = 0, decay_rate: float = 0.96,
                 alpha: float = 0.0):
    """Build a schedule by name. Returns the plain float for the
    no-schedule case (``constant`` with no warmup) so the default
    optimizer state layouts stay byte-identical with the reference-parity
    path (see ``train_state.sgd``)."""
    if name not in _SCHEDULES:
        raise ValueError(
            f"unknown lr_schedule {name!r}; available: {list(_SCHEDULES)}"
        )
    if name == "constant" and warmup_steps <= 0:
        return float(learning_rate)
    if name == "constant":
        base = constant(learning_rate)
    elif name == "cosine":
        base = cosine_decay(learning_rate, decay_steps, alpha=alpha)
    elif name == "linear":
        base = linear_decay(learning_rate, decay_steps)
    else:
        base = exponential_decay(learning_rate, decay_steps, decay_rate)
    return with_warmup(base, warmup_steps)


def schedule_from_flags(FLAGS):
    """FLAGS -> float | Schedule for ``get_optimizer``. ``--decay_steps=0``
    (the default) decays over the full ``--training_iter`` budget: warmup
    steps come out of the horizon (``training_iter - warmup_steps``) so the
    schedule reaches its floor exactly at the end of the run."""
    name = getattr(FLAGS, "lr_schedule", "constant")
    warmup = getattr(FLAGS, "warmup_steps", 0)
    if name == "constant" and warmup <= 0:
        return float(FLAGS.learning_rate)  # no horizon needed
    decay_steps = getattr(FLAGS, "decay_steps", 0) \
        or max(1, FLAGS.training_iter - warmup)
    return get_schedule(
        name, FLAGS.learning_rate, decay_steps,
        warmup_steps=warmup, decay_rate=getattr(FLAGS, "decay_rate", 0.96),
    )
