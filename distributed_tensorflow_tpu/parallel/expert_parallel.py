"""Expert parallelism: MoE experts sharded over the "model" axis.

The fifth parallelism family (dp/tp/pp/sp/ep — SURVEY.md §2c lists the
last four ABSENT from the reference; the mesh's open "model" axis hosts
them all). Layout: batch over "data", EXPERTS over "model" — every
device holds its E/P experts' weights (the leading E axis of the moe
leaves), routes ALL of its data shard's tokens identically (router
replicated, routing deterministic), computes only the dispatch columns
of ITS experts, and one ``psum`` inside ``ops/moe.switch_moe`` combines
the partial outputs. No all-to-all needed at this formulation's scale:
token activations are replicated over the expert axis, so the psum IS
the combine.

Gradient derivation (cf. sequence_parallel's two and
pipeline_parallel's): the per-device loss is computed from the psum'd
combine, i.e. every expert-axis device holds a REPLICATED copy. Seeding
each copy with cotangent 1.0 would make psum's transpose (another psum)
deliver P-scaled cotangents to the expert paths — so the step
differentiates ``loss / P`` instead: the psum of the 1/P seeds is
exactly 1.0, expert-shard gradients come out as EXACT partials (no
cross-device reduction — they are different experts), and the
replicated leaves' per-device partials (each 1/P of its copy's share)
total under one ``psum`` over the axis. Then the usual pmean over
"data". Exactness is pinned the only way that matters: EP trajectory ==
the identical MoE model on one device (tests/test_moe.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from distributed_tensorflow_tpu.training.train_state import (
    TrainState,
    apply_updates,
)

_EXPERT_LEAVES = ("w1", "b1", "w2", "b2")


def _is_expert_leaf(path) -> bool:
    keys = tuple(getattr(p, "key", getattr(p, "idx", None)) for p in path)
    return "moe" in keys and keys[-1] in _EXPERT_LEAVES


def ep_clip_transform(max_norm: float):
    """Axis-correct global-norm clip for INSIDE the EP ``shard_map``
    step: expert-sharded leaves contribute their local squares as exact
    partials (distinct experts per device), replicated leaves (router,
    attention, embeddings, head) count once, the squared norm ``psum``s
    over the expert axis, and every device applies the SAME scale — so
    replicated leaves stay bit-identical across the axis (the
    shard-local-norm divergence the plain ``clip_by_global_norm`` had
    under --expert_parallel --clip_norm)."""
    from distributed_tensorflow_tpu.training.train_state import (
        clip_by_global_norm,
    )

    return clip_by_global_norm(max_norm, axis=MODEL_AXIS,
                               sharded_leaf=_is_expert_leaf)


def ep_state_specs(state: TrainState) -> TrainState:
    """PartitionSpec pytree: expert leaves split on their leading E axis
    over "model", everything else replicated; optimizer slots follow
    their params (structure-matched)."""
    def spec(path, _leaf):
        return P(MODEL_AXIS) if _is_expert_leaf(path) else P()

    pspecs = jax.tree_util.tree_map_with_path(spec, state.params)
    pstruct = jax.tree.structure(state.params)
    pleaves = jax.tree.leaves(pspecs, is_leaf=lambda v: isinstance(v, P))

    def opt_specs(entry):
        if jax.tree.structure(entry) == pstruct:
            return jax.tree.unflatten(pstruct, pleaves)
        if isinstance(entry, dict):
            return {k: opt_specs(v) for k, v in entry.items()}
        return jax.tree.map(lambda _: P(), entry)

    return TrainState(params=pspecs, opt_state=opt_specs(state.opt_state),
                      step=P(), rng=P(),
                      model_state=jax.tree.map(lambda _: P(),
                                               state.model_state))


def shard_state_ep(state: TrainState, mesh) -> TrainState:
    """Place a host-built MoE TrainState with the EP layout. The pytree
    LAYOUT is the standard one (checkpoints need no conversion —
    single-process EP leaves stay fully addressable)."""
    specs = ep_state_specs(state)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda v: isinstance(v, P))
    return jax.device_put(state, shardings)


def _ep_step_fn(model, optimizer, mesh, keep_prob: float, grad_transform):
    """Validate the EP configuration and build the raw per-shard step
    ``(state, (x, y)) -> (state, metrics)`` — the body both the host-fed
    wrapper (``make_ep_train_step``) and the device-resident sampler
    (``training/device_step.make_ep_device_train_step``) run inside
    ``shard_map``."""
    if getattr(model, "moe_axis", None) != MODEL_AXIS:
        raise ValueError(
            f"model.moe_axis must be {MODEL_AXIS!r} for the EP step "
            f"(got {getattr(model, 'moe_axis', None)!r})")
    ways = mesh.shape[MODEL_AXIS]
    if model.moe_experts % ways:
        raise ValueError(f"moe_experts={model.moe_experts} must divide "
                         f"over the {ways}-way expert axis")

    def per_shard(state: TrainState, batch):
        x, y = batch
        rng, sub = jax.random.split(state.rng)
        # dropout keys fold the DATA index only: expert-axis devices
        # must apply IDENTICAL masks (the replicated-activation
        # invariant the psum-combine rests on)
        sub = jax.random.fold_in(sub, lax.axis_index(DATA_AXIS))
        inv_p = 1.0 / ways

        def loss_fn(params):
            loss, metrics = model.loss_with_metrics(
                params, x, y, keep_prob=keep_prob, rng=sub, train=True)
            # the 1/P seed — see the module docstring's derivation
            return loss * inv_p, metrics

        grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)

        def reduce_g(path, g):
            if _is_expert_leaf(path):
                return g  # exact partial of a distinct shard
            return lax.psum(g, MODEL_AXIS)

        grads = jax.tree_util.tree_map_with_path(reduce_g, grads)
        grads = jax.tree.map(lambda g: lax.pmean(g, DATA_AXIS), grads)
        if grad_transform is not None:
            grads = grad_transform(grads)
        metrics = jax.tree.map(lambda v: lax.pmean(v, DATA_AXIS), metrics)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params, state.step)
        params = apply_updates(state.params, updates)
        return (TrainState(params, opt_state, state.step + 1, rng,
                           state.model_state), metrics)

    return per_shard


def make_ep_train_step(model, optimizer, mesh, keep_prob: float = 1.0,
                       donate: bool = True, grad_transform=None):
    """Compiled expert-parallel train step: (EP-layout state, staged
    batch) -> (state, metrics). ``model`` must carry
    ``moe_axis=MODEL_AXIS`` (its switch_moe then slices local experts
    and psums the combine) and ``moe_experts`` divisible by the axis."""
    per_shard = _ep_step_fn(model, optimizer, mesh, keep_prob,
                            grad_transform)
    data_spec = (P(DATA_AXIS, None), P(DATA_AXIS, None))
    cache: dict = {}

    def call(state, batch):
        fn = cache.get("fn")
        if fn is None:
            sharded = jax.shard_map(
                per_shard, mesh=mesh,
                in_specs=(ep_state_specs(state), data_spec),
                out_specs=(ep_state_specs(state), P()),
                check_vma=False)
            fn = cache["fn"] = jax.jit(
                sharded, donate_argnums=(0,) if donate else ())
        return fn(state, batch)

    return call


def make_ep_eval_step(model, mesh):
    """Dropout-off EP metrics (same layout; loss is the plain CE)."""
    if getattr(model, "moe_axis", None) != MODEL_AXIS:
        raise ValueError("model.moe_axis must be set for the EP eval")

    def per_shard(params, batch):
        x, y = batch
        _, metrics = model.loss_with_metrics(params, x, y, train=False)
        return jax.tree.map(lambda v: lax.pmean(v, DATA_AXIS), metrics)

    data_spec = (P(DATA_AXIS, None), P(DATA_AXIS, None))
    cache: dict = {}

    def eval_step(params, batch, model_state=()):
        fn = cache.get("fn")
        if fn is None:
            pspecs = jax.tree_util.tree_map_with_path(
                lambda path, _: (P(MODEL_AXIS) if _is_expert_leaf(path)
                                 else P()),
                params)
            fn = cache["fn"] = jax.jit(jax.shard_map(
                per_shard, mesh=mesh, in_specs=(pspecs, data_spec),
                out_specs=P(), check_vma=False))
        return fn(params, batch)

    return eval_step


def ep_comm_rows(act_bytes: int, n_moe_layers: int,
                 rep_grad_bytes: int = 0) -> list[dict]:
    """Static per-step combine bytes for expert parallelism — the comm
    ledger's EP rows. Every device routes identically and computes its
    own experts' tokens; ONE psum per MoE layer combines the partial
    outputs (~2|A| on the wire per the all-reduce convention), and the
    backward psums the cotangent the same way (psum's transpose IS a
    psum — the P-scaling trap the 1/P loss seed exists for).

    ``rep_grad_bytes`` prices the step's third model-axis collective:
    the REPLICATED leaves' (router/attention/embeddings/head) gradient
    partials — each device holds 1/P of its copy's share — total under
    one psum over the expert axis (~2x bytes). Unpriced before r18;
    ``tools/dttcheck`` proved the gap against the lowered jaxpr."""
    if n_moe_layers <= 0:
        return []
    per_pass = 2 * act_bytes * n_moe_layers
    rows = [
        {"collective": "psum(expert combine, forward)", "axis": "model",
         "bytes": per_pass,
         "note": f"{n_moe_layers} MoE layers x ~2|A| combine"},
        {"collective": "psum(expert combine, backward)", "axis": "model",
         "bytes": per_pass,
         "note": "the combine's transpose redistributes cotangents"},
    ]
    if rep_grad_bytes > 0:
        rows.append({
            "collective": "all_reduce(replicated-leaf grads)",
            "axis": "model", "bytes": 2 * rep_grad_bytes,
            "note": "non-expert leaves' per-device partials total "
                    "under one psum over the expert axis (~2x, "
                    "all-reduce convention)"})
    return rows
