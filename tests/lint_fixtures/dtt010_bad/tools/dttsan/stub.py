"""Fixture dttsan presence marker: the self-disable guard checks the
walk set contains tools/dttsan/ sources, not this file's content."""
