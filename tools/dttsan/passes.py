"""dttsan passes 2-4 — the concurrency model and the proofs over it.

The model is a TYPED, lock-annotated call graph built from the AST
(RacerD's compositional shape, scaled to this repo): every function and
method is scanned once, statement-ordered, carrying the set of lock
tokens held at each point (``with self._lock:`` scopes, ``with
self.stats.lock:`` cross-object scopes, manual ``cv.acquire()`` /
``release()`` discipline, module-level locks); every ``self.*``
attribute access is resolved to its OWNING class and recorded with the
locks held around it. Types come only from places the tree states them
(constructor assignments, parameter/return/local annotations,
module-level singletons) — never guessed, so a resolution miss degrades
to silence, not a false finding.

Reachability seeds from the inventory's roots (plus the ``main``
pseudo-root: everything the public API can run on the caller's thread)
and a fixpoint propagates HELD-AT-ENTRY contexts through call edges, so
a helper like the batcher's ``_expire_locked`` — which never takes the
cv itself but is only ever called with it held — is judged with the cv
in hand.

The passes:

- **SAN002 shared-state** — a ``self.*`` attribute reached from >= 2
  roots with a write outside ``__init__`` must have every write inside
  a scope holding one COMMON lock (lock-set intersection over all
  writes), and reads must hold it too. Unguarded reads of documented
  monotonic/ring fields are exemptible only via a baseline reason —
  the StreamingHistogram snapshot-vs-count and MetricsLogger dual-sink
  classes (PR 6's hand fixes), machine-checked.
- **SAN003 lock-order** — the acquisition graph (edge A->B when B is
  taken while A is held, across call edges) must be acyclic (the
  static dual of the r11 watchdog's deadlock classes); a plain Lock
  must never be re-acquired while already held on the same path
  (self-deadlock — the excepthook/atexit reentrancy class);
  condition-variable discipline: ``wait`` only inside a ``while``
  predicate loop, ``notify`` only while holding, no ``wait``/``sleep``
  /``join``/``result`` while holding any OTHER lock a serve/display
  path also takes.
- **SAN004 lifecycle** — daemon/join hygiene for every inventory
  thread/timer; restartable start methods must not reuse a set stop
  Event (the CheckpointWatcher class of bug); rings (the telemetry
  span ring, flight ring, reqtrace audit ring) must be append-BOUNDED
  (``deque(maxlen=...)``) and snapshot-CONSISTENT (iteration only
  under the ring's common lock); excepthook/atexit/signal handlers
  must not block.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tools._analysis_common import Finding
from tools.dttlint.rules import _dotted

LOCK_TYPES = {"Lock", "RLock"}
COND_TYPES = {"Condition"}
EVENT_TYPES = {"Event"}
#: method calls on attrs of these types are synchronization, not state
SAFE_TYPES = (LOCK_TYPES | COND_TYPES | EVENT_TYPES
              | {"Semaphore", "BoundedSemaphore", "Barrier", "local",
                 "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"})

#: mutating container/object methods — a call through an attr counts as
#: a WRITE to that attr (list/dict/deque/set surface)
MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
            "pop", "popleft", "popitem", "remove", "discard", "clear",
            "add", "update", "setdefault", "sort", "reverse", "put",
            "put_nowait", "rotate"}

MAX_CONTEXTS = 16  # held-at-entry variants kept per function


# --------------------------------------------------------------- model


@dataclass
class Access:
    owner: str          # "{rel}::{Class}" the attribute belongs to
    attr: str
    kind: str           # "read" | "write" | "iter"
    held: frozenset     # lock tokens held locally around the access
    fn: str             # funcid of the accessing function
    line: int
    in_init: bool       # inside the owner's own __init__


@dataclass
class FuncInfo:
    fnid: str
    rel: str
    qual: str
    line: int
    accesses: list = field(default_factory=list)
    calls: list = field(default_factory=list)      # (callee, held, line)
    acquires: list = field(default_factory=list)   # (held_before, tok, line)
    waits: list = field(default_factory=list)      # (tok, line, in_while, held)
    notifies: list = field(default_factory=list)   # (tok, line, held)
    blocking: list = field(default_factory=list)   # (desc, held, line)


@dataclass
class ClassInfo:
    rel: str
    name: str
    line: int
    methods: dict = field(default_factory=dict)      # name -> fnid
    attr_types: dict = field(default_factory=dict)   # attr -> ctor name
    attr_classes: dict = field(default_factory=dict)  # attr -> classkey
    ring_bounded: dict = field(default_factory=dict)  # deque attr -> bool
    ring_lines: dict = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.rel}::{self.name}"


@dataclass
class SanModel:
    classes: dict = field(default_factory=dict)   # classkey -> ClassInfo
    funcs: dict = field(default_factory=dict)     # fnid -> FuncInfo
    tok_kind: dict = field(default_factory=dict)  # token -> Lock/RLock/Condition/expr
    roots: list = field(default_factory=list)     # inventory roots
    root_funcs: dict = field(default_factory=dict)  # root key -> set(fnid)
    reach: dict = field(default_factory=dict)     # root key -> set(fnid)
    main_reach: set = field(default_factory=set)  # fnids on caller threads
    contexts: dict = field(default_factory=dict)  # fnid -> set(frozenset)

    def guaranteed_entry(self, fnid: str) -> frozenset:
        ctxs = self.contexts.get(fnid)
        if not ctxs:
            return frozenset()
        it = iter(ctxs)
        out = set(next(it))
        for c in it:
            out &= c
        return frozenset(out)

    def roots_of(self, fnid: str) -> set:
        out = {key for key, fns in self.reach.items() if fnid in fns}
        if fnid in self.main_reach:
            out.add("main")
        return out


def _module_rel(index, dotted: str) -> str | None:
    """'distributed_tensorflow_tpu.utils.telemetry' -> its index rel
    path (module file or package __init__), when in the walk set."""
    base = dotted.replace(".", "/")
    for cand in (f"{base}.py", f"{base}/__init__.py"):
        if cand in index.trees:
            return cand
    return None


class _ModuleTable:
    """Per-module symbol resolution: local classes/functions, imported
    names, module-level singletons and locks."""

    def __init__(self, index, rel: str, tree):
        self.rel = rel
        self.classes: dict[str, str] = {}    # local name -> classkey
        self.functions: set[str] = set()
        self.modules: dict[str, str] = {}    # alias -> rel
        self.imported_fns: dict[str, tuple] = {}   # name -> (rel, fname)
        self.singletons: dict[str, str] = {}  # NAME -> classkey
        self.locks: set[str] = set()          # module-level lock names
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = f"{rel}::{node.name}"
            elif isinstance(node, ast.FunctionDef):
                self.functions.add(node.name)
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = _module_rel(index, alias.name)
                    if target:
                        self.modules[alias.asname or alias.name] = target
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    bound = alias.asname or alias.name
                    sub = _module_rel(index,
                                      f"{node.module}.{alias.name}")
                    if sub:
                        self.modules[bound] = sub
                        continue
                    src = _module_rel(index, node.module)
                    if src is None:
                        continue
                    src_tree = index.trees[src]
                    for n in src_tree.body:
                        if isinstance(n, ast.ClassDef) and \
                                n.name == alias.name:
                            self.classes[bound] = f"{src}::{alias.name}"
                            break
                        if isinstance(n, ast.FunctionDef) and \
                                n.name == alias.name:
                            self.imported_fns[bound] = (src, alias.name)
                            break
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                name = node.targets[0].id
                ctor = _ctor_name(node.value)
                if ctor in LOCK_TYPES | COND_TYPES:
                    self.locks.add(name)
                elif ctor in self.classes:
                    self.singletons[name] = self.classes[ctor]


def _ctor_name(call: ast.Call) -> str:
    chain = _dotted(call.func) or ""
    return chain.rsplit(".", 1)[-1]


def _annotation_class(ann, table: _ModuleTable) -> str | None:
    """Resolve a parameter/return annotation to a repo classkey. Handles
    ``T``, ``"T"``, ``T | None``, ``Optional[T]``."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.BinOp):  # T | None
        return (_annotation_class(ann.left, table)
                or _annotation_class(ann.right, table))
    if isinstance(ann, ast.Subscript):  # Optional[T]
        return _annotation_class(ann.slice, table)
    name = _dotted(ann) if isinstance(ann, (ast.Name, ast.Attribute)) \
        else None
    if name:
        return table.classes.get(name.rsplit(".", 1)[-1])
    return None


# ------------------------------------------------------- class scanning


def _scan_class_shape(rel: str, node: ast.ClassDef,
                      table: _ModuleTable) -> ClassInfo:
    ci = ClassInfo(rel, node.name, node.lineno)
    for item in node.body:
        if isinstance(item, ast.FunctionDef):
            ci.methods[item.name] = f"{rel}::{node.name}.{item.name}"
        elif isinstance(item, ast.AnnAssign) and \
                isinstance(item.target, ast.Name):
            # dataclass fields: type from the annotation, or the
            # field(default_factory=...) constructor
            attr = item.target.id
            t = None
            if isinstance(item.value, ast.Call) and \
                    _ctor_name(item.value) == "field":
                for k in item.value.keywords:
                    if k.arg == "default_factory":
                        t = (_dotted(k.value) or "").rsplit(".", 1)[-1]
            if t is None and item.annotation is not None:
                t = (_dotted(item.annotation) or "").rsplit(".", 1)[-1]
            if t:
                ci.attr_types[attr] = t
    init = next((i for i in node.body if isinstance(i, ast.FunctionDef)
                 and i.name == "__init__"), None)
    if init is not None:
        # parameter annotations type the attrs they're stored into
        param_cls = {}
        args = init.args
        for a in list(args.args) + list(args.kwonlyargs):
            ck = _annotation_class(a.annotation, table)
            if ck:
                param_cls[a.arg] = ck
        for sub in ast.walk(init):
            tgt = val = None
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                tgt, val = sub.targets[0], sub.value
            elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                tgt, val = sub.target, sub.value
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            attr = tgt.attr
            if isinstance(val, ast.Call):
                ctor = _ctor_name(val)
                ci.attr_types.setdefault(attr, ctor)
                if ctor in table.classes:
                    ci.attr_classes[attr] = table.classes[ctor]
                if ctor == "deque":
                    ci.ring_bounded[attr] = any(
                        k.arg == "maxlen" for k in val.keywords)
                    ci.ring_lines[attr] = sub.lineno
            elif isinstance(val, ast.Name) and val.id in param_cls:
                ci.attr_classes[attr] = param_cls[val.id]
    return ci


# ----------------------------------------------------- function scanner


class _FnScanner:
    """One statement-ordered walk of a function body, tracking held
    locks (with-scopes + manual acquire/release), local types and lock
    aliases, and recording accesses / call edges / CV discipline."""

    def __init__(self, model: SanModel, table: _ModuleTable, rel: str,
                 qual: str, cls: ClassInfo | None, node,
                 types: dict | None = None):
        self.model = model
        self.table = table
        self.rel = rel
        self.cls = cls
        self.qual = qual
        self.fnid = f"{rel}::{qual}"
        self.info = FuncInfo(self.fnid, rel, qual, node.lineno)
        self.node = node
        self.types: dict[str, str] = dict(types or {})  # name -> classkey
        self.lock_alias: dict[str, tuple] = {}          # name -> token
        self.held: list[tuple] = []
        self.while_depth = 0
        self.in_init = (cls is not None
                        and qual == f"{cls.name}.__init__")
        args = node.args
        for a in list(args.args) + list(args.kwonlyargs):
            ck = _annotation_class(a.annotation, table)
            if ck:
                self.types[a.arg] = ck

    # -- resolution helpers

    def _class_of(self, expr) -> str | None:
        """classkey of an expression's value, or None."""
        if isinstance(expr, ast.Name):
            if expr.id == "self" and self.cls is not None:
                return self.cls.key
            if expr.id in self.types:
                return self.types[expr.id]
            if expr.id in self.table.singletons:
                return self.table.singletons[expr.id]
            return None
        if isinstance(expr, ast.Attribute):
            base = self._class_of(expr.value)
            if base and base in self.model.classes:
                return self.model.classes[base].attr_classes.get(
                    expr.attr)
            return None
        if isinstance(expr, ast.Call):
            chain = _dotted(expr.func) or ""
            name = chain.rsplit(".", 1)[-1]
            if name in self.table.classes:
                return self.table.classes[name]
            # typed factory: fn() -> T (return annotation)
            fnid = self._callee_fnid(expr)
            if fnid:
                ret = _RETURNS.get(fnid)
                if ret:
                    return ret
            return None
        return None

    def _lock_token(self, expr) -> tuple | None:
        """Resolve a with-item / receiver to a lock token, else None."""
        if isinstance(expr, ast.Name):
            if expr.id in self.lock_alias:
                return self.lock_alias[expr.id]
            if expr.id in self.table.locks:
                return (f"{self.rel}::<module>", expr.id)
            return None
        if isinstance(expr, ast.Attribute):
            base_cls = self._class_of(expr.value)
            if base_cls and base_cls in self.model.classes:
                ci = self.model.classes[base_cls]
                t = ci.attr_types.get(expr.attr)
                if t in LOCK_TYPES | COND_TYPES:
                    tok = (base_cls, expr.attr)
                    self.model.tok_kind.setdefault(tok, t)
                    return tok
            return None
        if isinstance(expr, ast.Call):
            # a lock-returning helper (per-key lock maps): token by
            # call text, so identical sites share a guard identity
            name = (_dotted(expr.func) or "").rsplit(".", 1)[-1]
            if "lock" in name.lower():
                tok = (f"{self.rel}::{self.qual}", ast.unparse(expr))
                self.model.tok_kind.setdefault(tok, "Lock")
                return tok
        return None

    def _attr_kind(self, expr) -> str | None:
        """ctor type of an attribute expr (self.X / obj.X), or None."""
        if not isinstance(expr, ast.Attribute):
            return None
        base = self._class_of(expr.value)
        if base and base in self.model.classes:
            return self.model.classes[base].attr_types.get(expr.attr)
        return None

    def _callee_fnid(self, call: ast.Call) -> str | None:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in self.table.imported_fns:
                src, fname = self.table.imported_fns[f.id]
                return f"{src}::{fname}"
            if f.id in self.table.functions:
                return f"{self.rel}::{f.id}"
            if f.id in self.table.classes:
                ck = self.table.classes[f.id]
                ci = self.model.classes.get(ck)
                if ci and "__init__" in ci.methods:
                    return ci.methods["__init__"]
            # a closure defined in an enclosing scope of this function
            for scope in _enclosing_quals(self.qual):
                cand = f"{self.rel}::{scope}.{f.id}" if scope \
                    else f"{self.rel}::{f.id}"
                if cand in _KNOWN_FNIDS:
                    return cand
            return None
        if isinstance(f, ast.Attribute):
            recv_cls = self._class_of(f.value)
            if recv_cls and recv_cls in self.model.classes:
                return self.model.classes[recv_cls].methods.get(f.attr)
            if isinstance(f.value, ast.Name) and \
                    f.value.id in self.table.modules:
                mod = self.table.modules[f.value.id]
                return f"{mod}::{f.attr}"
        return None

    # -- access recording

    def _record_attr(self, expr: ast.Attribute, kind: str,
                     line: int) -> None:
        base_cls = self._class_of(expr.value)
        if not base_cls:
            return
        ci = self.model.classes.get(base_cls)
        if ci is None:
            return
        attr = expr.attr
        t = ci.attr_types.get(attr)
        if t in SAFE_TYPES and kind != "write":
            return  # calls/reads of sync primitives are the guards
        if attr in ci.methods:
            # property / method read — a call edge, not a state access
            self.info.calls.append((ci.methods[attr],
                                    frozenset(self.held), line))
            return
        in_init = (self.in_init and self.cls is not None
                   and base_cls == self.cls.key)
        self.info.accesses.append(Access(
            base_cls, attr, kind, frozenset(self.held), self.fnid,
            line, in_init))

    # -- the walk

    def scan(self) -> FuncInfo:
        self._stmts(self.node.body)
        return self.info

    def _stmts(self, stmts) -> None:
        for s in stmts:
            self._stmt(s)

    def _stmt(self, s) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a closure: its body runs LATER on whoever calls it —
            # scanned as its own function with a fresh held set but the
            # enclosing type environment (captured params stay typed)
            _scan_function(self.model, self.table, self.rel,
                           f"{self.qual}.{s.name}", self.cls, s,
                           dict(self.types))
            return
        if isinstance(s, ast.With):
            toks = []
            for item in s.items:
                tok = self._lock_token(item.context_expr)
                if tok is not None:
                    self.info.acquires.append(
                        (frozenset(self.held), tok, s.lineno))
                    self.held.append(tok)
                    toks.append(tok)
                else:
                    self._expr(item.context_expr)
            self._stmts(s.body)
            for tok in toks:
                self.held.remove(tok)
            return
        if isinstance(s, (ast.If,)):
            self._expr(s.test)
            self._stmts(s.body)
            self._stmts(s.orelse)
            return
        if isinstance(s, ast.While):
            self._expr(s.test)
            self.while_depth += 1
            self._stmts(s.body)
            self.while_depth -= 1
            self._stmts(s.orelse)
            return
        if isinstance(s, ast.For):
            self._expr(s.target)
            self._iter_expr(s.iter)
            self.while_depth += 1
            self._stmts(s.body)
            self.while_depth -= 1
            self._stmts(s.orelse)
            return
        if isinstance(s, ast.Try):
            self._stmts(s.body)
            for h in s.handlers:
                self._stmts(h.body)
            self._stmts(s.orelse)
            self._stmts(s.finalbody)
            return
        if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._assign(s)
            return
        if isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, ast.Attribute):
                    self._record_attr(t, "write", s.lineno)
                elif isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Attribute):
                    self._record_attr(t.value, "write", s.lineno)
            return
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)

    def _assign(self, s) -> None:
        value = s.value
        if value is not None:
            self._expr(value)
        targets = (s.targets if isinstance(s, ast.Assign)
                   else [s.target])
        for t in targets:
            if isinstance(t, ast.Attribute):
                self._record_attr(t, "write", s.lineno)
                if isinstance(s, ast.AugAssign):
                    self._record_attr(t, "read", s.lineno)
            elif isinstance(t, ast.Subscript):
                self._expr(t.slice)
                if isinstance(t.value, ast.Attribute):
                    self._record_attr(t.value, "write", s.lineno)
                elif isinstance(t.value, ast.Name):
                    pass  # local container
            elif isinstance(t, ast.Name) and value is not None:
                # local typing: alias to a lock, or a typed value
                tok = self._lock_token(value)
                if tok is not None:
                    self.lock_alias[t.id] = tok
                else:
                    ck = self._class_of(value)
                    if ck:
                        self.types[t.id] = ck
                ann = getattr(s, "annotation", None)
                ck = _annotation_class(ann, self.table)
                if ck:
                    self.types[t.id] = ck
            elif isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    if isinstance(el, ast.Attribute):
                        self._record_attr(el, "write", s.lineno)

    def _iter_expr(self, expr) -> None:
        """A for-loop iterable: iterating an attribute IS a read that
        must be snapshot-consistent (kind 'iter')."""
        if isinstance(expr, ast.Attribute):
            self._record_attr(expr, "iter", expr.lineno)
        else:
            self._expr(expr)

    def _expr(self, e) -> None:
        if e is None:
            return
        if isinstance(e, ast.Call):
            self._call(e)
            return
        if isinstance(e, ast.Attribute):
            self._record_attr(e, "read", e.lineno)
            if not isinstance(e.value, ast.Name):
                self._expr(e.value)
            return
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.DictComp,
                          ast.GeneratorExp)):
            for gen in e.generators:
                self._iter_expr(gen.iter)
                for cond in gen.ifs:
                    self._expr(cond)
            for sub in ast.iter_child_nodes(e):
                if isinstance(sub, ast.expr) and sub not in [
                        g.iter for g in e.generators]:
                    self._expr(sub)
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._expr(child)

    def _call(self, call: ast.Call) -> None:
        chain = _dotted(call.func) or ""
        method = chain.rsplit(".", 1)[-1]
        handled_recv = False
        if isinstance(call.func, ast.Attribute):
            recv = call.func.value
            tok = self._lock_token(recv)
            kind = self.model.tok_kind.get(tok) if tok else None
            if tok is not None:
                handled_recv = True
                if method == "acquire":
                    self.info.acquires.append(
                        (frozenset(self.held), tok, call.lineno))
                    self.held.append(tok)
                elif method == "release":
                    if tok in self.held:
                        self.held.remove(tok)
                elif method == "wait" and kind in COND_TYPES:
                    self.info.waits.append(
                        (tok, call.lineno, self.while_depth > 0,
                         frozenset(self.held)))
                elif method in ("notify", "notify_all"):
                    self.info.notifies.append(
                        (tok, call.lineno, frozenset(self.held)))
            else:
                akind = self._attr_kind(recv)
                if akind in EVENT_TYPES and method == "wait" \
                        and self.held:
                    self.info.blocking.append(
                        (f"{_dotted(recv)}.wait", frozenset(self.held),
                         call.lineno))
                recv_cls = self._class_of(recv)
                if recv_cls and recv_cls in self.model.classes and \
                        method in self.model.classes[recv_cls].methods:
                    pass  # resolved call edge below
                elif isinstance(recv, ast.Attribute):
                    handled_recv = True
                    if akind in SAFE_TYPES:
                        pass  # sync-primitive op (put/get/set/clear)
                    elif method in MUTATORS:
                        self._record_attr(recv, "write", call.lineno)
                    else:
                        self._record_attr(recv, "read", call.lineno)
        # blocking calls while holding a lock
        if self.held:
            if chain == "time.sleep":
                self.info.blocking.append(
                    ("time.sleep", frozenset(self.held), call.lineno))
            elif method in ("join", "result") and \
                    isinstance(call.func, ast.Attribute) and \
                    not isinstance(call.func.value, ast.Constant) and \
                    not chain.startswith(("os.path", "posixpath")):
                self.info.blocking.append(
                    (chain or method, frozenset(self.held), call.lineno))
        callee = self._callee_fnid(call)
        if callee is not None:
            self.info.calls.append((callee, frozenset(self.held),
                                    call.lineno))
        if not handled_recv and isinstance(call.func, ast.Attribute):
            self._expr(call.func.value)
        for a in call.args:
            self._expr(a)
        for k in call.keywords:
            self._expr(k.value)


def _enclosing_quals(qual: str):
    parts = qual.split(".")
    for i in range(len(parts), -1, -1):
        yield ".".join(parts[:i])


# module-global scratch for one build (single-threaded, rebuilt per run)
_KNOWN_FNIDS: set = set()
_RETURNS: dict = {}


def _scan_function(model: SanModel, table: _ModuleTable, rel: str,
                   qual: str, cls: ClassInfo | None, node,
                   types: dict | None = None) -> None:
    sc = _FnScanner(model, table, rel, qual, cls, node, types)
    model.funcs[sc.fnid] = sc.scan()


# ------------------------------------------------------------ the build


def build_model(index, roots) -> SanModel:
    """Two passes over the walk set: shape (classes, attr types,
    signatures) then bodies (accesses under held locks, call edges),
    followed by reachability + held-at-entry fixpoints."""
    model = SanModel(roots=list(roots))
    tables = {rel: _ModuleTable(index, rel, tree)
              for rel, tree in index.trees.items()}
    # pass 1: shapes
    for rel, tree in index.trees.items():
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                ci = _scan_class_shape(rel, node, tables[rel])
                model.classes[ci.key] = ci
    _KNOWN_FNIDS.clear()
    _RETURNS.clear()
    # known fnids + return annotations (for typed factories)
    for rel, tree in index.trees.items():
        def collect(node, qual, rel=rel):
            for child in ast.iter_child_nodes(node):
                if isinstance(child,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{qual}.{child.name}" if qual else child.name
                    fnid = f"{rel}::{q}"
                    _KNOWN_FNIDS.add(fnid)
                    ck = _annotation_class(child.returns, tables[rel])
                    if ck:
                        _RETURNS[fnid] = ck
                    collect(child, q)
                elif isinstance(child, ast.ClassDef):
                    collect(child, f"{qual}.{child.name}"
                            if qual else child.name)
                else:
                    collect(child, qual)

        collect(tree, "")
    # pass 2: bodies (top-level functions and class methods; closures
    # recurse from inside the scanner)
    for rel, tree in index.trees.items():
        table = tables[rel]
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                _scan_function(model, table, rel, node.name, None, node)
            elif isinstance(node, ast.ClassDef):
                ci = model.classes[f"{rel}::{node.name}"]
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        _scan_function(model, table, rel,
                                       f"{node.name}.{item.name}", ci,
                                       item)
    _resolve_roots(model, index)
    _propagate(model)
    return model


def _resolve_roots(model: SanModel, index) -> None:
    """Map inventory roots to the function ids they execute."""
    for r in model.roots:
        fns: set = set()
        if r.kind == "crash":
            model.root_funcs[r.key] = fns
            continue
        if r.kind == "handler":
            ck = f"{r.path}::{r.target}"
            ci = model.classes.get(ck)
            if ci:
                fns |= set(ci.methods.values())
        elif r.target.startswith("self."):
            parts = r.target.split(".")
            cls_name = r.scope.split(".", 1)[0] if r.scope else ""
            ci = model.classes.get(f"{r.path}::{cls_name}")
            if ci and len(parts) == 2 and parts[1] in ci.methods:
                fns.add(ci.methods[parts[1]])
            elif ci and len(parts) == 3:
                inner = model.classes.get(
                    ci.attr_classes.get(parts[1], ""))
                if inner and parts[2] in inner.methods:
                    fns.add(inner.methods[parts[2]])
        else:
            for scope in _enclosing_quals(r.scope):
                cand = f"{r.path}::{scope}.{r.target}" if scope \
                    else f"{r.path}::{r.target}"
                if cand in model.funcs:
                    fns.add(cand)
                    break
        model.root_funcs[r.key] = fns


def seed_callbacks(model: SanModel, registry_entries) -> None:
    """callback registry entries: the named closure runs under the
    named thread root (the one edge kind the AST cannot see)."""
    for e in registry_entries:
        key = e.get("key", "")
        if not key.startswith("callback:"):
            continue
        parts = key.split(":", 2)
        if len(parts) != 3:
            continue
        fnid = f"{parts[1]}::{parts[2]}"
        if fnid in model.funcs and e.get("root") in model.root_funcs:
            model.root_funcs[e["root"]].add(fnid)


def _propagate(model: SanModel) -> None:
    """Reachability per root + the main pseudo-root, then the
    held-at-entry context fixpoint along call edges."""
    edges: dict[str, list] = {}
    for fnid, fi in model.funcs.items():
        edges[fnid] = [(c, h) for c, h, _l in fi.calls
                       if c in model.funcs]

    def closure(seed: set) -> set:
        out = set(seed)
        stack = list(seed)
        while stack:
            for callee, _h in edges.get(stack.pop(), ()):
                if callee not in out:
                    out.add(callee)
                    stack.append(callee)
        return out

    root_targets: set = set()
    for key, fns in model.root_funcs.items():
        model.reach[key] = closure(fns)
        root_targets |= fns
    main_seed = set()
    for fnid, fi in model.funcs.items():
        leaf = fi.qual.rsplit(".", 1)[-1]
        public = not leaf.startswith("_") or (
            leaf.startswith("__") and leaf.endswith("__"))
        if public and fnid not in root_targets:
            main_seed.add(fnid)
    model.main_reach = closure(main_seed)

    # held-at-entry contexts
    ctxs: dict[str, set] = {}
    work = []
    for key, fns in model.root_funcs.items():
        for fnid in fns:
            ctxs.setdefault(fnid, set()).add(frozenset())
            work.append(fnid)
    for fnid in main_seed:
        ctxs.setdefault(fnid, set()).add(frozenset())
        work.append(fnid)
    seen_push = 0
    while work and seen_push < 200000:
        fnid = work.pop()
        for callee, held in edges.get(fnid, ()):
            target = ctxs.setdefault(callee, set())
            changed = False
            for c in list(ctxs.get(fnid, {frozenset()})):
                ctx = c | held
                if ctx not in target:
                    if len(target) >= MAX_CONTEXTS:
                        # collapse: keep the intersection (the
                        # guaranteed part survives; variants drop)
                        inter = frozenset.intersection(*target, ctx)
                        target.clear()
                        target.add(inter)
                        changed = True
                        break
                    target.add(ctx)
                    changed = True
            if changed:
                work.append(callee)
                seen_push += 1
    model.contexts = ctxs


# --------------------------------------------------------------- SAN002


def _tok_str(tok) -> str:
    owner, name = tok
    return f"{owner.split('::')[-1]}.{name}"


def pass_shared_state(model: SanModel) -> list[Finding]:
    """SAN002: lock-set intersection per shared attribute (see module
    docstring). One finding per (class, attr, category) — the key is
    symbol-stable, the line points at the first offending site."""
    out: list[Finding] = []
    by_attr: dict[tuple, list] = {}
    for fi in model.funcs.values():
        for a in fi.accesses:
            by_attr.setdefault((a.owner, a.attr), []).append(a)
    for (owner, attr), accs in sorted(by_attr.items()):
        ci = model.classes.get(owner)
        if ci is None:
            continue
        roots: set = set()
        for a in accs:
            if not a.in_init:
                roots |= model.roots_of(a.fn)
        if len(roots) < 2:
            continue
        writes = [a for a in accs if a.kind == "write" and not a.in_init
                  and model.roots_of(a.fn)]
        if not writes:
            continue
        guaranteed = {}
        for a in accs:
            guaranteed[id(a)] = model.guaranteed_entry(a.fn) | a.held
        rel, cls = owner.split("::")
        base = f"{rel}:{cls}.{attr}"
        naked = [a for a in writes if not guaranteed[id(a)]]
        if naked:
            w = min(naked, key=lambda a: (a.fn, a.line))
            out.append(Finding(
                "SAN002", f"{base}:unguarded-write",
                w.fn.split("::")[0], w.line,
                f"{cls}.{attr} is written without any lock in "
                f"{w.fn.split('::')[-1]}() but is reached from "
                f"{len(roots)} concurrent roots "
                f"({', '.join(sorted({_root_short(r) for r in roots}))}) "
                f"— every mutating access needs one common lock"))
            continue
        common = frozenset.intersection(
            *[guaranteed[id(a)] for a in writes])
        if not common:
            w = writes[0]
            locksets = sorted({", ".join(sorted(map(_tok_str,
                                                    guaranteed[id(a)])))
                               for a in writes})
            out.append(Finding(
                "SAN002", f"{base}:mixed-locks",
                w.fn.split("::")[0], w.line,
                f"{cls}.{attr} is written under DIFFERENT locks "
                f"({' | '.join(locksets)}) from {len(roots)} roots — "
                f"the lock sets do not intersect, so two writers can "
                f"hold their own lock simultaneously"))
            continue
        bad_reads = [a for a in accs
                     if a.kind in ("read", "iter") and not a.in_init
                     and model.roots_of(a.fn)
                     and not (guaranteed[id(a)] & common)]
        if bad_reads:
            rd = min(bad_reads, key=lambda a: (a.fn, a.line))
            out.append(Finding(
                "SAN002", f"{base}:unguarded-read",
                rd.fn.split("::")[0], rd.line,
                f"{cls}.{attr} is read lock-free in "
                f"{rd.fn.split('::')[-1]}() while writers hold "
                f"{'/'.join(sorted(map(_tok_str, common)))} — a torn "
                f"or stale read; take the lock, or baseline with the "
                f"documented monotonic/ring reason"))
    return out


def _root_short(key: str) -> str:
    if key == "main":
        return "main"
    parts = key.split(":")
    return f"{parts[0]}:{parts[-1]}"


# --------------------------------------------------------------- SAN003


def pass_lock_order(model: SanModel) -> list[Finding]:
    out: list[Finding] = []
    # acquisition graph across call edges (entry contexts already fold
    # callers' held sets in)
    graph: dict[tuple, set] = {}
    sites: dict[tuple, tuple] = {}
    for fi in model.funcs.values():
        entries = model.contexts.get(fi.fnid, {frozenset()})
        for held_before, tok, line in fi.acquires:
            for ctx in entries:
                for h in ctx | held_before:
                    if h != tok:
                        graph.setdefault(h, set()).add(tok)
                        sites.setdefault((h, tok), (fi.rel, fi.qual,
                                                    line))
                # plain-Lock re-acquire on the same path = self-deadlock
                if tok in (ctx | held_before) and \
                        model.tok_kind.get(tok) in LOCK_TYPES:
                    key = f"double-acquire:{fi.rel}:{fi.qual}:" \
                          f"{_tok_str(tok)}"
                    if not any(f.key == key for f in out):
                        out.append(Finding(
                            "SAN003", key, fi.rel, line,
                            f"{_tok_str(tok)} is a plain Lock acquired "
                            f"in {fi.qual}() while a caller already "
                            f"holds it — self-deadlock (the excepthook/"
                            f"atexit reentrancy class); use RLock or "
                            f"move the call outside the locked region"))
    # cycles
    seen_cycles = set()
    for start in sorted(graph):
        path, on_path = [], set()

        def dfs(tok):
            if tok in on_path:
                cyc = tuple(path[path.index(tok):] + [tok])
                norm = frozenset(cyc)
                if norm not in seen_cycles:
                    seen_cycles.add(norm)
                    rel, qual, line = sites.get(
                        (cyc[0], cyc[1]), ("tools/dttsan", "?", 0))
                    out.append(Finding(
                        "SAN003",
                        "lock-cycle:" + "->".join(
                            sorted(_tok_str(t) for t in set(cyc))),
                        rel, line,
                        f"lock acquisition cycle "
                        f"{' -> '.join(_tok_str(t) for t in cyc)} — "
                        f"two threads taking the ends in opposite "
                        f"order deadlock"))
                return
            if tok not in graph:
                return
            path.append(tok)
            on_path.add(tok)
            for nxt in sorted(graph[tok]):
                dfs(nxt)
            path.pop()
            on_path.remove(tok)

        dfs(start)
    # CV discipline + blocking-while-holding
    for fi in model.funcs.values():
        g = model.guaranteed_entry(fi.fnid)
        for tok, line, in_while, _held in fi.waits:
            if not in_while:
                out.append(Finding(
                    "SAN003",
                    f"wait-no-while:{fi.rel}:{fi.qual}:{_tok_str(tok)}",
                    fi.rel, line,
                    f"{_tok_str(tok)}.wait() outside a while-predicate "
                    f"loop in {fi.qual}() — spurious wakeups and "
                    f"stolen notifies make a bare wait a missed-signal "
                    f"hang"))
        for tok, line, in_while, held in fi.waits:
            others = (g | held) - {tok}
            if others:
                out.append(Finding(
                    "SAN003",
                    f"wait-holding:{fi.rel}:{fi.qual}:{_tok_str(tok)}",
                    fi.rel, line,
                    f"{_tok_str(tok)}.wait() in {fi.qual}() releases "
                    f"only its own lock but "
                    f"{'/'.join(sorted(map(_tok_str, others)))} stays "
                    f"held through the wait — anyone needing that lock "
                    f"to produce the notify deadlocks"))
        for tok, line, held in fi.notifies:
            if tok not in (g | held):
                out.append(Finding(
                    "SAN003",
                    f"notify-unheld:{fi.rel}:{fi.qual}:{_tok_str(tok)}",
                    fi.rel, line,
                    f"{_tok_str(tok)}.notify() in {fi.qual}() without "
                    f"holding the condition — the waiter can miss the "
                    f"signal between its predicate check and wait"))
        for desc, held, line in fi.blocking:
            g_all = g | held
            if g_all:
                out.append(Finding(
                    "SAN003",
                    f"blocking-held:{fi.rel}:{fi.qual}:{desc}",
                    fi.rel, line,
                    f"blocking call {desc}() in {fi.qual}() while "
                    f"holding "
                    f"{'/'.join(sorted(map(_tok_str, g_all)))} — every "
                    f"other thread needing that lock stalls behind an "
                    f"unbounded wait"))
    return out


# --------------------------------------------------------------- SAN004


def pass_lifecycle(model: SanModel, index) -> list[Finding]:
    out: list[Finding] = []
    # (a) daemon/join hygiene per inventory thread/timer site
    for r in model.roots:
        if r.kind not in ("thread", "timer"):
            continue
        tree = index.trees.get(r.path)
        if tree is None:
            continue
        call = _call_at(tree, r.line)
        if call is None:
            continue
        daemon = any(k.arg == "daemon" and
                     isinstance(k.value, ast.Constant) and
                     k.value.value is True for k in call.keywords)
        if daemon:
            continue
        src = index.sources.get(r.path, "")
        release = ".cancel(" if r.kind == "timer" else ".join("
        setter = ".daemon = True"
        if release not in src and setter not in src:
            out.append(Finding(
                "SAN004", f"thread-hygiene:{r.key}", r.path, r.line,
                f"{r.kind} {r.target!r} is neither daemon=True nor "
                f"ever {release.strip('.(')}ed — a non-daemon thread "
                f"without a join outlives the run (hangs interpreter "
                f"shutdown)"))
    # (b) stop-Event reuse across restart (the CheckpointWatcher class)
    for ci in model.classes.values():
        events = {a for a, t in ci.attr_types.items()
                  if t in EVENT_TYPES}
        if not events:
            continue
        starters = _thread_starters(model, ci)
        for meth, target_fnid, line in starters:
            if meth == "__init__":
                continue  # one-shot construction cannot restart
            tgt = model.funcs.get(target_fnid)
            if tgt is None:
                continue
            src_tgt = _fn_source(index, tgt)
            loop_events = {e for e in events
                           if f"self.{e}.wait" in src_tgt
                           or f"self.{e}.is_set" in src_tgt}
            if not loop_events:
                continue
            start_src = _fn_source(index, model.funcs.get(
                ci.methods.get(meth, ""), None))
            set_elsewhere = any(
                f"self.{e}.set(" in index.sources.get(ci.rel, "")
                for e in loop_events)
            # a restart may either clear() the event or re-point the
            # attr at a FRESH one (the handed-to-the-thread pattern)
            clears = any(f"self.{e}.clear(" in (start_src or "")
                         or f"self.{e} =" in (start_src or "")
                         for e in loop_events)
            if set_elsewhere and not clears:
                out.append(Finding(
                    "SAN004",
                    f"stop-reuse:{ci.rel}:{ci.name}.{meth}",
                    ci.rel, line,
                    f"{ci.name}.{meth}() can restart the worker thread "
                    f"but never clear()s the stop Event its loop "
                    f"conditions on — start() after close() launches a "
                    f"thread that exits immediately (a silently dead "
                    f"worker)"))
    # (c) rings append-bounded
    for ci in model.classes.values():
        for attr, bounded in ci.ring_bounded.items():
            if bounded:
                continue
            appended = any(
                a.attr == attr and a.owner == ci.key and
                a.kind == "write" and not a.in_init and
                model.roots_of(a.fn)
                for fi in model.funcs.values() for a in fi.accesses)
            if appended:
                out.append(Finding(
                    "SAN004",
                    f"ring-unbounded:{ci.rel}:{ci.name}.{attr}",
                    ci.rel, ci.ring_lines.get(attr, ci.line),
                    f"{ci.name}.{attr} is a deque ring appended at "
                    f"runtime but constructed WITHOUT maxlen — a "
                    f"monitoring/audit ring must be append-bounded by "
                    f"construction, not by pruning logic someone can "
                    f"break"))
    # (d) hooks must not block (excepthook/atexit/signal run inside
    # arbitrary interpreter states)
    for r in model.roots:
        if r.kind not in ("excepthook", "atexit", "signal"):
            continue
        for fnid in model.reach.get(r.key, ()):
            fi = model.funcs[fnid]
            for desc, _held, line in fi.blocking:
                out.append(Finding(
                    "SAN004", f"hook-blocks:{r.key}:{desc}",
                    fi.rel, line,
                    f"{r.kind} handler path {fi.qual}() makes blocking "
                    f"call {desc}() — a crash/shutdown hook must not "
                    f"wait on other threads (they may hold the very "
                    f"locks the interpreter is tearing down)"))
            for tok, line, in_while, _h in fi.waits:
                out.append(Finding(
                    "SAN004", f"hook-blocks:{r.key}:wait",
                    fi.rel, line,
                    f"{r.kind} handler path {fi.qual}() waits on "
                    f"{_tok_str(tok)} — a crash/shutdown hook must "
                    f"not block"))
    return out


def _thread_starters(model: SanModel, ci: ClassInfo):
    """(method, target_fnid, line) for every Thread construction inside
    a method of ``ci`` whose target is a self-method."""
    out = []
    for r in model.roots:
        if r.kind != "thread" or r.path != ci.rel:
            continue
        scope_cls = r.scope.split(".", 1)[0] if r.scope else ""
        if scope_cls != ci.name or "." not in r.scope:
            continue
        meth = r.scope.split(".", 1)[1].split(".", 1)[0]
        if r.target.startswith("self."):
            tname = r.target.split(".")[1]
            if tname in ci.methods:
                out.append((meth, ci.methods[tname], r.line))
    return out


def _call_at(tree, line: int) -> ast.Call | None:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and node.lineno == line:
            chain = _dotted(node.func) or ""
            if chain.rsplit(".", 1)[-1] in ("Thread", "Timer"):
                return node
    return None


def _fn_source(index, fi) -> str:
    if fi is None:
        return ""
    src = index.sources.get(fi.rel, "")
    if not src:
        return ""
    lines = src.splitlines()
    node = None
    tree = index.trees.get(fi.rel)
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                n.lineno == fi.line:
            node = n
            break
    if node is None:
        return ""
    return "\n".join(lines[node.lineno - 1:(node.end_lineno or
                                            node.lineno)])
