"""Autoregressive decode for ``models/transformer.TransformerLM`` with a
preallocated device-resident KV cache — bitwise-consistent with
full-prefix recompute.

The training stack computes every position's attention from scratch each
forward; serving must emit one token at a time, and recomputing the whole
prefix per token is O(S^2) work per sequence. The classic fix is the KV
cache: each block's key/value projections are computed ONCE per position
and kept in device memory; a decode step projects only the newest token
and attends its single query against the cached keys.

The consistency contract here is stronger than "numerically close": a
decode step's logits are **bitwise identical** to the same position's row
of a full-prefix forward pass (asserted for 64+ generated tokens by
tests/test_serving.py). Three design choices make that hold:

- **One block implementation.** The prefill runs the model's own
  ``_attn_half_kv``/``_mlp_half`` (models/transformer.py) — the exact
  functions the training forward composes — capturing each block's (k, v)
  as a side output. The decode step re-expresses the same ops for a
  single position (same einsum strings, same dtype-cast order, same
  scale placement as ``ops.attention.multi_head_attention``).
- **Fixed cache capacity = ``model.seq_len``.** Every attention row is a
  softmax over exactly ``seq_len`` scores with future positions masked to
  ``-inf`` (giving exact zeros after exp) — the decode step's masked row
  has the same length, the same mask pattern, and therefore the same
  reduction shapes as the corresponding row of the full forward. Masked
  cache entries multiply probabilities that are exactly ``0.0``, so the
  pad/garbage content beyond the current position cannot perturb a bit.
  (This also matches the model's own contract: ``apply`` requires
  ``S == seq_len`` — the positional table broadcasts, it is not sliced.)
- **Per-position ops only elsewhere.** Embedding rows, layernorm, and the
  residual adds are elementwise per position, so the single-position step
  computes literally the same scalar expressions.

No MoE / sequence-parallel support (those models route per-batch state
through collectives); ``attn_block`` models decode fine — the cache step
computes the dense triangle the blockwise form equals.
"""

from __future__ import annotations

import time

import numpy as np

from distributed_tensorflow_tpu.models.transformer import (
    TransformerLM,
    _attn_half_kv,
    _layernorm,
    _mlp_half,
)
from distributed_tensorflow_tpu.ops import nn
from distributed_tensorflow_tpu.ops.attention import multi_head_attention
from distributed_tensorflow_tpu.serving import reqtrace


def check_decodable(model) -> None:
    """Loud rejection of model configs the KV-cache step cannot serve."""
    if not isinstance(model, TransformerLM):
        raise ValueError(f"KV-cache decode serves TransformerLM; got "
                         f"{type(model).__name__}")
    if model.seq_axis is not None:
        raise ValueError("KV-cache decode does not run inside the "
                         "sequence-parallel shard_map step; serve with "
                         "seq_axis=None")
    if model.moe_experts:
        raise ValueError("KV-cache decode does not support MoE blocks yet")


def make_prefill(model, jit: bool = True):
    """(params, tokens (B, C) int32) -> (logits (B, C, V) f32, cache).

    ``C`` must equal ``model.seq_len`` (the cache capacity); tokens beyond
    the real prompt are pad — their cache entries are overwritten as
    decode proceeds and their scores are causally masked meanwhile.
    ``cache`` is a tuple of per-block (k, v) pairs, each (B, C, H, Dh).
    The computation is the model's own dense-causal forward (one shared
    block implementation) with the head applied to every position, so
    ``logits[:, t]`` is bitwise the full-recompute answer at ``t``.
    """
    check_decodable(model)
    import jax
    import jax.numpy as jnp

    cd = model.compute_dtype
    attn = lambda q, k, v: multi_head_attention(q, k, v, causal=True)

    def prefill(params, tokens):
        h = jnp.take(params["tok"], tokens, axis=0)
        h = h + params["pos"].astype(h.dtype)
        if cd is not None:
            h = h.astype(cd)
        cache = []
        for blk in params["blocks"]:
            h, k, v = _attn_half_kv(h, blk, attn, cd)
            h = _mlp_half(h, blk, cd)
            cache.append((k, v))
        h = _layernorm(h, params["ln_f"]["g"], params["ln_f"]["b"])
        logits = nn.dense(h, params["head"]["w"], params["head"]["b"],
                          compute_dtype=cd)
        return logits.astype(jnp.float32), tuple(cache)

    return jax.jit(prefill) if jit else prefill


def make_decode_step(model, jit: bool = True):
    """(params, cache, tok (B,) int32, t int32) -> (logits (B, V) f32,
    cache) — one KV-cache decode tick at absolute position ``t``.

    Writes the new token's (k, v) into every block's cache at ``t``, then
    attends the single query row against the full cache with positions
    ``> t`` masked to ``-inf`` — the same masked row, shapes included,
    that the full forward computes at position ``t``. The cache is
    DONATED under jit so the preallocated buffers are updated in place
    dispatch-to-dispatch."""
    check_decodable(model)
    import jax
    import jax.numpy as jnp
    from jax import lax

    cd = model.compute_dtype
    capacity = model.seq_len
    dh = model.d_model // model.num_heads

    def step(params, cache, tok, t):
        h = jnp.take(params["tok"], tok[:, None], axis=0)  # (B, 1, d)
        pos_t = lax.dynamic_slice_in_dim(params["pos"], t, 1, axis=0)
        h = h + pos_t.astype(h.dtype)
        if cd is not None:
            h = h.astype(cd)
        # row t of the causal mask, full cache width — same pattern as
        # multi_head_attention's arange(sk) <= arange(sq) triangle
        mask = jnp.arange(capacity)[None, :] <= t
        new_cache = []
        for blk, (k_cache, v_cache) in zip(params["blocks"], cache):
            y = _layernorm(h, blk["ln1_g"], blk["ln1_b"])
            qkv = jnp.einsum("bsd,dthe->tbshe", y,
                             blk["qkv"].astype(y.dtype))
            k_cache = lax.dynamic_update_slice_in_dim(
                k_cache, qkv[1].astype(k_cache.dtype), t, axis=1)
            v_cache = lax.dynamic_update_slice_in_dim(
                v_cache, qkv[2].astype(v_cache.dtype), t, axis=1)
            s = jnp.einsum("bqhd,bkhd->bhqk", qkv[0],
                           k_cache).astype(jnp.float32)
            s = s / jnp.sqrt(jnp.float32(dh))
            s = jnp.where(mask, s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1)
            # the p @ V contraction runs at q-width 2 (row duplicated,
            # row 0 kept): a width-1 dot takes the GEMV kernel, whose
            # k-accumulation order differs from the GEMM the full
            # forward uses — the one op where shape specialization
            # breaks bitwise parity (1-ulp drift, measured). Width >= 2
            # selects the GEMM kernel, whose per-row reduction order is
            # independent of the row count.
            p2 = jnp.concatenate([p, p], axis=2).astype(qkv[0].dtype)
            a = jnp.einsum("bhqk,bkhd->bqhd", p2, v_cache)[:, :1]
            a = a.reshape(*a.shape[:2], -1)  # (B, 1, H*Dh)
            h = h + nn.dense(a, blk["proj"], compute_dtype=cd)
            h = _mlp_half(h, blk, cd)
            new_cache.append((k_cache, v_cache))
        h = _layernorm(h, params["ln_f"]["g"], params["ln_f"]["b"])
        logits = nn.dense(h, params["head"]["w"], params["head"]["b"],
                          compute_dtype=cd)
        return logits.astype(jnp.float32)[:, 0], tuple(new_cache)

    if jit:
        return jax.jit(step, donate_argnums=(1,))
    return step


def make_slot_pools(model, page_size: int, num_pages: int):
    """Device KV pools for the paged slot step: a tuple, one
    ``(k_pool, v_pool)`` pair per block, each
    ``(num_pages + 1, page_size, H, Dh)`` zeros in the cache dtype.

    Row 0 is the reserved SCRATCH page: a free slot's page-table row is
    all zeros, so its (masked, discarded) reads and its writes land
    here instead of clobbering a live request's pages. One extra row
    buys a branch-free step — no "is this slot live" select inside the
    traced computation."""
    check_decodable(model)
    import jax.numpy as jnp

    cd = model.compute_dtype
    dh = model.d_model // model.num_heads
    dtype = cd if cd is not None else jnp.float32
    shape = (num_pages + 1, page_size, model.num_heads, dh)
    return tuple((jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                 for _ in range(model.num_blocks))


def make_slot_step(model, page_size: int, jit: bool = True):
    """(params, pools, page_table (S, P) i32, tok (S,) i32, t (S,) i32)
    -> (logits (S, V) f32, pools) — one iteration-level decode tick over
    ``S`` independent slots against a PAGED KV cache.

    The continuous scheduler's single traced computation (r21). Each
    slot ``i`` feeds token ``tok[i]`` at its own absolute position
    ``t[i]``; ``page_table[i, j]`` names the physical pool row backing
    logical page ``j`` of slot ``i`` (0 = the scratch page for
    free/unmapped entries — see ``make_slot_pools``). The step scatters
    the new (k, v) into ``pools[block][t // page_size][t % page_size]``
    and attends each slot's query against its GATHERED dense view
    ``pool[page_table].reshape(S, capacity, H, Dh)``.

    Bitwise contract: the body is ``make_decode_step`` verbatim — same
    einsum strings, same dtype-cast order, same scale placement, same
    width->=2 p@V trick — with the batch's shared scalar ``t`` widened to
    a per-slot vector and the dense cache update replaced by the
    page scatter/gather (index plumbing: gathers and scatters move
    bits, they do not do arithmetic). Free slots run the same ops
    against scratch garbage: every score beyond ``t[i]`` is masked to
    ``-inf`` pre-softmax (exact 0.0 probability) and a free slot's
    logits row is discarded by the scheduler, so garbage cannot reach a
    live request. ``S >= 2`` keeps every contraction on the GEMM (not
    GEMV) kernel, whose per-row reduction order is row-count
    independent — the property the whole-batch pin already relies on.

    Shapes are static (slot count, page table, pools), so continuous
    mode adds exactly ONE traced signature however requests come and
    go. Pools are DONATED under jit — updated in place
    dispatch-to-dispatch."""
    check_decodable(model)
    import jax
    import jax.numpy as jnp

    cd = model.compute_dtype
    capacity = model.seq_len
    dh = model.d_model // model.num_heads
    if page_size < 1 or capacity % page_size:
        raise ValueError(
            f"page_size ({page_size}) must be >= 1 and divide the cache "
            f"capacity ({capacity}) so a slot's logical pages tile it "
            f"exactly")

    def step(params, pools, page_table, tok, t):
        s_count = tok.shape[0]
        h = jnp.take(params["tok"], tok[:, None], axis=0)  # (S, 1, d)
        pos_t = jnp.take(params["pos"], t, axis=0)[:, None, :]
        h = h + pos_t.astype(h.dtype)
        if cd is not None:
            h = h.astype(cd)
        # row t[i] of the causal mask per slot, full cache width
        mask = (jnp.arange(capacity)[None, :] <= t[:, None])[:, None, None, :]
        rows = jnp.arange(s_count)
        dest = page_table[rows, t // page_size]  # (S,) physical pages
        offset = t % page_size
        new_pools = []
        for blk, (k_pool, v_pool) in zip(params["blocks"], pools):
            y = _layernorm(h, blk["ln1_g"], blk["ln1_b"])
            qkv = jnp.einsum("bsd,dthe->tbshe", y,
                             blk["qkv"].astype(y.dtype))
            k_pool = k_pool.at[dest, offset].set(
                qkv[1][:, 0].astype(k_pool.dtype))
            v_pool = v_pool.at[dest, offset].set(
                qkv[2][:, 0].astype(v_pool.dtype))
            k_cache = k_pool[page_table].reshape(
                s_count, capacity, model.num_heads, dh)
            v_cache = v_pool[page_table].reshape(
                s_count, capacity, model.num_heads, dh)
            s = jnp.einsum("bqhd,bkhd->bhqk", qkv[0],
                           k_cache).astype(jnp.float32)
            s = s / jnp.sqrt(jnp.float32(dh))
            s = jnp.where(mask, s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1)
            # width-2 p @ V — see the GEMV note in make_decode_step
            p2 = jnp.concatenate([p, p], axis=2).astype(qkv[0].dtype)
            a = jnp.einsum("bhqk,bkhd->bqhd", p2, v_cache)[:, :1]
            a = a.reshape(*a.shape[:2], -1)  # (S, 1, H*Dh)
            h = h + nn.dense(a, blk["proj"], compute_dtype=cd)
            h = _mlp_half(h, blk, cd)
            new_pools.append((k_pool, v_pool))
        h = _layernorm(h, params["ln_f"]["g"], params["ln_f"]["b"])
        logits = nn.dense(h, params["head"]["w"], params["head"]["b"],
                          compute_dtype=cd)
        return logits.astype(jnp.float32)[:, 0], tuple(new_pools)

    if jit:
        return jax.jit(step, donate_argnums=(1,))
    return step


def generate(model, params, prompts, max_new_tokens: int, *,
             temperature: float = 0.0, rng=None,
             prefill_fn=None, step_fn=None):
    """Greedy (``temperature == 0``) or temperature-sampled decode.

    ``prompts``: int array (B, P) with 1 <= P and
    P + max_new_tokens <= model.seq_len (the cache capacity — serving
    stays inside the trained context window). Returns
    ``{"tokens": (B, P + N), "logits": (B, N, V)}`` — ``logits[:, i]``
    is the distribution the (P + i)'th token was drawn from, each row
    bitwise equal to the full-prefix recompute at that position.

    ``prefill_fn``/``step_fn`` let the engine pass its per-bucket cached
    jitted functions; omitted, fresh jitted ones are built (fine for
    one-off library use, wasteful per request)."""
    import jax
    import jax.numpy as jnp

    check_decodable(model)
    prompts = np.asarray(prompts)
    if prompts.ndim != 2 or prompts.shape[1] < 1:
        raise ValueError(f"prompts must be (B, P>=1); got {prompts.shape}")
    if prompts.size and (prompts.min() < 0
                         or prompts.max() >= model.vocab_size):
        # jnp.take would silently CLAMP an out-of-vocab id to the edge
        # embedding — a tokenizer/vocab mismatch must be a loud 400,
        # not a 200 with wrong tokens
        raise ValueError(
            f"prompt ids must be in [0, {model.vocab_size}); got range "
            f"[{prompts.min()}, {prompts.max()}]")
    b, p = prompts.shape
    n = int(max_new_tokens)
    if n < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {n}")
    capacity = model.seq_len
    if p + n > capacity:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({n}) exceeds the model's "
            f"context window / cache capacity ({capacity})")
    if prefill_fn is None:
        prefill_fn = make_prefill(model)
    if step_fn is None:
        step_fn = make_decode_step(model)
    if temperature > 0.0 and rng is None:
        rng = jax.random.PRNGKey(0)

    # a single sequence is served as a row-duplicated pair: at batch 1
    # the per-token dense layers take the GEMV kernel (see the q-width
    # note in make_decode_step) and bitwise parity with the batched
    # full forward is lost
    b_real = b
    if b == 1:
        prompts = np.concatenate([prompts, prompts], axis=0)
        b = 2

    padded = np.zeros((b, capacity), dtype=np.int32)
    padded[:, :p] = prompts
    # request plane: the prompt pass (prefill_fn dispatch + the first
    # logits readback) is the "prefill" phase; the autoregressive loop
    # below is "decode" with one tick per generated token
    t0 = time.perf_counter()
    logits_all, cache = prefill_fn(params, jnp.asarray(padded))
    step_logits = np.asarray(logits_all[:, p - 1])
    reqtrace.note_phase("prefill", time.perf_counter() - t0)

    t0 = time.perf_counter()
    out_tokens = [prompts.astype(np.int32)]
    out_logits = []
    for i in range(n):
        out_logits.append(step_logits)
        if temperature > 0.0:
            key = jax.random.fold_in(rng, i)
            tok = np.asarray(jax.random.categorical(
                key, jnp.asarray(step_logits) / temperature, axis=-1),
                dtype=np.int32)
        else:
            tok = step_logits.argmax(axis=-1).astype(np.int32)
        out_tokens.append(tok[:, None])
        if i + 1 < n:
            step_logits, cache = step_fn(params, cache,
                                         jnp.asarray(tok),
                                         jnp.int32(p + i))
            step_logits = np.asarray(step_logits)
    reqtrace.note_phase("decode", time.perf_counter() - t0, ticks=n)
    return {"tokens": np.concatenate(out_tokens, axis=1)[:b_real],
            "logits": np.stack(out_logits, axis=1)[:b_real]}
