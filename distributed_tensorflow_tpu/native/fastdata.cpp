// Native host-side data plane.
//
// The reference leans on TensorFlow's C++ runtime for everything host-side
// (the tutorial DataSet feeding sess.run, MNISTDist.py:167,178-188). The
// TPU rebuild keeps the device plane in XLA, and puts the host data plane
// here: IDX decoding and batch assembly (gather + u8->f32 normalize +
// one-hot) in C++, multithreaded, bound via ctypes (build: `make` in this
// directory or the auto-build in __init__.py). A pure-NumPy fallback with
// identical semantics lives in data/datasets.py.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Parse an IDX header. Returns the dtype code (0x08=u8 ...) or -1 on error.
// Writes ndim and up to 8 dims. The payload starts at *payload_off.
int idx_header(const char* path, int* ndim, int64_t* dims, int64_t* payload_off) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    unsigned char magic[4];
    if (std::fread(magic, 1, 4, f) != 4 || magic[0] != 0 || magic[1] != 0) {
        std::fclose(f);
        return -1;
    }
    int dtype = magic[2];
    int nd = magic[3];
    if (nd > 8) { std::fclose(f); return -1; }
    *ndim = nd;
    for (int i = 0; i < nd; i++) {
        unsigned char b[4];
        if (std::fread(b, 1, 4, f) != 4) { std::fclose(f); return -1; }
        dims[i] = (int64_t(b[0]) << 24) | (int64_t(b[1]) << 16) |
                  (int64_t(b[2]) << 8) | int64_t(b[3]);
    }
    *payload_off = 4 + 4 * nd;
    std::fclose(f);
    return dtype;
}

// Read n bytes of u8 payload at offset into out. Returns bytes read.
int64_t idx_read_u8(const char* path, int64_t offset, uint8_t* out, int64_t n) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    if (std::fseek(f, long(offset), SEEK_SET) != 0) { std::fclose(f); return -1; }
    int64_t got = int64_t(std::fread(out, 1, size_t(n), f));
    std::fclose(f);
    return got;
}

// Batch assembly: out[i,:] = images[idx[i],:] / 255.0f, multithreaded.
void gather_normalize(const uint8_t* images, int64_t pixels,
                      const int64_t* idx, int64_t batch, float* out,
                      int threads) {
    if (threads < 1) threads = 1;
    auto work = [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; i++) {
            const uint8_t* src = images + idx[i] * pixels;
            float* dst = out + i * pixels;
            for (int64_t p = 0; p < pixels; p++) dst[p] = float(src[p]) * (1.0f / 255.0f);
        }
    };
    if (threads == 1 || batch < 64) {
        work(0, batch);
        return;
    }
    std::vector<std::thread> pool;
    int64_t chunk = (batch + threads - 1) / threads;
    for (int t = 0; t < threads; t++) {
        int64_t lo = t * chunk, hi = lo + chunk < batch ? lo + chunk : batch;
        if (lo >= hi) break;
        pool.emplace_back(work, lo, hi);
    }
    for (auto& th : pool) th.join();
}

// One-hot: out[i, labels[idx[i]]] = 1.0f (out must be zeroed by caller).
void onehot_gather(const int64_t* labels, const int64_t* idx, int64_t batch,
                   int64_t classes, float* out) {
    for (int64_t i = 0; i < batch; i++) {
        int64_t c = labels[idx[i]];
        if (c >= 0 && c < classes) out[i * classes + c] = 1.0f;
    }
}

// Fisher-Yates permutation with xorshift64*, for epoch shuffles.
void permutation(int64_t n, uint64_t seed, int64_t* out) {
    for (int64_t i = 0; i < n; i++) out[i] = i;
    uint64_t s = seed ? seed : 0x9E3779B97F4A7C15ull;
    for (int64_t i = n - 1; i > 0; i--) {
        s ^= s >> 12; s ^= s << 25; s ^= s >> 27;
        uint64_t r = s * 0x2545F4914F6CDD1Dull;
        int64_t j = int64_t(r % uint64_t(i + 1));
        int64_t tmp = out[i]; out[i] = out[j]; out[j] = tmp;
    }
}

}  // extern "C"
