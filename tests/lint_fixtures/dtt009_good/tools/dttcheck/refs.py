"""Fixture dttcheck: every collective path is traced."""
from parallel.mod import make_traced_step, orphan_collective_path

SCENARIOS = (make_traced_step, orphan_collective_path)
