"""The --prng rbg fast path: training and checkpoint round-trips work with
the hardware-RNG key implementation (key shapes differ from threefry, so
the round-trip is the thing to pin)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture
def rbg_prng():
    prev = jax.config.jax_default_prng_impl
    jax.config.update("jax_default_prng_impl", "rbg")
    try:
        yield
    finally:
        jax.config.update("jax_default_prng_impl", prev)


def test_rbg_train_step_and_checkpoint_roundtrip(tmp_path, rbg_prng):
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        restore_latest,
        save_checkpoint,
    )
    from distributed_tensorflow_tpu.models import DeepCNN
    from distributed_tensorflow_tpu.training import (
        adam,
        create_train_state,
        make_train_step,
    )

    model = DeepCNN()
    opt = adam(1e-3)
    state = create_train_state(model, opt, seed=0)
    assert state.rng.shape == (4,)  # rbg key, vs threefry's (2,)
    step_fn = make_train_step(model, opt, keep_prob=0.75, donate=False)
    x = jnp.ones((4, 784), jnp.float32)
    y = jax.nn.one_hot(jnp.arange(4) % 10, 10)
    state, m = step_fn(state, (x, y))
    assert np.isfinite(float(m["loss"]))

    save_checkpoint(str(tmp_path), state, 1)
    restored, step = restore_latest(
        str(tmp_path), create_train_state(model, opt, seed=1))
    assert step == 1
    np.testing.assert_array_equal(np.asarray(restored.rng),
                                  np.asarray(state.rng))
    # the restored state steps again
    restored, m = step_fn(restored, (x, y))
    assert np.isfinite(float(m["loss"]))


def test_rbg_device_sampling(rbg_prng):
    from distributed_tensorflow_tpu.data.device_data import DeviceData
    from distributed_tensorflow_tpu.models import DeepCNN
    from distributed_tensorflow_tpu.training import (
        create_train_state,
        sgd,
    )
    from distributed_tensorflow_tpu.training.device_step import (
        make_device_train_step,
    )

    n = 64
    data = DeviceData(
        jnp.asarray((np.arange(n * 784) % 255).astype(np.uint8).reshape(n, 784)),
        jnp.asarray((np.arange(n) % 10).astype(np.int32)),
    )
    model = DeepCNN()
    opt = sgd(0.1)
    state = create_train_state(model, opt, seed=0)
    fn = make_device_train_step(model, opt, 8, keep_prob=0.75, chunk=3,
                                donate=False)
    state, m = fn(state, data)
    assert int(state.step) == 3
    assert np.isfinite(float(m["loss"]))
