"""Step timing / throughput meters + jax.profiler hooks.

The reference has no tracing or profiling at all (``import time`` at
MNISTDist.py:8 is dead — SURVEY.md §5). The build needs them for the
BASELINE metric (images/sec/chip), so they are first-class here.
"""

from __future__ import annotations

import contextlib
import time

import jax


class StepTimer:
    """Wall-clock per-step timer that excludes the first (compile) step."""

    def __init__(self):
        self.times: list[float] = []
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is not None:
            self.times.append(time.perf_counter() - self._t0)
            self._t0 = None

    @property
    def mean_step_s(self) -> float:
        steady = self.times[1:] if len(self.times) > 1 else self.times
        return sum(steady) / max(len(steady), 1)


class Throughput:
    """images/sec (and per-chip) meter over a training window."""

    def __init__(self, batch_size: int, n_chips: int = 1):
        self.batch_size = batch_size
        self.n_chips = n_chips
        self.reset()

    def reset(self):
        self._start = time.perf_counter()
        self._images = 0

    def step(self, n: int | None = None):
        self._images += n if n is not None else self.batch_size

    @property
    def images_per_sec(self) -> float:
        dt = time.perf_counter() - self._start
        return self._images / dt if dt > 0 else 0.0

    @property
    def images_per_sec_per_chip(self) -> float:
        return self.images_per_sec / max(self.n_chips, 1)


def collective_sync_cadence(multi_device: bool) -> int:
    """How often (in steps) a multi-device training loop must
    ``block_until_ready`` to bound in-flight collective programs; 0 = never.

    XLA:CPU runs each virtual device on a pool thread and collective
    programs rendezvous across all of them; dozens of concurrently enqueued
    mesh programs can interleave across device threads and deadlock the
    rendezvous (observed at ~60 deep on an 8-device host — PERF.md). TPU
    streams execute strictly in enqueue order per chip, so no cap there.
    """
    if not multi_device:
        return 0
    return 16 if jax.default_backend() == "cpu" else 0


@contextlib.contextmanager
def trace(logdir: str | None):
    """jax.profiler trace scope; no-op when logdir is falsy."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
