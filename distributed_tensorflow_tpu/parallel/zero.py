"""ZeRO-sharded data parallelism: shard the redundant state over "data".

Plain sync DP (``data_parallel.py``) replicates params AND the full
optimizer state (Adam's ``m``/``v``, momentum's velocity) on every chip
and pays one full-gradient all-reduce per step. ZeRO (Rajbhandari et
al., 2020) observes that under *synchronous* DP the replicated optimizer
state is pure waste: every replica computes the identical update, so the
state can be PARTITIONED 1/D per data-parallel rank at identical math.
PyTorch FSDP (Zhao et al., 2023) extends the same partitioning to the
parameters themselves. This module implements both on the existing
``shard_map`` style where the collectives stay explicit in the program:

``--zero 1`` (optimizer-state sharding)
    Gradients leave the backward pass as full local leaves, are
    flattened, zero-padded to a multiple of D, and ``lax.psum_scatter``
    over the data axis — each rank receives its 1/D chunk of the
    SUMMED gradient (one reduce-scatter, |G| bytes on the wire instead
    of the all-reduce's 2|G|). The optimizer update then runs on each
    rank's 1/D chunk of the opt state against its 1/D chunk of the
    (replicated) params, and ONE ``all_gather`` (|P| bytes) rebuilds
    the full updated params everywhere. Per-step comm: |G| + |P| vs
    the all-reduce's 2|G|; per-chip optimizer memory: 1/D.

``--zero 3`` (FSDP-style: params sharded too)
    Params themselves LIVE as 1/D flat chunks and are all-gathered
    inside the forward (the gather is wrapped in ``jax.checkpoint`` so
    the backward re-gathers instead of keeping a second full copy —
    the "free remat of the gather"). No hand-written reduce-scatter is
    needed: differentiating through ``all_gather`` IS the
    reduce-scatter — its transpose routes each rank's gradient
    contributions straight into the owning rank's chunk, bitwise equal
    to the explicit ``psum_scatter`` (pinned by tests). Per-chip
    params at rest: 1/D (the step transiently materializes one full
    copy for the forward/backward, same as replicated compute needs).

Exactness: the arithmetic is IDENTICAL to replicated sync DP — on this
backend ``psum_scatter`` chunks bit-match the ``psum`` they partition
(both reduce contributions in the same rank order), every optimizer op
is elementwise, and padding lanes are inert under sgd/momentum/adam
(zero grads beget zero updates) — so unclipped trajectories are
BIT-IDENTICAL to ``make_dp_train_step`` step-for-step, dropout and
``accum_steps`` included (tests/test_zero.py). ``--clip_norm`` needs
the ZeRO-aware transform (``zero_clip_transform``): every grad leaf
inside the step is a distinct shard, so the squared-norm partials must
``psum`` over the data axis before ONE scale applies everywhere — the
same replicated-leaf-divergence class of bug the PP/EP clips fixed.
The psum'd partial assembly can differ from the replicated clip's
full-leaf reduction in the last ulp (float addition is not
associative), so clipped trajectories match replicated DP to float
tolerance while staying bit-identical ACROSS ZeRO levels and across
replicas.

Checkpoints stay STANDARD-LAYOUT (the PP stacking machinery's
contract): ``shard_state_zero``/``fetch_state_zero`` convert between
the flat-chunk device layout and the ordinary pytree, so a ``--zero``
run restores a replicated checkpoint and vice versa, bitwise, through
the verified-restore fallback ladder; serving's params-only restore is
untouched.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel.mesh import DATA_AXIS
from distributed_tensorflow_tpu.parallel.pipeline_parallel import (
    _map_params_shaped,
)
from distributed_tensorflow_tpu.training.train_state import (
    TrainState,
    apply_augment,
    apply_updates,
    compute_grads,
    create_train_state,
    loss_and_metrics,
)

def _leaf_size(sds) -> int:
    """Element count of a (possibly scalar) leaf."""
    return math.prod(sds.shape) if sds.shape else 1


def abstract_params(model):
    """ShapeDtypeStruct tree of the model's params — the per-leaf
    (shape, dtype) metadata every gather/scatter needs to undo the flat
    padded chunking. ``jax.eval_shape`` so no compute and no chip."""
    variables = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0)))
    if getattr(model, "stateful", False):
        return variables["params"]
    return variables


def _gather_leaf(chunk, sds):
    """Local 1/D chunk -> the full leaf: tiled all_gather over the data
    axis, drop the padding lanes, restore the original shape."""
    n = _leaf_size(sds)
    full = lax.all_gather(chunk, DATA_AXIS, tiled=True)
    return full[:n].reshape(sds.shape)


def _gather_params(chunks, meta):
    return jax.tree.map(_gather_leaf, chunks, meta)


def _scatter_leaf(g):
    """Full local leaf -> this rank's 1/D chunk of the cross-rank SUM:
    flatten, zero-pad to a multiple of the axis size, psum_scatter. The
    padding lanes reduce exact zeros, so they stay inert through every
    optimizer."""
    d = lax.axis_size(DATA_AXIS)
    flat = g.reshape(-1)
    pad = (-flat.size) % d
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return lax.psum_scatter(flat, DATA_AXIS, scatter_dimension=0,
                            tiled=True)


DEFAULT_BUCKET_MB = 4.0  # --zero_bucket_mb default (the comm/latency knob)


def _bucket_plan(leaves, d: int, bucket_bytes: int) -> list[list[int]]:
    """Host-side static bucketing: consecutive leaves (canonical
    flatten order) grouped while the PADDED payload stays within
    ``bucket_bytes`` (every bucket holds >= 1 leaf; a dtype change
    starts a new bucket — buckets concatenate). Static so the compiled
    program's collective count is fixed."""
    d = max(1, int(d))
    bucket_bytes = max(1, int(bucket_bytes))
    plan: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i, leaf in enumerate(leaves):
        n = _leaf_size(leaf)
        padded = (-(-n // d)) * d * np.dtype(leaf.dtype).itemsize
        if cur and (leaf.dtype != cur_dtype
                    or cur_bytes + padded > bucket_bytes):
            plan.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += padded
        cur_dtype = leaf.dtype
    if cur:
        plan.append(cur)
    return plan


def n_buckets(model, d: int, bucket_mb: float) -> int:
    """Static bucket count for a model's param/grad tree at one bucket
    size — the analytic fact the comm ledger and bench record."""
    meta = jax.tree.leaves(abstract_params(model))
    return len(_bucket_plan(meta, d, int(bucket_mb * 2 ** 20)))


def _scatter_bucketed(grads, d: int, bucket_bytes: int):
    """Bucketed reduce-scatter: leaves pad and reshape to [D, c] (row r
    IS rank r's chunk — identical ownership to the per-leaf
    ``_scatter_leaf``), concatenate along the chunk axis per bucket,
    one ``psum_scatter`` per bucket, split back. Elementwise the same
    sums over the same ranks as the per-leaf scatters, so the chunks
    are BITWISE equal (pinned by tests/test_zero.py) — what changes is
    the collective count: ceil(|G|/bucket) right-sized ops that XLA's
    async scheduler can issue as backward produces their operands,
    instead of leaf-granular ops or one serial flat scatter."""
    leaves, treedef = jax.tree.flatten(grads)
    plan = _bucket_plan(leaves, d, bucket_bytes)
    out = [None] * len(leaves)
    for bucket in plan:
        mats = []
        for i in bucket:
            flat = leaves[i].reshape(-1)
            pad = (-flat.size) % d
            if pad:
                flat = jnp.pad(flat, (0, pad))
            mats.append(flat.reshape(d, -1))
        buck = mats[0] if len(mats) == 1 else jnp.concatenate(mats, axis=1)
        red = lax.psum_scatter(buck, DATA_AXIS, scatter_dimension=0,
                               tiled=True).reshape(-1)
        off = 0
        for i, mat in zip(bucket, mats):
            c = mat.shape[1]
            out[i] = red[off:off + c]
            off += c
    return jax.tree.unflatten(treedef, out)


def _gather_bucketed(chunks, meta, d: int, bucket_bytes: int):
    """Bucketed all-gather: per-leaf 1/D chunks concatenate per bucket,
    one tiled ``all_gather`` per bucket, then per-leaf chunks slice
    back out of the [D, C] view and reassemble exactly like
    ``_gather_leaf`` would — pure data movement, bitwise equal to the
    per-leaf gathers."""
    cleaves, treedef = jax.tree.flatten(chunks)
    mleaves = jax.tree.leaves(meta)
    plan = _bucket_plan(mleaves, d, bucket_bytes)
    out = [None] * len(cleaves)
    for bucket in plan:
        cat = (cleaves[bucket[0]] if len(bucket) == 1
               else jnp.concatenate([cleaves[i] for i in bucket]))
        full = lax.all_gather(cat, DATA_AXIS, tiled=True).reshape(d, -1)
        off = 0
        for i in bucket:
            c = cleaves[i].shape[0]
            n = _leaf_size(mleaves[i])
            out[i] = full[:, off:off + c].reshape(-1)[:n].reshape(
                mleaves[i].shape)
            off += c
    return jax.tree.unflatten(treedef, out)


def _local_chunk(x):
    """This rank's 1/D flat chunk of a REPLICATED full leaf (the ZeRO-1
    param slice the optimizer updates): pad, then slice at the rank's
    offset — bit-identical to the chunk a psum_scatter would own."""
    d = lax.axis_size(DATA_AXIS)
    flat = x.reshape(-1)
    pad = (-flat.size) % d
    if pad:
        flat = jnp.pad(flat, (0, pad))
    c = flat.shape[0] // d
    return lax.dynamic_slice_in_dim(flat, lax.axis_index(DATA_AXIS) * c, c)


def zero_clip_transform(max_norm: float):
    """Axis-correct global-norm clip for INSIDE a ZeRO ``shard_map``
    step. Every grad leaf the transform sees is a DISTINCT 1/D shard of
    the mean gradient, so each rank's local squared sum is an exact
    partial of the global squared norm; one ``psum`` over the data axis
    totals them and the SAME scale applies on every rank — replicated
    params (ZeRO-1's all-gathered update) stay bit-identical across
    replicas, and the clipped trajectory is bit-identical across ZeRO
    levels. (A plain ``clip_by_global_norm`` here would scale by a
    rank-LOCAL norm — the divergence class PR 1 fixed for PP/EP.) This
    is ``clip_by_global_norm(axis=DATA_AXIS, sharded_leaf=always)``
    specialized: kept as its own named transform because the ZeRO step
    is the one place every leaf is guaranteed sharded."""
    from distributed_tensorflow_tpu.training.train_state import (
        clip_by_global_norm,
    )

    return clip_by_global_norm(max_norm, axis=DATA_AXIS,
                               sharded_leaf=lambda path: True)


def zero_state_specs(state: TrainState, level: int) -> TrainState:
    """PartitionSpec pytree for a ZeRO-layout TrainState — the one place
    the chunked-over-"data" rule is written (shard_map specs and device
    shardings both derive from it). Works on either layout (the flat
    chunking preserves tree structure): params-shaped optimizer subtrees
    are chunked, scalar slots (adam's ``t``) replicate; params chunk
    only at level 3."""
    level = _check_level(level)
    pstruct = jax.tree.structure(state.params)
    chunked = lambda sub: jax.tree.map(lambda _: P(DATA_AXIS), sub)
    replicated = lambda sub: jax.tree.map(lambda _: P(), sub)
    return TrainState(
        params=(chunked if level >= 3 else replicated)(state.params),
        opt_state=_map_params_shaped(state.opt_state, pstruct, chunked,
                                     replicated),
        step=P(), rng=P(),
        model_state=replicated(state.model_state))


def zero_state_sharding(state: TrainState, mesh, level: int) -> TrainState:
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        zero_state_specs(state, level),
                        is_leaf=lambda v: isinstance(v, P))


def _check_level(level: int) -> int:
    level = int(level)
    if level not in (1, 3):
        raise ValueError(f"zero level must be 1 (optimizer-state "
                         f"sharding) or 3 (params too); got {level}")
    return level


def shard_state_zero(state: TrainState, mesh, level: int) -> TrainState:
    """Standard-layout (host) TrainState -> the ZeRO device layout:
    params-shaped optimizer subtrees (and, at level 3, the params)
    become flat zero-padded vectors of global length D*ceil(n/D),
    sharded 1/D per rank over the data axis; everything else replicates.
    The inverse is ``fetch_state_zero`` — checkpoints only ever see the
    standard layout."""
    level = _check_level(level)
    d = mesh.shape[DATA_AXIS]

    def chunk_host(x):
        a = np.asarray(x)
        flat = a.reshape(-1)
        pad = (-flat.size) % d
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, dtype=a.dtype)])
        return flat

    chunkify = lambda tree: jax.tree.map(chunk_host, tree)
    pstruct = jax.tree.structure(state.params)
    out = state._replace(
        params=chunkify(state.params) if level >= 3 else state.params,
        opt_state=_map_params_shaped(state.opt_state, pstruct, chunkify,
                                     lambda e: e))
    return jax.device_put(out, zero_state_sharding(out, mesh, level))


def fetch_state_zero(state: TrainState, model, level: int) -> TrainState:
    """ZeRO-layout state -> host state in the STANDARD layout (the
    checkpoint format): undo the flat padded chunking on the params (at
    level 3) and on every params-shaped optimizer subtree — so
    checkpoints are identical whatever ``--zero`` level (or none) the
    run trained under."""
    level = _check_level(level)
    host = jax.device_get(state)
    meta = abstract_params(model)

    def unchunk_leaf(flat, sds):
        n = _leaf_size(sds)
        return np.asarray(flat)[:n].reshape(sds.shape)

    unchunk = lambda tree: jax.tree.map(unchunk_leaf, tree, meta)
    pstruct = jax.tree.structure(host.params)
    return host._replace(
        params=unchunk(host.params) if level >= 3 else host.params,
        opt_state=_map_params_shaped(host.opt_state, pstruct, unchunk,
                                     lambda e: e))


def _zero_step_core(model, optimizer, mesh, level, keep_prob,
                    grad_transform, accum_steps: int = 1,
                    overlap: bool = False, bucket_bytes: int | None = None):
    """The per-shard ZeRO step body shared by the host-fed builder and
    the device-resident sampler (``device_step.make_zero_device_train_
    step``): ``core(state, batch, sub, rng, prefetched=None) ->
    (state, metrics, next_full)`` for inside ``shard_map``. The caller
    owns the rng-split/augment/sample derivations (they must bit-match
    its replicated twin's); the core owns grads -> reduce-scatter ->
    clip -> sharded update -> gather.

    ``overlap=True`` switches to the comm/compute-overlap collective
    pattern — BITWISE the same trajectory (tests pin it), different
    wire schedule:

    - grads reduce-scatter in ``bucket_bytes`` BUCKETS (same padding,
      same per-leaf chunk ownership as the per-leaf scatters — the
      [D, c] row layout), so the collectives issue as backward
      produces their operands instead of leaf-granular or one flat
      serial scatter at the end;
    - at level 3 the params materialize from ONE bucketed all_gather
      reused by forward AND backward (grads are taken w.r.t. the full
      params and explicitly reduce-scattered — bitwise equal to the
      serial path's remat'd gather transpose, pinned); the wire stays
      |G|+|P| like the serial path's (whose checkpointed gather's
      output is itself the saved residual — dttcheck-proven, r18),
      but the gather leaves the critical path: after the update the
      NEXT step's
      gather issues immediately (``next_full``), so a chunked caller
      carrying it double-buffers the gather behind the step epilogue
      and the next step's on-device sampling — the prefetch window.
      A caller that ignores ``next_full`` (the host-fed one-step
      wrapper) costs nothing: XLA dead-code-eliminates the unused
      gather."""
    level = _check_level(level)
    d = mesh.shape[DATA_AXIS]
    meta = abstract_params(model)
    bucket_bytes = int(bucket_bytes or DEFAULT_BUCKET_MB * 2 ** 20)

    def scatter_mean(grads):
        if overlap:
            return jax.tree.map(lambda g: g / d,
                                _scatter_bucketed(grads, d, bucket_bytes))
        return jax.tree.map(lambda g: _scatter_leaf(g) / d, grads)

    def gather_full(chunks):
        if overlap:
            return _gather_bucketed(chunks, meta, d, bucket_bytes)
        return _gather_params(chunks, meta)

    def core(state: TrainState, batch, sub, rng, prefetched=None):
        next_full = None
        if level >= 3 and overlap:
            full = prefetched if prefetched is not None \
                else gather_full(state.params)
            if accum_steps <= 1:
                def loss_fn(fp):
                    return loss_and_metrics(
                        model, fp, batch, keep_prob=keep_prob, rng=sub,
                        train=True, model_state=state.model_state)

                gfull, aux = jax.grad(loss_fn, has_aux=True)(full)
                metrics = aux["metrics"]
                model_state = aux["model_state"]
            else:
                gfull, metrics, model_state = compute_grads(
                    model, full, batch, keep_prob=keep_prob, rng=sub,
                    model_state=state.model_state,
                    accum_steps=accum_steps)
            # explicit bucketed reduce-scatter of the full grad — the
            # serial path's gather TRANSPOSE computes the same chunks
            # (pinned bitwise), this one just issues them bucket-wise
            gchunks = scatter_mean(gfull)
            pchunks = state.params
        elif level >= 3:
            if accum_steps <= 1:
                # grads w.r.t. the CHUNKS through a remat'd gather: the
                # all_gather transpose IS the reduce-scatter (bitwise
                # equal to the explicit psum_scatter — tests pin it),
                # and jax.checkpoint re-gathers in the backward instead
                # of keeping a second full param copy alive
                gathered = jax.checkpoint(
                    lambda ch: _gather_params(ch, meta))

                def loss_fn(pchunks):
                    return loss_and_metrics(
                        model, gathered(pchunks), batch,
                        keep_prob=keep_prob, rng=sub, train=True,
                        model_state=state.model_state)

                gsum, aux = jax.grad(loss_fn, has_aux=True)(state.params)
                gchunks = jax.tree.map(lambda g: g / d, gsum)
                metrics = aux["metrics"]
                model_state = aux["model_state"]
            else:
                # accumulation: gather ONCE per step (not per
                # microbatch), accumulate full local grads exactly as
                # the replicated step does, then one reduce-scatter —
                # the same reduction order, so trajectories stay
                # bit-identical to replicated accumulation
                full = _gather_params(state.params, meta)
                grads, metrics, model_state = compute_grads(
                    model, full, batch, keep_prob=keep_prob, rng=sub,
                    model_state=state.model_state,
                    accum_steps=accum_steps)
                gchunks = jax.tree.map(lambda g: _scatter_leaf(g) / d,
                                       grads)
            pchunks = state.params
        else:
            grads, metrics, model_state = compute_grads(
                model, state.params, batch, keep_prob=keep_prob, rng=sub,
                model_state=state.model_state, accum_steps=accum_steps)
            # reduce-scatter (|G| on the wire) where the replicated step
            # all-reduces (2|G|); /d after, matching pmean's psum-then-
            # divide bit-for-bit
            gchunks = scatter_mean(grads)
            pchunks = jax.tree.map(_local_chunk, state.params)
        if grad_transform is not None:
            gchunks = grad_transform(gchunks)
        metrics = lax.pmean(metrics, DATA_AXIS)
        if model_state:
            model_state = lax.pmean(model_state, DATA_AXIS)
        # every optimizer op is elementwise over (grads, slots, params),
        # so running it on 1/D chunks computes bit-identical values to
        # the replicated full-leaf update — on 1/D the memory and FLOPs
        updates, opt_state = optimizer.update(gchunks, state.opt_state,
                                              pchunks, state.step)
        pchunks = apply_updates(pchunks, updates)
        if level >= 3:
            params = pchunks  # stays sharded; the next step re-gathers
            if overlap:
                # prefetch: issue the NEXT step's gather now — a
                # chunked caller carries it, hiding the gather behind
                # the epilogue + the next step's sampling
                next_full = gather_full(pchunks)
        else:
            # ONE all_gather (|P|) rebuilds the replicated params
            params = gather_full(pchunks)
        return TrainState(params, opt_state, state.step + 1, rng,
                          model_state), metrics, next_full

    return core


def make_zero_train_step(model, optimizer, mesh, level: int,
                         keep_prob: float = 1.0, donate: bool = True,
                         grad_transform=None, accum_steps: int = 1,
                         augment_fn=None, overlap: bool = False,
                         bucket_mb: float = DEFAULT_BUCKET_MB):
    """Compiled ZeRO-sharded sync-DP train step: (ZeRO-layout state,
    sharded batch) -> (state, metrics). Drop-in for
    ``make_dp_train_step`` on a state placed by ``shard_state_zero``;
    unclipped trajectories are BIT-IDENTICAL to it (same rng folds,
    same augment stream, same elementwise update arithmetic — only the
    collective pattern changes). ``grad_transform`` runs on the
    SCATTERED mean-grad chunks — pass ``zero_clip_transform`` for an
    axis-correct ``--clip_norm``. ``overlap``/``bucket_mb`` switch to
    the bucketed/prefetched collective pattern (``--zero_overlap``;
    still bit-identical — see ``_zero_step_core``)."""
    core = _zero_step_core(model, optimizer, mesh, level, keep_prob,
                           grad_transform, accum_steps, overlap=overlap,
                           bucket_bytes=int(bucket_mb * 2 ** 20))

    def per_shard(state: TrainState, batch):
        rng, sub = jax.random.split(state.rng)
        # identical key evolution to make_dp_train_step's per_shard
        sub = jax.random.fold_in(sub, lax.axis_index(DATA_AXIS))
        batch = apply_augment(augment_fn, batch, state.rng,
                              shard_index=lax.axis_index(DATA_AXIS))
        state, metrics, _ = core(state, batch, sub, rng)
        return state, metrics

    batch_spec = (P(DATA_AXIS), P(DATA_AXIS))
    cache: dict = {}

    def call(state, batch):
        fn = cache.get("fn")
        if fn is None:
            specs = zero_state_specs(state, level)
            sharded = jax.shard_map(
                per_shard, mesh=mesh,
                in_specs=(specs, batch_spec),
                out_specs=(specs, P()),
                check_vma=False)
            fn = cache["fn"] = jax.jit(
                sharded, donate_argnums=(0,) if donate else ())
        return fn(state, batch)

    return call


def make_zero_eval_step(model, mesh, level: int):
    """Sharded full-batch eval for a ZeRO-layout state. Level 1 params
    are replicated, so the plain DP eval step applies verbatim; level 3
    all-gathers the param chunks inside ``shard_map`` first (identical
    reconstruction, so metrics bit-match the DP eval)."""
    level = _check_level(level)
    from distributed_tensorflow_tpu.parallel.data_parallel import (
        make_dp_eval_step,
    )

    if level < 3:
        return make_dp_eval_step(model, mesh)
    meta = abstract_params(model)

    def per_shard(pchunks, batch, model_state):
        params = _gather_params(pchunks, meta)
        _, aux = loss_and_metrics(model, params, batch, train=False,
                                  model_state=model_state)
        return lax.pmean(aux["metrics"], DATA_AXIS)

    return jax.jit(
        jax.shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(P(DATA_AXIS), (P(DATA_AXIS), P(DATA_AXIS)), P()),
            out_specs=P(),
            check_vma=False,
        )
    )


def zero_memory_budget(model, optimizer, d: int) -> dict:
    """STATIC per-chip memory budget (no chip, no compute —
    ``jax.eval_shape``): param/grad/optimizer bytes per leaf and per
    ``--zero`` level, so the D-fold saving is auditable anywhere
    (``tools/trace_ops.py --mem`` prints it; bench.py records the
    totals even in the degraded/outage record).

    Per-chip accounting: replicated holds full params + full opt
    state; ZeRO-1 holds full params + ceil(n/D) elements of every
    params-shaped opt slot (padding included — the figures are what
    the chips actually allocate); ZeRO-3 chunks the params the same
    way. Grad bytes are the transient full-leaf backward output,
    identical in every mode, listed for the complete picture."""
    d = int(d)
    if d < 1:
        raise ValueError(f"data-axis size must be >= 1, got {d}")
    abstract = jax.eval_shape(
        lambda: create_train_state(model, optimizer))
    rows: list[dict] = []

    from distributed_tensorflow_tpu.utils.pytree import path_key

    def add_rows(kind, tree, chunked: bool, prefix: str = ""):
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            n = _leaf_size(leaf)
            isz = np.dtype(leaf.dtype).itemsize
            rows.append({
                "kind": kind,
                "leaf": (prefix + path_key(path)).rstrip("/") or "(scalar)",
                "elements": n,
                "bytes": n * isz,
                "sharded_bytes": (-(-n // d)) * isz if chunked else n * isz,
                "chunked": chunked,
            })

    add_rows("param", abstract.params, chunked=True)
    pstruct = jax.tree.structure(abstract.params)

    def walk_opt(entry, prefix: str):
        # mirrors _map_params_shaped's rule (params-shaped subtrees are
        # the chunked ones) but keeps the container path for the table
        if jax.tree.structure(entry) == pstruct:
            add_rows("opt", entry, chunked=True, prefix=prefix)
        elif isinstance(entry, dict):
            for k, v in entry.items():
                walk_opt(v, f"{prefix}{k}/")
        else:
            add_rows("opt", entry, chunked=False, prefix=prefix)

    walk_opt(abstract.opt_state, "")

    def total(kind, key):
        return sum(r[key] for r in rows if r["kind"] == kind)

    p_full, p_shard = total("param", "bytes"), total("param", "sharded_bytes")
    o_full, o_shard = total("opt", "bytes"), total("opt", "sharded_bytes")
    per_chip = {
        "replicated": {"params": p_full, "opt": o_full, "grads": p_full},
        "zero1": {"params": p_full, "opt": o_shard, "grads": p_full},
        "zero3": {"params": p_shard, "opt": o_shard, "grads": p_full},
    }
    return {
        "d": d, "rows": rows,
        "param_bytes": p_full, "opt_bytes": o_full,
        "per_chip": per_chip,
        "opt_reduction": (o_full / o_shard) if o_shard else 1.0,
        "param_reduction": (p_full / p_shard) if p_shard else 1.0,
    }


def zero_comm_rows(grad_bytes: int, param_bytes: int, level: int,
                   d: int, overlap: bool = False,
                   bucket_mb: float = DEFAULT_BUCKET_MB) -> list[dict]:
    """Static per-step collective wire bytes for this module's data-axis
    patterns — the comm ledger's ZeRO/DP rows (utils/resources.
    comm_ledger composes them; the formula lives next to the
    collectives it prices). Conventions per the module docstring:
    all-reduce ~2|G|, reduce-scatter |G|, all-gather |P|. ``level=0``
    is plain replicated DP's grad all-reduce. A 1-way data axis moves
    nothing.

    Each row carries ``exposed_bytes`` — the analytic share that sits
    on the step's critical path. Serial rows expose everything.
    ``overlap=True`` prices the ``--zero_overlap`` pattern: a bucketed
    reduce-scatter exposes only its LAST bucket (earlier buckets issue
    while backward still produces later grads) and the level-3 gather
    is prefetched (it issued right after the previous update, hidden
    behind the epilogue + next-step sampling — exposed 0).

    Level-3 wire volume is |G| + |P| in BOTH schedules — machine-proven
    by ``tools/dttcheck`` (r18) against the lowered jaxpr: the serial
    path's ``jax.checkpoint`` wraps only the gather, whose OUTPUT is
    itself the saved residual the backward consumes, so no re-gather
    ever reaches the wire (the pre-r18 ledger priced a phantom
    backward-remat |P| here). What overlap changes is the SCHEDULE —
    bucketing and the one-step prefetch — i.e. the exposed column, not
    the volume."""
    if d < 2:
        return []
    if level == 0:
        return [{"collective": "all_reduce(grads)", "axis": "data",
                 "bytes": 2 * grad_bytes, "exposed_bytes": 2 * grad_bytes,
                 "note": "replicated DP: ring all-reduce moves ~2|G|"}]
    _check_level(level)
    bucket_bytes = max(1, int(bucket_mb * 2 ** 20))
    scatter_exposed = (min(bucket_bytes, grad_bytes) if overlap
                      else grad_bytes)
    scatter_note = (
        f"bucketed reduce-scatter ({-(-grad_bytes // bucket_bytes)} "
        f"bucket(s) of <= {bucket_mb:g} MB): buckets issue as backward "
        f"produces leaves; only the last is exposed" if overlap else
        "reduce-scatter: each rank receives its 1/D chunk of the "
        "summed gradient (|G| on the wire)")
    rows = [{"collective": "psum_scatter(grads)", "axis": "data",
             "bytes": grad_bytes, "exposed_bytes": scatter_exposed,
             "note": scatter_note}]
    if level == 1:
        rows.append({
            "collective": "all_gather(params)", "axis": "data",
            "bytes": param_bytes,
            "exposed_bytes": (min(bucket_bytes, param_bytes) if overlap
                              else param_bytes),
            "note": ("bucketed gather rebuilds the replicated params; "
                     "the next step's sampling hides all but the last "
                     "bucket" if overlap else
                     "one gather rebuilds the replicated updated "
                     "params (|P|)")})
    elif overlap:  # level 3 overlapped: ONE prefetched gather, reused
        rows[0]["collective"] = "psum_scatter(grads, bucketed)"
        rows.append({
            "collective": "all_gather(params, prefetched)",
            "axis": "data", "bytes": param_bytes, "exposed_bytes": 0,
            "note": "issued right after the previous update and reused "
                    "by forward AND backward — off the critical path"})
    else:  # level 3 serial: params live sharded, ONE gather per step
        rows[0]["collective"] = "reduce_scatter(grad transpose)"
        rows[0]["note"] = ("the all_gather's transpose routes grad "
                           "contributions to the owning rank (|G|)")
        rows.append({"collective": "all_gather(params, forward)",
                     "axis": "data", "bytes": param_bytes,
                     "exposed_bytes": param_bytes,
                     "note": "sharded params materialize once per step "
                             "(|P|); the checkpointed gather's output "
                             "is the saved residual, so the backward "
                             "re-uses it — no re-gather on the wire "
                             "(dttcheck-proven, r18)"})
    return rows


def zero_exposed_comm_bytes(grad_bytes: int, param_bytes: int, level: int,
                            d: int, overlap: bool = False,
                            bucket_mb: float = DEFAULT_BUCKET_MB) -> int:
    """Analytic critical-path wire bytes per step — the bench's
    ``zero_exposed_comm_bytes`` fact (sum of the rows' exposure)."""
    return int(sum(r["exposed_bytes"]
                   for r in zero_comm_rows(grad_bytes, param_bytes, level,
                                           d, overlap, bucket_mb)))
