"""Long-context sweep: TransformerLM step time + compiled HBM vs length.

Runs the production train step (make_train_step — forward, backward,
adam, step increment in ONE executable) across sequence lengths and
attention/remat variants on whatever chip is default, and prints one
JSON line per config:

  {"seq_len": N, "variant": "...", "ms_per_step": ..., "tokens_per_sec":
   ..., "temp_bytes": ..., "arg_bytes": ..., "status": "ok"|"oom"}

``temp_bytes`` is the XLA compiler's own peak-temporary-allocation
figure (``compiled.memory_analysis()``) — the runtime memory_stats API
is unavailable on tunneled chips, and the compiler's number is exact
and reproducible. OOMs (compile- or run-time) are caught and recorded,
not crashed on: hitting the dense wall IS a datapoint.

Usage: python tools/lm_longctx_sweep.py [--quick]
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp


def run_config(seq_len: int, variant: str, batch: int = 8,
               d_model: int = 256, num_heads: int = 4,
               num_blocks: int = 4, steps: int = 10,
               vocab_size: int = 64, attn_block_size: int = 512) -> dict:
    """``variant`` tokens: "dense"/"block" (attention form), "+remat",
    "+ce" (streamed loss head, ce_block=attn_block_size — the
    vocab-axis flash; without it the head materializes (B, S, V) f32
    logits + grads)."""
    from distributed_tensorflow_tpu.data.lm import LMDataSet
    from distributed_tensorflow_tpu.models.transformer import TransformerLM
    from distributed_tensorflow_tpu.training import (
        create_train_state,
        get_optimizer,
        make_train_step,
    )

    attn_block = attn_block_size if "block" in variant else None
    remat = "remat" in variant
    ce_block = attn_block_size if "ce" in variant else None
    rec = {"seq_len": seq_len, "variant": variant, "batch": batch,
           "d_model": d_model, "num_blocks": num_blocks,
           "vocab_size": vocab_size}
    model = TransformerLM(vocab_size=vocab_size, seq_len=seq_len,
                          d_model=d_model, num_heads=num_heads,
                          num_blocks=num_blocks,
                          attn_block=attn_block, remat=remat,
                          ce_block=ce_block,
                          compute_dtype=jnp.bfloat16)
    opt = get_optimizer("adam", 1e-3)
    step = make_train_step(model, opt, keep_prob=1.0)
    try:
        state = create_train_state(model, opt, seed=0)
        ds = LMDataSet(max(batch, 8), seq_len=seq_len,
                       vocab_size=vocab_size, seed=0)
        b = ds.next_batch(batch)
        lowered = step.lower(state, b)
        compiled = lowered.compile()
        ma = compiled.memory_analysis()
        if ma is not None:
            rec["temp_bytes"] = int(ma.temp_size_in_bytes)
            rec["arg_bytes"] = int(ma.argument_size_in_bytes)
        state, m = compiled(state, b)
        jax.block_until_ready(state.params)
        t0 = time.time()
        for _ in range(steps):
            state, m = compiled(state, b)
        jax.block_until_ready(state.params)
        dt = (time.time() - t0) / steps
        rec["ms_per_step"] = round(dt * 1000, 2)
        rec["tokens_per_sec"] = round(batch * seq_len / dt)
        rec["loss"] = round(float(m["loss"]), 4)
        rec["status"] = "ok"
    except Exception as e:  # noqa: BLE001 — OOM is a datapoint
        msg = str(e)
        if ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
                or "exceeds" in msg):
            rec["status"] = "oom"
        elif "remote_compile" in msg or "tpu_compile_helper" in msg:
            # tunneled chips surface compile-stage failures (incl. the
            # compiler running out of memory for the buffer assignment)
            # as an opaque HTTP 500 from the compile helper — classify
            # separately so "the dense wall" stays a queryable datapoint
            rec["status"] = "compile_failed"
        else:
            rec["status"] = "error"
        rec["error"] = msg[:200]
    return rec


def main():
    quick = "--quick" in sys.argv
    vocab = "--vocab" in sys.argv
    if vocab:
        # the vocab axis (r5): at real vocab sizes the UNSTREAMED loss
        # head's (B, S, V) f32 logits+grads dwarf what the flash
        # attention backward saved; "+ce" streams them (ce_block).
        # Expect: naive head OOMs/compile-fails where block+ce runs.
        for v_size in (8192, 32768):
            for s in (4096, 8192, 16384):
                for var in ("block", "block+ce"):
                    # the naive head hitting its wall IS a datapoint —
                    # no skip for the "block" (unstreamed-loss) rows
                    print(json.dumps(run_config(s, var, vocab_size=v_size)),
                          flush=True)
        return
    lengths = [512, 2048, 4096] if quick else [512, 1024, 2048, 4096, 8192,
                                               16384]
    variants = ["dense", "dense+remat", "block", "block+remat"]
    for s in lengths:
        for v in variants:
            if s > 8192 and "block" not in v:
                continue  # dense past 8k: known wall, skip the compile
            print(json.dumps(run_config(s, v)), flush=True)


if __name__ == "__main__":
    main()
