from distributed_tensorflow_tpu.utils.metrics import MetricsLogger, reference_log_line
from distributed_tensorflow_tpu.utils.profiling import (
    StepTimer,
    Throughput,
    collective_sync_cadence,
)

__all__ = [
    "MetricsLogger",
    "reference_log_line",
    "StepTimer",
    "Throughput",
    "collective_sync_cadence",
]
