"""TPU-native distributed training framework.

A ground-up JAX/XLA rebuild of the capabilities of the reference
``ellie-ba/Distributed_TensorFlow`` (a distributed deep-CNN MNIST classifier
on TensorFlow's parameter-server runtime, ``/root/reference/.idea/MNISTDist.py``),
re-designed TPU-first:

- model/ops layer: pure-JAX functional CNN / ResNet (XLA:TPU kernels, MXU)
- parallelism: synchronous data-parallel over a ``jax.sharding.Mesh``
  (``psum`` gradients over ICI) as the default mode, plus an async
  parameter-server emulation mode reproducing the reference's
  stale-gradient SGD (worker/ps roles over host-side RPC)
- orchestration: chief-led init, periodic checkpoint + auto-restore,
  cadenced logging, shared-global-step termination — the Supervisor
  semantics of the reference (``MNISTDist.py:158-193``)
- CLI surface: identical flags (``--job_name --task_index --ps_hosts
  --worker_hosts`` + model/training flags, ``MNISTDist.py:13-31``)
"""

__version__ = "0.1.0"
