"""Efficiency accounting (utils/efficiency.py): FLOPs budgets pinned
against hand arithmetic and the XLA cost-analysis cross-check, peak
resolution, goodput/MFU meters, the trace_ops --flops CLI, and the
bench efficiency phase."""

import math

import pytest

from distributed_tensorflow_tpu.models import get_model
from distributed_tensorflow_tpu.utils import efficiency
from distributed_tensorflow_tpu.utils.efficiency import (
    EfficiencyMeter,
    GoodputMeter,
    flops_budget,
    peak_flops_per_sec,
)

# ------------------------------------------------------------- budgets


def test_cnn_budget_matches_hand_arithmetic():
    """The flagship CNN's per-layer forward FLOPs, computed by hand from
    the architecture (conv 2*K*K*Cin*Cout*H*W, dense 2*M*N)."""
    m = get_model("deep_cnn", image_size=28, channels=1, num_classes=10)
    b = flops_budget(m, 128)
    expect = {
        "conv1 5x5": 2 * 5 * 5 * 1 * 32 * 28 * 28,
        "conv2 5x5": 2 * 5 * 5 * 32 * 64 * 14 * 14,
        "dense1": 2 * 3136 * 1024,
        "logits": 2 * 1024 * 10,
    }
    got = {r["layer"]: r["flops"] for r in b["rows"]}
    assert got == expect
    fwd = sum(expect.values())
    assert b["fwd_flops_per_example"] == fwd
    assert b["train_flops_per_example"] == 3 * fwd
    assert b["flops_per_step"] == 3 * fwd * 128
    assert b["source"] == "analytic"


def test_mlp_budget_exact_and_batch_scaling():
    m = get_model("mlp", image_size=28, channels=1, num_classes=10,
                  hidden_units=100)
    b1 = flops_budget(m, 1)
    assert b1["fwd_flops_per_example"] == 2 * 784 * 100 + 2 * 100 * 10
    b64 = flops_budget(m, 64)
    assert b64["flops_per_step"] == 64 * b1["flops_per_step"]


def test_lm_budget_scales_with_blocks_and_counts_head():
    mk = lambda nb: get_model("lm", vocab_size=64, seq_len=32, d_model=32,
                              num_heads=2, num_blocks=nb)
    b1, b2 = flops_budget(mk(1)), flops_budget(mk(2))
    per_block = b2["fwd_flops_per_example"] - b1["fwd_flops_per_example"]
    s, d, mlp = 32, 32, 4 * 32
    assert per_block == (4 * s * 2 * d * d + 2 * (2 * s * s * d)
                         + 2 * s * 2 * d * mlp)
    head = [r for r in b1["rows"] if r["layer"] == "lm_head"]
    assert head and head[0]["flops"] == s * 2 * d * 64


def test_resnet_and_transformer_budgets_positive():
    for name, kw in (("resnet20", dict(image_size=32, channels=3,
                                       num_classes=10)),
                     ("transformer", dict(image_size=28, channels=1,
                                          num_classes=10, d_model=32,
                                          num_heads=2, num_blocks=2))):
        b = flops_budget(get_model(name, **kw))
        assert b["fwd_flops_per_example"] > 0
        assert all(r["flops"] > 0 for r in b["rows"])


def test_unknown_model_raises():
    class Exotic:
        pass

    with pytest.raises(ValueError, match="no analytic FLOPs rule"):
        flops_budget(Exotic())
    with pytest.raises(ValueError, match="batch_size"):
        flops_budget(get_model("mlp", image_size=28, channels=1,
                               num_classes=10), 0)


def test_xla_cost_analysis_cross_check_in_band():
    """The dual pattern's measured half: where the backend reports
    FLOPs, the cost-analysis total must land in the same decade as the
    analytic budget (XLA fuses/simplifies, so equality is not expected
    — a 2x band catches unit errors like fwd-only vs fwd+bwd)."""
    m = get_model("deep_cnn", image_size=28, channels=1, num_classes=10)
    b = flops_budget(m, 8, xla=True)
    if b["xla_flops_per_step"] is None:
        pytest.skip("backend reports no cost-analysis FLOPs")
    ratio = b["xla_flops_per_step"] / b["flops_per_step"]
    assert 0.5 <= ratio <= 2.0, ratio
    assert b["source"] == "analytic+xla_cost_analysis"


# ---------------------------------------------------------------- peak


def test_peak_resolution_and_cache():
    efficiency._reset_peak_cache()
    peak, src = peak_flops_per_sec()
    assert peak > 0
    assert src == "matmul_calibration" or src.startswith("device_table")
    peak2, src2 = peak_flops_per_sec()  # cached: same answer
    assert (peak2, src2) == (peak, src)
    po, so = peak_flops_per_sec(override=123.0)
    assert po == 123.0 and so == "flag_override"


# -------------------------------------------------------------- meters


def test_goodput_meter_arithmetic():
    g = GoodputMeter()
    g.charge(0.5, "ckpt")
    g.charge(0.25, "eval")
    g.charge(-1.0, "eval")  # negative clamps to 0, never credits back
    assert g.lost_s == pytest.approx(0.75)
    assert g.by_kind() == {"ckpt": 0.5, "eval": 0.25}
    s = g.scalars()
    assert 0.0 <= s["goodput"] <= 1.0
    assert s["goodput_lost_s"] == pytest.approx(0.75)


def test_efficiency_meter_scalars():
    m = get_model("deep_cnn", image_size=28, channels=1, num_classes=10)
    eff = EfficiencyMeter(m, 128, 2, peak_override=1e12)
    assert eff.peak_flops_total == 2e12  # per-chip peak x chips
    s = eff.scalars(1000.0)  # 1000 examples/sec
    assert s["model_flops_per_sec"] == pytest.approx(
        1000.0 * eff.train_flops_per_example)
    assert s["mfu"] == pytest.approx(
        1000.0 * eff.train_flops_per_example / 2e12, rel=1e-4)
    assert 0.0 <= s["goodput"] <= 1.0
    assert math.isfinite(s["goodput_lost_s"])


def test_meter_from_flags_gates():
    class F:
        mfu = False
        mfu_peak_flops = 0.0

    m = get_model("mlp", image_size=28, channels=1, num_classes=10)
    assert efficiency.meter_from_flags(F(), m, 32, 1) is None

    class F2:
        mfu = True
        mfu_peak_flops = 1e12

    class Exotic:
        pass

    # unknown model: accounting declines quietly, training must proceed
    assert efficiency.meter_from_flags(F2(), Exotic(), 32, 1) is None
    eff = efficiency.meter_from_flags(F2(), m, 32, 4)
    assert eff is not None and eff.peak_flops_total == 4e12


# ------------------------------------------------------ CLI and bench


def test_trace_ops_flops_printer(capsys):
    from tools import trace_ops

    trace_ops.print_flops("deep_cnn", 64)
    out = capsys.readouterr().out
    assert "conv2 5x5" in out and "dense1" in out
    assert "train FLOPs/step at batch 64" in out
    assert f"{3 * 27767808 * 64:,}" in out  # the hand-pinned total
    with pytest.raises(SystemExit, match="unknown model"):
        trace_ops.print_flops("nope", 1)


def test_bench_efficiency_phase_fields():
    import bench

    out = bench.efficiency_phase()
    assert out.get("efficiency_error") is None, out
    assert 0.0 < out["mfu"] <= 1.0
    assert 0.0 < out["goodput"] <= 1.0
    assert out["flops_per_step"] == 3 * 27767808 * bench.EFFICIENCY_BATCH
    assert out["model_flops_per_sec"] > 0
    assert out["mfu_peak_flops_per_sec"] > 0
    assert out["mfu_peak_source"]
