"""Switch-style top-1 mixture-of-experts MLP — the EP compute core.

The reference has no MoE (SURVEY.md §2c: expert parallelism ABSENT);
this is the build's fifth parallelism family, designed XLA-first: all
static shapes, routing + dispatch as one-hot EINSUMS (the Switch
Transformer formulation), no gather loops, so the MXU sees three big
batched matmuls per expert group and the compiler fuses the rest.

Routing: per token, softmax over E router logits, top-1 expert, the
chosen probability as the gate. Capacity C = ceil(cf * T / E) tokens
per expert — positions beyond C are DROPPED (the token's MoE output is
zero; its residual stream passes through unchanged), which is what
keeps every shape static. The load-balance auxiliary loss is the
Switch one: E * sum_e(fraction_of_tokens_e * mean_router_prob_e),
minimized at uniform routing; the model adds it to the training loss
scaled by ``moe_aux``.

EXPERT PARALLELISM: pass ``axis_name`` inside ``shard_map`` with the
expert leaves sharded on their leading E axis — every device routes
ALL tokens identically (router params replicated, h replicated over
the axis), slices ITS experts' dispatch columns, computes only those,
and one ``psum`` combines the partial outputs. Gradient accounting
(the trap family sequence_parallel/pipeline_parallel document): the
caller differentiates loss/P per device; the psum transpose then
delivers UNSCALED cotangents, so expert-shard grads are exact partials
(no reduction) and replicated-leaf grads total under one psum over the
axis — parallel/expert_parallel.py owns that derivation; this op just
takes the axis.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def moe_capacity(tokens: int, num_experts: int,
                 capacity_factor: float) -> int:
    """Static per-expert token capacity (>=1)."""
    return max(1, math.ceil(capacity_factor * tokens / num_experts))


def switch_moe(h, params, *, capacity_factor: float = 1.25,
               axis_name: str | None = None, compute_dtype=None):
    """(B, S, d) -> ((B, S, d), aux_dict).

    ``params``: {"router": (d, E), "w1": (E, d, m), "b1": (E, m),
    "w2": (E, m, d), "b2": (E, d)} — under ``axis_name`` the expert
    leaves are the LOCAL (E/P, ...) shards. ``aux``: {"lb_loss"
    (scalar, identical on every device), "dropped_frac"}."""
    b, s, d = h.shape
    t = b * s
    hf = h.reshape(t, d)
    cd = compute_dtype
    router = params["router"]
    e_local = params["w1"].shape[0]
    if axis_name is None:
        e_total = e_local
        e_start = 0
    else:
        e_total = e_local * lax.axis_size(axis_name)
        e_start = lax.axis_index(axis_name) * e_local
    cap = moe_capacity(t, e_total, capacity_factor)

    # routing in f32 — identical on every device (replicated inputs)
    logits = jnp.dot(hf.astype(jnp.float32), router.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)          # (T, E)
    expert = jnp.argmax(probs, axis=-1)              # (T,)
    gate = jnp.max(probs, axis=-1)                   # (T,)
    # 1-based arrival position of each token in its expert's queue,
    # computed with an INT32 cumsum — an f32 cumsum loses integer
    # exactness past 2^24 tokens/shard and would silently corrupt
    # dispatch slots; tokens past the capacity are dropped (static
    # shapes). The f32 assignment matrix is a cast of the same one_hot.
    assign_i = jax.nn.one_hot(expert, e_total, dtype=jnp.int32)
    assign = assign_i.astype(jnp.float32)
    pos = jnp.cumsum(assign_i, axis=0) * assign_i    # (T, E) int32
    keep = assign * (pos <= cap)
    slot = jax.nn.one_hot(pos - 1, cap,
                          dtype=jnp.float32) * keep[..., None]  # (T,E,C)

    # load balance (Switch): E * sum_e f_e * p_e — from the FULL
    # assignment, so it is identical on every device
    f_e = jnp.mean(assign, axis=0)
    p_e = jnp.mean(probs, axis=0)
    lb_loss = e_total * jnp.sum(f_e * p_e)
    dropped = 1.0 - jnp.sum(keep) / jnp.maximum(jnp.sum(assign), 1.0)

    # this device's experts only
    local = lax.dynamic_slice_in_dim(slot, e_start, e_local, axis=1)
    if cd is not None:
        xe = jnp.einsum("tec,td->ecd", local.astype(cd), hf.astype(cd))
        he = jax.nn.relu(
            jnp.einsum("ecd,edm->ecm", xe, params["w1"].astype(cd))
            + params["b1"].astype(cd)[:, None, :])
        ye = (jnp.einsum("ecm,emd->ecd", he, params["w2"].astype(cd))
              + params["b2"].astype(cd)[:, None, :])
        comb = (local * gate[:, None, None]).astype(cd)
        y = jnp.einsum("tec,ecd->td", comb, ye).astype(h.dtype)
    else:
        xe = jnp.einsum("tec,td->ecd", local, hf)
        he = jax.nn.relu(
            jnp.einsum("ecd,edm->ecm", xe, params["w1"])
            + params["b1"][:, None, :])
        ye = (jnp.einsum("ecm,emd->ecd", he, params["w2"])
              + params["b2"][:, None, :])
        y = jnp.einsum("tec,ecd->td", local * gate[:, None, None], ye)
        y = y.astype(h.dtype)
    if axis_name is not None:
        y = lax.psum(y, axis_name)
    return y.reshape(b, s, d), {"lb_loss": lb_loss,
                                "dropped_frac": dropped}
