"""The training loop: reference hot-loop semantics on a TPU-native step.

Reference loop (``MNISTDist.py:172-188``): while not stopped and
``step < training_iter`` — draw a minibatch, every ``display_step`` print
job/task + step + minibatch loss/accuracy (evaluated *before* the update,
dropout off, ``:179-182``), then run one optimizer step. Termination is on
the shared global step. On exit: ``sv.stop()`` + "Optimization Finished!"
(``:192-193``).

This loop keeps those semantics; what changed is underneath: the step is
one compiled XLA executable with state resident in HBM, and display-step
evaluation reuses a cached compiled eval fn. Modes:

- "local": single device (CPU parity config / one TPU chip)
- "sync":  synchronous DP over all local devices (mesh + psum over ICI)
The async "ps" mode lives in parallel/ps_emulation.py and drives this
same loop through a PS-backed step function.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax

from distributed_tensorflow_tpu.data import read_data_sets
from distributed_tensorflow_tpu.models import get_model
from distributed_tensorflow_tpu.parallel import make_dp_train_step, make_mesh, shard_batch
from distributed_tensorflow_tpu.parallel.data_parallel import (
    make_dp_eval_step,
    replicate_state,
)
from distributed_tensorflow_tpu.training import (
    create_train_state,
    get_optimizer,
    make_eval_step,
    make_train_step,
)
from distributed_tensorflow_tpu.training.supervisor import Supervisor
from distributed_tensorflow_tpu.training.train_state import evaluate
from distributed_tensorflow_tpu.utils import MetricsLogger, Throughput


@dataclass
class TrainResult:
    final_step: int
    train_metrics: dict[str, float]
    test_metrics: dict[str, float] | None
    images_per_sec: float
    images_per_sec_per_chip: float
    n_chips: int


def build_model_for(FLAGS, meta: dict):
    import jax.numpy as jnp

    compute_dtype = jnp.bfloat16 if FLAGS.bf16 else None
    kwargs = {}
    if FLAGS.model == "deep_cnn" and getattr(FLAGS, "pallas", False):
        kwargs["use_pallas"] = True
    return get_model(
        FLAGS.model,
        image_size=meta["image_size"],
        channels=meta["channels"],
        num_classes=meta["num_classes"],
        compute_dtype=compute_dtype,
        **kwargs,
    )


def train(FLAGS, mode: str = "local") -> TrainResult:
    """Run a full training job in "local" or "sync" mode."""
    ds = read_data_sets(FLAGS.data_dir, one_hot=True, dataset=FLAGS.dataset,
                        seed=FLAGS.seed)
    model = build_model_for(FLAGS, ds.meta)
    opt = get_optimizer(FLAGS.optimizer, FLAGS.learning_rate)
    state = create_train_state(model, opt, seed=FLAGS.seed)

    n_chips = 1
    if mode == "sync":
        mesh = make_mesh()
        n_chips = mesh.devices.size
        if FLAGS.batch_size % n_chips:
            raise ValueError(
                f"--batch_size={FLAGS.batch_size} must be divisible by the "
                f"{n_chips} devices in the data mesh"
            )
        state = replicate_state(mesh, state)
        step_fn = make_dp_train_step(model, opt, mesh, keep_prob=FLAGS.keep_prob)
        eval_fn = make_dp_eval_step(model, mesh)
        prep = lambda b: shard_batch(mesh, b)
    else:
        step_fn = make_train_step(model, opt, keep_prob=FLAGS.keep_prob)
        eval_fn = make_eval_step(model)
        prep = lambda b: b

    sv = Supervisor(
        is_chief=(FLAGS.task_index == 0),
        logdir=FLAGS.logdir,
        save_model_secs=FLAGS.save_model_secs,
    )
    logger = MetricsLogger(FLAGS.logdir if sv.is_chief else None,
                           job_name=FLAGS.job_name or "worker",
                           task_index=FLAGS.task_index)
    meter = Throughput(FLAGS.batch_size, n_chips)
    last_display = {}

    with sv.managed(state) as box:
        state, step = box.state, box.step
        meter.reset()
        while not sv.should_stop() and step < FLAGS.training_iter:
            batch = prep(ds.train.next_batch(FLAGS.batch_size))
            if step % FLAGS.display_step == 0:
                m = eval_fn(state.params, batch, state.model_state)
                last_display = {k: float(v) for k, v in m.items()}
                logger.log_display(step, last_display["loss"],
                                   last_display["accuracy"])
                logger.scalars(step, {"images_per_sec": meter.images_per_sec})
            state, _ = step_fn(state, batch)
            step += 1
            meter.step()
            box.update(state, step)
            sv.maybe_checkpoint(state, step)
        jax.block_until_ready(state.params)

    test_metrics = None
    if FLAGS.test_eval:
        test_metrics = evaluate(model, jax.device_get(state.params), ds.test,
                                model_state=jax.device_get(state.model_state))
        print("test accuracy: ", test_metrics["accuracy"],
              "test loss: ", test_metrics["loss"])
        logger.scalars(step, {"test_accuracy": test_metrics["accuracy"],
                              "test_loss": test_metrics["loss"]})
    print("Optimization Finished!")
    logger.close()
    return TrainResult(
        final_step=step,
        train_metrics=last_display,
        test_metrics=test_metrics,
        images_per_sec=meter.images_per_sec,
        images_per_sec_per_chip=meter.images_per_sec_per_chip,
        n_chips=n_chips,
    )
