"""Fixture dttcheck: references only the traced builder."""
from parallel.mod import make_traced_step

SCENARIOS = (make_traced_step,)
