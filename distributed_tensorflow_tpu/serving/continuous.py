"""Continuous batching: iteration-level scheduling over paged KV slots
(r21) — the serving answer to the long-generation adversary.

The whole-batch path (``DynamicBatcher`` + ``engine.generate``) commits
a microbatch for its ENTIRE generation: one 512-token request holds its
batch — and the worker — hostage while 8-token requests queue behind
it, and every batch member is billed a dense ``(B, seq_len, H, Dh)``
KV allocation regardless of its actual length. The Orca line of work
fixes the first problem (schedule between decode ITERATIONS, not
batches); vLLM's PagedAttention fixes the second (block-allocate the
cache so memory tracks live tokens). This module is both, on this
repo's bitwise-pinned decode:

- ``ContinuousScheduler`` owns a fixed set of batch SLOTS over one
  traced step (``decode.make_slot_step``). Every iteration it feeds
  each resident slot its next token at its own position; requests are
  admitted into free slots and retired out of finished ones BETWEEN
  iterations, so a long generation never blocks a short one behind it.
- Prefill is chunked maximally: a prompt enters the cache one token
  per iteration through the SAME step (prefill-as-decode), so a long
  prompt cannot stall in-flight decodes for more than one iteration —
  and the bitwise induction (see ``make_slot_step``) holds from
  position 0 with no separate prefill computation to pin.
- The KV cache is paged: ``kvpage.PageAllocator`` commits a request's
  worst-case footprint at admission (no-preemption guarantee) and hands
  out physical pages as generation crosses page boundaries, so
  ``pages_in_use`` tracks live tokens. Occupancy feeds the ``/metrics``
  ``hbm`` block and the ``--serve_hbm_headroom_pct`` drain floor.
- ``ContinuousBatcher`` is the drop-in sibling of ``DynamicBatcher``:
  same ``Future``/expiry/stats machinery (imported, not reimplemented),
  same admission contract (reject-never-hang, ``serve_admit`` fault
  point, request-plane dispositions on every exit), same
  close/drain/die story — so ``server.py`` and the loadgen drive
  either through one interface, selected by ``--serve_scheduler``.

Phase accounting under mid-batch admission: a request's slot residency
is bracketed by ``taken()``/``run_start()`` at slot admission and
``run_end()`` at retirement; every iteration's wall duration is noted
to every resident request (phase ``decode`` with one tick when that
slot sampled a token this iteration, ``prefill`` while its prompt is
still entering the cache) — each request WAITED the full iteration
whatever its share of the math was, exactly the whole-batch
convention. All notes land inside the request's own run window, so the
plane's ``sum(phases) == wall`` invariant survives admission and
retirement at any iteration, including rejections and expiries.

Greedy parity contract: with ``temperature == 0`` the per-request token
sequence is BITWISE identical to whole-batch ``generate()`` — asserted
per-request on mixed-length workloads by tests/test_continuous.py.
Temperature sampling is served (per-request stream seeded by the
request's ``seed``) but makes no cross-scheduler reproducibility
promise: the whole-batch path draws from one batch-shaped stream that
has no per-request decomposition.

Threads (dttsan registry): ``ContinuousBatcher`` starts a scheduler
thread (``_sched_loop`` — the iteration loop) and an expiry thread
(``_expiry_loop`` — deadline enforcement independent of iteration
progress). Queue and lifecycle state live under the batcher's
condition variable; counters under their own locks; the step dispatch
itself runs OUTSIDE every lock so admission never waits on the chip.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from distributed_tensorflow_tpu.serving import reqtrace
from distributed_tensorflow_tpu.serving.batcher import (
    BatcherStats,
    Future,
    RejectedError,
    _Request,
)
from distributed_tensorflow_tpu.serving.kvpage import PageAllocator
from distributed_tensorflow_tpu.utils import resources
from distributed_tensorflow_tpu.utils.faults import fault_point


class HostSlotBackend:
    """Chip-free slot stepper: deterministic logits from a tiny seeded
    embedding/head pair, no jax anywhere. The test and bench double for
    ``EngineSlotBackend`` — the scheduler state machine, the page
    ledger, the phase accounting, and the A/B throughput drill all run
    against it without a backend or a compile. ``step_cost`` (a
    callable) lets the bench charge a controlled amount of work per
    iteration so both arms of the A/B pay the same per-step price."""

    def __init__(self, *, n_slots: int = 4, capacity: int = 64,
                 page_size: int = 16, num_pages: int = 0,
                 vocab_size: int = 32, step_cost=None):
        if n_slots < 2:
            raise ValueError(f"n_slots must be >= 2, got {n_slots}")
        if page_size < 1 or capacity % page_size:
            raise ValueError(f"page_size ({page_size}) must divide the "
                             f"capacity ({capacity})")
        self.n_slots = int(n_slots)
        self.capacity = int(capacity)
        self.page_size = int(page_size)
        self.pages_per_slot = self.capacity // self.page_size
        self.num_pages = int(num_pages) or self.n_slots * self.pages_per_slot
        self.vocab_size = int(vocab_size)
        self._step_cost = step_cost
        rng = np.random.default_rng(0)
        self._emb = rng.standard_normal(
            (self.vocab_size, 16)).astype(np.float32)
        self._head = rng.standard_normal(
            (16, self.vocab_size)).astype(np.float32)

    def step(self, page_table, tok, t):
        if self._step_cost is not None:
            self._step_cost()
        # position-dependent so greedy sequences are non-trivial
        h = self._emb[tok] + np.asarray(t)[:, None].astype(np.float32)
        return h @ self._head

    def wants_refresh(self) -> bool:
        return False

    def refresh(self) -> None:
        pass

    def reset(self) -> None:
        pass


class EngineSlotBackend:
    """Device-backed slot stepper over the paged KV pools.

    Holds the engine's current (params, step) pinned for in-flight
    requests — the scheduler refreshes the pin (``refresh``) only when
    no slot is resident, so a hot-swap changes what FUTURE requests
    see, never one mid-generation (drain-to-swap; the whole-batch
    analogue reads ``engine.current()`` once per batch).

    Recompile sentry: slot count, page-table shape, and pool shapes are
    all static, so continuous mode contributes exactly ONE traced
    signature (``serve_continuous_step``) however requests arrive —
    noted per dispatch like the whole-batch sites.

    All mutable state (params pin, device pools) is guarded by one lock:
    the scheduler thread steps while tests and /metrics handlers may
    probe."""

    def __init__(self, engine, *, n_slots: int = 4, page_size: int = 16,
                 num_pages: int = 0):
        from distributed_tensorflow_tpu.serving import decode as dec

        dec.check_decodable(engine.model)
        if engine.mesh is not None:
            raise ValueError(
                "the continuous scheduler serves one replica per device; "
                "multi-device meshes / --serve_tp are whole-batch only")
        if n_slots < 2:
            # width >= 2 keeps every contraction on the GEMM kernel —
            # the same floor the whole-batch decode enforces for parity
            raise ValueError(f"n_slots must be >= 2, got {n_slots}")
        capacity = engine.model.seq_len
        if page_size < 1 or capacity % page_size:
            raise ValueError(f"page_size ({page_size}) must divide the "
                             f"cache capacity ({capacity})")
        pages_per_slot = capacity // page_size
        if num_pages <= 0:
            # full provisioning: every slot can hold a max-length request
            num_pages = n_slots * pages_per_slot
        if num_pages < pages_per_slot:
            raise ValueError(
                f"num_pages ({num_pages}) cannot hold one full-context "
                f"request ({pages_per_slot} pages)")
        self.engine = engine
        self.n_slots = int(n_slots)
        self.capacity = capacity
        self.page_size = int(page_size)
        self.pages_per_slot = pages_per_slot
        self.num_pages = int(num_pages)
        self.vocab_size = engine.model.vocab_size
        self._lock = threading.Lock()
        self._step_fn = dec.make_slot_step(engine.model, page_size,
                                           jit=engine.jit)
        self._pools = dec.make_slot_pools(engine.model, page_size,
                                          self.num_pages)
        self._params, self._params_step = engine.current()

    @property
    def params_step(self) -> int:
        with self._lock:
            return self._params_step

    def wants_refresh(self) -> bool:
        with self._lock:
            pinned = self._params_step
        return self.engine.step != pinned

    def refresh(self) -> None:
        """Re-pin the engine's current params. Only called by the
        scheduler with zero residents (drain-to-swap)."""
        with self._lock:
            self._params, self._params_step = self.engine.current()

    def reset(self) -> None:
        """Re-zero the device pools (scheduler abort path — donated
        buffers are in an unknown state after a failed dispatch)."""
        from distributed_tensorflow_tpu.serving import decode as dec

        with self._lock:
            self._pools = dec.make_slot_pools(
                self.engine.model, self.page_size, self.num_pages)

    def step(self, page_table, tok, t) -> np.ndarray:
        import jax.numpy as jnp

        resources.note_signature(
            "serve_continuous_step",
            (self.n_slots, self.capacity, self.page_size, self.num_pages))
        with self._lock:
            logits, self._pools = self._step_fn(
                self._params, self._pools,
                jnp.asarray(page_table), jnp.asarray(tok), jnp.asarray(t))
        return np.asarray(logits)


class _Slot:
    """One resident request's decode state: ``fed`` counts positions
    already written into the cache (prompt first, then generated
    tokens); the request retires when ``len(generated) == n``."""

    __slots__ = ("req", "prompt", "n", "fed", "generated", "reservation",
                 "temperature", "seed", "rng", "keep_logits", "logits")

    def __init__(self, req, prompt, n, reservation):
        self.req = req
        self.prompt = prompt
        self.n = n
        self.fed = 0
        self.generated: list[int] = []
        self.reservation = reservation
        self.temperature = float(req.opts.get("temperature", 0.0) or 0.0)
        self.seed = req.opts.get("seed")
        self.rng = None
        self.keep_logits = bool(req.opts.get("return_logits", False))
        self.logits: list[np.ndarray] = []


class ContinuousScheduler:
    """Slot/page state machine driven by the batcher's scheduler thread.

    State per slot: empty (``None`` — page-table row all zeros, feeds
    the scratch page) or resident (a ``_Slot``). One iteration
    (``_iterate``) feeds every resident its next token at its own
    position through ONE backend step, samples where a slot's prompt
    is already consumed, and retires slots whose generation completed.
    Underscored methods run on the scheduler thread only; ``snapshot``
    and ``allocator.occupancy()`` are the cross-thread read surface
    (lock-guarded counters, nothing else shared).

    Token feed schedule (the bitwise mirror of ``generate()``): a
    request with prompt length P and N new tokens feeds positions
    ``0 .. P+N-2`` — prompt tokens first, then its own samples; the
    sample drawn after feeding position ``P-1+k`` is output token
    ``k``, and the final token is sampled but never fed (whole-batch
    stops stepping there too). Cache footprint is therefore exactly
    ``P+N-1`` tokens = the page commitment."""

    def __init__(self, backend):
        self.backend = backend
        self.n_slots = backend.n_slots
        self.capacity = backend.capacity
        self.page_size = backend.page_size
        self.pages_per_slot = backend.pages_per_slot
        self.allocator = PageAllocator(backend.num_pages, backend.page_size)
        self._slots: list = [None] * self.n_slots
        self._free_slots = list(range(self.n_slots - 1, -1, -1))
        self._page_table = np.zeros((self.n_slots, self.pages_per_slot),
                                    np.int32)
        self._tok = np.zeros(self.n_slots, np.int32)
        self._t = np.zeros(self.n_slots, np.int32)
        # slot state (slots, free list, page table, feed buffers) is
        # touched by exactly one scheduler thread, but the failure path
        # (_abort_residents) can also run from close(); one uncontended
        # lock makes the ownership explicit. Order: batcher cv →
        # _slot_lock → {_lock, allocator._lock, backend._lock}
        self._slot_lock = threading.Lock()
        # counters: written by the scheduler thread, read by /metrics
        # and the bench via snapshot() — one lock guards them
        self._lock = threading.Lock()
        self._iterations = 0
        self._tokens_emitted = 0
        self._resident_iterations = 0
        self._live_tokens_high = 0
        self._ledger_ok = True

    # ------------------------------------------------- admission checks

    def _validate(self, prompt: np.ndarray, n: int) -> str | None:
        """Reject reasons mirroring ``decode.generate``'s loud
        ValueErrors (vocab range, capacity) plus the page-pool bound;
        None when servable."""
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            return f"prompt must be 1-D with >= 1 token; got shape " \
                   f"{tuple(prompt.shape)}"
        if n < 1:
            return f"max_new_tokens must be >= 1, got {n}"
        p = int(prompt.shape[0])
        if p + n > self.capacity:
            return (f"prompt ({p}) + max_new_tokens ({n}) exceeds the "
                    f"model's context window / cache capacity "
                    f"({self.capacity})")
        vocab = getattr(self.backend, "vocab_size", None)
        if vocab is not None and prompt.size and (
                int(prompt.min()) < 0 or int(prompt.max()) >= vocab):
            return (f"prompt ids must be in [0, {vocab}); got range "
                    f"[{prompt.min()}, {prompt.max()}]")
        if self.allocator.pages_for(p + n - 1) > self.allocator.num_pages:
            return (f"request footprint ({p + n - 1} tokens) exceeds the "
                    f"KV page pool ({self.allocator.num_pages} pages of "
                    f"{self.page_size})")
        return None

    def _can_admit(self, req) -> bool:
        with self._slot_lock:
            if not self._free_slots:
                return False
        p = int(np.asarray(req.payload).shape[-1])
        n = int(req.opts.get("max_new_tokens", 16))
        return self.allocator.can_admit(p + n - 1)

    def _has_residents(self) -> bool:
        with self._slot_lock:
            return len(self._free_slots) < self.n_slots

    def _wants_refresh(self) -> bool:
        return self.backend.wants_refresh()

    def _refresh(self) -> None:
        self.backend.refresh()

    # ---------------------------------------------------- slot lifecycle

    def _admit(self, req) -> None:
        """Move a validated, page-feasible request into a free slot.
        Caller guarantees ``_can_admit`` held; runs under the batcher cv
        (cheap: no device work here)."""
        prompt = np.asarray(req.payload, np.int32).reshape(-1)
        n = int(req.opts.get("max_new_tokens", 16))
        reservation = self.allocator.reserve(len(prompt) + n - 1)
        with self._slot_lock:
            i = self._free_slots.pop()
            self._slots[i] = _Slot(req, prompt, n, reservation)
        tr = req.trace
        if tr is not None:
            tr.taken()
            tr.run_start()
        with self._lock:
            it = self._iterations
        reqtrace.note_slot_admit(tr, iteration=it, slot=i)

    def _retire(self, i: int):
        """Free slot ``i`` (generation complete): release its pages,
        zero its page-table row back to scratch, hand back (request,
        result)."""
        s = self._slots[i]
        tr = s.req.trace
        if tr is not None:
            tr.run_end()
        with self._lock:
            it = self._iterations
        reqtrace.note_slot_retire(tr, iteration=it)
        self.allocator.release(s.reservation)
        self._page_table[i, :] = 0
        self._tok[i] = 0
        self._t[i] = 0
        self._slots[i] = None
        self._free_slots.append(i)
        tokens = np.concatenate(
            [s.prompt, np.asarray(s.generated, np.int32)])
        if s.keep_logits:
            return s.req, {"tokens": tokens, "logits": np.stack(s.logits)}
        return s.req, tokens

    def _abort_residents(self) -> list:
        """Failure path: evict every resident (pages released, slots
        zeroed, pools re-zeroed) and return their requests for the
        batcher to fail. The scheduler keeps serving afterwards."""
        failed = []
        with self._slot_lock:
            for i in range(self.n_slots):
                s = self._slots[i]
                if s is None:
                    continue
                if s.req.trace is not None:
                    s.req.trace.run_end()
                self.allocator.release(s.reservation)
                self._page_table[i, :] = 0
                self._tok[i] = 0
                self._t[i] = 0
                self._slots[i] = None
                self._free_slots.append(i)
                failed.append(s.req)
        self.backend.reset()
        return failed

    # -------------------------------------------------------- iteration

    def _sample(self, s: _Slot, row: np.ndarray) -> int:
        if s.temperature > 0.0:
            import jax
            import jax.numpy as jnp

            if s.rng is None:
                s.rng = jax.random.PRNGKey(
                    int(s.seed) if s.seed is not None else 0)
            key = jax.random.fold_in(s.rng, len(s.generated))
            return int(np.asarray(jax.random.categorical(
                key, jnp.asarray(row) / s.temperature)))
        return int(row.argmax())

    def _iterate(self):
        """One decode tick over the residents. Returns
        ``(finished, n_active)`` where ``finished`` is a list of
        (request, result) pairs retired this iteration."""
        with self._slot_lock:
            return self._iterate_locked()

    def _iterate_locked(self):
        t0 = time.perf_counter()
        active = [i for i in range(self.n_slots)
                  if self._slots[i] is not None]
        for i in active:
            s = self._slots[i]
            if s.fed % self.page_size == 0:
                # crossing into a fresh logical page: map a physical one
                # (the admission commitment guarantees availability)
                self._page_table[i, s.fed // self.page_size] = \
                    self.allocator.alloc(s.reservation)
            p = len(s.prompt)
            self._tok[i] = (s.prompt[s.fed] if s.fed < p
                            else s.generated[s.fed - p])
            self._t[i] = s.fed
        logits = self.backend.step(self._page_table, self._tok, self._t)
        d = time.perf_counter() - t0
        finished = []
        n_sampled = 0
        for i in active:
            s = self._slots[i]
            sampling = s.fed >= len(s.prompt) - 1
            tr = s.req.trace
            if tr is not None:
                # every resident waited the whole iteration — same
                # convention as whole-batch note_phase; noting BEFORE
                # any run_end keeps the note inside the run window, so
                # sum(phases) == wall survives mid-batch retirement
                tr.note("decode" if sampling else "prefill", d,
                        ticks=1 if sampling else None)
            s.fed += 1
            if sampling:
                n_sampled += 1
                tok = self._sample(s, logits[i])
                s.generated.append(tok)
                if s.keep_logits:
                    s.logits.append(np.array(logits[i], copy=True))
                if len(s.generated) >= s.n:
                    finished.append(self._retire(i))
        # analytic page ledger: in-use pages must equal the sum of every
        # resident's ceil(fed / page_size) — i.e. memory tracks LIVE
        # tokens, the paged-cache claim, checked every iteration
        expect = sum(
            -(-self._slots[i].fed // self.page_size)
            for i in range(self.n_slots) if self._slots[i] is not None)
        in_use = self.allocator.occupancy()["pages_in_use"]
        live_tokens = sum(
            self._slots[i].fed for i in range(self.n_slots)
            if self._slots[i] is not None)
        with self._lock:
            self._iterations += 1
            self._tokens_emitted += n_sampled
            self._resident_iterations += len(active)
            self._ledger_ok = self._ledger_ok and (in_use == expect)
            if live_tokens > self._live_tokens_high:
                self._live_tokens_high = live_tokens
        return finished, len(active)

    # ---------------------------------------------------------- reports

    def snapshot(self) -> dict:
        """The cross-thread read surface: scheduler counters + page
        occupancy, for /metrics' ``continuous`` block and the bench's
        analytic facts."""
        with self._lock:
            iterations = self._iterations
            tokens = self._tokens_emitted
            resident = self._resident_iterations
            live_high = self._live_tokens_high
            ledger_ok = self._ledger_ok
        return {
            "n_slots": self.n_slots,
            "iterations": iterations,
            "tokens_emitted": tokens,
            "tokens_per_iteration": round(tokens / iterations, 4)
            if iterations else 0.0,
            "slot_occupancy": round(
                resident / (iterations * self.n_slots), 4)
            if iterations else 0.0,
            "live_tokens_high_water": live_high,
            "page_ledger_ok": ledger_ok,
            "kv_pages": self.allocator.occupancy(),
        }


class ContinuousBatcher:
    """``DynamicBatcher``'s continuous-mode sibling: same bounded
    admission, Future, expiry, stats, and request-plane contract —
    but the worker is an iteration-level scheduler loop instead of a
    take-batch/run-batch loop. One "batch" in the stats is one
    scheduler ITERATION (``mean_batch_size`` therefore reads as mean
    slot occupancy).

    Admission is strict FIFO: the queue head is admitted as soon as a
    slot AND its full page commitment are free; nothing overtakes it
    (no starvation of long requests behind cheap ones). Validation
    failures (vocab, capacity, page-pool bound) raise ``ValueError`` at
    submit — the same loud-400 contract as the whole-batch runner —
    with a "failed" disposition.
    """

    def __init__(self, backend, *, queue_depth: int = 64,
                 default_timeout_ms: float = 1000.0,
                 latency=None, on_iteration=None, name: str = "generate"):
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, "
                             f"got {queue_depth}")
        self.queue_depth = int(queue_depth)
        self.default_timeout_s = float(default_timeout_ms) / 1000.0
        self.latency = latency
        self._on_iteration = on_iteration
        self._route = name
        self.scheduler = ContinuousScheduler(backend)
        self.max_batch = backend.n_slots  # interface parity (stats/UX)
        self.stats = BatcherStats()
        self._queue: list[_Request] = []
        self._cv = threading.Condition()
        self._closed = False
        self._sched = threading.Thread(
            target=self._sched_loop, name=f"{name}-sched", daemon=True)
        self._sched.start()
        # deadlines fire independently of iteration progress, exactly
        # like the whole-batch expiry thread
        self._expirer = threading.Thread(
            target=self._expiry_loop, name=f"{name}-expiry", daemon=True)
        self._expirer.start()

    # ------------------------------------------------------- admission

    def submit(self, payload, timeout_ms: float | None = None,
               request_id: str | None = None, **opts) -> Future:
        """Admit one request; returns its Future. Same contract as
        ``DynamicBatcher.submit`` (reject-never-hang, echoed
        request_id) plus submit-time validation against the decode
        capacity and page pool."""
        now = time.monotonic()
        rid = str(request_id) if request_id else reqtrace.new_request_id()
        plane = reqtrace.get_plane()
        tr = (plane.begin(rid, self._route, payload)
              if plane is not None else None)
        timeout_s = (self.default_timeout_s if timeout_ms is None
                     else float(timeout_ms) / 1000.0)
        prompt = np.asarray(payload)
        n = int(opts.get("max_new_tokens", 16))
        err = self.scheduler._validate(prompt, n)
        if err is not None:
            with self.stats.lock:
                self.stats.failed += 1
            reqtrace.finish(tr, "failed", reason=err)
            raise ValueError(err)
        req = _Request(payload=prompt, opts=opts, group=None,
                       future=Future(), t_submit=now,
                       deadline=now + timeout_s, request_id=rid,
                       trace=tr)
        req.future.request_id = rid
        with self._cv:
            if self._closed:
                with self.stats.lock:
                    self.stats.rejected_closed += 1
                reqtrace.finish(tr, "rejected_closed",
                                reason="batcher closed")
                raise RejectedError("batcher closed", request_id=rid)
            if len(self._queue) >= self.queue_depth:
                with self.stats.lock:
                    self.stats.rejected_full += 1
                reason = (f"queue full (depth={self.queue_depth}); "
                          f"retry later")
                reqtrace.finish(tr, "rejected_full", reason=reason)
                raise RejectedError(reason, request_id=rid)
            with self.stats.lock:
                admit_count = self.stats.admitted + 1
            try:
                fault_point("serve_admit", count=admit_count)
            except Exception as e:
                with self.stats.lock:
                    self.stats.rejected_fault += 1
                reqtrace.finish(tr, "rejected_fault",
                                reason=f"admission fault: {e}")
                raise RejectedError(f"admission fault: {e}",
                                    request_id=rid) from e
            self._queue.append(req)
            if tr is not None:
                tr.admitted()
            with self.stats.lock:
                self.stats.admitted += 1
                self.stats.queue_depth = len(self._queue)
            self._cv.notify_all()
        return req.future

    # ------------------------------------------------- scheduler thread

    def _expire_locked(self) -> None:
        now = time.monotonic()
        keep = []
        for r in self._queue:
            if r.deadline <= now:
                with self.stats.lock:
                    self.stats.rejected_deadline += 1
                r.future.meta = reqtrace.finish(
                    r.trace, "expired",
                    reason="deadline exceeded before execution")
                r.future.set_error(RejectedError(
                    "deadline exceeded before execution",
                    request_id=r.request_id))
            else:
                keep.append(r)
        if len(keep) != len(self._queue):
            self._queue = keep
            with self.stats.lock:
                self.stats.queue_depth = len(self._queue)

    def _admit_locked(self) -> None:
        """Strict-FIFO slot admission from the queue head; stops at the
        first request that doesn't fit (slot or pages)."""
        sched = self.scheduler
        admitted = False
        while self._queue and sched._can_admit(self._queue[0]):
            r = self._queue.pop(0)
            sched._admit(r)
            admitted = True
        if admitted:
            with self.stats.lock:
                self.stats.queue_depth = len(self._queue)

    def _sched_loop(self) -> None:
        sched = self.scheduler
        while True:
            with self._cv:
                while True:
                    self._expire_locked()
                    draining = sched._wants_refresh()
                    if not draining:
                        self._admit_locked()
                    if sched._has_residents():
                        break
                    if self._closed and not self._queue:
                        return
                    if draining:
                        # drain-to-swap: zero residents is the moment a
                        # params hot-swap is safe (nothing mid-flight)
                        sched._refresh()
                        continue
                    self._cv.wait(0.05)
            # the step dispatch runs OUTSIDE the cv: admission (submit)
            # must never wait on the chip
            try:
                with self.stats.lock:
                    self.stats.batches += 1
                    n_iter = self.stats.batches
                fault_point("serve_batch", count=n_iter)
                finished, n_active = sched._iterate()
                with self.stats.lock:
                    self.stats.batched_requests += n_active
                now = time.monotonic()
                for r, res in finished:
                    if self.latency is not None:
                        self.latency.record((now - r.t_submit) * 1e3)
                    # meta BEFORE the result, like the whole-batch path
                    r.future.meta = reqtrace.finish(r.trace, "ok")
                    r.future.set_result(res)
                if finished:
                    with self.stats.lock:
                        self.stats.completed += len(finished)
                if self._on_iteration is not None:
                    try:
                        self._on_iteration(self)
                    except Exception as e:  # hooks never kill serving
                        print(f"serving on_iteration hook failed: {e}")
            except Exception as e:
                # one bad iteration (including an injected serve_batch
                # fault): fail the RESIDENTS, reset the slots, keep
                # serving the queue
                self._fail_residents(e, died=False)
            except BaseException as e:
                self._fail_residents(e, died=True)
                self._die(e)
                return

    def _fail_residents(self, error: BaseException, died: bool) -> None:
        requests = self.scheduler._abort_residents()
        if not requests:
            return
        with self.stats.lock:
            self.stats.failed += len(requests)
        what = "scheduler died" if died else f"{type(error).__name__}"
        for r in requests:
            if not r.future.done():
                r.future.meta = reqtrace.finish(
                    r.trace, "failed", reason=f"{what}: {error}")
                r.future.set_error(error)

    def _die(self, error: BaseException) -> None:
        with self._cv:
            self._closed = True
            pending, self._queue = self._queue, []
            with self.stats.lock:
                self.stats.queue_depth = 0
                self.stats.failed += len(pending)
            self._cv.notify_all()
        for r in pending:
            if not r.future.done():
                r.future.meta = reqtrace.finish(
                    r.trace, "failed",
                    reason=f"scheduler died: {error}")
                r.future.set_error(RejectedError(
                    f"scheduler died: {error}",
                    request_id=r.request_id))
        print(f"serving scheduler died: {type(error).__name__}: {error}")

    def _expiry_loop(self) -> None:
        while True:
            with self._cv:
                if self._closed and not self._queue:
                    return
                self._expire_locked()
                if self._queue:
                    wake = min(r.deadline for r in self._queue)
                    self._cv.wait(
                        max(wake - time.monotonic(), 0.0) + 1e-3)
                else:
                    self._cv.wait(0.05)

    # ----------------------------------------------------------- admin

    @property
    def closed(self) -> bool:
        with self._cv:
            return self._closed

    def close(self, drain: bool = True) -> None:
        """Stop the scheduler. ``drain=True`` finishes the residents
        AND the queue first; False rejects the queue (residents still
        finish — there is no preemption to cut them short)."""
        with self._cv:
            self._closed = True
            if not drain:
                pending, self._queue = self._queue, []
                for r in pending:
                    r.future.meta = reqtrace.finish(
                        r.trace, "rejected_closed",
                        reason="batcher closed")
                    r.future.set_error(RejectedError(
                        "batcher closed", request_id=r.request_id))
                with self.stats.lock:
                    self.stats.queue_depth = 0
            self._cv.notify_all()
        self._sched.join(timeout=30)
