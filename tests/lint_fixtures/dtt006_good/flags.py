"""DTT006 conforming fixture: every flag read by a registered
validator — one directly, one through a reader helper (the _require
pattern)."""


def DEFINE_integer(name, default, help_str=""):
    pass


DEFINE_integer("checked", 1, "covered directly")
DEFINE_integer("helped", 2, "covered via the helper")


def _require(values, name, check, what):
    v = values.get(name)
    if v is not None and not check(v):
        raise ValueError(f"--{name}={v} {what}")


def _validate(values):
    if int(values.get("checked") or 0) < 0:
        raise ValueError("--checked must be >= 0")
    _require(values, "helped", lambda v: int(v) >= 1, "must be >= 1")


FLAGS._register_validator(_validate)  # noqa: F821 — parsed, not run
