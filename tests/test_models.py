"""Model parity: shapes, parameter count (~3.27M), init distributions.

Reference: conv_net (MNISTDist.py:66-90), weights/biases dicts (:117-141),
weight_variable/bias_variable (:42-49).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.models import DeepCNN, get_model


@pytest.fixture(scope="module")
def model():
    return DeepCNN()


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


def test_registry():
    m = get_model("deep_cnn")
    assert isinstance(m, DeepCNN)


def test_param_shapes(params):
    w, b = params["weights"], params["biases"]
    assert w["wc1"].shape == (5, 5, 1, 32)
    assert w["wc2"].shape == (5, 5, 32, 64)
    assert w["wd1"].shape == (7 * 7 * 64, 1024)
    assert w["out"].shape == (1024, 10)
    assert b["bc1"].shape == (32,)
    assert b["bc2"].shape == (64,)
    assert b["bd1"].shape == (1024,)
    assert b["out"].shape == (10,)


def test_param_count(model, params):
    # reference model is ~3.27M params (SURVEY.md C6)
    n = model.num_params(params)
    expected = (
        5 * 5 * 1 * 32 + 5 * 5 * 32 * 64 + 3136 * 1024 + 1024 * 10
        + 32 + 64 + 1024 + 10
    )
    assert n == expected
    assert 3_270_000 < n < 3_280_000


def test_init_distributions(params):
    wd1 = np.asarray(params["weights"]["wd1"])
    # truncated normal sigma=0.1: bounded at +-0.2, std close to 0.1 (slightly less)
    assert np.abs(wd1).max() <= 0.2 + 1e-6
    assert 0.07 < wd1.std() < 0.11
    np.testing.assert_allclose(np.asarray(params["biases"]["bd1"]), 0.1)


def test_forward_shape(model, params):
    x = jnp.ones((4, 784))
    logits = model.apply(params, x)
    assert logits.shape == (4, 10)


def test_forward_accepts_image_shape(model, params):
    # reference reshapes [-1, 28,28,1] internally (MNISTDist.py:68)
    x = jnp.ones((4, 28, 28, 1))
    logits = model.apply(params, x)
    assert logits.shape == (4, 10)


def test_forward_deterministic_eval(model, params):
    x = jax.random.normal(jax.random.key(1), (2, 784))
    a = model.apply(params, x)
    b = model.apply(params, x)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dropout_active_in_train_mode(model, params):
    x = jax.random.normal(jax.random.key(1), (2, 784))
    a = model.apply(params, x, keep_prob=0.5, rng=jax.random.key(2), train=True)
    b = model.apply(params, x, keep_prob=0.5, rng=jax.random.key(3), train=True)
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_fashion_mnist_drop_in(model):
    # identical graph on a 28x28 grayscale drop-in: same model class works
    m2 = DeepCNN(image_size=28, num_classes=10)
    assert m2.flat_dim == model.flat_dim
