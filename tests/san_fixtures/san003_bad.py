"""SAN003 bad fixture: the lock-order/CV-discipline violations — an
AB-BA acquisition cycle, a bare wait (no while predicate), a notify
without holding, a blocking sleep under a lock, and a wait that keeps a
SECOND lock held through it."""
import time
import threading


class Deadlocky:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._cv = threading.Condition()
        self.items = []
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            with self._a:       # A -> B
                with self._b:
                    pass

    def backwards(self):
        with self._b:           # B -> A: the cycle
            with self._a:
                pass

    def bad_wait(self):
        with self._cv:
            self._cv.wait()     # no while predicate around it

    def bad_notify(self):
        self._cv.notify_all()   # not holding the condition

    def slow_under_lock(self):
        with self._a:
            time.sleep(0.5)     # blocking with _a held

    def wait_holding_other(self):
        with self._b:
            with self._cv:
                while not self.items:
                    self._cv.wait()  # _b stays held through the wait
