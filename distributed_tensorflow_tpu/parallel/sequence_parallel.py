"""Sequence/context parallelism: shard the TOKEN axis over the mesh.

The reference framework predates attention; this is the build's
long-context machinery (meta-goal: sequence parallelism as a first-class
mode). Layout: batch over the "data" axis, sequence over the "model"
axis of the standard ("data", "model") mesh. Params replicate; inside
``shard_map`` every device holds one (batch-slice, token-block) tile,
attention runs as a RING over the sequence axis (one ppermute hop per
step, k/v blocks rotating while queries stay — ops/attention), and the
model mean-pools with a psum so the classifier head sees the full
sequence. Peak per-device activation memory is one token block
regardless of total sequence length — the property that makes long
contexts fit at all.

Gradient reduction is the subtle half, and the two loss families need
separate derivations (both land on the SAME uniform pmean, for
different reasons):

POOLED CLASSIFIER (MiniTransformer): each sequence shard
differentiates its own replicated copy of the loss and the pooled
psum's transpose is itself a psum, so per-token parameter gradients
arrive as their true partials scaled by the axis size P, while the
post-pool head's gradients arrive bitwise-replicated — ONE uniform
pmean over the sequence axis reduces both exactly (mean of P-scaled
partials = the total; mean of replicas = identity).

PER-TOKEN LOSS (TransformerLM): nothing is replicated — shard p's
local loss L_p is the mean over ITS OWN (B_local, S/P) tokens, a
different scalar on every shard, and the global loss is
L = (1/P) * sum_p L_p (equal shard sizes make the mean of means the
token mean). Inside shard_map each shard seeds reverse-mode with
cotangent 1.0 on its OWN L_p; the joint transposed program therefore
computes the gradient of sum_p L_p = P*L. Cross-shard paths are
handled by the collectives' transposes — a query on shard q attends
keys shard p produced, and the ppermute transpose (the reverse
rotation) carries that cotangent back to shard p's backward — so the
per-shard grad outputs g_p are EXACT partitions of the total:
sum_p g_p = d(P*L)/dtheta. The uniform pmean (1/P)*sum_p g_p is then
exactly dL/dtheta. Note what changed from the pooled case: there the
factor P came from the psum transpose P-scaling every pre-pool
cotangent; here it comes from P independent loss seeds. Same
reduction, different proof — and the METRICS differ too: pooled
metrics are replicated over the sequence axis (pmean = identity),
per-token metrics are shard-local means that MUST be pmean'd over the
sequence axis to report the global mean (the step does both
unconditionally, exact in either case).

Then pmean over "data" as in ordinary sync DP, and every device
applies the identical update so the replicated state stays in sync.
Exactness vs the dense single-device step is pinned by
tests/test_attention.py (pooled) and tests/test_lm.py (per-token).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from distributed_tensorflow_tpu.training.train_state import (
    TrainState,
    apply_updates,
    compute_grads,
    loss_and_metrics,
)


def stage_batch_sp(mesh, batch, per_token_targets: bool = False):
    """(x, y) host batch -> device arrays with x tiled batch-over-"data",
    tokens-over-"model". Targets: batch-sharded for the pooled
    classifier (one label per example), or tiled EXACTLY like x when
    ``per_token_targets`` (the LM's (B, S) next-token targets live on
    the same shard as the tokens whose logits they score).

    Multi-process: ``batch`` is this process's LOCAL slice of the global
    batch with the FULL token axis (the "model"/sequence axis must stay
    within each host — the loop guards this); slices assemble into one
    global-mesh array via ``make_array_from_process_local_data``, each
    host uploading only to its own chips, exactly like DP/TP staging."""
    from distributed_tensorflow_tpu.parallel.mesh import put_global

    x, y = batch
    y_spec = (P(DATA_AXIS, MODEL_AXIS) if per_token_targets
              else P(DATA_AXIS))
    return put_global(
        (NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS)),
         NamedSharding(mesh, y_spec)),
        (x, y),
    )


def _span_tile_slices(sh, shape) -> tuple:
    """This process's contiguous tile of a global array under ``sh`` —
    the bounding box of its devices' shard indices, validated (once per
    (sharding, shape)) to be exactly covered by those shards so the
    packed-local-data contract cannot silently misplace rows."""
    import numpy as np

    imap = sh.addressable_devices_indices_map(shape)
    starts, stops = [], []
    for d in range(len(shape)):
        starts.append(min((idx[d].start or 0) for idx in imap.values()))
        stops.append(max((idx[d].stop if idx[d].stop is not None
                          else shape[d]) for idx in imap.values()))
    box = 1
    for a, b in zip(starts, stops):
        box *= b - a
    uniq = {
        tuple((s.start or 0, s.stop if s.stop is not None else shape[i])
              for i, s in enumerate(idx))
        for idx in imap.values()}
    covered = sum(int(np.prod([e - s for s, e in key])) if key else 1
                  for key in uniq)
    if covered != box:
        raise ValueError(
            f"process-local devices do not tile a contiguous box "
            f"(box {box}, covered {covered}); --sp_span_hosts needs "
            f"a standard-order mesh")
    return tuple(slice(a, b) for a, b in zip(starts, stops))


def make_sp_span_stager(mesh, per_token_targets: bool = False):
    """Span-host staging (--sp_span_hosts): the token axis crosses
    process boundaries, so every process holds the SAME global (x, y)
    batch (same-seed draw — processes in one data row are token-slices
    of the same sequences) and uploads only ITS tile. Ring hops between
    the processes' token blocks then ride DCN (``ppermute`` is
    process-transparent under ``jax.distributed``). The tile slices and
    their contiguity validation are computed ONCE per array shape (the
    hot input path re-slices with cached tuples); single-process falls
    back to plain ``stage_batch_sp`` placement."""
    import numpy as np

    from distributed_tensorflow_tpu.parallel.mesh import put_global

    xs = NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS))
    ys = NamedSharding(mesh, (P(DATA_AXIS, MODEL_AXIS)
                              if per_token_targets else P(DATA_AXIS)))
    cache: dict = {}

    def stage(batch):
        x, y = batch
        if jax.process_count() == 1:
            return put_global((xs, ys), (x, y))
        out = []
        for arr, sh in ((x, xs), (y, ys)):
            arr = np.asarray(arr)
            key = (id(sh), arr.shape)
            sl = cache.get(key)
            if sl is None:
                sl = cache[key] = _span_tile_slices(sh, arr.shape)
            out.append(jax.make_array_from_process_local_data(
                sh, arr[sl], arr.shape))
        return tuple(out)

    return stage


def stage_batch_sp_span(mesh, batch, per_token_targets: bool = False):
    """One-shot form of ``make_sp_span_stager`` (tests / library use)."""
    return make_sp_span_stager(mesh, per_token_targets)(batch)


def reshape_for_sp(model, x):
    """Flat (B, F) pixels -> (B, S, token) BEFORE staging, so the token
    axis exists to shard. A host-side numpy view — staging does the one
    upload (a jnp reshape here would bounce the batch host->device->host
    on the hot input path)."""
    import numpy as np

    return np.asarray(x).reshape(-1, model.seq_len, model.token_dim)


def make_sp_train_step(model, optimizer, mesh, keep_prob: float = 1.0,
                       donate: bool = True,
                       per_token_targets: bool = False,
                       grad_transform=None, accum_steps: int = 1):
    """Compiled sequence-parallel train step: (state, staged batch) ->
    (state, metrics).

    ``model`` must be constructed with ``seq_axis=MODEL_AXIS`` (it then
    ring-attends over that axis). State (params + opt slots) replicates.
    ``per_token_targets`` matches ``stage_batch_sp``'s: the LM's (B, S)
    targets are sharded over the token axis like the inputs.
    ``grad_transform`` (e.g. global-norm clip) runs on the FULLY
    aggregated grads — after both pmeans, identically on every device —
    and ``accum_steps`` splits the shard's batch slice into microbatches
    before the one reduction+update (``train_state.compute_grads``):
    both are pure post-reduction/pre-reduction transforms with no SP
    interaction, which is why they compose here exactly as in the DP
    step.
    """
    if getattr(model, "seq_axis", None) != MODEL_AXIS:
        raise ValueError(
            f"model.seq_axis must be {MODEL_AXIS!r} for the SP step "
            f"(got {getattr(model, 'seq_axis', None)!r})")

    def per_shard(state: TrainState, batch):
        rng, sub = jax.random.split(state.rng)
        # dropout key: distinct per data shard. Across SEQUENCE shards
        # the key stays identical — the pooled classifier's post-pool
        # dropout REQUIRES that (the replicated head computation must
        # not diverge between shards); the LM folds the sequence index
        # in itself (its per-token dropout wants decorrelated masks).
        sub = jax.random.fold_in(sub, lax.axis_index(DATA_AXIS))

        grads, shard_metrics, model_state = compute_grads(
            model, state.params, batch, keep_prob=keep_prob, rng=sub,
            model_state=state.model_state, accum_steps=accum_steps,
        )
        # ONE uniform pmean over the sequence axis is exact for EVERY
        # parameter and BOTH loss families — see the module docstring's
        # two derivations (pooled: psum-transpose P-scaling + replicated
        # head; per-token: P independent loss seeds whose per-shard
        # grads partition d(P*L)/dtheta, with ppermute transposes
        # carrying cross-shard cotangents home).
        # tests/test_attention.py and tests/test_lm.py pin both.
        grads = lax.pmean(grads, MODEL_AXIS)
        grads = lax.pmean(grads, DATA_AXIS)
        if grad_transform is not None:
            grads = grad_transform(grads)
        # metrics: pooled-classifier metrics are replicated over the
        # sequence axis (pmean = identity); per-token metrics are
        # shard-local token means that NEED the sequence pmean to be
        # the global token mean. Unconditional, exact for both.
        metrics = lax.pmean(shard_metrics, MODEL_AXIS)
        metrics = lax.pmean(metrics, DATA_AXIS)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params, state.step)
        params = apply_updates(state.params, updates)
        return (TrainState(params, opt_state, state.step + 1, rng,
                           model_state), metrics)

    y_spec = (P(DATA_AXIS, MODEL_AXIS) if per_token_targets
              else P(DATA_AXIS))
    sharded = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), (P(DATA_AXIS, MODEL_AXIS), y_spec)),
        out_specs=(P(), P()),
        check_vma=False,  # rng ops + replicated-out pattern
    )
    if donate:
        return jax.jit(sharded, donate_argnums=(0,))
    return jax.jit(sharded)


def make_sp_eval_step(model, mesh, per_token_targets: bool = False):
    """Dropout-off metrics over the SP layout, pmean'd over both axes
    (sequence pmean is the identity for pooled metrics and the global
    token mean for per-token metrics — same argument as the train
    step's).

    Accepts (and ignores) a trailing ``model_state`` so the training
    loop can call every mode's eval step with one signature (the
    transformer is stateless)."""
    def per_shard(params, batch):
        _, aux = loss_and_metrics(model, params, batch, train=False)
        m = lax.pmean(aux["metrics"], MODEL_AXIS)
        return lax.pmean(m, DATA_AXIS)

    y_spec = (P(DATA_AXIS, MODEL_AXIS) if per_token_targets
              else P(DATA_AXIS))
    sharded = jax.jit(jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), (P(DATA_AXIS, MODEL_AXIS), y_spec)),
        out_specs=P(),
        check_vma=False,
    ))

    def eval_step(params, batch, model_state=()):
        return sharded(params, batch)

    return eval_step


def sp_comm_rows(kv_block_bytes: int, ways: int,
                 n_attn_layers: int,
                 grad_bytes: int = 0) -> list[dict]:
    """Static per-step ring-attention bytes — the comm ledger's SP
    rows, hop-exact against ``ops/attention``'s lowered rings
    (machine-proven by ``tools/dttcheck``, r18). Forward: each layer's
    scan runs ``ways - 1`` prefetch iterations of 2 ppermutes (k and
    v; the last block is consumed outside the scan, no trailing hop).
    Backward (the custom flash VJP): ``ways`` iterations of 4
    ppermutes — the k/v replay ring PLUS the dk/dv accumulators riding
    home with their blocks (attend-then-rotate, one extra hop, which
    is exactly what delivers each block's gradient to its owner). The
    pre-r18 row approximated backward as 2x forward, undercounting by
    4 blocks per layer; online-softmax statistics stay local (no
    collective — the tracer confirms).

    ``grad_bytes`` prices the step's other sequence-axis collective:
    the uniform grad pmean over the token axis (every leaf replicated
    — see the module docstring's two derivations), ~2|G| on the wire.
    Unpriced before r18."""
    if ways < 2 or n_attn_layers <= 0:
        return []
    fwd = n_attn_layers * (ways - 1) * 2 * kv_block_bytes
    bwd = n_attn_layers * ways * 4 * kv_block_bytes
    rows = [
        {"collective": "ppermute(k/v ring, forward)", "axis": "model",
         "bytes": fwd,
         "note": f"{n_attn_layers} layers x {ways - 1} scan hops x "
                 f"(k+v) blocks"},
        {"collective": "ppermute(k/v ring + dk/dv, backward)",
         "axis": "model", "bytes": bwd,
         "note": f"{n_attn_layers} layers x {ways} hops x "
                 f"(k+v+dk+dv) blocks (flash-VJP replay ring)"},
    ]
    if grad_bytes > 0:
        rows.append({
            "collective": "all_reduce(grads, sequence axis)",
            "axis": "model", "bytes": 2 * grad_bytes,
            "note": "the ONE uniform pmean over the token axis (exact "
                    "for both loss families — module docstring), "
                    "~2|G| all-reduce convention"})
    return rows
