"""IDX file format reader (the MNIST/Fashion-MNIST on-disk format).

The reference gets this from ``tensorflow.examples.tutorials.mnist.input_data``
(``MNISTDist.py:11,167``) which downloads the four gzipped IDX files into
``--data_dir``. This module reads those same files with zero TF dependency.
A native C++ fast path (see ``distributed_tensorflow_tpu/native``) is used
when its shared library has been built; this pure-NumPy path is the fallback
and the reference implementation for tests.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

_IDX_DTYPES = {
    0x08: np.uint8,
    0x09: np.int8,
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}


def _open_maybe_gzip(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def read_idx(path: str) -> np.ndarray:
    """Parse one IDX file (optionally gzipped) into a numpy array."""
    with _open_maybe_gzip(path) as f:
        magic = f.read(4)
        if len(magic) != 4 or magic[0] != 0 or magic[1] != 0:
            raise ValueError(f"{path}: not an IDX file (bad magic {magic!r})")
        dtype_code, ndim = magic[2], magic[3]
        if dtype_code not in _IDX_DTYPES:
            raise ValueError(f"{path}: unknown IDX dtype 0x{dtype_code:02x}")
        dims = struct.unpack(f">{ndim}i", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=_IDX_DTYPES[dtype_code])
        if data.size != int(np.prod(dims)):
            raise ValueError(
                f"{path}: payload has {data.size} elements, header says {dims}"
            )
        return data.reshape(dims)


def find_idx_file(data_dir: str, stem: str) -> str | None:
    """Locate ``stem`` under data_dir, tolerating .gz and the common
    '-idx3-ubyte'/'.idx3-ubyte' naming variants."""
    candidates = [
        stem,
        stem + ".gz",
        stem.replace("-idx", ".idx"),
        stem.replace("-idx", ".idx") + ".gz",
    ]
    for name in candidates:
        p = os.path.join(data_dir, name)
        if os.path.exists(p):
            return p
    return None
