"""Device-resident datasets: the endpoint of the host-boundary elimination.

The reference uploads every batch from the client process per step (the
feed_dict at ``MNISTDist.py:179,188`` — ~3 kB/image over gRPC). The
thin-wire path (``DataSet.next_batch_raw`` + prefetch) cuts that 4x; this
module cuts it to ZERO: the full split (MNIST train = 60k x 784 uint8 ≈
47 MB) is staged into HBM once, and each compiled train step gathers its
minibatch on device from the step PRNG. Host↔device traffic per step is
nothing at all; combined with ``lax.scan`` chunking (training/device_step)
the dispatch overhead amortizes too.

Batches are sampled uniformly WITH replacement — statistically equivalent
to shuffled epochs for SGD but not the reference's exact epoch walk; the
host-fed paths keep exact reference semantics, this mode is the
TPU-native fast path (``--device_data``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class DeviceData(NamedTuple):
    """One split resident on device. ``images`` uint8 [N, ...] (models
    normalize on device — the thin-wire format), ``labels`` int32 [N]."""

    images: jnp.ndarray
    labels: jnp.ndarray

    @property
    def num_examples(self) -> int:
        return self.labels.shape[0]


def put_device_data(split, mesh=None) -> DeviceData:
    """Stage a host ``DataSet`` split into HBM.

    With a mesh the arrays are replicated on every device (MNIST u8 is
    ~47 MB — cheap next to multi-GB HBM), so each data-parallel shard
    samples its sub-batch locally with no collective on the input side.
    Multi-process (one process per host, reference topology): every host
    already holds the full split (``MNISTDist.py:167`` semantics), so each
    supplies its own copy to the global replicated array — each host
    uploads only to its own chips.
    """
    x = split._raw_u8()
    y = split.labels_int.astype(np.int32)
    if mesh is not None:
        from distributed_tensorflow_tpu.parallel.mesh import replicated_sharding

        sharding = replicated_sharding(mesh)
        if jax.process_count() > 1:
            return DeviceData(
                jax.make_array_from_process_local_data(sharding, np.asarray(x)),
                jax.make_array_from_process_local_data(sharding, np.asarray(y)),
            )
        return DeviceData(jax.device_put(jnp.asarray(x), sharding),
                          jax.device_put(jnp.asarray(y), sharding))
    return DeviceData(jax.device_put(jnp.asarray(x)),
                      jax.device_put(jnp.asarray(y)))
