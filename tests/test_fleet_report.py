"""Cross-host trace aggregation (tools/fleet_report.py) and the
multi-file trace_view: clock alignment from coord_clock markers,
per-step skew + straggler attribution, and the one-track-per-host
Chrome export."""

import json
import os

import pytest

from tools import fleet_report, trace_view

STEPS = 10
BOUNDARIES = (0, 1, 2)
OFFSET = 50.0  # worker-1's wall clock runs 50 s ahead


def _write_fleet(tmp_path, straggler_dur=0.3, base_dur=0.1):
    """Two hosts' span files: same steps, worker-1's clock shifted by
    OFFSET and its per-step work 3x slower. coord_clock markers land at
    matching boundaries (shifted by the same clock offset — the marker
    pair is what encodes the offset)."""
    t0 = 1000.0
    for host, shift, dur in (("worker-0", 0.0, base_dur),
                             ("worker-1", OFFSET, straggler_dur)):
        recs = []
        for i in range(STEPS):
            recs.append({"name": "train_step", "step": i,
                         "ts": t0 + shift + i * 1.0, "dur_s": dur,
                         "thread": "MainThread", "depth": 0})
        for b in BOUNDARIES:
            recs.append({"name": "coord_clock", "boundary": b,
                         "step": b * 4, "ts": t0 + shift + b * 4.0,
                         "dur_s": 0.0, "instant": True})
        with open(tmp_path / f"spans-{host}.jsonl", "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
    return [str(tmp_path / f"spans-worker-{i}.jsonl") for i in (0, 1)]


def test_clock_offsets_from_coord_clock(tmp_path):
    paths = _write_fleet(tmp_path)
    by_host = {f"worker-{i}": trace_view.load_records(p)
               for i, p in enumerate(paths)}
    offsets = fleet_report.clock_offsets(by_host)
    assert offsets["worker-0"] == 0.0  # the chief-looking reference
    assert offsets["worker-1"] == pytest.approx(OFFSET)
    merged = fleet_report.align(by_host, offsets)
    # aligned: both hosts' step-i spans land at the same instant
    step0 = [r["ts"] for r in merged
             if r.get("name") == "train_step" and r["step"] == 0]
    assert step0[0] == pytest.approx(step0[1])


def test_straggler_attribution_and_skew(tmp_path):
    paths = _write_fleet(tmp_path)
    report = fleet_report.analyze(paths)
    assert report["n_hosts"] == 2
    assert report["attribution"] == "step_spans"  # no work_us markers
    assert report["steps_compared"] == STEPS
    assert report["straggler_host"] == "worker-1"
    assert report["straggler_share"] == 1.0
    assert report["skew_p50_s"] == pytest.approx(0.2)
    assert report["skew_max_s"] == pytest.approx(0.2)
    assert report["hosts"]["worker-1"]["straggler_steps"] == STEPS
    assert report["hosts"]["worker-1"]["clock_offset_s"] == pytest.approx(
        OFFSET)
    # single host: attribution explicitly n/a, never a false positive
    solo = fleet_report.analyze(paths[:1])
    assert solo["straggler_host"] is None
    assert solo["steps_compared"] == 0


def test_vote_work_attribution_preferred(tmp_path):
    """coord_clock markers carrying work_us (the live vote's numerator)
    override span-duration attribution — a host whose slowness hides in
    host_wait (no span) is still named."""
    t0 = 1000.0
    for host, work in (("worker-0", 900), ("worker-1", 45000)):
        with open(tmp_path / f"spans-{host}.jsonl", "w") as f:
            for i in range(STEPS):  # dispatch spans: EQUAL durations
                f.write(json.dumps(
                    {"name": "train_step", "step": i, "ts": t0 + i,
                     "dur_s": 0.001}) + "\n")
            for b in BOUNDARIES:
                f.write(json.dumps(
                    {"name": "coord_clock", "boundary": b, "step": b * 4,
                     "ts": t0 + b * 4.0, "work_us": work,
                     "instant": True}) + "\n")
    report = fleet_report.analyze(
        [str(tmp_path / f"spans-worker-{i}.jsonl") for i in (0, 1)])
    assert report["attribution"] == "vote_work"
    assert report["straggler_host"] == "worker-1"
    assert report["steps_compared"] == len(BOUNDARIES)
    assert report["skew_max_s"] == pytest.approx((45000 - 900) / 1e6)
    assert report["per_boundary"][0]["work_us"] == {
        "worker-0": 900, "worker-1": 45000}


def test_fleet_report_cli_text_json_and_chrome(tmp_path, capsys):
    _write_fleet(tmp_path)
    assert fleet_report.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "straggler: worker-1" in out
    assert "worker-0" in out and "clock_off" in out

    chrome = str(tmp_path / "fleet.json")
    assert fleet_report.main([str(tmp_path), "--chrome", chrome,
                              "--json"]) == 0
    out = capsys.readouterr().out
    rep = json.loads(out.splitlines()[-1])
    assert rep["straggler_host"] == "worker-1"
    ct = json.load(open(chrome))
    meta = [e for e in ct["traceEvents"] if e.get("ph") == "M"]
    assert {m["args"]["name"] for m in meta} == {"worker-0", "worker-1"}
    pids = {e["pid"] for e in ct["traceEvents"]}
    assert len(pids) == 2  # one track per host
    # empty target: loud nonzero exit
    assert fleet_report.main([str(tmp_path / "nothing-here")]) == 2


def test_trace_view_multi_file_host_tags(tmp_path, capsys):
    paths = _write_fleet(tmp_path)
    assert trace_view.main(paths) == 0
    out = capsys.readouterr().out
    assert "<worker-0>" in out and "<worker-1>" in out

    # single file: no host column (the pre-r12 rendering)
    assert trace_view.main(paths[:1]) == 0
    out = capsys.readouterr().out
    assert "<worker-0>" not in out and "train_step" in out

    chrome = str(tmp_path / "view.json")
    assert trace_view.main([*paths, "--chrome", chrome]) == 0
    ct = json.load(open(chrome))
    assert len({e["pid"] for e in ct["traceEvents"]}) == 2


def test_host_from_path_convention():
    assert trace_view.host_from_path("/x/spans-worker-3.jsonl") == "worker-3"
    assert trace_view.host_from_path("/x/flightrec-serve-1.jsonl") == "serve-1"
    assert trace_view.host_from_path("/x/custom.jsonl") == "custom"
