"""Pallas TPU kernels for the hot path.

XLA already fuses most of this model well; the one op worth a hand kernel
is the dominant FC layer (wd1: 3136x1024 — ~85% of the deep CNN's FLOPs,
reference MNISTDist.py:83-84) where fusing bias+ReLU into the matmul
epilogue keeps the activation write out of HBM round-trips.

``fused_dense_relu`` computes relu(x @ w + b) as one MXU kernel:
- grid over (M/TM, N/TN) output tiles, full K per tile in VMEM
- f32 accumulation via preferred_element_type (hardware-native for bf16)
- custom VJP: the backward is plain XLA (dx = g@wT etc.) — the fusion win
  is in the forward epilogue; XLA handles the transposed matmuls well
- caller-side zero-padding when shapes miss the (8,128) tile grid
- ``interpret=True`` runs the same kernel on CPU (used by tests)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend may be absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _MEMSPACE = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _MEMSPACE = None

TILE_M = 128
TILE_N = 128


def _kernel(x_ref, w_ref, b_ref, o_ref):
    acc = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)
    acc = acc + b_ref[:].astype(jnp.float32)  # b block is (1, TILE_N)
    o_ref[:] = jnp.maximum(acc, 0.0).astype(o_ref.dtype)


def _pad_to(v: int, m: int) -> int:
    return (v + m - 1) // m * m


@functools.partial(jax.jit, static_argnames=("interpret",))
def _forward(x, w, b, interpret: bool = False):
    M, K = x.shape
    K2, N = w.shape
    assert K == K2 and b.shape == (N,)
    Mp, Kp, Np = _pad_to(M, TILE_M), _pad_to(K, 128), _pad_to(N, TILE_N)
    xp = jnp.pad(x, ((0, Mp - M), (0, Kp - K)))
    wp = jnp.pad(w, ((0, Kp - K), (0, Np - N)))
    # bias as (1, Np): 1-D operands trip Mosaic/XLA layout mismatches
    bp = jnp.pad(b, (0, Np - N)).reshape(1, Np)

    kwargs = {}
    if _MEMSPACE is not None and not interpret:
        in_space = _MEMSPACE
    else:
        in_space = None

    def spec(shape, index_map):
        if in_space is not None:
            return pl.BlockSpec(shape, index_map, memory_space=in_space)
        return pl.BlockSpec(shape, index_map)

    out = pl.pallas_call(
        _kernel,
        grid=(Mp // TILE_M, Np // TILE_N),
        in_specs=[
            spec((TILE_M, Kp), lambda i, j: (i, 0)),
            spec((Kp, TILE_N), lambda i, j: (0, j)),
            spec((1, TILE_N), lambda i, j: (0, j)),
        ],
        out_specs=spec((TILE_M, TILE_N), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, Np), x.dtype),
        interpret=interpret,
        **kwargs,
    )(xp, wp, bp)
    return out[:M, :N]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_dense_relu(x, w, b, interpret: bool = False):
    """relu(x @ w + b) as a single fused Pallas TPU kernel."""
    return _forward(x, w, b, interpret)


def _fwd(x, w, b, interpret):
    y = _forward(x, w, b, interpret)
    return y, (x, w, y)


def _bwd(interpret, res, g):
    x, w, y = res
    g = jnp.where(y > 0, g, 0.0).astype(x.dtype)
    dx = jnp.dot(g, w.T)
    dw = jnp.dot(x.T, g)
    db = jnp.sum(g, axis=0).astype(x.dtype)
    return dx, dw, db


fused_dense_relu.defvjp(_fwd, _bwd)
