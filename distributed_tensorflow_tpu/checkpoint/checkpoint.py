"""Pytree checkpointing with the reference's Saver/Supervisor semantics.

Reference behavior: ``tf.train.Saver`` owned by the Supervisor
(``MNISTDist.py:154,163``), chief-only writes every ``save_model_secs=600``
into ``logdir=/tmp/train_logs`` (``:159-165``), automatic
restore-latest-or-init at session start (``:169-170``).

Implementation: the full TrainState pytree (params + optimizer slots +
global step + rng) flattens to path-keyed arrays in one ``.npz`` per step,
written atomically (tmp + rename) so a killed process never leaves a torn
checkpoint — the property that makes the reference's kill-and-rejoin
recovery story (SURVEY.md §5 failure detection) actually work. An index
file tracks the latest step, and old checkpoints are garbage-collected
beyond ``max_to_keep`` (TF Saver's default behavior).
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time

import numpy as np

from distributed_tensorflow_tpu.utils.pytree import flatten_pytree, unflatten_pytree

_INDEX = "checkpoint"  # index filename, same as TF's
_PREFIX = "ckpt"


def save_checkpoint(directory: str, state, step: int, max_to_keep: int = 5) -> str:
    """Atomic write of ``state`` at ``step``; returns the checkpoint path."""
    return _write_flat(directory, flatten_pytree(state, tag_bf16=True), step,
                       max_to_keep)


def _write_flat(directory: str, flat: dict[str, np.ndarray], step: int,
                max_to_keep: int) -> str:
    """The host-side half of a save: atomic npz write + index + GC of an
    already-fetched flat array dict (no device interaction — safe to run
    on a background thread)."""
    os.makedirs(directory, exist_ok=True)
    final = os.path.join(directory, f"{_PREFIX}-{step}.npz")
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **flat)
        os.replace(tmp, final)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    _write_index(directory, step)
    _gc(directory, max_to_keep)
    return final


def _write_index(directory: str, step: int):
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    with os.fdopen(fd, "w") as f:
        json.dump({"latest_step": step, "time": time.time()}, f)
    os.replace(tmp, os.path.join(directory, _INDEX))


def _all_steps(directory: str) -> list[int]:
    steps = []
    for name in os.listdir(directory):
        m = re.fullmatch(rf"{_PREFIX}-(\d+)\.npz", name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def _gc(directory: str, max_to_keep: int):
    steps = _all_steps(directory)
    for s in steps[:-max_to_keep]:
        try:
            os.unlink(os.path.join(directory, f"{_PREFIX}-{s}.npz"))
        except OSError:
            pass


def latest_checkpoint(directory: str) -> tuple[str, int] | None:
    """(path, step) of the newest complete checkpoint, or None."""
    if not os.path.isdir(directory):
        return None
    idx = os.path.join(directory, _INDEX)
    if os.path.exists(idx):
        try:
            with open(idx) as f:
                step = json.load(f)["latest_step"]
            p = os.path.join(directory, f"{_PREFIX}-{step}.npz")
            if os.path.exists(p):
                return p, step
        except (json.JSONDecodeError, KeyError, OSError):
            pass
    steps = _all_steps(directory)  # index torn/missing: fall back to files
    if not steps:
        return None
    step = steps[-1]
    return os.path.join(directory, f"{_PREFIX}-{step}.npz"), step


def restore_latest(directory: str, template):
    """Restore the newest checkpoint into the structure of ``template``;
    returns (state, step) or None if no checkpoint exists — the
    init-or-restore decision the Supervisor makes (MNISTDist.py:169-170)."""
    found = latest_checkpoint(directory)
    if found is None:
        return None
    path, step = found
    with np.load(path) as z:
        flat = {k: z[k] for k in z.files}
    try:
        return unflatten_pytree(template, flat), step
    except KeyError as e:
        raise KeyError(f"checkpoint {path}: {e}") from None


def background_save_from_flags(FLAGS) -> bool:
    """The one flag→feature mapping for ``--async_checkpoint`` (default
    False for flag-less library callers), shared by every loop that builds
    a Checkpointer so the modes cannot diverge."""
    return bool(getattr(FLAGS, "async_checkpoint", False))


def max_to_keep_from_flags(FLAGS) -> int:
    """Same role for ``--max_to_keep`` (default mirrors Checkpointer's)."""
    return int(getattr(FLAGS, "max_to_keep", 5))


class Checkpointer:
    """Time-cadenced, chief-only checkpointing (Supervisor parity).

    ``maybe_save`` is called every loop iteration; it writes only when
    ``save_model_secs`` have elapsed (MNISTDist.py:165) and only on the
    chief (``:159``). ``save`` forces a synchronous write (used at
    shutdown).

    With ``background=True`` the file writes happen off the training
    thread, the way the reference's Supervisor ran its Saver in background
    service threads (MNISTDist.py:159-170): ``maybe_save`` fetches the
    state to host on the calling thread (ordered with the dispatch queue
    — a background thread touching the device would race other in-flight
    multi-device programs and can deadlock XLA:CPU's collective
    rendezvous, see PERF.md — and host copies are donation-safe by
    construction), then hands the flat arrays to one writer thread for
    the npz serialization, atomic rename and GC. At most one save is in
    flight — a newer snapshot replaces an older one that has not started
    writing (latest wins), so a slow disk can never queue up unbounded
    checkpoints. A failed background write surfaces on the next
    ``maybe_save``/``wait``; the forced ``save`` drains pending writes
    first so the index always ends at the newest step."""

    def __init__(self, directory: str, is_chief: bool = True,
                 save_model_secs: int = 600, max_to_keep: int = 5,
                 background: bool = False):
        self.directory = directory
        self.is_chief = is_chief
        self.save_model_secs = save_model_secs
        self.max_to_keep = max_to_keep
        self.background = background
        self._last_save = time.time()
        self._cv = threading.Condition()
        self._pending: tuple | None = None
        self._busy = False
        self._error: BaseException | None = None
        self._thread: threading.Thread | None = None
        self._closed = False

    def cadence_due(self) -> bool:
        """True when the chief's time-based save cadence has elapsed —
        exposed so multi-host loops can broadcast the decision (the vote in
        training/loop._HostCoordinator) before entering the collective
        state fetch together."""
        return (self.is_chief and self.save_model_secs > 0
                and time.time() - self._last_save >= self.save_model_secs)

    def maybe_save(self, state, step: int) -> str | None:
        """Returns the path of a checkpoint written synchronously, else
        None. In background mode the cadenced write completes
        asynchronously (and may be superseded by a newer one before it
        starts — latest wins), so no path is promised; ``wait()`` then
        ``latest_checkpoint`` observe the result."""
        if not self.cadence_due():
            return None
        if self.background:
            self._submit(state, step)
            self._last_save = time.time()
            return None
        return self.save(state, step)

    def save(self, state, step: int) -> str | None:
        """Forced synchronous write (shutdown path). Drains any pending
        background write first so a stale step can never land in the index
        after this one."""
        if not self.is_chief:
            return None
        return self.save_fetched(flatten_pytree(state, tag_bf16=True), step)

    def save_fetched(self, flat: dict[str, np.ndarray], step: int) -> str | None:
        """Synchronous write of an ALREADY-FETCHED flat snapshot (the
        coordinated multi-host path: every process fetches collectively,
        only the chief lands here with the result)."""
        if not self.is_chief:
            return None
        self._drain()
        if self._error is not None:
            # an older periodic write failed; this newer forced save
            # supersedes it — report, don't mask the final save with it
            print(f"note: a background checkpoint write had failed: "
                  f"{self._error}")
            self._error = None
        path = _write_flat(self.directory, flat, step, self.max_to_keep)
        self._last_save = time.time()
        return path

    def submit_fetched(self, flat: dict[str, np.ndarray], step: int) -> None:
        """Background-or-sync write of an already-fetched snapshot, per the
        ``background`` setting — the cadenced half of the coordinated
        multi-host path."""
        if not self.is_chief:
            return
        if self.background:
            self._submit_flat(flat, step)
            self._last_save = time.time()
        else:
            self.save_fetched(flat, step)

    def wait(self):
        """Block until no background write is pending or running; raise if
        one failed."""
        self._drain()
        self._raise_pending_error()

    def close(self):
        """Stop the writer thread after draining. Idempotent."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60)
            if self._thread.is_alive():
                # do NOT pretend shutdown completed: the daemon thread is
                # mid-write and process exit would tear the tmp file (the
                # atomic rename means the previous checkpoint stays valid)
                print("warning: checkpoint writer still busy after 60s; "
                      "an in-flight write may not complete")
            else:
                self._thread = None

    def restore(self, template):
        return restore_latest(self.directory, template)

    # --- background machinery ---

    def _submit(self, state, step: int):
        self._submit_flat(flatten_pytree(state, tag_bf16=True), step)

    def _submit_flat(self, flat: dict[str, np.ndarray], step: int):
        # the device→host fetch happened on the calling thread (ordered
        # with the dispatch queue); only the file write backgrounds
        self._raise_pending_error()
        with self._cv:
            if self._closed:
                raise RuntimeError("Checkpointer is closed")
            self._pending = (flat, step)  # replaces an unstarted older save
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._writer_loop, name="checkpoint-writer",
                    daemon=True,
                )
                self._thread.start()
            self._cv.notify_all()

    def _writer_loop(self):
        while True:
            with self._cv:
                while self._pending is None and not self._closed:
                    self._cv.wait()
                if self._pending is None:
                    return  # closed and drained
                (flat, step), self._pending = self._pending, None
                self._busy = True
            try:
                _write_flat(self.directory, flat, step, self.max_to_keep)
            except BaseException as e:  # noqa: BLE001 — surfaced to callers
                with self._cv:
                    self._error = e
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _drain(self):
        with self._cv:
            while self._pending is not None or self._busy:
                self._cv.wait()

    def _raise_pending_error(self):
        if self._error is not None:
            e, self._error = self._error, None
            raise RuntimeError(f"background checkpoint write failed: {e}") from e
