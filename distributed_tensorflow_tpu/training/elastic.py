"""Elasticity supervisor: resize the world without losing a step.

The paper's Supervisor survives a worker restart only at the SAME
cluster shape; modern fleets run on preemptible capacity where the
world size itself changes mid-run. This module composes the machinery
earlier PRs built — deterministic fault injection and the CRC-verified
restore ladder (r8), cross-topology standard-layout checkpoints (r7/
r10/r14), the telemetry spine and flight recorder (r11), sentinel
emergency snapshots and goodput accounting (r12) — into a supervisor
that turns a membership change into a planned, accounted, bitwise-safe
resize instead of a crash (TorchElastic-style dynamic membership,
Bamboo/Varuna-style preemption tolerance):

1. **Detect.** The ``preempt`` injection point (utils/faults.py) models
   spot preemption; ``ElasticSupervisor.poll`` fires it at every loop
   boundary and catches the ``Preempted`` signal. ``mode=notice`` is an
   advance warning (a real fleet's preemption notice); ``mode=
   immediate`` loses the in-flight step with the capacity. Scheduled
   re-joins (``rejoin_steps``) surface here too. In multi-host runs the
   ``_HostCoordinator`` vote carries a per-host departure bit on the
   EXISTING cadenced allgather (no new collectives), so every survivor
   agrees on the membership epoch at the same sync boundary.

2. **Drain.** A due change forces the current iteration to a checkpoint
   boundary: the loop publishes its standard-layout host state to the
   Supervisor's StateBox and ``maybe_resize`` raises ``ResizeRequired``
   — ``Supervisor.managed`` treats it as a CLEAN exit, so its managed-
   exit final save writes the drain checkpoint through the verified
   (CRC-manifest) path at the agreed step. An ``immediate`` preemption
   skips the drain save (the step is lost) and the re-form instead
   restores the newest cadenced checkpoint — or ADOPTS the sentinel's
   last-good emergency snapshot when that is newer
   (``adopt_sentinel_snapshot``).

3. **Re-form.** ``train()``'s elastic wrapper catches ``ResizeRequired``,
   advances the membership epoch in ``cluster.py`` (``make_mesh``
   consults ``cluster.active_devices``, so every mesh the re-entered
   loop builds covers exactly the surviving world), re-initializes the
   distributed runtime through ``maybe_initialize_distributed``'s
   bounded retry at the new world size (epoch-namespaced coordinator —
   a stale peer from the previous epoch cannot race the re-formed
   cluster), and re-enters the loop. The re-entry restores the drain
   checkpoint and re-shards it into the rescaled DP/ZeRO layout: the
   cross-topology restore machinery makes the resize a RESTORE, not a
   migration, which is what makes it bitwise-safe — the post-resize
   trajectory is identical to a fresh run restored at the target shape
   (tests/test_elastic.py pins the rescale matrix).

4. **Account.** The downtime (drain save + teardown + re-init +
   restore) lands as the named ``resize`` charge in the goodput ledger
   — every loop emits it as the ``resize_s`` scalar next to
   ``goodput`` — plus a ``membership_change`` instant span (which rides
   the flight recorder) at the change and a ``resize`` instant when the
   re-formed loop is back up, so ``tools/fleet_report.py`` can
   attribute the lost time per host.

World membership: single-process runs treat each local DEVICE as a
world member ("device-hosts" — the same virtual topology the CPU test
mesh simulates; ``--world_size N`` caps the launch world so a resize
has headroom on the test mesh), multi-process runs treat each process
as a member. Stdlib-at-import (jax lazily inside methods), like every
robustness layer below it.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from distributed_tensorflow_tpu import cluster
from distributed_tensorflow_tpu.utils import faults, telemetry


@dataclass(frozen=True)
class MembershipChange:
    """One agreed world transition, ready to execute."""

    kind: str                     # "depart" | "join"
    hosts: tuple                  # member indices leaving/arriving
    step: int                     # detection step
    epoch: int                    # the epoch this change creates
    lost_step: bool = False       # immediate preemption: no drain save
    notice_s: float = 0.0         # the modeled grace window (recorded)
    rejoins: tuple = ()           # ((host, steps_after_drain), ...)


class ResizeRequired(RuntimeError):
    """Control-flow signal from the loop boundary to ``train()``'s
    elastic wrapper: drain here, re-form at ``new_world``. The managed
    Supervisor treats it as a clean exit (the drain save) unless
    ``lost_step``."""

    def __init__(self, change: MembershipChange, old_world: tuple,
                 new_world: tuple, drain_step: int):
        self.change = change
        self.old_world = tuple(old_world)
        self.new_world = tuple(new_world)
        self.drain_step = int(drain_step)
        self.drain_steps = max(0, int(drain_step) - int(change.step))
        self.lost_step = bool(change.lost_step)
        self.t0 = time.monotonic()
        super().__init__(
            f"membership change at step {drain_step}: {change.kind} "
            f"hosts {list(change.hosts)}, world "
            f"{len(old_world)}->{len(new_world)} (epoch {change.epoch})")


class Departed(RuntimeError):
    """Raised on the PREEMPTED process itself (multi-host runs) at the
    agreed boundary: this process leaves the world while the survivors
    resize. ``train()`` returns a stub result for it."""

    def __init__(self, step: int):
        self.step = int(step)
        super().__init__(f"this process departs the world at step {step}")


# run-scoped state that must survive the wrapper's loop re-entries
# (the re-entered loop builds a fresh ElasticSupervisor; membership
# itself lives in cluster._MEMBERSHIP)
_PENDING = {
    "resize": None,   # {"t0", "epoch", "kind", "step", "drain_steps"}
    "joins": [],      # [(due_step, host), ...] scheduled re-joins
    # departures already executed this run, keyed (rule host, rule
    # at_step): loop re-entry re-arms the fault rules (their fired
    # counters reset), so without this a no-at_step preempt rule with
    # rejoin_steps would re-fire after every re-join — an endless
    # depart/re-add churn instead of the one cycle the spec describes,
    # and an at_step rule could replay after a lost-step restore lands
    # BEFORE its step. Each distinct rule identity departs once per run.
    "handled": set(),
}


def begin_run(FLAGS) -> None:
    """Reset the elastic state for a fresh ``train()`` call (NOT a
    resize re-entry): full world at epoch 0, optionally capped to
    ``--world_size`` launch members, no pending joins or charges, and
    the launch topology (worker list + this process's launch member
    id) recorded so multi-host re-forms never resolve against
    post-resize process renumbering."""
    cluster.reset_membership()
    _PENDING["resize"] = None
    _PENDING["joins"] = []
    _PENDING["handled"] = set()
    cluster.set_launch_topology(
        [h for h in (getattr(FLAGS, "worker_hosts", "") or "").split(",")
         if h],
        int(getattr(FLAGS, "task_index", 0) or 0))
    ws = int(getattr(FLAGS, "world_size", 0) or 0)
    if ws > 0:
        cluster.set_world(range(ws), epoch=0)


def enabled(FLAGS) -> bool:
    """Elasticity arms via ``--elastic``, or automatically whenever a
    ``preempt`` fault rule is configured (the rule IS a statement that
    preemptions will happen; without the supervisor the injected signal
    would just kill the run — the honest un-elastic behavior, but never
    what a spec author armed the point for)."""
    if bool(getattr(FLAGS, "elastic", False)):
        return True
    return "preempt" in faults.armed_points()


def supervisor_from_flags(FLAGS) -> "ElasticSupervisor | None":
    """The one flag->feature mapping for ``--elastic``/``--world_size``,
    shared by every training loop; None when elasticity is unarmed (the
    default — zero cost on every boundary)."""
    if not enabled(FLAGS):
        return None
    return ElasticSupervisor()


class ElasticSupervisor:
    """Per-loop membership watcher. ``poll(step)`` at every iteration
    (cheap: one armed-rules check); when it returns True the loop must
    treat the iteration as a checkpoint boundary — publish host state
    to the StateBox — and then call ``maybe_resize(step)``, which
    raises the ``ResizeRequired`` the elastic wrapper executes."""

    def __init__(self):
        import jax

        self._n_procs = jax.process_count()
        # LAUNCH member id, stable across resizes (the runtime renumbers
        # process indices after a re-form; world membership never does)
        self._proc = cluster.self_host(jax.process_index())
        self._default_world = (self._n_procs if self._n_procs > 1
                               else len(jax.devices()))
        self._due: MembershipChange | None = None
        # multi-host: this process's pending-departure code for the
        # vote column — 0 none, else 1 | (2 if immediate) |
        # (rejoin_steps << 2), so the agreed change keeps the lost-step
        # and re-join semantics the detecting process saw
        self._announce = 0

    def _world(self) -> tuple:
        return cluster.world_hosts(self._default_world)

    # ------------------------------------------------------------ detect

    def poll(self, step: int) -> bool:
        """Fire the ``preempt`` injection point and check scheduled
        re-joins. Returns True when a membership change is due at this
        boundary. Multi-host: a caught notice is only ANNOUNCED here
        (``local_departure_bit``); the change becomes due when the
        coordinator's vote delivers it to every survivor at the same
        boundary (``on_vote``)."""
        if self._due is not None:
            return True
        world = self._world()
        # scheduled re-joins (the kill-and-re-add chaos shape)
        joining = tuple(h for (due, h) in _PENDING["joins"]
                        if step >= due and h not in world)
        if any(step >= due for (due, _h) in _PENDING["joins"]):
            # consume every due entry (a host already back in the world
            # has nothing left to join)
            _PENDING["joins"] = [(due, h) for (due, h)
                                 in _PENDING["joins"] if step < due]
        if joining:
            self._due = MembershipChange(
                kind="join", hosts=joining, step=int(step),
                epoch=cluster.membership_epoch() + 1)
            return True
        departing: list[int] = []
        immediate = False
        notice_s = 0.0
        rejoins: list[tuple] = []
        while True:
            try:
                faults.fault_point("preempt", step=int(step))
                break
            except faults.Preempted as p:
                rule_id = (p.host, p.at_step)
                if rule_id in _PENDING["handled"]:
                    # this configured departure already executed this
                    # run — loop re-entry re-armed the rule (and a
                    # lost-step restore can even replay its at_step);
                    # each rule identity departs at most once
                    continue
                host = p.host
                if self._n_procs > 1:
                    # the rule is armed ON the departing process (the
                    # straggler-chaos convention); the vote carries its
                    # identity to the peers
                    host = self._proc
                elif host is None:
                    # default: the highest-indexed member departs (the
                    # chief/coordinator at index 0 stays)
                    host = max(world)
                if host not in world or host in departing:
                    # a stale rule re-fired after its host already left
                    # (fault rules re-arm on loop re-entry) — ignore
                    continue
                _PENDING["handled"].add(rule_id)
                departing.append(host)
                immediate = immediate or p.immediate
                notice_s = max(notice_s, float(p.notice_s or 0.0))
                if p.rejoin_steps:
                    rejoins.append((host, int(p.rejoin_steps)))
        if not departing:
            return False
        if self._n_procs > 1:
            # delivered to every peer via the next vote's departure code
            rejoin = max((r for _h, r in rejoins), default=0)
            self._announce = (1 | (2 if immediate else 0)
                              | (min(rejoin, 2 ** 24) << 2))
            return False
        self._due = MembershipChange(
            kind="depart", hosts=tuple(departing), step=int(step),
            epoch=cluster.membership_epoch() + 1, lost_step=immediate,
            notice_s=notice_s, rejoins=tuple(rejoins))
        return True

    # -------------------------------------------- multi-host agreement

    def local_departure_bit(self) -> int:
        """This host's liveness/departure code for the coordinator
        vote: 0 = staying; nonzero = departing at this boundary, with
        the lost-step bit and any re-join schedule encoded (see
        ``_announce``)."""
        return int(self._announce)

    def on_vote(self, bits, step: int) -> None:
        """Deliver the vote's gathered departure column: every process
        sees the same codes, so every survivor installs the same change
        at the same boundary — membership-epoch agreement rides the
        existing allgather. Vote rows are CURRENT process ranks; ranks
        map to world member ids through the sorted current world (the
        re-form renumbers survivors in sorted member order), so the
        agreement stays correct after any number of resizes."""
        world = tuple(sorted(self._world()))
        hosts: list[int] = []
        rejoins: list[tuple] = []
        immediate = False
        for rank, code in enumerate(bits):
            code = int(code)
            if not code or rank >= len(world):
                continue
            member = world[rank]
            hosts.append(member)
            immediate = immediate or bool(code & 2)
            if code >> 2:
                rejoins.append((member, int(code >> 2)))
        if not hosts or self._due is not None:
            return
        self._due = MembershipChange(
            kind="depart", hosts=tuple(hosts), step=int(step),
            epoch=cluster.membership_epoch() + 1, lost_step=immediate,
            rejoins=tuple(rejoins))

    # ------------------------------------------------------------- drain

    def maybe_resize(self, step: int) -> None:
        """Execute a due change: raises ``ResizeRequired`` (survivors)
        or ``Departed`` (the preempted process itself in multi-host
        runs). Call AFTER the loop published this boundary's host state
        to the StateBox — the managed-exit save is the drain
        checkpoint. No-op when nothing is due."""
        change, self._due = self._due, None
        if change is None:
            return
        world = self._world()
        if change.kind == "join":
            new_world = tuple(sorted(set(world) | set(change.hosts)))
        else:
            new_world = tuple(h for h in world if h not in change.hosts)
            if not new_world:
                raise ValueError(
                    f"preemption of hosts {list(change.hosts)} would "
                    f"empty the world {list(world)} — the last member "
                    f"cannot be preempted (nothing left to re-form)")
        if self._n_procs > 1 and self._proc in change.hosts:
            self._announce = 0
            print(f"elastic: this process (host {self._proc}) departs "
                  f"the world at step {step} (epoch {change.epoch}); "
                  f"survivors re-form at {len(new_world)} members",
                  flush=True)
            raise Departed(step)
        raise ResizeRequired(change, world, new_world, step)


# ------------------------------------------------------------- execute


def apply_resize(rz: ResizeRequired, FLAGS) -> None:
    """The wrapper half of a resize (the drain checkpoint already
    landed via the managed exit): record the membership change, adopt
    the sentinel snapshot when the step was lost, install the new
    world/epoch, and — multi-host — re-initialize the distributed
    runtime at the new size. The re-entered loop then restores and
    continues; ``book_resize`` (called from its ``_log_recovery``)
    closes the accounting."""
    ch = rz.change
    print(f"elastic: {ch.kind} of hosts {list(ch.hosts)} at step "
          f"{rz.drain_step} — re-forming world "
          f"{len(rz.old_world)}->{len(rz.new_world)} "
          f"(epoch {ch.epoch}"
          + (", step lost: restoring last-good state" if rz.lost_step
             else f", drained {rz.drain_steps} step(s) after notice")
          + ")", flush=True)
    # NB: the attribute is named `change`, not `kind` — trace_view's
    # loaders use a top-level `kind` key as the flight-recorder
    # envelope discriminator and would drop the record
    telemetry.get_tracer().record_instant(
        "membership_change", change=ch.kind, hosts=list(ch.hosts),
        epoch=int(ch.epoch), step=int(rz.drain_step),
        old_world=len(rz.old_world), new_world=len(rz.new_world),
        lost_step=bool(rz.lost_step), notice_s=float(ch.notice_s),
        drain_steps=int(rz.drain_steps))
    telemetry.flight_recorder().record("note", {
        "note": f"membership_change: {ch.kind} {list(ch.hosts)} at "
                f"step {rz.drain_step}, world {len(rz.old_world)}->"
                f"{len(rz.new_world)} epoch {ch.epoch}"})
    if rz.lost_step:
        adopted = adopt_sentinel_snapshot(getattr(FLAGS, "logdir", ""))
        if adopted is not None:
            print(f"elastic: adopted the sentinel's last-good emergency "
                  f"snapshot (step {adopted}) — newer than the last "
                  f"cadenced checkpoint", flush=True)
    for host, steps in ch.rejoins:
        _PENDING["joins"].append((rz.drain_step + steps, host))
    cluster.set_world(rz.new_world, epoch=ch.epoch)
    _reform_distributed(rz, FLAGS)
    _PENDING["resize"] = {"t0": rz.t0, "epoch": int(ch.epoch),
                          "kind": ch.kind, "step": int(rz.drain_step),
                          "drain_steps": int(rz.drain_steps)}


def _reform_distributed(rz: ResizeRequired, FLAGS) -> None:
    """Multi-host re-form: tear down the previous epoch's runtime and
    re-join at the new world size through the bounded init retry, with
    the coordination service namespaced by the membership epoch (a
    stale peer from the old epoch cannot race the survivors). Rewrites
    ``--worker_hosts``/``--task_index`` so the re-entered loop sees the
    survivor topology. Single-process worlds resize by mesh rebuild
    alone and skip this entirely."""
    import jax

    if jax.process_count() <= 1:
        return
    from distributed_tensorflow_tpu.cluster import (
        ClusterSpec,
        maybe_initialize_distributed,
    )

    if rz.change.kind == "join":
        print("elastic: multi-host join is relaunch-driven (the new "
              "process joins through maybe_initialize_distributed at "
              "the next epoch); survivors re-form without it", flush=True)
    try:
        jax.distributed.shutdown()
    except Exception:  # noqa: BLE001 — half-dead runtime on a preemption
        pass
    # resolve against the LAUNCH topology, never the post-resize
    # renumbering: world member ids index the launch worker list, and
    # this process's identity is its launch id (a second resize would
    # otherwise mis-map addresses and drop live survivors)
    workers = list(cluster.launch_workers()) or [
        h for h in (FLAGS.worker_hosts or "").split(",") if h]
    survivors = [i for i in rz.new_world if i < len(workers)]
    my_id = cluster.self_host(int(getattr(FLAGS, "task_index", 0) or 0))
    new_workers = [workers[i] for i in survivors]
    new_index = survivors.index(my_id)
    spec = ClusterSpec({"ps": [], "worker": new_workers})
    maybe_initialize_distributed(
        spec, new_index,
        init_retries=int(getattr(FLAGS, "init_retries", 8) or 0),
        init_backoff_s=float(getattr(FLAGS, "init_backoff_s", 2.0)),
        init_timeout_s=float(getattr(FLAGS, "init_timeout_s", 0.0)),
        membership_epoch=rz.change.epoch)
    FLAGS.worker_hosts = ",".join(new_workers)
    FLAGS.task_index = new_index


def adopt_sentinel_snapshot(logdir: str) -> int | None:
    """Lost-step recovery: when the sentinel's last-good emergency
    snapshot (``<logdir>/sentinel/``, written through the verified-save
    path, outside main GC) is NEWER than the newest main checkpoint,
    copy it into the main directory so the re-form's restore ladder
    picks it up (the CRC manifest travels inside the file, so it is
    still verified on read). Returns the adopted step, else None."""
    import shutil

    from distributed_tensorflow_tpu.checkpoint import latest_checkpoint

    if not logdir:
        return None
    sent = latest_checkpoint(os.path.join(logdir, "sentinel"))
    if sent is None:
        return None
    main = latest_checkpoint(logdir)
    if main is not None and main[1] >= sent[1]:
        return None
    path, step = sent
    shutil.copy2(path, os.path.join(logdir, os.path.basename(path)))
    return int(step)


def book_resize(eff, logger, step: int) -> None:
    """Close a pending resize's accounting from the RE-FORMED loop
    (called by ``_log_recovery`` right after the restore): the downtime
    from the drain decision to here — drain save + teardown + re-init +
    restore — lands as the goodput ledger's named ``resize`` charge
    (the ``resize_s`` scalar every loop emits) and as a ``resize``
    instant span for fleet_report's per-host column."""
    pend, _PENDING["resize"] = _PENDING["resize"], None
    if pend is None:
        return
    dt = max(0.0, time.monotonic() - pend["t0"])
    if eff is not None:
        eff.charge(dt, "resize")
    telemetry.get_tracer().record_instant(
        "resize", step=int(step), epoch=pend["epoch"],
        change=pend["kind"], resize_s=round(dt, 4),
        drain_steps=pend["drain_steps"])
    if logger is not None:
        logger.scalars(step, {"membership_epoch": float(pend["epoch"])})
    print(f"elastic: re-formed at epoch {pend['epoch']} (resize "
          f"downtime {dt:.2f}s charged to the goodput ledger as "
          f"resize_s)", flush=True)
