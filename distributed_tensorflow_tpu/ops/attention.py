"""Attention ops: dense multi-head attention and RING attention for
sequence/context parallelism.

The reference framework predates attention entirely — this module is the
build's long-context extension, designed TPU-first: the sequence axis is
sharded over a mesh axis and the key/value blocks ROTATE around the ring
with ``lax.ppermute`` (one ICI hop per step) while each device's queries
accumulate the streaming-softmax statistics blockwise (the flash/online
softmax recurrence). Peak activation memory per device is one (q, k, v)
block regardless of total sequence length, and the collective traffic
rides neighbor-to-neighbor ICI links — the layout "How to Scale Your
Model"-style context parallelism wants.

Everything is expressed with ``lax.scan`` + differentiable collectives
(``ppermute`` has a transpose rule), so ``jax.grad`` through a ring step
is exact — no custom VJP required. Equivalence with dense attention (fwd
and grads) is pinned by tests/test_attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def multi_head_attention(q, k, v, causal: bool = False):
    """Dense (all-to-all) multi-head attention.

    q, k, v: (B, S, H, Dh) -> (B, S, H, Dh). f32 softmax statistics
    regardless of input dtype (bf16-safe). ``causal`` masks j > i (the
    autoregressive/LM form).
    """
    dh = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    s = s / jnp.sqrt(jnp.float32(dh))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)


def _online_softmax_step(qf, scale, o, m, l, k_blk, v_blk, mask):
    """Fold one k/v block into the streaming-softmax accumulators.

    The one implementation of the flash/online-softmax recurrence, shared
    by ``ring_attention`` (blocks arrive over ICI) and
    ``blockwise_attention`` (blocks are scanned locally): running max m,
    denominator l, unnormalized numerator o, all f32. ``mask`` (broadcast
    to (B, H, Sq, Skb)) or None."""
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
    s = s * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l = l * corr + p.sum(axis=-1)
    o = o * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
    return o, m_new, l


def _flash_bwd_block(qf, gf, dD, lse, scale, k_blk, v_blk, mask):
    """One k/v block of the flash backward — the single implementation
    both ``_blockwise_bwd`` (local scan) and ``_ring_bwd`` (ring hops)
    run, mirroring how ``_online_softmax_step`` is the one forward.

    With p = exp(s - lse) the row-exact softmax probs recomputed from
    the saved logsumexp, and D_i = sum_d(do_i * o_i): dv = p^T do,
    ds = p * (do @ v^T - D), dq_contrib = ds @ k * scale,
    dk = ds^T @ q * scale — the textbook softmax-through-attention
    transpose, one block at a time. Masked entries give p = 0 and drop
    out of every product. Returns (dq_contrib BQHD, dk_blk, dv_blk)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
    s = s * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    p = jnp.exp(s - lse[..., None])  # masked entries: exp(-inf) = 0
    dv_blk = jnp.einsum("bhqk,bhqd->bkhd", p, gf)
    dp = jnp.einsum("bhqd,bkhd->bhqk", gf, v_blk.astype(jnp.float32))
    ds = p * (dp - dD[..., None])
    dq_contrib = jnp.einsum("bhqk,bkhd->bqhd", ds,
                            k_blk.astype(jnp.float32)) * scale
    dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
    return dq_contrib, dk_blk, dv_blk


def blockwise_attention(q, k, v, block_size: int, causal: bool = False):
    """Single-device FLASH attention with O(S * block) peak memory —
    forward AND backward.

    Same math as ``multi_head_attention`` (pinned by tests), computed as
    a ``lax.scan`` over k/v blocks with the online-softmax recurrence —
    the full (Sq, Sk) score matrix never materializes. The backward pass
    is a CUSTOM VJP (the flash backward): plain autodiff of the forward
    scan would save each step's (B, H, Sq, block) probability panel as a
    residual — O(Sq * Sk) total, no better than dense (measured: WORSE,
    round-4 sweep) — so instead only (q, k, v, o, logsumexp) are saved
    and each block's probabilities are RECOMPUTED from them during a
    second scan that accumulates dq and emits per-block dk/dv. Peak
    activation is one (B, H, Sq, block) panel in both passes. This is
    the single-chip half of the long-context story; ``ring_attention``
    is the same recurrence with blocks arriving over the mesh.

    ``causal=True`` masks by absolute position, identical to the dense
    triangle. Blocks entirely above the diagonal still run (static scan
    length — XLA needs static shapes) but contribute exact zeros.
    """
    sk = k.shape[1]
    if sk % block_size:
        raise ValueError(f"key length {sk} must divide into blocks of "
                         f"{block_size}")
    return _blockwise(q, k, v, int(block_size), bool(causal))


def _blockwise_forward(q, k, v, block_size, causal):
    """Forward scan; returns (out BQHD in q.dtype, o_f32 BHQD, lse BHQ)."""
    b, sq, h, dh = q.shape
    sk = k.shape[1]
    n_blocks = sk // block_size
    scale = 1.0 / jnp.sqrt(jnp.float32(dh))
    qf = q.astype(jnp.float32)
    rows = jnp.arange(sq)
    kb = jnp.moveaxis(k.reshape(b, n_blocks, block_size, h, dh), 1, 0)
    vb = jnp.moveaxis(v.reshape(b, n_blocks, block_size, h, dh), 1, 0)

    def step(carry, inp):
        o, m, l = carry
        t, k_blk, v_blk = inp
        mask = None
        if causal:
            cols = t * block_size + jnp.arange(block_size)
            mask = (cols[None, :] <= rows[:, None])[None, None]
        o, m, l = _online_softmax_step(qf, scale, o, m, l, k_blk, v_blk,
                                       mask)
        return (o, m, l), None

    o0 = jnp.zeros((b, h, sq, dh), jnp.float32)
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (o, m, l), _ = lax.scan(step, (o0, m0, l0),
                            (jnp.arange(n_blocks), kb, vb))
    o = o / l[..., None]
    lse = m + jnp.log(l)  # logsumexp per row: p_ij = exp(s_ij - lse_i)
    out = jnp.einsum("bhqd->bqhd", o).astype(q.dtype)
    return out, o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _blockwise(q, k, v, block_size, causal):
    return _blockwise_forward(q, k, v, block_size, causal)[0]


def _blockwise_fwd(q, k, v, block_size, causal):
    out, o, lse = _blockwise_forward(q, k, v, block_size, causal)
    return out, (q, k, v, o, lse)


def _blockwise_bwd(block_size, causal, res, g):
    """The flash backward: one scan over k/v blocks, each block's
    probability panel recomputed from (q, lse) — never all at once.

    With p = softmax row-normalized probs, o = p @ v, and row constant
    D_i = sum_d(do_i * o_i): dv_j = p^T do, ds = p * (do @ v_j^T - D),
    dq += ds @ k_j * scale, dk_j = ds^T @ q * scale — the textbook
    softmax-through-attention transpose, evaluated blockwise. Exactness
    vs dense autodiff is pinned by tests/test_lm.py (values AND grads).
    """
    q, k, v, o, lse = res
    b, sq, h, dh = q.shape
    sk = k.shape[1]
    n_blocks = sk // block_size
    scale = 1.0 / jnp.sqrt(jnp.float32(dh))
    qf = q.astype(jnp.float32)
    gf = jnp.einsum("bqhd->bhqd", g.astype(jnp.float32))
    rows = jnp.arange(sq)
    dD = jnp.sum(gf * o, axis=-1)  # (B, H, Sq)
    kb = jnp.moveaxis(k.reshape(b, n_blocks, block_size, h, dh), 1, 0)
    vb = jnp.moveaxis(v.reshape(b, n_blocks, block_size, h, dh), 1, 0)

    def step(dq, inp):
        t, k_blk, v_blk = inp
        mask = None
        if causal:
            cols = t * block_size + jnp.arange(block_size)
            mask = (cols[None, :] <= rows[:, None])[None, None]
        dq_c, dk_blk, dv_blk = _flash_bwd_block(
            qf, gf, dD, lse, scale, k_blk, v_blk, mask)
        return dq + dq_c, (dk_blk, dv_blk)

    dq0 = jnp.zeros((b, sq, h, dh), jnp.float32)
    dq, (dkb, dvb) = lax.scan(step, dq0, (jnp.arange(n_blocks), kb, vb))
    dk = jnp.moveaxis(dkb, 0, 1).reshape(b, sk, h, dh)
    dv = jnp.moveaxis(dvb, 0, 1).reshape(b, sk, h, dh)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_blockwise.defvjp(_blockwise_fwd, _blockwise_bwd)


def ring_attention(q, k, v, axis_name: str, causal: bool = False):
    """Ring attention over the mesh axis ``axis_name`` (sequence-sharded).

    Call INSIDE shard_map with the sequence dimension of q/k/v sharded
    over ``axis_name``: q, k, v are the LOCAL blocks (B, S/P, H, Dh).
    Each of the P ring steps attends the local queries against the
    currently-held k/v block, folds the result into the online-softmax
    accumulators (running max m, denominator l, numerator o), and passes
    the k/v block to the next device (``ppermute``; P-1 hops — the local
    block is consumed before the scan). After P steps every query has
    seen every key exactly once; the result equals dense attention over
    the gathered sequence (tested to fp tolerance).

    The backward pass is a CUSTOM VJP — the DISTRIBUTED flash backward.
    Plain autodiff of the forward scan saves each ring step's
    (B, H, Sq/P, Sk/P) probability panel as a residual (O(S_local *
    S_global) per device — the memory the ring exists to avoid); instead
    only (q, k, v, o, logsumexp) are saved per shard and the backward
    RE-ROTATES k/v around the ring, recomputing each block's panel and
    accumulating dq locally while (dk, dv) accumulators ride the ring
    WITH their blocks — P hops (one more than forward) so each block's
    gradient arrives back at its owner with every shard's contribution.
    Exactness vs dense autodiff is pinned by tests/test_attention.py and
    tests/test_lm.py (SP == dense trajectories).

    ``causal=True`` masks by GLOBAL token position: at ring step t this
    device holds the k/v block of shard (me - t) mod P, so the mask
    compares (my_shard * Sq + i) against (owner * Sk + j) — the
    blockwise form of the LM triangle. Attending the local block first
    guarantees the running max is finite from step one (the diagonal is
    never masked), so fully-masked later blocks contribute exact zeros.
    """
    return _ring(q, k, v, axis_name, bool(causal))


def _ring_mask(causal, owner, sk_blk, row_global):
    if not causal:
        return None
    col_global = owner * sk_blk + jnp.arange(sk_blk)
    return (col_global[None, :] <= row_global[:, None])[None, None]


def _ring_forward(q, k, v, axis_name, causal):
    """Forward ring; returns (out BQHD q.dtype, o_f32 BHQD, lse BHQ)."""
    p_size = lax.axis_size(axis_name)
    dh = q.shape[-1]
    b, sq, h, _ = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(dh))
    # accumulate in f32: the online-softmax recurrence is exact in exact
    # arithmetic; f32 keeps the rescaling stable for bf16 inputs
    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % p_size) for i in range(p_size)]
    # axis_index only when the causal mask needs global positions: a
    # non-causal ring never reads it, and a dead PartitionId in the
    # lowered module breaks CPU SPMD partitioning on older jaxlibs
    me = lax.axis_index(axis_name) if causal else jnp.int32(0)
    row_global = me * sq + jnp.arange(sq)  # my queries' global positions

    def attend(o, m, l, k_blk, v_blk, owner):
        mask = _ring_mask(causal, owner, k_blk.shape[1], row_global)
        return _online_softmax_step(qf, scale, o, m, l, k_blk, v_blk, mask)

    def ring_step(carry, t):
        # DOUBLE-BUFFERED rotation: issue hop t+1's ppermute BEFORE
        # consuming block t, so the collective has no consumer until the
        # next iteration and XLA's async collective-permute overlaps it
        # with this step's attend — the hop leaves the critical path
        # (ICI hops are cheap; --sp_span_hosts DCN hops are the ones
        # this hides). After t rotations this device holds the block
        # ORIGINALLY owned by shard (me - t) mod P; accumulator math is
        # identical to the rotate-then-attend form (same blocks, same
        # order — trajectory-pinned by the SP tests).
        o, m, l, k_cur, v_cur = carry
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        o, m, l = attend(o, m, l, k_cur, v_cur, (me - t) % p_size)
        return (o, m, l, k_nxt, v_nxt), None

    o0 = jnp.zeros((b, h, sq, dh), jnp.float32)
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    # P-1 scan iterations, each with one (prefetch) hop; the LAST block
    # is consumed outside so no trailing rotation is compiled. Step 0
    # attends the local block (owner = me) — causal masking needs it
    # first so the running max is finite from the start.
    (o, m, l, k_last, v_last), _ = lax.scan(
        ring_step, (o0, m0, l0, k, v), jnp.arange(p_size - 1))
    o, m, l = attend(o, m, l, k_last, v_last, (me - (p_size - 1)) % p_size)
    o = o / l[..., None]
    lse = m + jnp.log(l)
    out = jnp.einsum("bhqd->bqhd", o).astype(q.dtype)
    return out, o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring(q, k, v, axis_name, causal):
    return _ring_forward(q, k, v, axis_name, causal)[0]


def _ring_fwd(q, k, v, axis_name, causal):
    out, o, lse = _ring_forward(q, k, v, axis_name, causal)
    return out, (q, k, v, o, lse)


def _ring_bwd(axis_name, causal, res, g):
    """Distributed flash backward.

    Same per-block math as ``_blockwise_bwd`` (p recomputed from lse,
    ds = p * (do @ v^T - D), dq/dk/dv contractions), with block traffic
    on the ring: step t attends the block of owner (me - t) mod P —
    attend-THEN-rotate, so the local block is step 0 and after the final
    attend one more rotation runs, P hops total, which is exactly what
    brings each block's (k, v, dk, dv) home to its owner with every
    shard's accumulated contribution."""
    q, k, v, o, lse = res
    p_size = lax.axis_size(axis_name)
    b, sq, h, dh = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(dh))
    qf = q.astype(jnp.float32)
    gf = jnp.einsum("bqhd->bhqd", g.astype(jnp.float32))
    perm = [(i, (i + 1) % p_size) for i in range(p_size)]
    # same dead-PartitionId gate as the forward ring
    me = lax.axis_index(axis_name) if causal else jnp.int32(0)
    row_global = me * sq + jnp.arange(sq)
    dD = jnp.sum(gf * o, axis=-1)  # (B, H, Sq)

    def step(carry, t):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        owner = (me - t) % p_size
        mask = _ring_mask(causal, owner, k_cur.shape[1], row_global)
        # half-double-buffered: the k/v prefetch hops are issued BEFORE
        # the block compute (no consumer until next step — XLA overlaps
        # them with _flash_bwd_block), halving the permute bytes left on
        # the critical path. dk/dv genuinely depend on this step's
        # output, so their hops follow the compute — they ride the ring
        # WITH their blocks and arrive home after P hops regardless.
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        dq_c, dk_blk, dv_blk = _flash_bwd_block(
            qf, gf, dD, lse, scale, k_cur, v_cur, mask)
        dq = dq + dq_c
        dk_cur = lax.ppermute(dk_cur + dk_blk, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur + dv_blk, axis_name, perm)
        return (dq, k_nxt, v_nxt, dk_cur, dv_cur), None

    dq0 = jnp.zeros((b, sq, h, dh), jnp.float32)
    z = jnp.zeros((b, k.shape[1], h, dh), jnp.float32)
    (dq, _, _, dk, dv), _ = lax.scan(
        step, (dq0, k, v, z, z), jnp.arange(p_size))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring.defvjp(_ring_fwd, _ring_bwd)
