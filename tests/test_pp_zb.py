"""Zero-bubble pipeline schedule (--pp_schedule zb:
parallel/pp_schedule.build_zb_schedule + the explicit F/B/W tick scan
in parallel/pipeline_parallel._pp_zb_grads). Pins:

- the combined table's structural invariants: every unit of the F/B/W
  inventory scheduled exactly once on its stage, every consumption
  strictly after its ring arrival, W strictly after B for the same
  unit, everything inside ONE step's tick range (a deferred W can
  never cross an optimizer update — the fold runs before it);
- the acceptance fact: zb's useful-tick fraction STRICTLY exceeds the
  interleaved schedule's at the same (K, M, V);
- EXACT trajectories: zb bit-matches gpipe AND interleaved on the
  8-device mesh, --clip_norm set and dropout on — host-fed and
  device-resident chunked steps both;
- cross-SCHEDULE checkpoint portability (save under zb -> restore
  under gpipe and the reverse) and mid-chunk --device_data CLI resume
  under --pp_schedule zb;
- parse-time flag validation (whitelist, parent-mode gating, the
  gpipe x V contradiction, the >= 2 blocks/group zb constraint);
- tools/trace_ops.py --schedule ... zb prints B/W ticks distinguished.
"""

import subprocess
import sys

import jax
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.lm import LMDataSet
from distributed_tensorflow_tpu.models.transformer import TransformerLM
from distributed_tensorflow_tpu.parallel import MeshSpec, make_mesh
from distributed_tensorflow_tpu.parallel.pipeline_parallel import (
    fetch_state_pp,
    make_pp_train_step,
    pp_clip_transform,
    pp_comm_rows,
    shard_state_pp,
    stage_batch_pp,
)
from distributed_tensorflow_tpu.parallel.pp_schedule import (
    ZB_B,
    ZB_F,
    ZB_NONE,
    ZB_W,
    build_zb_schedule,
    normalize_pp_schedule,
    schedule_useful_fraction,
    validate_zb_layout,
)
from distributed_tensorflow_tpu.training import (
    create_train_state,
    get_optimizer,
)

KW8 = dict(vocab_size=16, seq_len=32, d_model=32, num_heads=2,
           num_blocks=8)


# ------------------------------------------------------ schedule table


def _units_of(sched):
    """{(kind, m, j): tick} from the table, asserting uniqueness and
    stage placement on the way."""
    k, v = sched.k_stages, sched.virtual_stages
    seen = {}
    for t in range(sched.num_ticks):
        for s in range(k):
            kind = int(sched.kind[t, s])
            if kind == ZB_NONE:
                continue
            mm = int(sched.micro_index[t, s])
            j = int(sched.chunk_index[t, s]) * k + s
            key = (kind, mm, j)
            assert key not in seen, f"unit {key} scheduled twice"
            assert j % k == s  # owned by its stage
            seen[key] = t
    return seen


@pytest.mark.parametrize("k,m,v", [(2, 2, 1), (2, 8, 1), (4, 8, 1),
                                   (2, 4, 2), (4, 4, 2), (2, 6, 3)])
def test_zb_table_invariants(k, m, v):
    """The unit inventory (first group: F+W, last group: B+W, middle:
    F+B+W) runs exactly once each, and every dependency holds with the
    one-tick ring-arrival latency. All ticks live inside one step —
    W-tick deferral can never cross an optimizer update."""
    sched = build_zb_schedule(k, m, v)
    n_groups = k * v
    units = _units_of(sched)
    expect = set()
    for mm in range(m):
        for j in range(n_groups):
            if j < n_groups - 1:
                expect.add((ZB_F, mm, j))
            if j > 0:
                expect.add((ZB_B, mm, j))
            expect.add((ZB_W, mm, j))
    assert set(units) == expect
    for (kind, mm, j), t in units.items():
        assert 0 <= t < sched.num_ticks
        if kind == ZB_F and j > 0:
            # input activation arrived (producer tick + 1 ring hop)
            assert t >= units[(ZB_F, mm, j - 1)] + 1
        if kind == ZB_B:
            if j < n_groups - 1:
                assert t >= units[(ZB_B, mm, j + 1)] + 1  # cot arrival
            assert t >= units[(ZB_F, mm, j - 1)] + 1      # h arrival
        if kind == ZB_W:
            if j == 0:
                assert t >= units[(ZB_B, mm, 1)] + 1      # cot arrival
            else:
                assert t > units[(ZB_B, mm, j)]           # after own B


@pytest.mark.parametrize("k,m,v", [(2, 2, 1), (2, 8, 1), (4, 8, 1),
                                   (2, 4, 2), (4, 4, 2)])
def test_zb_fraction_strictly_exceeds_interleaved(k, m, v):
    """THE acceptance fact: the zb table's useful-tick fraction is
    strictly above the interleaved schedule's M*V/(M*V+K-1) at the
    same (K, M, V) — the deferred W ticks fill the cooldown."""
    zb = build_zb_schedule(k, m, v).useful_tick_fraction
    inter = schedule_useful_fraction("interleaved", k, m, v)
    assert zb > inter
    assert zb == schedule_useful_fraction("zb", k, m, v)


def test_zb_arrival_tables_route_consistently():
    """Every arrival cell points at a unit whose producer ran on the
    right neighbor the tick before — the stash routing the compiled
    scan trusts blindly."""
    sched = build_zb_schedule(4, 4, 2)
    k = sched.k_stages
    units = _units_of(sched)
    for t in range(sched.num_ticks):
        for s in range(k):
            if sched.fwd_in_valid[t, s]:
                mm = int(sched.fwd_in_micro[t, s])
                j = int(sched.fwd_in_chunk[t, s]) * k + s
                assert units[(ZB_F, mm, j - 1)] == t - 1
            if sched.bwd_in_valid[t, s]:
                mm = int(sched.bwd_in_micro[t, s])
                j = int(sched.bwd_in_chunk[t, s]) * k + s
                assert units[(ZB_B, mm, j + 1)] == t - 1


def test_zb_layout_validation():
    with pytest.raises(ValueError, match="k_stages >= 2"):
        build_zb_schedule(1, 4, 1)
    with pytest.raises(ValueError, match="rounds"):
        build_zb_schedule(2, 3, 2)  # M % K under V > 1
    with pytest.raises(ValueError, match="2 blocks per virtual"):
        validate_zb_layout(8, 4, 2)  # 1 block per group
    validate_zb_layout(8, 2, 2)  # 2 per group: fine
    with pytest.raises(ValueError, match="gpipe"):
        normalize_pp_schedule("gpipe", 2)
    with pytest.raises(ValueError, match="must be one of"):
        normalize_pp_schedule("1f1b", 1)
    assert normalize_pp_schedule("auto", 1) == "gpipe"
    assert normalize_pp_schedule("auto", 2) == "interleaved"
    assert normalize_pp_schedule("zb", 1) == "zb"


def test_pp_comm_rows_zb_exposure():
    """The ledger prices zb's backward ring as overlapped (the
    deferred-W slack) and the AD schedules as fully exposed. Byte
    volume is TICK-exact per schedule (r18, dttcheck-proven): the ring
    fires every tick of ITS OWN table, so zb — whose combined F/B/W
    table runs more ticks — moves more ring bytes than the AD
    schedules at the same (K, M, V); its win is exposure, not volume."""
    from distributed_tensorflow_tpu.parallel.pp_schedule import (
        build_pp_schedule,
        build_zb_schedule,
    )

    ad = pp_comm_rows(1000, 2, 4, 1, schedule="interleaved")
    zb = pp_comm_rows(1000, 2, 4, 1, schedule="zb")
    t_ad = build_pp_schedule(2, 4, 1).num_ticks
    t_zb = build_zb_schedule(2, 4, 1).num_ticks
    assert [r["bytes"] for r in ad[:2]] == [1000 * t_ad] * 2
    assert [r["bytes"] for r in zb[:2]] == [1000 * t_zb] * 2
    assert t_zb > t_ad
    assert all(r["exposed_bytes"] == r["bytes"] for r in ad)
    assert zb[0]["exposed_bytes"] == zb[0]["bytes"]  # forward exposed
    assert zb[1]["exposed_bytes"] == 0               # cotangents hidden
    # the degenerate 1-stage layout has no ring and no stage axis —
    # no rows, whatever the schedule asks for
    assert pp_comm_rows(1000, 1, 4, 1, schedule="gpipe") == []
    assert pp_comm_rows(1000, 1, 4, 1, schedule="zb",
                        rep_grad_bytes=10) == []


# ------------------------------------------- exact-trajectory equality


def _run_pp(model, opt, base, mesh, batches, v, schedule,
            microbatches=4, keep_prob=0.5, clip=0.05):
    st = shard_state_pp(base, mesh, virtual_stages=v)
    step = make_pp_train_step(
        model, opt, mesh, microbatches=microbatches, keep_prob=keep_prob,
        donate=False,
        grad_transform=pp_clip_transform(clip, virtual_stages=v),
        virtual_stages=v, schedule=schedule)
    for b in batches:
        st, m = step(st, stage_batch_pp(mesh, b))
    return fetch_state_pp(st, model, k_stages=mesh.shape["model"],
                          virtual_stages=v), m


def _assert_params_equal(a, b):
    for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_zb_trajectory_bitmatches_gpipe_and_interleaved():
    """THE acceptance test: --pp_schedule zb bit-matches gpipe (V=1)
    and interleaved (V=2) for the 8-block LM on the 8-device mesh
    (data=2, model=4 / data=4, model=2), --clip_norm set and dropout
    ON. Same units, same vjps, same descending-m fold — nothing may
    wobble."""
    model = TransformerLM(**KW8)
    opt = get_optimizer("sgd", 0.05)
    base = create_train_state(model, opt, seed=0)
    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=11)
    batches = [ds.next_batch(16) for _ in range(2)]

    # V=1 on the 4-stage mesh: gpipe vs zb (2 blocks per group)
    mesh4 = make_mesh(MeshSpec(data=2, model=4))
    hg, mg = _run_pp(model, opt, base, mesh4, batches, 1, "gpipe")
    hz, mz = _run_pp(model, opt, base, mesh4, batches, 1, "zb")
    assert float(mg["loss"]) == float(mz["loss"])
    assert float(mg["accuracy"]) == float(mz["accuracy"])
    _assert_params_equal(hg, hz)

    # V=2 on the 2-stage mesh: interleaved vs zb (2 blocks per group)
    mesh2 = make_mesh(MeshSpec(data=4, model=2))
    hi, mi = _run_pp(model, opt, base, mesh2, batches, 2, "interleaved")
    hz2, mz2 = _run_pp(model, opt, base, mesh2, batches, 2, "zb")
    assert float(mi["loss"]) == float(mz2["loss"])
    _assert_params_equal(hi, hz2)


def test_zb_device_chunked_bitmatches_interleaved():
    """The device-resident chunked sampler under zb == interleaved
    bitwise: the DATA-axis-only sample fold is schedule-independent,
    so the same rows are drawn and the tick-table equivalence carries
    through the scan-chunked composition (clip on)."""
    from distributed_tensorflow_tpu.data.device_data import (
        put_device_data,
    )
    from distributed_tensorflow_tpu.training.device_step import (
        make_pp_device_train_step,
    )

    model = TransformerLM(**KW8)
    opt = get_optimizer("sgd", 0.05)
    base = create_train_state(model, opt, seed=0)
    mesh = make_mesh(MeshSpec(data=4, model=2))
    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=3)
    data = put_device_data(ds, mesh, data_sharded=True)
    outs = {}
    for sched in ("interleaved", "zb"):
        dev = shard_state_pp(base, mesh, virtual_stages=2)
        dstep = make_pp_device_train_step(
            model, opt, mesh, 16, 4, keep_prob=1.0, chunk=2, donate=False,
            grad_transform=pp_clip_transform(0.05, virtual_stages=2),
            virtual_stages=2, schedule=sched)
        dev, m = dstep(dev, data)
        outs[sched] = (fetch_state_pp(dev, model, k_stages=2,
                                      virtual_stages=2), float(m["loss"]))
    assert outs["interleaved"][1] == outs["zb"][1]
    _assert_params_equal(outs["interleaved"][0], outs["zb"][0])


# ------------------------------------- checkpoint schedule independence


def test_checkpoint_roundtrip_across_schedules(tmp_path):
    """Save under zb -> restore under gpipe (and the reverse) continues
    the exact trajectory: the standard-layout checkpoint contract is
    schedule-independent because fetch_state_pp's output never depends
    on the tick table."""
    from distributed_tensorflow_tpu.checkpoint import (
        restore_latest,
        save_checkpoint,
    )

    model = TransformerLM(**KW8)
    opt = get_optimizer("sgd", 0.05)
    base = create_train_state(model, opt, seed=3)
    mesh = make_mesh(MeshSpec(data=2, model=4))
    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=1)
    batches = [ds.next_batch(16) for _ in range(2)]

    ref, _ = _run_pp(model, opt, base, mesh, batches, 1, "zb",
                     keep_prob=1.0)

    for s_save, s_resume in (("zb", "gpipe"), ("gpipe", "zb")):
        mid, _ = _run_pp(model, opt, base, mesh, batches[:1], 1, s_save,
                         keep_prob=1.0)
        d = tmp_path / f"ckpt_{s_save}to{s_resume}"
        save_checkpoint(str(d), mid, step=1)
        restored, step = restore_latest(
            str(d), create_train_state(model, opt, seed=9))
        assert step == 1
        done, _ = _run_pp(model, opt, restored, mesh, batches[1:], 1,
                          s_resume, keep_prob=1.0)
        _assert_params_equal(ref, done)


def _parse(flags, args):
    flags.FLAGS._reset()
    flags.FLAGS._parse(args)
    return flags.FLAGS


def test_device_zb_mid_chunk_resume(tmp_path):
    """--pipeline --device_data --pp_schedule=zb through the production
    CLI: stop at a step that is NOT a chunk boundary, resume from the
    standard-layout checkpoint, and land on bit-identical params vs
    the uninterrupted run (the resumed loop realigns with a short
    chunk; determinism must survive the different chunk partitioning
    and the stack/unstack round-trip)."""
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.checkpoint import restore_latest
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()

    def args_for(logdir, iters):
        return [f"--logdir={logdir}", f"--data_dir={tmp_path}/none",
                "--dataset=lm", "--model=lm", "--pipeline",
                "--model_axis=2", "--pp_schedule=zb", "--num_blocks=4",
                "--d_model=32", "--num_heads=2", "--seq_len=32",
                "--vocab_size=16", "--batch_size=16",
                f"--training_iter={iters}", "--display_step=3",
                "--device_data", "--device_chunk=3", "--clip_norm=0.5",
                "--test_eval=false"]

    try:
        res = train(_parse(flags, args_for(f"{tmp_path}/a", 5)),
                    mode="sync")
        assert res.final_step == 5
        res = train(_parse(flags, args_for(f"{tmp_path}/a", 9)),
                    mode="sync")
        assert res.final_step == 9
        res_b = train(_parse(flags, args_for(f"{tmp_path}/b", 9)),
                      mode="sync")
        assert res_b.final_step == 9
    finally:
        flags.FLAGS._reset()

    model = TransformerLM(vocab_size=16, seq_len=32, d_model=32,
                          num_heads=2, num_blocks=4)
    opt = get_optimizer("sgd", 0.001)
    tmpl = lambda: create_train_state(model, opt, seed=9)
    got_a, step_a = restore_latest(f"{tmp_path}/a", tmpl())
    got_b, step_b = restore_latest(f"{tmp_path}/b", tmpl())
    assert step_a == step_b == 9
    _assert_params_equal(got_a, got_b)


# ------------------------------------------------ parse-time validation


def test_pp_schedule_flag_validation():
    from distributed_tensorflow_tpu import flags

    flags.define_reference_flags()
    cases = [
        (["--pp_schedule=zb"], "only applies to --pipeline"),
        (["--pp_schedule=1f1b", "--pipeline"], "must be one of"),
        (["--pipeline", "--model_axis=2", "--num_blocks=8",
          "--virtual_stages=2", "--pp_schedule=gpipe"],
         "virtual_stages=1 special case"),
        (["--pipeline", "--model_axis=2", "--num_blocks=4",
          "--virtual_stages=2", "--batch_size=16",
          "--pp_schedule=zb"], "2 blocks per virtual-stage group"),
    ]
    try:
        for args, want in cases:
            flags.FLAGS._reset()
            with pytest.raises(ValueError, match=want):
                flags.FLAGS._parse(args)
        # the valid zb config parses clean; default stays auto
        flags.FLAGS._reset()
        flags.FLAGS._parse(["--pipeline", "--model_axis=2",
                            "--num_blocks=4", "--pp_schedule=zb",
                            "--batch_size=16"])
        assert flags.FLAGS.pp_schedule == "zb"
        flags.FLAGS._reset()
        flags.FLAGS._parse([])
        assert flags.FLAGS.pp_schedule == "auto"
    finally:
        flags.FLAGS._reset()


# ------------------------------------------------------------- tooling


def test_trace_ops_schedule_zb_cli():
    """tools/trace_ops.py --schedule K M [V] zb prints the combined
    F/B/W table with B and W ticks distinguished and the interleaved
    baseline for comparison — no chip, no trace file."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "trace_ops.py"),
         "--schedule", "2", "4", "zb"],
        capture_output=True, text=True, timeout=300, cwd=root)
    assert p.returncode == 0, p.stderr
    assert "zero-bubble" in p.stdout
    assert "B m0.v0" in p.stdout and "W m3.v0" in p.stdout
    assert "interleaved baseline" in p.stdout
