"""Serving entry point:

    python -m distributed_tensorflow_tpu.serving --logdir /tmp/train_logs \
        --dataset lm --model lm --seq_len 256 --vocab_size 64 \
        --serve_port 8000 [--serve_tp 2]

Builds the SAME model the training CLI's flags describe
(``training.loop.build_model_for``), restores the newest checkpoint's
params through the verified fallback ladder, and serves JSON-over-HTTP
(server.py routes) with dynamic batching, hot-reload on a checkpoint
watcher, and serving scalars in the logdir's metrics.jsonl + TB events.
"""

from __future__ import annotations

from distributed_tensorflow_tpu import flags
from distributed_tensorflow_tpu.flags import FLAGS, define_reference_flags


def _dataset_meta(FLAGS) -> dict:
    """The dataset facts model construction needs, WITHOUT loading any
    data (serving has no training split)."""
    if FLAGS.dataset == "lm":
        return {"kind": "lm", "seq_len": FLAGS.seq_len,
                "vocab_size": FLAGS.vocab_size}
    if FLAGS.dataset in ("mnist", "fashion_mnist"):
        return {"image_size": 28, "channels": 1, "num_classes": 10}
    if FLAGS.dataset == "cifar10":
        return {"image_size": 32, "channels": 3, "num_classes": 10}
    raise ValueError(f"unknown --dataset {FLAGS.dataset!r}")


def build_serving_stack(FLAGS):
    """(engine, client, watcher, metrics) from parsed flags — the
    testable core of main()."""
    from distributed_tensorflow_tpu.serving.batcher import DynamicBatcher
    from distributed_tensorflow_tpu.serving.engine import (
        CheckpointWatcher,
        InferenceEngine,
    )
    from distributed_tensorflow_tpu.serving.server import (
        InProcessClient,
        ServingMetrics,
        generate_group_key,
        make_generate_runner,
        make_predict_runner,
        predict_group_key,
    )
    from distributed_tensorflow_tpu.training.loop import build_model_for
    from distributed_tensorflow_tpu.utils import telemetry
    from distributed_tensorflow_tpu.utils.faults import configure_from_flags
    from distributed_tensorflow_tpu.utils.metrics import (
        MetricsLogger,
        StreamingHistogram,
    )

    configure_from_flags(FLAGS)
    # the serving engine registers with the telemetry spine too: spans
    # (serve_batch/serve_reload/ckpt_restore), the flight recorder, and
    # the optional --watchdog_s hang watchdog around batch execution.
    # job_name="serve": a replica pointed at the trainer's live logdir
    # must not collide with the trainer's spans/flightrec files
    telemetry.configure_from_flags(FLAGS, job_name="serve")
    # the request plane (r19) rides the same spine: per-request phase
    # timelines into spans-serve-N.jsonl, the audit ring behind the
    # /metrics tail block, and the --slo_* error-budget ledger
    from distributed_tensorflow_tpu.serving import reqtrace

    reqtrace.configure_from_flags(FLAGS)
    model = build_model_for(FLAGS, _dataset_meta(FLAGS))

    mesh = None
    tp = int(FLAGS.serve_tp) > 1
    import jax

    continuous = FLAGS.serve_scheduler == "continuous"
    if (tp or len(jax.devices()) > 1) and not continuous:
        from distributed_tensorflow_tpu.parallel import MeshSpec, make_mesh

        mesh = make_mesh(MeshSpec(data=-1, model=int(FLAGS.serve_tp)))
    # continuous mode serves one replica per device: no mesh, pools
    # live on the default device (the flag validator already rejects
    # --serve_tp > 1 with it)
    engine = InferenceEngine(model, FLAGS.logdir, mesh=mesh, tp=tp,
                             max_batch=FLAGS.serve_max_batch)
    # resource plane (r13): the replica's memory meter + compile sentry
    # (hbm_* scalars at the metrics cadence, the /metrics hbm block,
    # the --serve_hbm_headroom_pct health floor). Stashed on the engine
    # so the server and ServingMetrics share one monitor. No optimizer:
    # the budget prices the params the replica actually holds.
    from distributed_tensorflow_tpu.utils import resources

    engine.resources = resources.monitor_from_flags(
        FLAGS, model, None, FLAGS.serve_max_batch, len(jax.devices()),
        model_axis=int(FLAGS.serve_tp) if tp else None)
    print(f"serving step {engine.step} from {FLAGS.logdir} "
          f"(restore fallback depth "
          f"{engine.restore_report.fallback_depth})")

    profiler = None
    if FLAGS.serve_profile_batches > 0:
        import os

        from distributed_tensorflow_tpu.utils.profiling import (
            ServeTraceCapture,
        )

        profiler = ServeTraceCapture(
            FLAGS.serve_profile_dir
            or os.path.join(FLAGS.logdir, "serve_profile"),
            FLAGS.serve_profile_batches)

    logger = MetricsLogger(FLAGS.logdir, job_name="serve",
                           filename="serve_metrics.jsonl")
    # one ServingMetrics + latency histogram PER batcher: the emission
    # cadence tracks one completed-counter and the quantiles must not
    # mix routes (the profiler is shared — it locks internally)
    common = dict(max_batch=FLAGS.serve_max_batch,
                  max_delay_ms=FLAGS.serve_max_delay_ms,
                  queue_depth=FLAGS.serve_queue_depth,
                  default_timeout_ms=FLAGS.serve_timeout_ms)
    metrics = ServingMetrics(logger, engine, name="predict",
                             emit_every=FLAGS.serve_metrics_every,
                             profiler=profiler)
    predict_b = DynamicBatcher(make_predict_runner(engine),
                               group_key=predict_group_key,
                               latency=StreamingHistogram(),
                               on_batch=metrics.on_batch,
                               name="predict", **common)
    generate_b = None
    if FLAGS.model == "lm":
        gen_metrics = ServingMetrics(logger, engine, name="generate",
                                     emit_every=FLAGS.serve_metrics_every,
                                     profiler=profiler)
        if continuous:
            # r21: iteration-level slot scheduler over the paged KV
            # cache — same Future/stats/expiry surface, selected here
            # and nowhere else
            from distributed_tensorflow_tpu.serving.continuous import (
                ContinuousBatcher,
                EngineSlotBackend,
            )

            backend = EngineSlotBackend(
                engine, n_slots=FLAGS.serve_slots,
                page_size=FLAGS.serve_kv_page,
                num_pages=FLAGS.serve_kv_pages)
            generate_b = ContinuousBatcher(
                backend, queue_depth=FLAGS.serve_queue_depth,
                default_timeout_ms=FLAGS.serve_timeout_ms,
                latency=StreamingHistogram(),
                on_iteration=gen_metrics.on_batch,
                name="generate")
        else:
            generate_b = DynamicBatcher(
                make_generate_runner(engine),
                group_key=generate_group_key,
                latency=StreamingHistogram(),
                on_batch=gen_metrics.on_batch,
                name="generate", **common)
    # both batchers ride the CONSTRUCTOR: a post-construction attribute
    # write would race HTTP handler threads already reading the client
    # once the server starts (dttsan SAN002)
    client = InProcessClient(
        predict_batcher=predict_b,
        generate_batcher=generate_b,
        default_max_new_tokens=FLAGS.serve_max_new_tokens,
        max_new_tokens_cap=FLAGS.serve_max_new_tokens,
        default_temperature=FLAGS.serve_temperature)

    watcher = None
    if FLAGS.serve_reload_secs > 0:
        watcher = CheckpointWatcher(engine, FLAGS.serve_reload_secs)
    return engine, client, watcher, metrics


def main(argv):
    from distributed_tensorflow_tpu.serving.server import InferenceServer

    engine, client, watcher, _metrics = build_serving_stack(FLAGS)
    if watcher is not None:
        watcher.start()
    server = InferenceServer(
        engine, client, host=FLAGS.serve_host, port=FLAGS.serve_port,
        hbm_headroom_floor_pct=FLAGS.serve_hbm_headroom_pct)
    print(f"serving on {server.address} "
          f"(POST /v1/predict, /v1/generate; GET /healthz, /stats)")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("shutting down")
    finally:
        if watcher is not None:
            watcher.close()
        for b in (client.predict_batcher, client.generate_batcher):
            if b is not None:
                b.close(drain=False)
        server.close()
        # shutdown is the last guaranteed flush point: a short-lived
        # replica (fewer batches than the flush cadence) must not lose
        # its spans — the request plane's req:* records included
        from distributed_tensorflow_tpu.utils import telemetry

        telemetry.get_tracer().flush()
    return 0


if __name__ == "__main__":
    define_reference_flags()
    flags.run(main)
