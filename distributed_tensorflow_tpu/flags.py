"""Flag system with the reference's ``tf.app.flags`` surface.

The reference's public interface is 10 flags + ``tf.app.run()``
(``MNISTDist.py:13-31,197-198``). This module reproduces that API —
``DEFINE_string/integer/float/boolean``, a lazily-parsed ``FLAGS``
singleton, and ``run(main)`` — over argparse, with zero TF dependency.

CLI compatibility is a hard requirement (BASELINE.json): the same launch
scripts that address GPU workers must address TPU VMs, so ``--job_name``,
``--task_index``, ``--ps_hosts``, ``--worker_hosts`` keep their exact
meanings.
"""

from __future__ import annotations

import argparse
import sys
from typing import Any, Callable


class _FlagValues:
    """Lazy-parsing flag namespace (attribute access parses argv once),
    mirroring the TF-0.x FLAGS behavior the reference relies on."""

    def __init__(self):
        self.__dict__["_defs"] = {}  # name -> (type_fn, default, help)
        self.__dict__["_values"] = None
        self.__dict__["_extra_argv"] = []
        self.__dict__["_validators"] = []  # fns(values) run after parse

    def _define(self, name: str, default, help_str: str, type_fn: Callable):
        if self._values is not None:
            # late definition after parse: make it visible with its default
            self._values[name] = default
        self._defs[name] = (type_fn, default, help_str)

    def _register_validator(self, fn: Callable):
        """Cross-flag consistency check run at PARSE time: ``fn(values)``
        raises ValueError with an actionable message. This is how config
        mistakes (e.g. a --virtual_stages/--num_blocks mismatch) surface
        at the command line instead of minutes later mid-trace.
        Idempotent by function identity."""
        if fn not in self._validators:
            self._validators.append(fn)

    def _parse(self, argv=None):
        parser = argparse.ArgumentParser(allow_abbrev=False)
        for name, (type_fn, default, help_str) in self._defs.items():
            if type_fn is bool:
                parser.add_argument(
                    f"--{name}",
                    type=_parse_bool,
                    default=default,
                    nargs="?",
                    const=True,
                    help=help_str,
                )
            else:
                parser.add_argument(f"--{name}", type=type_fn, default=default, help=help_str)
        ns, extra = parser.parse_known_args(
            sys.argv[1:] if argv is None else list(argv)
        )
        self.__dict__["_values"] = vars(ns)
        self.__dict__["_extra_argv"] = extra
        for check in self._validators:
            check(self._values)
        return extra

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        if self._values is None:
            self._parse()
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(f"unknown flag {name!r}") from None

    def __setattr__(self, name: str, value: Any):
        if self._values is None:
            self._parse()
        self._values[name] = value

    def _reset(self):
        """Testing hook: forget parsed values (definitions stay)."""
        self.__dict__["_values"] = None
        self.__dict__["_extra_argv"] = []


def _parse_bool(s):
    if isinstance(s, bool):
        return s
    if str(s).lower() in ("1", "true", "t", "yes", "y"):
        return True
    if str(s).lower() in ("0", "false", "f", "no", "n"):
        return False
    raise argparse.ArgumentTypeError(f"invalid boolean {s!r}")


FLAGS = _FlagValues()


def DEFINE_string(name: str, default: str | None, help_str: str = ""):
    FLAGS._define(name, default, help_str, str)


def DEFINE_integer(name: str, default: int | None, help_str: str = ""):
    FLAGS._define(name, default, help_str, int)


def DEFINE_float(name: str, default: float | None, help_str: str = ""):
    FLAGS._define(name, default, help_str, float)


def DEFINE_boolean(name: str, default: bool | None, help_str: str = ""):
    FLAGS._define(name, default, help_str, bool)


DEFINE_bool = DEFINE_boolean


def run(main: Callable | None = None, argv=None):
    """``tf.app.run`` parity (MNISTDist.py:198): parse flags, call
    ``main(unparsed_argv)``, exit with its return code. A parse-time
    validator rejection exits 2 with the message on stderr — the
    argparse usage-error convention, so a bad flag combination looks
    the same to launch scripts however it was caught."""
    try:
        extra = FLAGS._parse(argv)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        sys.exit(2)
    main = main or sys.modules["__main__"].main
    sys.exit(main([sys.argv[0]] + extra))


COORD_STEPS_DEFAULT = 50


def coord_steps_from_flags(FLAGS) -> int:
    """The one flag→feature mapping for ``--coord_steps`` (multi-host
    vote cadence), shared by every loop that builds a _HostCoordinator so
    the flag default and the flag-less library default cannot diverge."""
    return int(getattr(FLAGS, "coord_steps", COORD_STEPS_DEFAULT))


def define_reference_flags():
    """The reference's exact 10-flag surface (MNISTDist.py:13-31) plus this
    build's extensions. Idempotent."""
    if "job_name" in FLAGS._defs:
        return
    # --- reference flags, same names/defaults/meanings ---
    DEFINE_string("data_dir", "/tmp/mnist-data", "Directory for string mnist data")
    DEFINE_string("ps_hosts", "", "Comma-separated list of hostname:port pairs")
    DEFINE_string("worker_hosts", "", "Comma-separated list of hostname:port pairs")
    DEFINE_string("job_name", "", "One of 'ps', 'worker'")
    DEFINE_integer("task_index", 0, "Index of task within the job")
    DEFINE_integer("hidden_units", 100, "Number of units in the hidden layer of the NN")
    DEFINE_integer("batch_size", 128, "Training batchsize")
    DEFINE_integer("training_iter", 10000, "Training iteration")
    DEFINE_float("learning_rate", 0.001, "Learning rate")
    DEFINE_integer("display_step", 100, "display step")
    # --- build extensions (TPU-native modes and configs) ---
    DEFINE_string("mode", "auto", "Parallel mode: auto|local|sync|ps. auto = "
                  "'ps' roles when --ps_hosts is set (reference semantics), "
                  "else sync DP over all local devices")
    DEFINE_string("model", "deep_cnn", "Model architecture: "
                  "deep_cnn|mlp|resnet20|resnet32|transformer|lm (mlp "
                  "reads --hidden_units; lm is the causal next-token "
                  "family and requires --dataset lm)")
    DEFINE_string("dataset", "mnist", "Dataset: mnist|fashion_mnist|"
                  "cifar10|lm (lm: procedural associative-recall token "
                  "sequences for the causal-LM family; --seq_len/"
                  "--vocab_size shape it)")
    DEFINE_string("optimizer", "sgd", "Optimizer: sgd|momentum|adam (reference: sgd)")
    DEFINE_float("weight_decay", 0.0, "Decoupled weight decay: the update "
                 "subtracts lr*wd*param alongside the gradient step "
                 "(AdamW semantics for adam; classic L2 for plain sgd). "
                 "local/sync/TP/device_data modes; ps mode rejects it")
    DEFINE_float("keep_prob", 0.75, "Dropout keep probability during training. "
                 "The reference defines DROPOUT=0.75 but feeds 1.0 (disabled); "
                 "this build applies it")
    DEFINE_string("logdir", "/tmp/train_logs", "Checkpoint/metrics directory (reference default)")
    DEFINE_integer("save_model_secs", 600,
                   "Checkpoint cadence in seconds (reference default). In "
                   "multi-host runs saves are quantized to --coord_steps "
                   "boundaries (the cadenced stop/save vote), so a due "
                   "save can land up to coord_steps steps late")
    DEFINE_integer("max_to_keep", 5, "Checkpoints retained before GC "
                   "(TF Saver's default); older ones are deleted")
    DEFINE_integer("seed", 0, "PRNG seed")
    DEFINE_boolean("bf16", False, "Run matmuls/convs in bfloat16 on the MXU")
    DEFINE_boolean("pallas", False, "Use the fused Pallas kernel for the "
                   "dominant FC layer (deep_cnn only)")
    DEFINE_boolean("test_eval", True, "Evaluate on the test split at the end "
                   "(the reference never does; targets require it)")
    DEFINE_boolean("eval_only", False, "Restore the latest checkpoint from "
                   "--logdir and evaluate the full test split — no "
                   "training. Works on checkpoints from every mode")
    DEFINE_integer("eval_step", 0, "If > 0, also evaluate on the FULL test "
                   "split every this many steps (logged as test_accuracy/"
                   "test_loss scalars). 0 = end-of-run only; the reference "
                   "never touches the test split at all")
    DEFINE_boolean("shard_data", False, "Give each worker a disjoint data shard "
                   "(reference: every worker samples the full dataset)")
    DEFINE_string("profile_dir", "", "If set, capture a jax.profiler trace of "
                  "--profile_steps post-compile training steps into this dir")
    DEFINE_integer("profile_steps", 10, "Number of steps in the profiler window")
    DEFINE_integer("validation_size", 0, "Examples held out of the train split "
                   "as a validation DataSet (0 = none, reference behavior). "
                   "With --eval_step the periodic evals run on this split "
                   "(validation_accuracy/validation_loss scalars) and the "
                   "test split is touched only by the final --test_eval")
    DEFINE_boolean("raw_input", False, "Feed uint8 images + int32 labels and "
                   "normalize on device (4x less host->device traffic; "
                   "fastest path on bandwidth-limited links)")
    DEFINE_boolean("device_data", False, "Stage the train split into HBM once "
                   "and sample batches ON DEVICE inside the compiled step "
                   "(zero host->device bytes per step; lax.scan runs "
                   "--device_chunk steps per dispatch). Composes with every "
                   "parallel mode: plain DP/TP, --seq_parallel (token-"
                   "sharded split), --pipeline and --expert_parallel (data-"
                   "sharded split, per-shard salted PRNG streams). Training "
                   "batches are sampled with replacement rather than the "
                   "reference's shuffled-epoch walk; display-step evals keep "
                   "reference semantics where a host batch exists (PP "
                   "displays the step's own training metrics instead)")
    DEFINE_integer("device_chunk", 50, "Steps per compiled scan chunk in "
                   "--device_data mode (clamped to divide display_step)")
    DEFINE_float("clip_norm", 0.0, "If > 0, clip gradients to this global "
                 "L2 norm before the optimizer update (every mode except "
                 "ps, which keeps reference parity). Under --pipeline / "
                 "--expert_parallel the squared norm is psum'd over the "
                 "model axis before scaling (stage/expert shards are exact "
                 "partials), so the clipped trajectory exactly matches the "
                 "single-device one and replicated leaves stay bit-"
                 "identical. Guards against early loss spikes at high "
                 "learning rates")
    DEFINE_integer("model_axis", 1, "Tensor-parallel ways on the mesh's "
                   "'model' axis (sync mode): the CNN's FC stack is "
                   "column/row-split and XLA inserts the collectives. "
                   "1 = pure data parallelism (reference-equivalent). "
                   "With --seq_parallel this is the SEQUENCE ways instead")
    DEFINE_boolean("seq_parallel", False, "Sequence/context parallelism "
                   "(sync mode, --model transformer only): the token axis "
                   "shards --model_axis ways over the mesh's 'model' axis, "
                   "attention runs as a RING (k/v blocks rotating over "
                   "ICI with online-softmax accumulation), per-device "
                   "activation memory stays one token block regardless "
                   "of context length")
    DEFINE_boolean("sp_span_hosts", False, "--seq_parallel only: allow "
                   "the token axis to SPAN processes — ring hops between "
                   "hosts ride DCN, and the context length is no longer "
                   "bounded by one host's chips. Every process then draws "
                   "the SAME global batch (shared seed; hosts in a data "
                   "row hold token-slices of the same sequences) and "
                   "uploads only its tile. Default: the token axis must "
                   "stay within each host's chips")
    DEFINE_string("lr_schedule", "constant", "Learning-rate schedule: "
                  "constant|cosine|linear|exponential — evaluated inside "
                  "the compiled step (reference: constant). Decays over "
                  "--decay_steps from --learning_rate")
    DEFINE_integer("warmup_steps", 0, "Linear learning-rate warmup steps "
                   "before --lr_schedule takes over (0 = none)")
    DEFINE_integer("decay_steps", 0, "Schedule decay horizon in steps "
                   "(0 = the full --training_iter budget)")
    DEFINE_float("decay_rate", 0.96, "Decay factor per --decay_steps for "
                 "--lr_schedule=exponential")
    DEFINE_boolean("augment", False, "On-device data augmentation compiled "
                   "into the train step: zero-pad by --augment_pad, random "
                   "crop back, and — for 3-channel natural images only — "
                   "random horizontal flip (digits are never mirrored). "
                   "Zero host cost. local/sync/TP and --device_data modes")
    DEFINE_integer("augment_pad", 4, "Padding for --augment's random crop")
    DEFINE_integer("accum_steps", 1, "Gradient accumulation: split each "
                   "batch into this many equal microbatches, one backward "
                   "pass each (lax.scan — live activations are one "
                   "microbatch's worth), average, then a single optimizer "
                   "update. local/sync/TP modes; incompatible with "
                   "--device_data (whose batches are already sampled "
                   "on device per step)")
    DEFINE_integer("seq_len", 256, "Context length for --dataset lm "
                   "(tokens per training sequence; targets are the "
                   "sequence shifted one token)")
    DEFINE_integer("vocab_size", 64, "Vocabulary for --dataset lm")
    DEFINE_integer("d_model", 128, "Transformer width (transformer|lm)")
    DEFINE_integer("num_heads", 4, "Attention heads (transformer|lm)")
    DEFINE_integer("num_blocks", 2, "Transformer blocks (transformer|lm)")
    DEFINE_integer("attn_block", 0, "If > 0, single-device attention "
                   "streams over key/value blocks of this many tokens "
                   "(online softmax — O(S*block) peak memory instead of "
                   "the dense O(S^2) score matrix; the one-chip "
                   "long-context path). lm model only; mutually "
                   "exclusive with --seq_parallel's ring attention")
    DEFINE_integer("ce_block", 0, "If > 0, the LM loss head streams "
                   "over row blocks of this many tokens (custom-VJP "
                   "softmax-CE — the (B,S,V) f32 logits never "
                   "materialize; O(block*V) peak both passes). The "
                   "large-vocab half of the long-context memory story; "
                   "lm model only")
    DEFINE_boolean("pipeline", False, "GPipe-style pipeline parallelism "
                   "for --model lm: transformer blocks staged "
                   "--model_axis ways over the mesh's 'model' axis, "
                   "activations ppermute stage-to-stage while every "
                   "stage works a different microbatch "
                   "(parallel/pipeline_parallel.py). Mutually exclusive "
                   "with --seq_parallel; num_blocks must divide by "
                   "--model_axis. Composes with --device_data (the "
                   "resident chunked sampler), --clip_norm (axis-"
                   "aware) and --virtual_stages (the interleaved "
                   "schedule — a ~V-fold smaller pipeline bubble)")
    DEFINE_integer("pp_microbatches", 0, "Microbatches per step under "
                   "--pipeline (0 = the stage count, the GPipe "
                   "default); must divide the per-data-shard batch, "
                   "and by --model_axis when --virtual_stages > 1 "
                   "(the interleaved schedule works microbatches in "
                   "rounds of the stage count)")
    DEFINE_integer("virtual_stages", 1, "Interleaved virtual-stage "
                   "pipeline schedule (Megatron-LM) for --pipeline: "
                   "each stage owns this many NONCONTIGUOUS round-"
                   "robin block groups, activations make V shorter "
                   "trips around the ppermute ring, and the fill/"
                   "drain bubble shrinks ~V-fold (useful-tick "
                   "fraction M*V/(M*V+K-1) vs GPipe's M/(M+K-1)). "
                   "Bit-identical trajectories to the default V=1; "
                   "checkpoints stay layout-independent. Requires "
                   "num_blocks divisible by model_axis*virtual_stages "
                   "and microbatches divisible by model_axis")
    DEFINE_string("pp_schedule", "auto", "Pipeline tick schedule for "
                  "--pipeline: auto (default: interleaved when "
                  "--virtual_stages > 1, else gpipe — the pre-flag "
                  "behavior), gpipe, interleaved, or zb (zero-bubble, "
                  "ZB-H1 family: backward splits into activation-grad "
                  "B and weight-grad W ticks and the deferred W ticks "
                  "fill the cooldown bubble — useful-tick fraction "
                  "strictly above interleaved at the same layout). "
                  "All three compute the same function: trajectories "
                  "are bit-identical across schedules at the same "
                  "(K, M, V) and checkpoints restore across them "
                  "bitwise. zb composes with --virtual_stages and "
                  "needs >= 2 blocks per virtual-stage group")
    DEFINE_integer("moe_experts", 0, "If > 0, the LM's MLPs become "
                   "top-1 Switch mixture-of-experts layers with this "
                   "many experts (ops/moe.py); the training loss adds "
                   "--moe_aux times the load-balance term")
    DEFINE_float("moe_capacity", 1.25, "Per-expert token capacity "
                 "factor (tokens beyond ceil(cf*T/E) drop to the "
                 "residual stream — Switch semantics)")
    DEFINE_float("moe_aux", 0.01, "Load-balance auxiliary loss "
                 "coefficient for --moe_experts")
    DEFINE_boolean("expert_parallel", False, "Shard the MoE experts "
                   "--model_axis ways over the mesh's 'model' axis "
                   "(expert parallelism: every device routes "
                   "identically, computes its experts' tokens, one "
                   "psum combines — parallel/expert_parallel.py). "
                   "Requires --moe_experts divisible by --model_axis. "
                   "Composes with --device_data (the resident chunked "
                   "sampler) and --clip_norm (axis-aware)")
    DEFINE_boolean("remat", False, "Rematerialize each transformer block "
                   "in the backward pass (jax.checkpoint): activation "
                   "memory drops to one block's worth at the cost of "
                   "one extra forward — the standard long-context trade")
    DEFINE_integer("zero", 0, "ZeRO-sharded data parallelism (sync DP "
                   "only, parallel/zero.py): 0 = replicated (default), "
                   "1 = shard the optimizer state 1/D per data rank "
                   "(grads reduce-scatter instead of all-reduce — |G|+|P| "
                   "on the wire vs 2|G| — and one all_gather rebuilds the "
                   "updated params), 3 = FSDP-style (params live sharded "
                   "too, gathered inside forward/backward). Trajectories "
                   "match replicated DP bit-for-bit (last-ulp under "
                   "--clip_norm); checkpoints stay standard-layout, so "
                   "--zero runs and replicated runs restore each other's "
                   "checkpoints. Composes with --device_data, "
                   "--accum_steps, --clip_norm, --augment; mutually "
                   "exclusive with the model-axis strategies "
                   "(--pipeline/--seq_parallel/--expert_parallel/"
                   "--model_axis>1) and ps mode")
    DEFINE_boolean("zero_overlap", False, "ZeRO comm/compute overlap "
                   "(requires --zero 1|3): grads reduce-scatter in "
                   "--zero_bucket_mb buckets that issue as backward "
                   "produces leaves (instead of one serial flat "
                   "scatter at the end), and — at level 3 — the param "
                   "all_gather is prefetched one step ahead inside the "
                   "--device_data scan (double-buffered; XLA's async "
                   "collectives hide it behind compute) and reused by "
                   "forward AND backward — the |G|+|P| wire volume "
                   "leaves the critical path. Trajectories stay "
                   "bit-identical to the serial ZeRO path (same "
                   "padding, same chunk ownership)")
    DEFINE_float("zero_bucket_mb", 4.0, "Bucket size in MB for "
                 "--zero_overlap's bucketed reduce-scatter/all-gather "
                 "(the comm-latency/overlap-granularity knob): leaves "
                 "group in canonical order until a bucket exceeds "
                 "this, one collective per bucket")
    DEFINE_string("prng", "threefry", "PRNG implementation: threefry "
                  "(default, partition-invariant) or rbg (hardware RNG — "
                  "measured ~4% faster steps on TPU; dropout masks and "
                  "on-device batch sampling draw from it). Checkpoints "
                  "store the rng key, whose shape differs between "
                  "implementations: resume with the same --prng")
    DEFINE_string("ps_wire", "f32", "PS-mode transport precision: f32 "
                  "(exact, reference parity) or bf16 — every pulled "
                  "param and pushed grad moves at half width over BOTH "
                  "the TCP wire and the host<->chip link (ps-side master "
                  "params stay f32; same precision class as bf16 compute)")
    DEFINE_boolean("ps_prefetch", True, "PS mode, full-pull cycle only "
                   "(sgd runs the --ps_mirror cycle by default; set "
                   "--ps_mirror=false for this flag to apply): keep one "
                   "parameter pull in flight, overlapping the next pull "
                   "with the chip's gradient computation and the push "
                   "(the pulled snapshot is one own-push staler — "
                   "async-SGD staleness class). false = serial "
                   "pull/compute/push reference cycle")
    DEFINE_boolean("ps_mirror", True, "PS mode: keep a device-resident "
                   "mirror of the params (and, for momentum/adam, the "
                   "optimizer slots) and replay each pushed gradient's "
                   "identical ps-side update ON CHIP instead of re-"
                   "pulling + re-uploading the full parameter set every "
                   "cycle (the dominant transfer). The mirror resyncs "
                   "params (+slots) from the ps every --ps_resync_steps "
                   "and immediately when another worker's push is "
                   "detected (the returned global step skips ahead); "
                   "=false restores the pull cycle --ps_prefetch "
                   "controls")
    DEFINE_integer("ps_resync_steps", 50, "Steps between full parameter "
                   "resyncs in --ps_mirror mode (bounds any numeric drift "
                   "between the ps-side and device-side sgd applies)")
    DEFINE_integer("coord_steps", COORD_STEPS_DEFAULT,
                   "Multi-host coordination cadence in "
                   "steps: sync-mode processes agree on stop/checkpoint "
                   "decisions with one tiny allgather every this many "
                   "steps (worst-case stop latency = this many extra "
                   "steps). Single-process runs never vote")
    DEFINE_boolean("sharded_checkpoint", True, "Cross-host-sharded state "
                   "checkpoints as per-process shard files (each host "
                   "writes its locally-owned slices; NO allgather — the "
                   "save moves 1/P of the model per host instead of "
                   "O(model) to every host). Restore reassembles from "
                   "the complete set; --eval_only and the inspect CLI "
                   "read both formats. =false keeps the monolithic "
                   "single-file format. Locally-fetchable state always "
                   "writes the monolithic file")
    DEFINE_boolean("async_checkpoint", True, "Write cadenced checkpoints "
                   "from a background thread (the state is fetched to "
                   "host on the training thread, then serialized and "
                   "written off-thread; training never blocks on the "
                   "disk). The final checkpoint on exit is always "
                   "synchronous")
    DEFINE_string("fault_spec", "", "Deterministic fault injection "
                  "(utils/faults.py): comma-separated rules, each "
                  "point[:key=value]... — e.g. "
                  "'ckpt_write:at_step=40:mode=crash', "
                  "'restore:mode=torn_file', 'init:mode=refuse:times=2'. "
                  "Empty (default) injects nothing and leaves every path "
                  "byte-identical in behavior; the DTT_FAULT_SPEC env "
                  "var is the fallback for subprocesses. "
                  "'python tools/trace_ops.py --faults' lists the points")
    DEFINE_integer("init_retries", 8, "Bounded retries around "
                   "jax.distributed.initialize for a worker relaunched "
                   "after a crash (the coordinator may still be coming "
                   "back); linear backoff of --init_backoff_s per "
                   "attempt, loud failure when exhausted. 0 = fail on "
                   "the first refusal (the pre-recovery behavior)")
    DEFINE_float("init_backoff_s", 2.0, "Backoff unit (seconds) between "
                 "--init_retries attempts; attempt k waits k*this, "
                 "capped at 30s")
    DEFINE_float("init_timeout_s", 0.0, "Per-attempt cap (seconds) on "
                 "jax.distributed.initialize's own connection wait "
                 "(0 = the library default, 300s); lower it so "
                 "--init_retries attempts turn over quickly in "
                 "fast-relaunch deployments")
    DEFINE_boolean("telemetry", True, "The always-on observability "
                   "spine (utils/telemetry.py): span tracing into "
                   "<logdir>/spans-<host>.jsonl (Chrome-trace export "
                   "via tools/trace_view.py), step-time breakdown "
                   "scalars (step_host_wait_s/step_dispatch_s/"
                   "step_device_s) next to the throughput numbers, and "
                   "the crash flight recorder "
                   "(<logdir>/flightrec-<host>.jsonl). Overhead is "
                   "bench-asserted (< 5 us/span, < 2% on the flagship "
                   "step); =false disables recording entirely. "
                   "--profile_dir/--serve_profile_batches remain the "
                   "deep-dive (one-shot jax.profiler) path")
    DEFINE_float("watchdog_s", 0.0, "If > 0, arm a hang watchdog "
                 "around every device dispatch and collective: an "
                 "operation still incomplete after this many seconds "
                 "dumps all-thread stacks (faulthandler), the last "
                 "spans, and the in-flight op's context to stderr and "
                 "the flight recorder — turning a silent collective-"
                 "rendezvous deadlock into a diagnosable report. "
                 "0 = off. Set it well above a legitimate step/compile "
                 "time (first-step XLA compiles are armed too)")
    DEFINE_boolean("watchdog_abort", False, "After a watchdog report, "
                   "hard-exit the process (status 124) instead of "
                   "continuing to wait — the unattended-run setting "
                   "(an orchestrator relaunches; the report survives "
                   "in the flight recorder). Requires --watchdog_s > 0")
    DEFINE_integer("flightrec_events", 512, "Flight-recorder ring "
                   "length: how many recent spans/scalars/notes the "
                   "crash postmortem (flightrec-<host>.jsonl) holds")
    DEFINE_boolean("mfu", True, "Efficiency accounting "
                   "(utils/efficiency.py): emit mfu, "
                   "model_flops_per_sec and goodput scalars next to "
                   "images_per_sec at the display cadence in every "
                   "training loop. The FLOPs budget is analytic "
                   "(per-layer, no chip interaction); goodput charges "
                   "restore/checkpoint/eval/compile stalls against the "
                   "wall clock. =false drops the scalars entirely")
    DEFINE_float("mfu_peak_flops", 0.0, "Per-chip peak FLOP/s the MFU "
                 "denominator uses. 0 = auto: known TPU chips resolve "
                 "from a spec table by device_kind; anything else runs "
                 "a one-shot cached matmul calibration (achieved "
                 "FLOP/s stands in for peak). Set explicitly when the "
                 "auto answer is wrong for your part")
    DEFINE_string("sentinel_action", "", "Training-health sentinels "
                  "(utils/sentinel.py): '' (default) = unarmed; "
                  "warn = report trips (loud print, sentinel:<kind> "
                  "span, scalar, flight-recorder dump); snapshot = "
                  "warn + an emergency checkpoint of the last "
                  "known-good state through the verified-save path "
                  "into <logdir>/sentinel/; abort = snapshot + raise "
                  "so the run exits loudly. Checks run at the display "
                  "cadence on the scalars the loop already computes — "
                  "no extra device work")
    DEFINE_string("sentinel_kinds", "nan,loss_spike,grad_explosion,"
                  "throughput_collapse",
                  "Comma-separated sentinel kinds to arm (subset of "
                  "nan, loss_spike, grad_explosion, "
                  "throughput_collapse)")
    DEFINE_integer("sentinel_window", 32, "Rolling-history length (in "
                   "display-cadence observations) behind the sentinel "
                   "median/MAD baselines")
    DEFINE_float("sentinel_threshold", 10.0, "MADs above the rolling "
                 "median at which loss_spike/grad_explosion trip")
    DEFINE_integer("hbm_sample_every", 1, "Live HBM accounting "
                   "(utils/resources.MemoryMeter): sample "
                   "device.memory_stats() every this many display "
                   "boundaries and emit hbm_in_use_bytes/hbm_peak_bytes/"
                   "hbm_headroom_pct scalars next to images_per_sec "
                   "(backends without the stat fall back to live-array "
                   "bytes, labeled; headroom is -1 without a reported "
                   "limit). Samples ride the EXISTING display cadence — "
                   "no new sync points — and land as hbm_sample spans "
                   "for fleet_report/the OOM postmortem. 0 = off. "
                   "Rides the telemetry spine (--telemetry=false "
                   "disables it)")
    DEFINE_boolean("elastic", False, "Elastic, preemption-tolerant "
                   "training (training/elastic.py): on a membership "
                   "change — a spot preemption modeled by the "
                   "'preempt' fault point, or a departure bit on the "
                   "multi-host coordinator vote — the run drains to "
                   "the next checkpoint boundary (a verified-save "
                   "drain checkpoint; an 'immediate' preemption loses "
                   "the step and falls back to the last checkpoint or "
                   "the sentinel's emergency snapshot), re-forms the "
                   "mesh at the new world size, restores the standard-"
                   "layout checkpoint into the rescaled DP/ZeRO "
                   "layout, and continues — bitwise on the trajectory "
                   "a fresh run restored at the target shape would "
                   "take. The resize downtime lands as the goodput "
                   "ledger's named resize_s charge plus "
                   "membership_change/resize spans. Auto-armed "
                   "whenever --fault_spec names the preempt point")
    DEFINE_integer("world_size", 0, "Launch-world size for elastic "
                   "training: cap the run to this many world members "
                   "(single-process: local devices — the device-host "
                   "topology; multi-process: processes). 0 = the full "
                   "device/process set. A smaller launch world leaves "
                   "headroom for a resize to GROW into (the re-add "
                   "half of the elastic story)")
    DEFINE_integer("recompile_budget", 0, "Recompilation sentry "
                   "(utils/resources.CompileSentry): if > 0, more than "
                   "this many traced-signature recompiles inside a "
                   "rolling 60 s window trips a storm report naming "
                   "the churned shape/dtype delta (loud print, "
                   "recompile_storm span, flight-recorder dump). "
                   "0 = count only: the compiles_total/compile_time_s/"
                   "recompiles_total scalars are always emitted while "
                   "telemetry is on")
    FLAGS._register_validator(_validate_core_flags)
    FLAGS._register_validator(_validate_model_data_flags)
    FLAGS._register_validator(_validate_pairing_flags)
    FLAGS._register_validator(_validate_pipeline_flags)
    FLAGS._register_validator(_validate_elastic_flags)
    FLAGS._register_validator(_validate_zero_flags)
    FLAGS._register_validator(_validate_fault_spec)
    FLAGS._register_validator(_validate_telemetry_flags)
    FLAGS._register_validator(_validate_efficiency_flags)
    FLAGS._register_validator(_validate_resource_flags)
    define_serving_flags()


def define_serving_flags():
    """The serving CLI surface (``python -m
    distributed_tensorflow_tpu.serving``); idempotent, and also defined
    for the training CLI so one launch-script flag namespace covers the
    whole lifecycle."""
    if "serve_port" in FLAGS._defs:
        return
    DEFINE_string("serve_host", "127.0.0.1", "Bind address for the "
                  "serving HTTP front end")
    DEFINE_integer("serve_port", 8000, "Port for the serving HTTP front "
                   "end (0 = ephemeral)")
    DEFINE_integer("serve_max_batch", 8, "Largest microbatch the dynamic "
                   "batcher assembles; must be a power of two (batches "
                   "pad to power-of-two buckets so the jitted-executable "
                   "cache stays one entry per bucket)")
    DEFINE_float("serve_max_delay_ms", 5.0, "Longest the batcher holds "
                 "the oldest queued request while waiting to fill a "
                 "batch — the latency/throughput knob")
    DEFINE_integer("serve_queue_depth", 64, "Bounded request queue; a "
                   "full queue REJECTS new requests immediately "
                   "(backpressure with a reason, never a hang). Must "
                   "hold at least one full --serve_max_batch")
    DEFINE_float("serve_timeout_ms", 1000.0, "Default per-request "
                 "deadline: a request still queued past it completes "
                 "with a deadline rejection instead of burning chip "
                 "time on an answer nobody awaits")
    DEFINE_integer("serve_max_new_tokens", 32, "Default (and cap) for "
                   "generate requests' new-token budget; prompt + "
                   "budget must fit the model's context window")
    DEFINE_float("serve_temperature", 0.0, "Default sampling "
                 "temperature for generate requests (0 = greedy)")
    DEFINE_float("serve_reload_secs", 10.0, "Checkpoint-watcher poll "
                 "cadence: a newer step in --logdir hot-swaps into the "
                 "engine between microbatches (0 = watching off)")
    DEFINE_integer("serve_profile_batches", 0, "If > 0, capture one "
                   "jax.profiler trace around this many served batches "
                   "and log the artifact path (utils/profiling."
                   "ServeTraceCapture)")
    DEFINE_string("serve_profile_dir", "", "Trace directory for "
                  "--serve_profile_batches (default: <logdir>/"
                  "serve_profile)")
    DEFINE_integer("serve_tp", 1, "Tensor-parallel ways for serving "
                   "placement over the mesh's 'model' axis (Megatron "
                   "block split via parallel/tensor_parallel); 1 = "
                   "DP-replicated params. Must divide --num_heads and "
                   "the MLP width")
    DEFINE_integer("serve_metrics_every", 50, "Emit serving scalars "
                   "(queue depth, p50/p99 latency, throughput, reload "
                   "counters) every this many microbatches (0 = off)")
    DEFINE_float("serve_hbm_headroom_pct", 0.0, "Replica-drain floor: "
                 "/healthz flips to 503 (ok=false, hbm_low_headroom) "
                 "when the replica's live HBM headroom drops below "
                 "this percent of the device limit — a router can "
                 "drain a leaking replica before the allocator kills "
                 "it mid-request. 0 = off. Only meaningful where the "
                 "backend reports a memory limit (headroom reads -1 "
                 "elsewhere and never trips the floor)")
    DEFINE_float("slo_p99_ms", 0.0, "Serving latency SLO (the request "
                 "plane, serving/reqtrace.py): a request is compliant "
                 "when it completes ok within this many milliseconds. "
                 "Arms the error-budget ledger — the /metrics slo "
                 "block (compliant_pct, budget_remaining, fast/slow "
                 "burn rates) and the /healthz 503 on a fast-burn "
                 "breach (joining the HBM-headroom drain floor). "
                 "0 = SLO accounting off (phase timelines and tail "
                 "attribution still run)")
    DEFINE_float("slo_target_pct", 99.0, "The SLO compliance target: "
                 "this percent of requests are promised within "
                 "--slo_p99_ms; the remainder is the error budget the "
                 "burn rates are measured against. Must be in "
                 "(50, 100]; only meaningful with --slo_p99_ms > 0")
    DEFINE_integer("reqtrace_ring", _REQTRACE_RING_DEFAULT,
                   "Bounded per-request audit "
                   "ring (the request plane): how many finished "
                   "request summaries — id, route, shape-bucket, "
                   "disposition, phase breakdown — the replica retains "
                   "for the /metrics tail exemplars and postmortems")
    DEFINE_integer("reqtrace_exemplars", _REQTRACE_EXEMPLARS_DEFAULT,
                   "How many worst live "
                   "exemplars (request_id + phase breakdown, by total "
                   "latency) the /metrics tail block names; must be "
                   "in [1, 64]")
    DEFINE_string("serve_scheduler", "whole_batch", "Generate-route "
                  "scheduler: 'whole_batch' (DynamicBatcher — one "
                  "microbatch committed for its entire generation) or "
                  "'continuous' (iteration-level slot scheduler over a "
                  "paged KV cache, serving/continuous.py — requests "
                  "admit/retire between decode steps, greedy outputs "
                  "bitwise identical to whole_batch). Continuous "
                  "serves --model lm, one replica per device (no "
                  "--serve_tp)")
    DEFINE_integer("serve_slots", 4, "Continuous scheduler: fixed "
                   "number of batch slots (concurrent in-flight "
                   "generations). Must be >= 2 — slot width >= 2 keeps "
                   "the decode contractions on the GEMM kernel, the "
                   "same bitwise-parity floor the whole-batch decode "
                   "enforces")
    DEFINE_integer("serve_kv_page", 16, "Continuous scheduler: tokens "
                   "per KV-cache page; must divide --seq_len (a slot's "
                   "logical pages tile the context window exactly)")
    DEFINE_integer("serve_kv_pages", 0, "Continuous scheduler: physical "
                   "KV pages in the pool. 0 = full provisioning "
                   "(serve_slots * seq_len / serve_kv_page — every slot "
                   "can hold a max-length request); smaller pools "
                   "oversubscribe slots against pages and admission "
                   "gates on the page commitment. Must hold at least "
                   "one full-context request (seq_len / serve_kv_page)")
    DEFINE_string("router_replicas", "", "Fleet router (serving/"
                  "router.py): comma-separated host:port replica list "
                  "the router fans traffic over (empty = router off; "
                  "required by python -m distributed_tensorflow_tpu."
                  "serving.router)")
    DEFINE_string("router_host", "127.0.0.1", "Bind address for the "
                  "router HTTP front end")
    DEFINE_integer("router_port", 8100, "Port for the router HTTP "
                   "front end (0 = ephemeral)")
    DEFINE_float("router_poll_ms", 200.0, "Health-poller cadence: each "
                 "tick folds every replica's /healthz (and every k-th "
                 "tick /metrics) into its router-side state. Must be "
                 "in [10, 60000]")
    DEFINE_integer("router_retries", 2, "Max per-request retry "
                   "attempts after the first dispatch, on connect-fail "
                   "or 5xx only (4xx/429 pass through). Must be in "
                   "[0, 10]")
    DEFINE_float("router_backoff_ms", 20.0, "Base retry backoff "
                 "(exponential with full jitter: base * 2^(n-1) * "
                 "U[0.5, 1]). Must be in [0, 10000]")
    DEFINE_float("router_retry_budget_pct", 10.0, "Global retry budget "
                 "as a percent of observed requests (plus a small "
                 "burst floor) — a fleet outage cannot amplify into a "
                 "retry storm. Must be in [0, 100]")
    DEFINE_float("router_hedge_ms", 0.0, "Latency budget after which a "
                 "still-unresolved request fires ONE hedged duplicate "
                 "onto a different replica; first success wins and the "
                 "SLO ledger books one outcome per request id. "
                 "0 = hedging off. Requires --telemetry (the hedge "
                 "race is audited through route_hedge spans)")
    DEFINE_float("router_hedge_budget_pct", 5.0, "Hedge volume cap as "
                 "a percent of observed requests. Must be in [0, 100]")
    DEFINE_integer("router_breaker_fails", 3, "Circuit breaker: "
                   "consecutive dispatch/poll failures that eject a "
                   "replica. Must be in [1, 100]")
    DEFINE_float("router_eject_s", 1.0, "Ejection cooldown before the "
                 "half-open probe (doubling per consecutive "
                 "re-ejection, capped 8x). Must be in (0, 3600]")
    DEFINE_integer("router_min_healthy", 1, "Rolling reload / fleet "
                   "health floor: the healthy-replica count the router "
                   "never lets orchestration drop below. Must be >= 0 "
                   "and, with --router_replicas set, < the replica "
                   "count (draining one replica must stay legal)")
    FLAGS._register_validator(_validate_serving_flags)
    FLAGS._register_validator(_validate_reqtrace_flags)
    FLAGS._register_validator(_validate_router_flags)


def _require(values: dict, name: str, check, what: str):
    """One bounds check: skip when the flag is absent from this parse
    set (partial namespaces), raise with the flag and the bound NAMED
    otherwise — the dttlint DTT006 contract (every flag is either read
    by a registered validator or carries an explicit baseline entry)."""
    v = values.get(name)
    if v is not None and not check(v):
        raise ValueError(f"--{name}={v} {what}")


def _validate_core_flags(values: dict):
    """Parse-time bounds for the reference surface + the loop-numeric
    extensions (the PR-2 _register_validator pattern, swept over the
    whole flag table by dttlint DTT006): a zero step budget, a
    non-positive learning rate, or a dead display cadence surfaces at
    the command line, not as a silently-empty run. Range checks ONLY —
    cross-flag pairings live in _validate_pairing_flags (r18) or, where
    the tests pin a train()-time message (e.g. --accum_steps vs
    --device_data), stay library errors."""
    _require(values, "training_iter", lambda v: int(v) >= 1,
             "must be >= 1 (the step budget)")
    _require(values, "learning_rate", lambda v: float(v) > 0,
             "must be > 0")
    _require(values, "display_step", lambda v: int(v) >= 1,
             "must be >= 1 (the display/eval cadence)")
    _require(values, "task_index", lambda v: int(v) >= 0,
             "must be >= 0 (a cluster-member index)")
    _require(values, "hidden_units", lambda v: int(v) >= 1,
             "must be >= 1")
    _require(values, "keep_prob", lambda v: 0 < float(v) <= 1,
             "must be in (0, 1] (a dropout KEEP probability)")
    _require(values, "weight_decay", lambda v: float(v) >= 0,
             "must be >= 0")
    _require(values, "clip_norm", lambda v: float(v) >= 0,
             "must be >= 0 (0 = no clipping)")
    _require(values, "save_model_secs", lambda v: int(v) >= 0,
             "must be >= 0 (0 = checkpoint every boundary)")
    _require(values, "max_to_keep", lambda v: int(v) >= 1,
             "must be >= 1 (GC must keep at least the newest)")
    _require(values, "seed", lambda v: int(v) >= 0,
             "must be >= 0 (PRNG keys are unsigned)")
    _require(values, "eval_step", lambda v: int(v) >= 0,
             "must be >= 0 (0 = end-of-run eval only)")
    _require(values, "validation_size", lambda v: int(v) >= 0,
             "must be >= 0 (0 = no held-out split)")
    _require(values, "accum_steps", lambda v: int(v) >= 1,
             "must be >= 1 (microbatches per update)")
    _require(values, "device_chunk", lambda v: int(v) >= 1,
             "must be >= 1 (steps per compiled scan chunk)")
    _require(values, "coord_steps", lambda v: int(v) >= 1,
             "must be >= 1 (the multi-host vote cadence)")
    _require(values, "profile_steps", lambda v: int(v) >= 1,
             "must be >= 1 (the profiler window)")
    _require(values, "init_retries", lambda v: int(v) >= 0,
             "must be >= 0 (0 = fail on the first refusal)")
    _require(values, "init_backoff_s", lambda v: float(v) >= 0,
             "must be >= 0 seconds")
    _require(values, "init_timeout_s", lambda v: float(v) >= 0,
             "must be >= 0 seconds (0 = the library default)")
    _require(values, "ps_resync_steps", lambda v: int(v) >= 1,
             "must be >= 1 (the mirror resync cadence)")
    mode = values.get("mode")
    if mode is not None and mode not in ("auto", "local", "sync", "ps"):
        raise ValueError(f"--mode={mode!r} must be one of auto, local, "
                         f"sync, ps")


def _validate_model_data_flags(values: dict):
    """Parse-time domain checks for the model/data surface: an unknown
    model/dataset/optimizer/schedule/prng name, or an impossible LM
    shape, surfaces at the command line with the whitelist named —
    instead of a KeyError minutes later from the registry."""
    model = values.get("model")
    if model is not None:
        # importing the package runs the @register_model decorators —
        # the whitelist IS the registry, no second list to drift. The
        # import is guarded: flag PARSING must stay possible when the
        # jax backend is broken (the outage class bench's degraded
        # records exist for); get_model re-raises loudly on use.
        try:
            import distributed_tensorflow_tpu.models  # noqa: F401
            from distributed_tensorflow_tpu.models.registry import (
                available_models,
            )
        except Exception:
            available_models = None
        if available_models is not None and \
                model not in available_models():
            raise ValueError(f"--model={model!r} must be one of "
                             f"{', '.join(available_models())}")
    dataset = values.get("dataset")
    if dataset is not None and dataset not in (
            "mnist", "fashion_mnist", "cifar10", "lm"):
        raise ValueError(f"--dataset={dataset!r} must be one of mnist, "
                         f"fashion_mnist, cifar10, lm")
    opt = values.get("optimizer")
    if opt is not None and opt not in ("sgd", "momentum", "adam"):
        raise ValueError(f"--optimizer={opt!r} must be one of sgd, "
                         f"momentum, adam")
    sched = values.get("lr_schedule")
    if sched is not None and sched not in (
            "constant", "cosine", "linear", "exponential"):
        raise ValueError(f"--lr_schedule={sched!r} must be one of "
                         f"constant, cosine, linear, exponential")
    prng = values.get("prng")
    if prng is not None and prng not in (
            "threefry", "threefry2x32", "rbg", "unsafe_rbg"):
        raise ValueError(f"--prng={prng!r} must be one of threefry, "
                         f"threefry2x32, rbg, unsafe_rbg")
    wire = values.get("ps_wire")
    if wire is not None and wire not in ("f32", "bf16"):
        raise ValueError(f"--ps_wire={wire!r} must be f32 or bf16")
    _require(values, "warmup_steps", lambda v: int(v) >= 0,
             "must be >= 0 (0 = no warmup)")
    _require(values, "decay_steps", lambda v: int(v) >= 0,
             "must be >= 0 (0 = the full step budget)")
    _require(values, "decay_rate", lambda v: float(v) > 0,
             "must be > 0 (a decay factor)")
    _require(values, "augment_pad", lambda v: int(v) >= 0,
             "must be >= 0 (crop padding)")
    _require(values, "seq_len", lambda v: int(v) >= 2,
             "must be >= 2 (targets are the sequence shifted one token)")
    _require(values, "vocab_size", lambda v: int(v) >= 2,
             "must be >= 2")
    _require(values, "attn_block", lambda v: int(v) >= 0,
             "must be >= 0 (0 = dense attention)")
    _require(values, "ce_block", lambda v: int(v) >= 0,
             "must be >= 0 (0 = dense loss head)")
    _require(values, "moe_experts", lambda v: int(v) >= 0,
             "must be >= 0 (0 = dense MLPs)")
    _require(values, "moe_capacity", lambda v: float(v) > 0,
             "must be > 0 (a per-expert capacity factor)")
    _require(values, "moe_aux", lambda v: float(v) >= 0,
             "must be >= 0 (the load-balance coefficient)")


def _validate_pairing_flags(values: dict):
    """Parse-time loud-pairing checks promoted OUT of the dttlint
    DTT006 baseline (r18 — four entries fixed for real instead of
    suppressed): a flag that would be silently inert (or invalid) for
    the named configuration surfaces at the command line. The
    train()-time library checks that overlap these stay (non-CLI
    callers remain protected); this is the fail-fast front door, the
    --zero_overlap/--virtual_stages precedent."""
    job = values.get("job_name")
    if job is not None and job not in ("", "ps", "worker"):
        raise ValueError(
            f"--job_name={job!r} must be 'ps', 'worker' or empty "
            f"(reference semantics, MNISTDist.py:13-31: the role this "
            f"process plays in the --ps_hosts topology)")
    if values.get("sp_span_hosts") and not values.get("seq_parallel"):
        raise ValueError(
            "--sp_span_hosts only applies with --seq_parallel (it lets "
            "the TOKEN axis span processes); without it the flag would "
            "silently change nothing — drop it or add --seq_parallel")
    model = values.get("model")
    if values.get("pallas") and model is not None and \
            model != "deep_cnn":
        raise ValueError(
            f"--pallas fuses the deep_cnn FC stack's dominant matmul; "
            f"with --model={model} it would silently change nothing — "
            f"drop it or use --model=deep_cnn")
    if values.get("augment") and values.get("dataset") == "lm":
        raise ValueError(
            "--augment crops/flips images; --dataset=lm feeds token "
            "sequences with no image layout to augment — drop one")


def _validate_serving_flags(values: dict):
    """Parse-time --serve_* validation (the PR-2 _register_validator
    pattern): a non-bucketable batch size, an impossible queue bound, or
    a TP degree the head count can't divide surfaces at the command
    line, not mid-request."""
    mb = values.get("serve_max_batch")
    if mb is None:
        return  # serving flags not defined in this parse set
    mb = int(mb)
    if mb < 1:
        raise ValueError(f"--serve_max_batch={mb} must be >= 1")
    if mb & (mb - 1):
        raise ValueError(
            f"--serve_max_batch={mb} must be a power of two — batches "
            f"pad to power-of-two buckets, and a non-bucketable cap "
            f"would leave its own executable permanently cold")
    qd = int(values.get("serve_queue_depth") or 0)
    if qd < mb:
        raise ValueError(
            f"--serve_queue_depth={qd} must hold at least one full "
            f"--serve_max_batch={mb}")
    if float(values.get("serve_max_delay_ms") or 0.0) < 0:
        raise ValueError("--serve_max_delay_ms must be >= 0")
    if float(values.get("serve_timeout_ms") or 0.0) <= 0:
        raise ValueError("--serve_timeout_ms must be > 0")
    mnt = values.get("serve_max_new_tokens")
    if mnt is not None and int(mnt) < 1:
        raise ValueError("--serve_max_new_tokens must be >= 1")
    port = values.get("serve_port")
    if port is not None and not 0 <= int(port) <= 65535:
        raise ValueError(f"--serve_port={port} must be in [0, 65535] "
                         f"(0 = ephemeral)")
    temp = values.get("serve_temperature")
    if temp is not None and float(temp) < 0:
        raise ValueError("--serve_temperature must be >= 0 (0 = greedy)")
    if int(values.get("serve_profile_batches") or 0) < 0:
        raise ValueError("--serve_profile_batches must be >= 0")
    if float(values.get("serve_reload_secs") or 0.0) < 0:
        raise ValueError("--serve_reload_secs must be >= 0")
    if int(values.get("serve_metrics_every") or 0) < 0:
        raise ValueError("--serve_metrics_every must be >= 0 (0 = off)")
    tp = values.get("serve_tp")
    tp = 1 if tp is None else int(tp)
    if tp < 1:
        raise ValueError(f"--serve_tp={tp} must be >= 1")
    if tp > 1:
        heads = int(values.get("num_heads") or 0)
        if heads and heads % tp:
            raise ValueError(
                f"--serve_tp={tp} must divide --num_heads={heads} (the "
                f"attention split is head-aligned)")
        d_model = int(values.get("d_model") or 0)
        if d_model and d_model % tp:
            raise ValueError(
                f"--serve_tp={tp} must divide --d_model={d_model}")
    sched = values.get("serve_scheduler")
    if sched is not None:
        if sched not in ("whole_batch", "continuous"):
            raise ValueError(
                f"--serve_scheduler={sched!r} must be one of "
                f"whole_batch, continuous")
        slots = values.get("serve_slots")
        if slots is not None and int(slots) < 2:
            raise ValueError(
                f"--serve_slots={slots} must be >= 2 (slot width >= 2 "
                f"keeps decode on the GEMM kernel — the bitwise-parity "
                f"floor)")
        page = values.get("serve_kv_page")
        if page is not None and int(page) < 1:
            raise ValueError(f"--serve_kv_page={page} must be >= 1")
        seq_len = int(values.get("seq_len") or 0)
        if page is not None and seq_len and seq_len % int(page):
            raise ValueError(
                f"--serve_kv_page={page} must divide --seq_len="
                f"{seq_len} (a slot's pages tile the context window)")
        pages = values.get("serve_kv_pages")
        if pages is not None and int(pages) < 0:
            raise ValueError(
                f"--serve_kv_pages={pages} must be >= 0 "
                f"(0 = full provisioning)")
        if pages and page and seq_len:
            per_slot = -(-seq_len // int(page))
            if int(pages) < per_slot:
                raise ValueError(
                    f"--serve_kv_pages={pages} cannot hold one "
                    f"full-context request ({per_slot} pages of "
                    f"{page} tokens for --seq_len={seq_len})")
        if sched == "continuous":
            model = values.get("model")
            if model is not None and model != "lm":
                raise ValueError(
                    f"--serve_scheduler=continuous serves --model lm "
                    f"only (token decode); got --model={model!r}")
            if tp > 1:
                raise ValueError(
                    "--serve_scheduler=continuous serves one replica "
                    "per device; --serve_tp > 1 is whole_batch only")
    # prompt-vs-context fit is a PER-REQUEST property (prompt lengths
    # vary); decode.generate enforces it loudly at request time


def _validate_zero_flags(values: dict):
    """Parse-time --zero validation (the PR-2 _register_validator
    pattern): an unknown level, a model-axis strategy collision, or the
    async ps topology surfaces at the command line with a message that
    names the flags — not mid-trace from inside the step builder. The
    library layer re-checks (parallel/zero._check_level, loop.train) so
    non-CLI callers stay protected; this is the fail-fast front door.
    Divisibility needs NO check here: ZeRO leaves flatten and zero-pad
    to a multiple of D (parallel/zero), so every model splits over any
    data-axis size. A data axis of 1 is legal-but-pointless and depends
    on the device count, unknowable at parse time — the loop prints a
    warning at startup instead."""
    raw = values.get("zero")
    z = 0 if raw is None else int(raw)
    if z not in (0, 1, 3):
        raise ValueError(
            f"--zero={z} must be 0 (replicated DP), 1 (shard the "
            f"optimizer state over the data axis) or 3 (shard the params "
            f"too, FSDP-style); level 2 (grad persistence sharding) does "
            f"not exist in this build — grads are already transient")
    overlap = bool(values.get("zero_overlap"))
    bucket = values.get("zero_bucket_mb")
    if bucket is not None and not 0 < float(bucket) <= 1024:
        raise ValueError(
            f"--zero_bucket_mb={bucket} must be in (0, 1024] MB (one "
            f"collective per bucket; 0 or negative would bucket "
            f"nothing, >1 GB is one flat scatter by another name)")
    if overlap and z == 0:
        raise ValueError(
            "--zero_overlap only applies to --zero 1|3 (it reschedules "
            "the ZeRO collectives); without --zero it would silently "
            "change nothing — drop it or pick a --zero level")
    if not overlap and bucket is not None and float(bucket) != 4.0:
        raise ValueError(
            f"--zero_bucket_mb={bucket} only applies with "
            f"--zero_overlap (it sizes the overlap pattern's buckets); "
            f"without it the flag would silently change nothing — drop "
            f"it or add --zero_overlap")
    if z == 0:
        return
    for flag, what in (("pipeline", "pipeline stages"),
                       ("seq_parallel", "the token axis"),
                       ("expert_parallel", "MoE experts")):
        if values.get(flag):
            raise ValueError(
                f"--zero={z} with --{flag} is not supported: ZeRO "
                f"shards the whole TrainState over the DATA axis while "
                f"--{flag} shards {what} over the model axis — the two "
                f"state layouts collide. Drop one (ZeRO-over-PP/EP is a "
                f"future composition)")
    k = int(values.get("model_axis") or 1)
    if k > 1:
        raise ValueError(
            f"--zero={z} with --model_axis={k} (tensor parallelism) is "
            f"not supported: the TP GSPMD layout already partitions "
            f"params, and composing it with ZeRO's data-axis chunking "
            f"needs a 2-D sharding rule this build doesn't have. Use "
            f"--model_axis=1")
    mode = values.get("mode") or "auto"
    if mode == "ps" or values.get("ps_hosts"):
        raise ValueError(
            f"--zero={z} requires SYNCHRONOUS data parallelism (the "
            f"sharded optimizer update must see the same summed gradient "
            f"on every rank); the ps topology is asynchronous. Drop "
            f"--ps_hosts / use --mode=sync")
    if mode == "local":
        raise ValueError(
            f"--zero={z} requires sync mode (a device mesh with a data "
            f"axis to shard over); --mode=local has no mesh. Use "
            f"--mode=sync on a host with >1 device (ZeRO is "
            f"single-process in this version, so a multi-host launch "
            f"won't help) — note --mode=auto only upgrades to sync when "
            f"the host has >1 device; on a 1-chip host it resolves to "
            f"local and the run refuses at startup")


def _validate_telemetry_flags(values: dict):
    """Parse-time telemetry validation (the PR-2 _register_validator
    pattern): a negative watchdog timeout, an abort flag with no armed
    watchdog, or a zero-length flight ring surfaces at the command
    line, not as silently-dead observability mid-run."""
    wd = values.get("watchdog_s")
    wd = 0.0 if wd is None else float(wd)
    if wd < 0:
        raise ValueError(f"--watchdog_s={wd} must be >= 0 (0 = off)")
    telemetry_flag = values.get("telemetry")
    if wd > 0 and telemetry_flag is not None and not telemetry_flag:
        raise ValueError(
            "--watchdog_s > 0 with --telemetry=false is silently inert "
            "(the watchdog is part of the telemetry spine and is never "
            "installed when telemetry is off) — drop --watchdog_s or "
            "re-enable --telemetry")
    if values.get("watchdog_abort") and wd <= 0:
        raise ValueError(
            "--watchdog_abort only applies with --watchdog_s > 0 (no "
            "watchdog ever fires without a timeout); without it the "
            "flag would silently change nothing — drop it or set "
            "--watchdog_s")
    fe = values.get("flightrec_events")
    if fe is not None and int(fe) < 1:
        raise ValueError(f"--flightrec_events={fe} must be >= 1 (the "
                         f"crash postmortem needs at least one slot; "
                         f"use --telemetry=false to disable telemetry)")


def _validate_efficiency_flags(values: dict):
    """Parse-time validation of the --mfu_* / --sentinel_* surface (the
    PR-2 _register_validator pattern): an unknown sentinel kind or
    action, a sentinel armed under --telemetry=false (its spans/flight
    dumps would be silently inert), or a nonsensical window/threshold/
    peak surfaces at the command line, not mid-run."""
    if float(values.get("mfu_peak_flops") or 0.0) < 0:
        raise ValueError("--mfu_peak_flops must be >= 0 (0 = auto-detect)")
    action = (values.get("sentinel_action") or "").strip()
    if action:
        from distributed_tensorflow_tpu.utils.sentinel import (
            ACTIONS,
            parse_kinds,
        )

        if action not in ACTIONS:
            raise ValueError(
                f"--sentinel_action={action!r} must be one of "
                f"{', '.join(ACTIONS)} (or empty = unarmed)")
        telemetry_flag = values.get("telemetry")
        if telemetry_flag is not None and not telemetry_flag:
            raise ValueError(
                "--sentinel_action with --telemetry=false is silently "
                "degraded (the sentinel's trip spans and flight-recorder "
                "postmortems ride the telemetry spine) — drop "
                "--sentinel_action or re-enable --telemetry")
        try:
            parse_kinds(values.get("sentinel_kinds") or "")
        except ValueError as e:
            raise ValueError(f"--sentinel_kinds: {e}") from None
        if int(values.get("sentinel_window") or 0) < 4:
            raise ValueError(
                f"--sentinel_window={values.get('sentinel_window')} must "
                f"be >= 4 (the rolling median needs history to judge "
                f"against)")
        if float(values.get("sentinel_threshold") or 0.0) <= 0:
            raise ValueError("--sentinel_threshold must be > 0 (MADs "
                             "above the rolling median)")


def _validate_resource_flags(values: dict):
    """Parse-time validation of the resource-plane surface (the PR-2
    _register_validator pattern): out-of-bounds values, or an ARMED
    resource instrument under --telemetry=false (its samples, storm
    spans, and OOM postmortems all ride the telemetry spine and would
    be silently inert), surface at the command line with the bounds
    named — not as dead observability mid-run."""
    hse = values.get("hbm_sample_every")
    if hse is not None and int(hse) < 0:
        raise ValueError(f"--hbm_sample_every={hse} must be >= 0 "
                         f"(0 = off; N = sample every Nth display "
                         f"boundary)")
    rb = values.get("recompile_budget")
    if rb is not None and int(rb) < 0:
        raise ValueError(f"--recompile_budget={rb} must be >= 0 "
                         f"(0 = count recompiles but never trip)")
    shp = values.get("serve_hbm_headroom_pct")
    if shp is not None and not (0.0 <= float(shp) < 100.0):
        raise ValueError(f"--serve_hbm_headroom_pct={shp} must be in "
                         f"[0, 100) percent of the device limit "
                         f"(0 = off; 100 would 503 a healthy replica)")
    if shp is not None and float(shp) > 0 and hse is not None \
            and int(hse) == 0:
        raise ValueError(
            "--serve_hbm_headroom_pct > 0 with --hbm_sample_every=0 is "
            "silently inert (the drain floor reads the memory meter, "
            "which 0 disables) — drop the floor or re-enable sampling")
    telemetry_flag = values.get("telemetry")
    if telemetry_flag is None or telemetry_flag:
        return
    # telemetry off: reject explicitly-armed resource instruments (the
    # watchdog_s precedent — defaults pass, deviations in the armed
    # direction are silently inert and must be named)
    if rb is not None and int(rb) > 0:
        raise ValueError(
            "--recompile_budget > 0 with --telemetry=false is silently "
            "inert (the recompile sentry's storm spans and flight-"
            "recorder dumps ride the telemetry spine) — drop it or "
            "re-enable --telemetry")
    if shp is not None and float(shp) > 0:
        raise ValueError(
            "--serve_hbm_headroom_pct > 0 with --telemetry=false is "
            "silently inert (the serving memory meter is part of the "
            "telemetry spine and is never installed when telemetry is "
            "off) — drop it or re-enable --telemetry")
    if hse is not None and int(hse) > 1:
        raise ValueError(
            "--hbm_sample_every > 1 with --telemetry=false is silently "
            "inert (HBM sampling rides the telemetry spine; "
            "--telemetry=false already disables it) — drop one")


# the request plane's flag defaults, shared by the DEFINE_* calls and
# the telemetry=false armed-deviation checks below so they cannot
# drift (a retuned default must not start rejecting plain
# --telemetry=false invocations)
_REQTRACE_RING_DEFAULT = 512
_REQTRACE_EXEMPLARS_DEFAULT = 5


def _validate_reqtrace_flags(values: dict):
    """Parse-time validation of the request-plane surface (the PR-2
    _register_validator pattern): out-of-bounds --slo_*/--reqtrace_*
    values, an SLO target without the SLO armed, or request-plane
    knobs explicitly armed under --telemetry=false (the plane rides
    the telemetry spine and would be silently inert — the DTT006
    armed-deviation rule), all surface at the command line with the
    bounds named."""
    p99 = values.get("slo_p99_ms")
    if p99 is not None and float(p99) < 0:
        raise ValueError(f"--slo_p99_ms={p99} must be >= 0 ms "
                         f"(0 = SLO accounting off)")
    tgt = values.get("slo_target_pct")
    if tgt is not None and not (50.0 < float(tgt) <= 100.0):
        raise ValueError(f"--slo_target_pct={tgt} must be in (50, 100] "
                         f"(the promised compliant fraction; <= 50 "
                         f"leaves no meaningful error budget)")
    if tgt is not None and float(tgt) != 99.0 \
            and (p99 is None or float(p99) <= 0):
        raise ValueError(
            "--slo_target_pct without --slo_p99_ms > 0 is silently "
            "inert (the target only parameterizes the armed "
            "error-budget ledger) — set --slo_p99_ms or drop the "
            "target")
    ring = values.get("reqtrace_ring")
    if ring is not None and not (16 <= int(ring) <= 1_048_576):
        raise ValueError(f"--reqtrace_ring={ring} must be in "
                         f"[16, 1048576] retained request summaries")
    ex = values.get("reqtrace_exemplars")
    if ex is not None and not (1 <= int(ex) <= 64):
        raise ValueError(f"--reqtrace_exemplars={ex} must be in "
                         f"[1, 64] named tail exemplars")
    telemetry_flag = values.get("telemetry")
    if telemetry_flag is None or telemetry_flag:
        return
    # telemetry off: reject explicitly-armed request-plane knobs (the
    # watchdog_s precedent — defaults pass, deviations in the armed
    # direction are silently inert and must be named)
    if p99 is not None and float(p99) > 0:
        raise ValueError(
            "--slo_p99_ms > 0 with --telemetry=false is silently inert "
            "(the request plane's ledger, audit ring, and req:* spans "
            "ride the telemetry spine) — drop it or re-enable "
            "--telemetry")
    if ring is not None and int(ring) != _REQTRACE_RING_DEFAULT:
        raise ValueError(
            "--reqtrace_ring with --telemetry=false is silently inert "
            "(the audit ring is part of the request plane, which "
            "--telemetry=false leaves unconfigured) — drop it or "
            "re-enable --telemetry")
    if ex is not None and int(ex) != _REQTRACE_EXEMPLARS_DEFAULT:
        raise ValueError(
            "--reqtrace_exemplars with --telemetry=false is silently "
            "inert (the tail block is part of the request plane, which "
            "--telemetry=false leaves unconfigured) — drop it or "
            "re-enable --telemetry")


def _validate_router_flags(values: dict):
    """Parse-time validation of the fleet-router surface (r22, the
    PR-2 _register_validator pattern): --router_* bounds, a min-healthy
    floor the configured fleet cannot honor, and hedging armed under
    --telemetry=false (the hedge race is only auditable through the
    route_hedge/route_retry spans — armed-but-inert is the DTT006
    deviation rule) all surface at the command line, flags NAMED."""
    replicas = [t for t in (values.get("router_replicas") or "").split(",")
                if t.strip()]
    _require(values, "router_host", lambda v: bool(str(v).strip()),
             "must be a non-empty bind address")
    _require(values, "router_port",
             lambda v: 0 <= int(v) <= 65535,
             "must be in [0, 65535] (0 = ephemeral)")
    _require(values, "router_poll_ms",
             lambda v: 10.0 <= float(v) <= 60000.0,
             "must be in [10, 60000] ms between health sweeps")
    _require(values, "router_retries",
             lambda v: 0 <= int(v) <= 10,
             "must be in [0, 10] retry attempts")
    _require(values, "router_backoff_ms",
             lambda v: 0.0 <= float(v) <= 10000.0,
             "must be in [0, 10000] ms base backoff")
    _require(values, "router_retry_budget_pct",
             lambda v: 0.0 <= float(v) <= 100.0,
             "must be in [0, 100] percent of observed requests")
    _require(values, "router_hedge_ms",
             lambda v: 0.0 <= float(v) <= 60000.0,
             "must be in [0, 60000] ms (0 = hedging off)")
    _require(values, "router_hedge_budget_pct",
             lambda v: 0.0 <= float(v) <= 100.0,
             "must be in [0, 100] percent of observed requests")
    _require(values, "router_breaker_fails",
             lambda v: 1 <= int(v) <= 100,
             "must be in [1, 100] consecutive failures")
    _require(values, "router_eject_s",
             lambda v: 0.0 < float(v) <= 3600.0,
             "must be in (0, 3600] seconds of ejection cooldown")
    mh = values.get("router_min_healthy")
    if mh is not None and int(mh) < 0:
        raise ValueError(f"--router_min_healthy={mh} must be >= 0")
    if mh is not None and replicas and int(mh) >= len(replicas):
        raise ValueError(
            f"--router_min_healthy={mh} must be < the configured "
            f"replica count ({len(replicas)}): rolling reload drains "
            f"one replica at a time, so the floor can never be met "
            f"while any replica reloads")
    hedge = values.get("router_hedge_ms")
    telemetry_flag = values.get("telemetry")
    if (hedge is not None and float(hedge) > 0
            and telemetry_flag is not None and not telemetry_flag):
        raise ValueError(
            "--router_hedge_ms > 0 with --telemetry=false is flying "
            "blind (the hedge race books through route_hedge/"
            "route_retry spans and the request plane's SLO dedupe, "
            "all of which ride the telemetry spine) — drop the hedge "
            "or re-enable --telemetry")


def _validate_elastic_flags(values: dict):
    """Parse-time elastic-surface validation (the PR-2
    _register_validator pattern): a negative world, or elasticity armed
    on the asynchronous ps topology (whose membership is the reference's
    static ClusterSpec — there is no mesh to re-form), surfaces at the
    command line with the flags named."""
    ws = values.get("world_size")
    if ws is not None and int(ws) < 0:
        raise ValueError(f"--world_size={ws} must be >= 0 (0 = the full "
                         f"device/process set)")
    el = bool(values.get("elastic"))
    spec = values.get("fault_spec") or ""
    preempt_armed = "preempt" in spec
    if not (el or preempt_armed):
        return
    mode = values.get("mode") or "auto"
    if mode == "ps" or values.get("ps_hosts"):
        raise ValueError(
            "--elastic (or a --fault_spec preempt rule) with the ps "
            "topology is not supported: ps membership is the "
            "reference's static ClusterSpec and there is no device "
            "mesh to re-form — use --mode=sync")


def _validate_fault_spec(values: dict):
    """Parse-time --fault_spec validation: a typo'd injection point or
    mode surfaces at the command line with the registered-point list, not
    as a silently-never-firing rule mid-run."""
    spec = values.get("fault_spec") or ""
    if not spec:
        return
    from distributed_tensorflow_tpu.utils.faults import (
        FaultSpecError,
        parse_fault_spec,
    )

    try:
        parse_fault_spec(spec)
    except FaultSpecError as e:
        raise ValueError(f"--fault_spec: {e}") from None


def _validate_pipeline_flags(values: dict):
    """Parse-time pipeline-config validation: every constraint here used
    to surface as a mid-trace ValueError from inside the compiled step
    builder (parallel/pipeline_parallel._pp_step_fn) — catch it at the
    command line with a message that names the flags instead. The
    library-level checks stay (non-CLI callers are still protected);
    this is the fail-fast front door."""
    from distributed_tensorflow_tpu.parallel.pp_schedule import (
        PP_SCHEDULES,
        normalize_pp_schedule,
    )

    raw_v = values.get("virtual_stages")
    v = 1 if raw_v is None else int(raw_v)
    micro_flag = int(values.get("pp_microbatches") or 0)
    if v < 1:
        raise ValueError(f"--virtual_stages={v} must be >= 1")
    if micro_flag < 0:
        raise ValueError(f"--pp_microbatches={micro_flag} must be >= 0 "
                         f"(0 = the stage count)")
    raw_sched = (values.get("pp_schedule") or "auto").strip().lower()
    if raw_sched not in PP_SCHEDULES:
        raise ValueError(
            f"--pp_schedule={raw_sched!r} must be one of "
            f"{', '.join(PP_SCHEDULES)}")
    if not values.get("pipeline"):
        if v > 1:
            raise ValueError(
                f"--virtual_stages={v} only applies to --pipeline (the "
                f"interleaved schedule splits pipeline stages); without "
                f"--pipeline it would silently change nothing — drop it "
                f"or add --pipeline")
        if raw_sched != "auto":
            raise ValueError(
                f"--pp_schedule={raw_sched} only applies to --pipeline "
                f"(it picks the pipeline tick schedule); without "
                f"--pipeline it would silently change nothing — drop it "
                f"or add --pipeline")
        return
    # gpipe x virtual_stages>1 contradiction surfaces here with the
    # flags named; zb's V interaction is checked against the layout
    # below (same rounds rule as interleaved, plus >= 2 blocks/group)
    try:
        sched = normalize_pp_schedule(raw_sched, v)
    except ValueError as e:
        raise ValueError(f"--pp_schedule: {e}") from None
    k = int(values.get("model_axis") or 1)
    micro = micro_flag or k
    batch = int(values.get("batch_size") or 0)
    if batch and micro and batch % micro:
        raise ValueError(
            f"--batch_size={batch} must split into "
            f"--pp_microbatches={micro} microbatches (each data shard's "
            f"slice must divide further — checked against the mesh at "
            f"startup)")
    if k > 1:  # model_axis<2 is rejected with its own message at startup
        nb = int(values.get("num_blocks") or 0)
        if nb % (k * v):
            raise ValueError(
                f"--num_blocks={nb} must divide into --model_axis={k} "
                f"pipeline stages x --virtual_stages={v} block groups "
                f"({k * v} total)")
        if v > 1 and micro % k:
            raise ValueError(
                f"--virtual_stages={v} (interleaved schedule) works "
                f"microbatches in rounds of the stage count: "
                f"--pp_microbatches={micro} must be divisible by "
                f"--model_axis={k}")
        if sched == "zb" and nb and nb // (k * v) < 2:
            raise ValueError(
                f"--pp_schedule=zb needs >= 2 blocks per virtual-stage "
                f"group (the inner block scan's loop boundary is what "
                f"keeps zb bit-identical to gpipe/interleaved): "
                f"--num_blocks={nb} over --model_axis={k} x "
                f"--virtual_stages={v} leaves {nb // (k * v)} block(s) "
                f"per group — raise --num_blocks or lower the split")
