"""ResNet for CIFAR — the deeper-conv-stack config (BASELINE.md config 4).

Not in the reference (its only model is the MNIST CNN, MNISTDist.py:66-90);
this is the "stresses XLA conv fusion" config from the driver's BASELINE.
Classic CIFAR ResNet (He et al. 2015 §4.2): 3x3 stem, 3 stages of n basic
blocks at widths 16/32/64, stride-2 at stage transitions, 1x1-projection
shortcuts (option B), global average pool, dense head. depth = 6n+2 —
n=3 gives ResNet-20.

Stateful model protocol: ``init`` returns {"params", "state"} collections
and ``apply(params, x, state=...)`` returns (logits, new_state) in train
mode — the batch-norm running statistics live in the state collection and
are EMA-updated by the forward pass, never by gradients.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.registry import register_model
from distributed_tensorflow_tpu.ops import nn


def _he_normal(key, shape, dtype=jnp.float32):
    fan_in = 1
    for d in shape[:-1]:
        fan_in *= d
    std = (2.0 / fan_in) ** 0.5
    return std * jax.random.normal(key, shape, dtype)


def _conv_init(key, kh, kw, cin, cout):
    return _he_normal(key, (kh, kw, cin, cout))


def _bn_init(c):
    return {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}


def _bn_state_init(c):
    return {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))}


@register_model("resnet")
class ResNet:
    """CIFAR ResNet-(6n+2). ``blocks_per_stage=3`` -> ResNet-20."""

    stateful = True

    def __init__(
        self,
        blocks_per_stage: int = 3,
        widths: tuple = (16, 32, 64),
        num_classes: int = 10,
        channels: int = 3,
        image_size: int = 32,
        compute_dtype: Any = None,
        bn_momentum: float = 0.9,
    ):
        self.n = blocks_per_stage
        self.widths = tuple(widths)
        self.num_classes = num_classes
        self.channels = channels
        self.image_size = image_size
        self.compute_dtype = compute_dtype
        self.bn_momentum = bn_momentum

    # ------------------------------------------------------------ init

    def init(self, key):
        keys = iter(jax.random.split(key, 4 + 6 * self.n * len(self.widths)))
        params: dict = {"stem": {"conv": _conv_init(next(keys), 3, 3, self.channels, self.widths[0]),
                                 "bn": _bn_init(self.widths[0])}}
        state: dict = {"stem": {"bn": _bn_state_init(self.widths[0])}}
        cin = self.widths[0]
        for s, width in enumerate(self.widths):
            stage_p, stage_s = {}, {}
            for b in range(self.n):
                stride = 2 if (s > 0 and b == 0) else 1
                block_p = {
                    "conv1": _conv_init(next(keys), 3, 3, cin, width),
                    "bn1": _bn_init(width),
                    "conv2": _conv_init(next(keys), 3, 3, width, width),
                    "bn2": _bn_init(width),
                }
                block_s = {"bn1": _bn_state_init(width), "bn2": _bn_state_init(width)}
                if stride != 1 or cin != width:
                    block_p["proj"] = _conv_init(next(keys), 1, 1, cin, width)
                    block_p["proj_bn"] = _bn_init(width)
                    block_s["proj_bn"] = _bn_state_init(width)
                stage_p[f"block{b}"] = block_p
                stage_s[f"block{b}"] = block_s
                cin = width
            params[f"stage{s}"] = stage_p
            state[f"stage{s}"] = stage_s
        params["head"] = {
            "w": jnp.zeros((self.widths[-1], self.num_classes)),
            "b": jnp.zeros((self.num_classes,)),
        }
        return {"params": params, "state": state}

    # ----------------------------------------------------------- apply

    def _conv(self, x, w, stride=1):
        cd = self.compute_dtype
        in_dtype = x.dtype
        if cd is not None:
            x, w = x.astype(cd), w.astype(cd)
        y = jax.lax.conv_general_dilated(
            x, w, window_strides=(stride, stride), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return y.astype(in_dtype) if cd is not None else y

    def _bn(self, x, p, s, train):
        y, (m, v) = nn.batch_norm(
            x, p["scale"], p["bias"], s["mean"], s["var"],
            train=train, momentum=self.bn_momentum,
        )
        return y, {"mean": m, "var": v}

    def apply(self, variables, x, *, keep_prob=1.0, rng=None, train: bool = False,
              state=None):
        """Forward pass. ``variables`` may be the full {"params","state"}
        dict (then ``state`` is taken from it) or just the params collection
        with ``state`` passed separately. Returns (logits, new_state) when
        training, logits otherwise."""
        if state is None and "state" in variables:
            params, state = variables["params"], variables["state"]
        elif "params" in variables:
            params = variables["params"]
        else:
            params = variables
        assert state is not None, "ResNet.apply needs the state collection"

        new_state: dict = {"stem": {}, }
        x = nn.normalize_if_u8(x, self.compute_dtype)
        x = x.reshape(-1, self.image_size, self.image_size, self.channels)

        h = self._conv(x, params["stem"]["conv"])
        h, ns = self._bn(h, params["stem"]["bn"], state["stem"]["bn"], train)
        new_state["stem"]["bn"] = ns
        h = jax.nn.relu(h)

        for s_i in range(len(self.widths)):
            stage_p, stage_s = params[f"stage{s_i}"], state[f"stage{s_i}"]
            new_stage: dict = {}
            for b in range(self.n):
                bp, bs = stage_p[f"block{b}"], stage_s[f"block{b}"]
                stride = 2 if (s_i > 0 and b == 0) else 1
                nbs: dict = {}

                y = self._conv(h, bp["conv1"], stride)
                y, nbs["bn1"] = self._bn(y, bp["bn1"], bs["bn1"], train)
                y = jax.nn.relu(y)
                y = self._conv(y, bp["conv2"])
                y, nbs["bn2"] = self._bn(y, bp["bn2"], bs["bn2"], train)

                if "proj" in bp:
                    sc = self._conv(h, bp["proj"], stride)
                    sc, nbs["proj_bn"] = self._bn(sc, bp["proj_bn"], bs["proj_bn"], train)
                else:
                    sc = h
                h = jax.nn.relu(y + sc)
                new_stage[f"block{b}"] = nbs
            new_state[f"stage{s_i}"] = new_stage

        h = jnp.mean(h, axis=(1, 2))  # global average pool
        logits = nn.dense(h, params["head"]["w"], params["head"]["b"],
                          compute_dtype=self.compute_dtype)
        if train:
            return logits, new_state
        return logits

    def num_params(self, variables=None):
        if variables is None:
            variables = jax.eval_shape(lambda: self.init(jax.random.PRNGKey(0)))
        return sum(int(jnp.size(p)) for p in jax.tree.leaves(variables["params"]))


@register_model("resnet20")
class ResNet20(ResNet):
    def __init__(self, **kw):
        kw.setdefault("blocks_per_stage", 3)
        super().__init__(**kw)


@register_model("resnet32")
class ResNet32(ResNet):
    def __init__(self, **kw):
        kw.setdefault("blocks_per_stage", 5)
        super().__init__(**kw)
