"""SAN004 bad fixture: lifecycle violations — a restartable start()
reusing a set stop Event (the CheckpointWatcher class of bug), an
UNBOUNDED deque ring appended from a thread, and a non-daemon thread
nobody ever joins."""
import threading
from collections import deque


class Restartable:
    def __init__(self):
        self._stop = threading.Event()
        self._ring: deque = deque()   # no maxlen: unbounded ring
        self._thread = None
        self._lock = threading.Lock()

    def start(self):
        # BUG: after close() set the event, this restarts a thread that
        # observes it still set and exits immediately
        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()

    def _loop(self):
        while not self._stop.wait(0.1):
            with self._lock:
                self._ring.append(1)

    def close(self):
        self._stop.set()


def leak(job):
    # non-daemon, never joined: outlives the run
    t = threading.Thread(target=job_runner)
    t.start()


def job_runner():
    pass
