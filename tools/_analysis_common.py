"""Shared static-analysis runner infrastructure — the baseline-with-
reason / stale-fails machinery both in-tree analyzers ride:

- ``tools/dttlint`` — the AST invariant linter (r16), and
- ``tools/dttcheck`` — the jaxpr-level ledger/SPMD verifier (r18).

One ``Finding`` shape, one baseline format, one matching rule, so a
suppression behaves identically whichever layer produced the finding:
the checked-in baseline suppresses by STABLE key (symbols, never line
numbers), every entry carries a mandatory ``reason``, and an entry
whose finding no longer exists FAILS the run loudly — the baseline can
only shrink. Factored out of ``tools/dttlint`` when dttcheck became
its second consumer (the jaxpr layer must not fork the suppression
semantics the AST layer's tests already pin).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass
class Finding:
    """One rule/pass violation. ``key`` is the STABLE identity (no line
    numbers — lines churn, keys must survive unrelated edits) the
    baseline suppresses by; ``path``/``line`` locate it for humans."""

    rule: str
    key: str
    path: str
    line: int
    message: str
    baselined: bool = False
    # --fix support (dttlint DTT001): the literal to rewrite, when
    # the fix is mechanical
    fix: dict | None = None

    def format(self) -> str:
        tag = " [baselined]" if self.baselined else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{tag}"


@dataclass
class AnalysisResult:
    """The runner's verdict: non-baselined findings, matched
    suppressions, stale suppressions, and the rule/pass registry that
    ran. ``ok`` is the exit-code contract shared by both CLIs."""

    findings: list = field(default_factory=list)  # non-baselined
    baselined: list = field(default_factory=list)
    stale: list = field(default_factory=list)  # baseline keys w/o finding
    rules: tuple = ()
    report: dict = field(default_factory=dict)  # analyzer-specific facts

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale

    def to_json(self) -> dict:
        def row(f):
            return {"rule": f.rule, "key": f.key, "path": f.path,
                    "line": f.line, "message": f.message}

        out = {
            "ok": self.ok,
            "findings": [row(f) for f in self.findings],
            "baselined": [row(f) for f in self.baselined],
            "stale_suppressions": list(self.stale),
            "rules": list(self.rules),
        }
        if self.report:
            out["report"] = self.report
        return out


def load_baseline(path: str | None, default_path: str) -> list[dict]:
    """Read a suppression file; every entry must carry rule, key and a
    REASON (the reason IS the suppression's justification — an entry
    without one is an unexplained mute and is rejected)."""
    path = path or default_path
    if not os.path.exists(path):
        return []
    data = json.load(open(path, encoding="utf-8"))
    entries = data.get("entries", [])
    for e in entries:
        if not {"rule", "key", "reason"} <= set(e):
            raise ValueError(
                f"baseline entry {e!r} must carry rule, key and reason "
                f"(the reason IS the suppression's justification)")
    return entries


def apply_baseline(found: list, entries: list[dict], rules: tuple,
                   report: dict | None = None) -> AnalysisResult:
    """Split raw findings into (new, baselined) and detect stale
    suppressions — the one matching rule both analyzers share. Stale
    entries are only charged against rules/passes that actually RAN
    (``rules``), so a partial run (--mode/--rules filters) cannot
    spuriously fail entries belonging to skipped checks."""
    by_key = {(e["rule"], e["key"]): e for e in entries}
    result = AnalysisResult(rules=tuple(rules), report=dict(report or {}))
    matched = set()
    for f in sorted(found, key=lambda f: (f.path, f.line, f.rule)):
        hit = by_key.get((f.rule, f.key))
        if hit is not None:
            f.baselined = True
            matched.add((f.rule, f.key))
            result.baselined.append(f)
        else:
            result.findings.append(f)
    checked = set(result.rules)
    result.stale = [f"{r}:{k}" for (r, k) in by_key
                    if (r, k) not in matched and r in checked]
    return result
