"""DTT001 violating fixture: string-literal axis names (never imported,
only parsed by dttlint)."""

from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def step(x):
    return lax.psum(x, "data")  # literal axis


def scatter(x):
    return lax.psum_scatter(x, axis_name="model", scatter_dimension=0)


def specs(mesh, arr):
    return P("data", None), Mesh(arr, ("data", "model"))
