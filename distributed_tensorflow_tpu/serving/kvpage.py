"""Free-list allocator over fixed-size KV-cache pages (r21).

The whole-batch decode path preallocates a dense ``(B, seq_len, H, Dh)``
cache per block — every request is billed the full context window
whether it uses 8 tokens or 800. The paged cache (the vLLM
PagedAttention memory model) splits each slot's capacity into
fixed-size pages and lets a slot map only the pages its live tokens
actually occupy, so cache memory is proportional to live tokens.

This module is the HOST side of that story: pure bookkeeping over page
ids, no arrays. The device pools live with the jitted step
(``decode.make_slot_pools``); the scheduler asks this allocator which
physical page backs each (slot, logical-page) entry and writes the id
into the page table the step consumes.

Two-phase discipline — **commit at admission, allocate on demand**:

- ``reserve(n_tokens)`` at admission commits ``ceil(n / page_size)``
  pages against the pool WITHOUT taking any. Admission is refused
  (``can_admit``) unless the request's whole worst-case footprint fits,
  so a mid-generation allocation can never fail — the no-preemption
  guarantee: an admitted request always runs to completion, there is no
  swap/recompute path to fall back to.
- ``alloc(reservation)`` takes one physical page as generation actually
  crosses a page boundary, so ``pages_in_use`` tracks LIVE tokens
  (``pages_in_use == sum over residents of ceil(fed / page_size)`` —
  the ledger invariant the tests assert), while ``pages_committed``
  tracks admission headroom.
- ``release(reservation)`` at retirement returns the pages and the
  commitment in one motion.

Page id 0 is never handed out: the device pools reserve row 0 as the
scratch page free slots read and write (their page-table rows are all
zero), so a freshly-zeroed table is safe by construction.

Occupancy feeds the ``/metrics`` ``hbm`` block (``kv_pages``) and the
``--serve_hbm_headroom_pct`` drain floor: a replica whose free-page
ratio falls below the floor flips /healthz before admission failures
turn into client-visible 429 storms.

All state is guarded by one lock: the scheduler thread mutates while
/metrics and /healthz handler threads read ``occupancy()``.
"""

from __future__ import annotations

import threading


def pages_needed(n_tokens: int, page_size: int) -> int:
    """``ceil(n_tokens / page_size)`` — the page footprint of a token
    count (0 tokens = 0 pages)."""
    if n_tokens < 0:
        raise ValueError(f"n_tokens must be >= 0, got {n_tokens}")
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    return -(-n_tokens // page_size)


class PageReservation:
    """One request's committed page budget: ``budget`` pages promised at
    admission, ``pages`` the physical ids actually taken so far. Opaque
    to the scheduler — only the allocator reads or writes it (under its
    lock), so the commitment arithmetic cannot drift."""

    __slots__ = ("budget", "pages")

    def __init__(self, budget: int):
        self.budget = int(budget)
        self.pages: list[int] = []


class PageAllocator:
    """Free list over physical pages ``1..num_pages`` with
    commitment-based admission (see module docstring)."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        # pop() hands out 1, 2, 3, ... — deterministic layout, easy to
        # eyeball in a page-table dump
        self._free = list(range(self.num_pages, 0, -1))
        self._committed = 0
        self._in_use = 0
        self._high_water = 0
        self._allocs_total = 0
        self._reservations = 0

    def pages_for(self, n_tokens: int) -> int:
        return pages_needed(n_tokens, self.page_size)

    def can_admit(self, n_tokens: int) -> bool:
        """True when a request storing up to ``n_tokens`` can be
        admitted without ever failing a mid-generation allocation."""
        need = self.pages_for(n_tokens)
        with self._lock:
            return self._committed + need <= self.num_pages

    def reserve(self, n_tokens: int) -> PageReservation:
        """Commit the worst-case footprint. Raises ``RuntimeError`` when
        the commitment does not fit — the scheduler must gate on
        ``can_admit`` first, so reaching this is a scheduler bug, not
        load."""
        need = self.pages_for(n_tokens)
        with self._lock:
            if self._committed + need > self.num_pages:
                raise RuntimeError(
                    f"page commitment overflow: {need} pages requested, "
                    f"{self.num_pages - self._committed} uncommitted of "
                    f"{self.num_pages} — admission must gate on "
                    f"can_admit()")
            self._committed += need
            self._reservations += 1
        return PageReservation(need)

    def alloc(self, res: PageReservation) -> int:
        """Take one physical page against ``res``. The commitment made
        at reserve() guarantees the free list is never empty here."""
        with self._lock:
            if len(res.pages) >= res.budget:
                raise RuntimeError(
                    f"reservation budget exhausted ({res.budget} pages) "
                    f"— the scheduler fed more tokens than it admitted")
            page = self._free.pop()
            res.pages.append(page)
            self._in_use += 1
            self._allocs_total += 1
            if self._in_use > self._high_water:
                self._high_water = self._in_use
        return page

    def release(self, res: PageReservation) -> None:
        """Return ``res``'s pages and commitment to the pool (retire /
        abort). Idempotent: a second release of the same reservation is
        a no-op."""
        with self._lock:
            self._free.extend(res.pages)
            self._in_use -= len(res.pages)
            self._committed -= res.budget
            if res.budget or res.pages:
                self._reservations -= 1
            res.pages = []
            res.budget = 0

    def occupancy(self) -> dict:
        """One consistent snapshot for /metrics (``hbm.kv_pages``), the
        health floor, and the bench's analytic facts."""
        with self._lock:
            in_use = self._in_use
            committed = self._committed
            high = self._high_water
            allocs = self._allocs_total
            live = self._reservations
        return {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "pages_in_use": in_use,
            "pages_committed": committed,
            "pages_high_water": high,
            "allocs_total": allocs,
            "reservations": live,
            "occupancy_pct": round(100.0 * in_use / self.num_pages, 4),
            # the drain floor judges COMMITTED, not in-use: admission is
            # what fails when commitments exhaust the pool
            "free_pct": round(
                100.0 * (self.num_pages - committed) / self.num_pages, 4),
        }
