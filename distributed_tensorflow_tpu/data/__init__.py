from distributed_tensorflow_tpu.data.datasets import DataSet, read_data_sets
from distributed_tensorflow_tpu.data.pipeline import prefetch_to_device

__all__ = ["DataSet", "read_data_sets", "prefetch_to_device"]
