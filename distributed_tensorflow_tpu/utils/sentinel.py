"""Training-health sentinels: catch a silently-dying run at the moment
it starts dying.

Today a NaN loss or a 100x spike just scrolls past in metrics.jsonl and
the run burns its remaining budget on garbage. The sentinel watches the
scalars the loops ALREADY compute at the display cadence (no extra
device work, no new sync points) and trips on four kinds:

- ``nan``                 — non-finite loss / grad norm / any observed metric
- ``loss_spike``          — loss above rolling median + K x MAD
- ``grad_explosion``      — grad norm above rolling median + K x MAD
                            (checked when the loop's metrics carry a
                            ``grad_norm``/``global_grad_norm`` key)
- ``throughput_collapse`` — observed steps/sec below 20% of its rolling
                            median (self-clocked between observations)

The action ladder (``--sentinel_action``):

- ``warn``     — loud print, a ``sentinel:<kind>`` instant span, a
                 ``sentinel_trips`` scalar, and a flight-recorder dump
                 (the postmortem shows the seconds AROUND the trip).
- ``snapshot`` — all of warn, plus an EMERGENCY CHECKPOINT of the last
                 known-good state through the verified-save path (the
                 CRC-manifest writer every checkpoint uses) into
                 ``<logdir>/sentinel/`` — outside the main directory's
                 GC, so the last-good state is never lost even if the
                 sick run keeps checkpointing garbage over the ladder's
                 fallback depth.
- ``abort``    — all of snapshot, then raise ``SentinelTripped`` so the
                 run exits loudly (the orchestrator decides what's next;
                 the emergency checkpoint holds the resume point).

"Last known-good" is the newest state observed with finite metrics:
the loops hand ``observe`` their current host-layout state at every
display boundary, and the sentinel only adopts it when that
observation's metrics are finite — so a NaN trip snapshots the state
from the boundary BEFORE the poison, not the poisoned one.

Trip detection is rolling-median + MAD (robust to the noisy early
loss curve a mean/stddev would chase); the MAD is floored so a
perfectly-flat loss can't make an epsilon wiggle trip. Each kind holds
a cooldown after tripping so one incident reports once, not once per
display window.

stdlib-only (like utils/telemetry, which it reports through) so the
flags validator can name unknown kinds at the command line without
importing jax.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from statistics import median as _median

KINDS = ("nan", "loss_spike", "grad_explosion", "throughput_collapse")
ACTIONS = ("warn", "snapshot", "abort")

GRAD_NORM_KEYS = ("grad_norm", "global_grad_norm")

DEFAULT_WINDOW = 32
DEFAULT_THRESHOLD = 10.0    # MADs above the rolling median
COLLAPSE_FRACTION = 0.2     # throughput below this x median trips
MIN_HISTORY = 8             # observations before spike/collapse can judge
COOLDOWN_OBSERVATIONS = 4   # per-kind quiet period after a trip


class SentinelTripped(RuntimeError):
    """Raised by ``--sentinel_action=abort`` after the report +
    emergency checkpoint; carries the trip for the caller/orchestrator."""

    def __init__(self, report: "TripReport"):
        super().__init__(
            f"training-health sentinel tripped: {report.kind} at step "
            f"{report.step} ({report.detail})"
            + (f"; emergency checkpoint: {report.checkpoint_path}"
               if report.checkpoint_path else ""))
        self.report = report


@dataclass
class TripReport:
    kind: str
    step: int
    value: float
    detail: str
    action: str
    checkpoint_path: str | None = None


def _median_mad(values: list[float]) -> tuple[float, float]:
    med = _median(values)
    mad = _median([abs(v - med) for v in values])
    # floor: a flat history has MAD ~0 and any wiggle would trip
    return med, max(mad, 1e-3 * max(abs(med), 1.0), 1e-12)


def parse_kinds(spec: str) -> tuple[str, ...]:
    """``--sentinel_kinds`` csv -> kinds tuple; unknown kinds raise with
    the registry named (this backs the parse-time flag validator)."""
    kinds = tuple(k.strip() for k in (spec or "").split(",") if k.strip())
    unknown = [k for k in kinds if k not in KINDS]
    if unknown:
        raise ValueError(
            f"unknown sentinel kind(s) {unknown} — known kinds: "
            f"{', '.join(KINDS)}")
    return kinds or KINDS


class Sentinel:
    """One per training run; ``observe(step, metrics, state=...)`` at
    every display boundary. Returns the trips it fired (empty on a
    healthy observation); ``--sentinel_action=abort`` raises
    ``SentinelTripped`` after reporting."""

    def __init__(self, kinds=KINDS, action: str = "warn", *,
                 window: int = DEFAULT_WINDOW,
                 threshold: float = DEFAULT_THRESHOLD,
                 collapse_fraction: float = COLLAPSE_FRACTION,
                 min_history: int = MIN_HISTORY,
                 cooldown: int = COOLDOWN_OBSERVATIONS,
                 save_fn=None, logger=None, stop_fn=None,
                 time_fn=time.monotonic):
        if action not in ACTIONS:
            raise ValueError(f"sentinel action must be one of {ACTIONS}, "
                             f"got {action!r}")
        self.kinds = parse_kinds(",".join(kinds)) if kinds else KINDS
        self.action = action
        self.window = max(4, int(window))
        self.threshold = float(threshold)
        self.collapse_fraction = float(collapse_fraction)
        # a history can never grow past the window: cap the judging
        # threshold there too, or a small --sentinel_window would
        # silently disable every history-based kind (len >= min_history
        # would be unreachable)
        self.min_history = max(2, min(int(min_history), self.window))
        self.cooldown = max(0, int(cooldown))
        self._save_fn = save_fn  # (state, step) -> checkpoint path
        # abort's exit route: None = raise SentinelTripped (the loud
        # single-process exit). Multi-host loops pass the supervisor's
        # request_stop instead: a raise on the chief alone would strand
        # the peers in their next collective (the silent-hang class the
        # watchdog exists for) — the stop must travel through the
        # coordinated vote so every host leaves at the same step.
        self._stop_fn = stop_fn
        self._logger = logger
        self._time = time_fn
        self._losses: list[float] = []
        self._grads: list[float] = []
        self._rates: list[float] = []
        self._last_obs: tuple | None = None  # (step, t, stall_s)
        self._cooldowns: dict[str, int] = {}
        self._last_good: tuple | None = None  # (state, step)
        self._saved_steps: set[int] = set()
        self.trips: list[TripReport] = []

    # ------------------------------------------------------------ core

    @property
    def wants_state(self) -> bool:
        """True when observations should carry the state (the action
        ladder will need a last-good snapshot to checkpoint). ``warn``
        never touches the state, so loops can skip producing it."""
        return self._save_fn is not None and self.action in ("snapshot",
                                                             "abort")

    def observe(self, step: int, metrics: dict | None = None,
                state=None, stall_s: float = 0.0) -> list[TripReport]:
        """``state`` may be the host-layout state itself or a ZERO-ARG
        CALLABLE producing it — called only when this observation is
        healthy and the action ladder needs snapshots. Loops whose live
        state is device-resident with donated buffers (the DP/TP chunk
        steps) MUST pass a callable that fetches to host: a device
        reference is dead by the time a later trip wants it.

        ``stall_s`` is the loop's CUMULATIVE booked stall time (the
        goodput ledger's lost seconds): the throughput-collapse clock
        subtracts the delta since the previous observation, so a known
        stall — a slow checkpoint write, a long periodic eval, the
        restore — can never read as a collapse (and, under
        action=abort, kill a healthy run)."""
        metrics = metrics or {}
        now = self._time()
        for k in list(self._cooldowns):
            self._cooldowns[k] -= 1
            if self._cooldowns[k] <= 0:
                del self._cooldowns[k]

        loss = metrics.get("loss")
        grad = next((metrics[k] for k in GRAD_NORM_KEYS if k in metrics),
                    None)
        rate = None
        if self._last_obs is not None:
            prev_step, prev_t, prev_stall = self._last_obs
            # booked stalls (ckpt/eval/restore) don't count against the
            # throughput clock — only unexplained slowness should trip
            dt = (now - prev_t) - max(0.0, float(stall_s) - prev_stall)
            if dt > 0 and step > prev_step:
                rate = (step - prev_step) / dt
        self._last_obs = (step, now, float(stall_s))

        tripped: list[TripReport] = []

        def fire(kind, value, detail):
            if kind in self.kinds and kind not in self._cooldowns:
                self._cooldowns[kind] = self.cooldown
                tripped.append(self._fire(kind, step, value, detail))

        finite = all(
            v is None or (isinstance(v, bool))
            or (isinstance(v, (int, float)) and math.isfinite(float(v)))
            for v in [loss, grad, *metrics.values()])
        if not finite:
            bad = sorted(k for k, v in metrics.items()
                         if isinstance(v, (int, float))
                         and not isinstance(v, bool)
                         and not math.isfinite(float(v)))
            fire("nan", float("nan"),
                 f"non-finite metric(s): {', '.join(bad) or 'loss'}")
        else:
            if loss is not None and len(self._losses) >= self.min_history:
                med, mad = _median_mad(self._losses)
                if float(loss) > med + self.threshold * mad:
                    fire("loss_spike", float(loss),
                         f"loss {float(loss):.6g} > rolling median "
                         f"{med:.6g} + {self.threshold:g} x MAD {mad:.3g}")
            if grad is not None and len(self._grads) >= self.min_history:
                med, mad = _median_mad(self._grads)
                if float(grad) > med + self.threshold * mad:
                    fire("grad_explosion", float(grad),
                         f"grad norm {float(grad):.6g} > rolling median "
                         f"{med:.6g} + {self.threshold:g} x MAD {mad:.3g}")
            if rate is not None and len(self._rates) >= self.min_history:
                med = _median(self._rates)
                if med > 0 and rate < self.collapse_fraction * med:
                    fire("throughput_collapse", rate,
                         f"{rate:.3g} steps/s < "
                         f"{self.collapse_fraction:g} x rolling median "
                         f"{med:.3g}")
            # healthy observation: extend the histories and adopt the
            # state as last-known-good (a spike/collapse observation
            # still extends history — the state math is fine — but a
            # non-finite one must poison neither)
            if loss is not None:
                self._push(self._losses, float(loss))
            if grad is not None:
                self._push(self._grads, float(grad))
            if rate is not None:
                self._push(self._rates, rate)
            if state is not None and not tripped and self.wants_state:
                if callable(state):
                    state = state()
                if state is not None:
                    self._last_good = (state, int(step))

        if tripped and self.action == "abort":
            if self._stop_fn is not None:
                print(f"SENTINEL[abort]: coordinated stop requested "
                      f"(multi-host run: every process must leave the "
                      f"loop at the same voted step; the run ends at "
                      f"the next coordination boundary)", flush=True)
                self._stop_fn()
            else:
                raise SentinelTripped(tripped[0])
        return tripped

    def _push(self, hist: list[float], v: float) -> None:
        hist.append(v)
        if len(hist) > self.window:
            del hist[0]

    @property
    def last_good_step(self) -> int | None:
        return self._last_good[1] if self._last_good else None

    # ---------------------------------------------------------- firing

    def _fire(self, kind: str, step: int, value: float,
              detail: str) -> TripReport:
        from distributed_tensorflow_tpu.utils import telemetry

        report = TripReport(kind=kind, step=int(step), value=value,
                            detail=detail, action=self.action)
        self.trips.append(report)
        print(f"SENTINEL[{kind}] tripped at step {step}: {detail} "
              f"(action={self.action})", flush=True)
        telemetry.get_tracer().record_instant(
            f"sentinel:{kind}", step=int(step), value=value,
            detail=detail, action=self.action)
        if self._logger is not None:
            self._logger.scalars(int(step), {
                "sentinel_trips": float(len(self.trips)),
                f"sentinel_{kind}": 1.0,
            })
        if self.action in ("snapshot", "abort"):
            report.checkpoint_path = self._emergency_checkpoint()
        # dump AFTER the emergency save so the postmortem records its
        # ckpt_write span (and the save itself rides the flight ring)
        telemetry.flight_recorder().dump(f"sentinel:{kind}")
        return report

    def _emergency_checkpoint(self) -> str | None:
        if self._save_fn is None:
            return None
        if self._last_good is None:
            print("sentinel: no known-good state observed yet — nothing "
                  "to snapshot", flush=True)
            return None
        state, step = self._last_good
        if step in self._saved_steps:  # an ongoing incident re-trips on
            return None                # the cooldown; save once per state
        try:
            path = self._save_fn(state, step)
            self._saved_steps.add(step)
            print(f"sentinel: emergency checkpoint of last-good step "
                  f"{step} -> {path}", flush=True)
            return path
        except Exception as e:  # noqa: BLE001 — the report must still land
            print(f"sentinel: emergency checkpoint failed: "
                  f"{type(e).__name__}: {e}", flush=True)
            return None


def from_flags(FLAGS, *, save_fn=None, logger=None,
               stop_fn=None) -> Sentinel | None:
    """The one flag->feature mapping for the ``--sentinel_*`` surface,
    shared by every training loop. None when unarmed (the default) or
    when telemetry is off (the parse-time validator rejects that combo
    at the CLI; non-CLI callers get the same quiet no-op)."""
    action = (getattr(FLAGS, "sentinel_action", "") or "").strip()
    if not action:
        return None
    if not bool(getattr(FLAGS, "telemetry", True)):
        return None
    return Sentinel(
        parse_kinds(getattr(FLAGS, "sentinel_kinds", "") or ""),
        action,
        window=int(getattr(FLAGS, "sentinel_window", DEFAULT_WINDOW)
                   or DEFAULT_WINDOW),
        threshold=float(getattr(FLAGS, "sentinel_threshold",
                                DEFAULT_THRESHOLD) or DEFAULT_THRESHOLD),
        save_fn=save_fn, logger=logger, stop_fn=stop_fn)
