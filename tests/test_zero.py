"""ZeRO-sharded data parallelism (parallel/zero.py): exact trajectories
vs replicated sync DP on the 8-device virtual mesh, the reduce-scatter /
all-gather-transpose collective pin, cross-topology checkpoints through
the verified-restore ladder, the static memory budget, and the --zero
flag surface."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.models import MLP, DeepCNN, ResNet20
from distributed_tensorflow_tpu.parallel import (
    make_dp_train_step,
    make_mesh,
    shard_batch,
)
from distributed_tensorflow_tpu.parallel.data_parallel import (
    make_dp_eval_step,
    replicate_state,
)
from distributed_tensorflow_tpu.parallel.mesh import DATA_AXIS
from distributed_tensorflow_tpu.parallel.zero import (
    fetch_state_zero,
    make_zero_eval_step,
    make_zero_train_step,
    shard_state_zero,
    zero_clip_transform,
    zero_memory_budget,
)
from distributed_tensorflow_tpu.training import (
    adam,
    create_train_state,
    get_optimizer,
    sgd,
)
from distributed_tensorflow_tpu.training.train_state import momentum
from distributed_tensorflow_tpu.training.train_state import (
    clip_by_global_norm,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _batch(n=32, seed=1, pixels=784):
    x = jax.random.normal(jax.random.key(seed), (n, pixels))
    y = jax.nn.one_hot(jnp.arange(n) % 10, 10)
    return x, y


def _assert_trees_equal(a, b, exact=True, rtol=1e-4, atol=1e-6):
    la = jax.tree_util.tree_flatten_with_path(a)[0]
    lb = jax.tree.leaves(b)
    assert len(la) == len(lb)
    for (path, x), y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        if exact:
            np.testing.assert_array_equal(x, y, err_msg=str(path))
        else:  # clipped runs: last-ulp partial-assembly divergence,
            # amplified over a few adam steps
            np.testing.assert_allclose(x, y, rtol=rtol, atol=atol,
                                       err_msg=str(path))


def _run_pair(mesh, model, opt, level, *, steps=3, keep_prob=1.0,
              accum_steps=1, dp_clip=None, zero_clip=None, seed=0,
              batch=None, exact_metrics=True):
    """Run replicated DP and ZeRO side by side on the same batches from
    the same seed; return (dp_host_state, zero_host_state)."""
    state0 = create_train_state(model, opt, seed=seed)
    batch = shard_batch(mesh, batch if batch is not None else _batch())
    dp = make_dp_train_step(model, opt, mesh, keep_prob=keep_prob,
                            donate=False, grad_transform=dp_clip,
                            accum_steps=accum_steps)
    z = make_zero_train_step(model, opt, mesh, level, keep_prob=keep_prob,
                             donate=False, grad_transform=zero_clip,
                             accum_steps=accum_steps)
    s_dp = replicate_state(mesh, state0)
    s_z = shard_state_zero(state0, mesh, level)
    for _ in range(steps):
        s_dp, m_dp = dp(s_dp, batch)
        s_z, m_z = z(s_z, batch)
        if exact_metrics:
            np.testing.assert_array_equal(np.asarray(m_dp["loss"]),
                                          np.asarray(m_z["loss"]))
        else:  # clipped: last-ulp partial-assembly divergence is allowed
            np.testing.assert_allclose(np.asarray(m_dp["loss"]),
                                       np.asarray(m_z["loss"]), rtol=1e-5)
    return jax.device_get(s_dp), fetch_state_zero(s_z, model, level)


# ---------------------------------------------- exact trajectories


def test_zero1_trajectory_bitmatches_dp_with_dropout(mesh):
    """--zero 1 == replicated sync DP bit-for-bit, dropout on: same rng
    folds, same summed gradient (psum_scatter chunks the psum), same
    elementwise update — only the collective pattern changes."""
    hd, hz = _run_pair(mesh, DeepCNN(), adam(1e-3), 1, keep_prob=0.8)
    _assert_trees_equal(hd.params, hz.params)
    _assert_trees_equal(hd.opt_state, hz.opt_state)
    np.testing.assert_array_equal(np.asarray(hd.rng), np.asarray(hz.rng))
    assert int(hd.step) == int(hz.step) == 3


@pytest.mark.parametrize("model_cls", [MLP, DeepCNN])
def test_zero3_trajectory_bitmatches_dp(mesh, model_cls):
    """--zero 3 (params live sharded, gathered in forward/backward):
    still bit-identical — the all_gather transpose delivers the same
    chunks the explicit reduce-scatter would."""
    hd, hz = _run_pair(mesh, model_cls(), adam(1e-3), 3, keep_prob=0.8)
    _assert_trees_equal(hd.params, hz.params)
    _assert_trees_equal(hd.opt_state, hz.opt_state)


@pytest.mark.parametrize("opt_name", ["sgd", "momentum"])
def test_zero1_other_optimizers_bitmatch(mesh, opt_name):
    """Empty (sgd) and bare params-shaped (momentum velocity) opt_state
    layouts both survive the params-shaped-subtree chunking rule."""
    opt = {"sgd": sgd(0.05), "momentum": momentum(0.05)}[opt_name]
    hd, hz = _run_pair(mesh, DeepCNN(), opt, 1)
    _assert_trees_equal(hd.params, hz.params)
    _assert_trees_equal(hd.opt_state, hz.opt_state)


@pytest.mark.parametrize("level", [1, 3])
def test_zero_accum_steps_bitmatches_dp(mesh, level):
    """accum_steps > 1: ZeRO accumulates full local grads exactly like
    the replicated step (one gather per step at level 3, one
    reduce-scatter after the scan) — bitwise equal."""
    hd, hz = _run_pair(mesh, DeepCNN(), adam(1e-3), level, keep_prob=0.8,
                       accum_steps=2)
    _assert_trees_equal(hd.params, hz.params)
    _assert_trees_equal(hd.opt_state, hz.opt_state)


def test_zero_clip_matches_dp_to_tolerance_and_levels_bitmatch(mesh):
    """--clip_norm: the ZeRO transform psums per-shard squared-norm
    partials, so the clipped trajectory matches replicated DP to float
    tolerance (partial-assembly order differs in the last ulp) while
    staying BIT-identical across ZeRO levels."""
    kw = dict(steps=3, keep_prob=0.8, dp_clip=clip_by_global_norm(0.5),
              zero_clip=zero_clip_transform(0.5), exact_metrics=False)
    hd, hz1 = _run_pair(mesh, DeepCNN(), adam(1e-3), 1, **kw)
    _, hz3 = _run_pair(mesh, DeepCNN(), adam(1e-3), 3, **kw)
    _assert_trees_equal(hd.params, hz1.params, exact=False)
    _assert_trees_equal(hz1.params, hz3.params)  # bitwise across levels
    _assert_trees_equal(hz1.opt_state, hz3.opt_state)


def test_zero1_stateful_model_state_bitmatches(mesh):
    """Batch-norm running stats (model_state) pmean over the data axis
    exactly as replicated DP does."""
    model = ResNet20()
    x = jax.random.normal(jax.random.key(2), (16, 32 * 32 * 3))
    y = jax.nn.one_hot(jnp.arange(16) % 10, 10)
    hd, hz = _run_pair(mesh, model, momentum(0.1), 1, steps=2,
                       batch=(x, y))
    _assert_trees_equal(hd.params, hz.params)
    _assert_trees_equal(hd.model_state, hz.model_state)


def test_zero1_replicated_leaves_bit_identical_across_devices(mesh):
    """After every step, every device holds the SAME updated params (the
    all-gathered result) — the sync invariant replicated DP has, kept."""
    model = DeepCNN()
    opt = adam(1e-3)
    state = shard_state_zero(create_train_state(model, opt, seed=0),
                             mesh, 1)
    step = make_zero_train_step(model, opt, mesh, 1, keep_prob=0.8,
                                donate=False)
    batch = shard_batch(mesh, _batch())
    for _ in range(2):
        state, _ = step(state, batch)
        for leaf in jax.tree.leaves(state.params):
            shards = [np.asarray(s.data) for s in leaf.addressable_shards]
            assert len(shards) == 8
            for s in shards[1:]:
                np.testing.assert_array_equal(shards[0], s)


# ---------------------------------------------- collective-level pins


def test_all_gather_transpose_is_psum_scatter(mesh):
    """The ZeRO-3 gradient path rests on this: differentiating through a
    tiled all_gather routes each rank's cotangent into the owning rank's
    chunk — bitwise equal to the explicit psum_scatter ZeRO-1 uses."""
    d = 8
    c = 5  # chunk length per rank
    g = jax.random.normal(jax.random.key(3), (d, d * c))

    def per_shard(g_row):
        g_local = g_row.reshape(-1)
        chunk0 = jnp.zeros((c,), g_local.dtype)
        _, vjp = jax.vjp(
            lambda ch: lax.all_gather(ch, DATA_AXIS, tiled=True), chunk0)
        (via_transpose,) = vjp(g_local)
        explicit = lax.psum_scatter(g_local, DATA_AXIS,
                                    scatter_dimension=0, tiled=True)
        return via_transpose[None], explicit[None]

    fn = jax.shard_map(per_shard, mesh=mesh,
                       in_specs=P(DATA_AXIS, None),
                       out_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
                       check_vma=False)
    via_transpose, explicit = fn(g)
    np.testing.assert_array_equal(np.asarray(via_transpose),
                                  np.asarray(explicit))


def test_shard_fetch_roundtrip_and_padding(mesh):
    """shard_state_zero -> fetch_state_zero is the identity on the
    standard layout, and the device layout really is flat 1/D chunks:
    every chunked leaf holds ceil(n/D) elements per device."""
    model = DeepCNN()
    state = create_train_state(model, adam(1e-3), seed=4)
    z = shard_state_zero(state, mesh, 3)
    for leaf in jax.tree.leaves(z.params):
        assert leaf.ndim == 1 and leaf.shape[0] % 8 == 0
        assert leaf.addressable_shards[0].data.shape[0] == leaf.shape[0] // 8
    back = fetch_state_zero(z, model, 3)
    _assert_trees_equal(state.params, back.params)
    _assert_trees_equal(state.opt_state, back.opt_state)
    np.testing.assert_array_equal(np.asarray(state.rng),
                                  np.asarray(back.rng))


def test_zero_eval_step_matches_dp_eval(mesh):
    """Level-3 eval gathers the param chunks inside shard_map; metrics
    bit-match the replicated DP eval on the same params."""
    model = DeepCNN()
    state = create_train_state(model, adam(1e-3), seed=5)
    batch = shard_batch(mesh, _batch(seed=6))
    m_dp = make_dp_eval_step(model, mesh)(
        replicate_state(mesh, state).params, batch, ())
    z = shard_state_zero(state, mesh, 3)
    m_z = make_zero_eval_step(model, mesh, 3)(z.params, batch, ())
    np.testing.assert_array_equal(np.asarray(m_dp["loss"]),
                                  np.asarray(m_z["loss"]))
    np.testing.assert_array_equal(np.asarray(m_dp["accuracy"]),
                                  np.asarray(m_z["accuracy"]))


@pytest.mark.parametrize("level", [1, 3])
def test_zero_device_step_bitmatches_dp_device_step(mesh, level):
    """--zero --device_data: the resident-split sampler is the DP device
    step's verbatim, so chunked trajectories bit-match it — at level 3
    this pins the remat'd gather inside the lax.scan chunk too."""
    from distributed_tensorflow_tpu.data import read_data_sets
    from distributed_tensorflow_tpu.data.device_data import put_device_data
    from distributed_tensorflow_tpu.training.device_step import (
        make_device_dp_train_step,
        make_zero_device_train_step,
    )

    ds = read_data_sets("/nonexistent-zero", one_hot=True)
    data = put_device_data(ds.train, mesh)
    model = DeepCNN()
    opt = adam(1e-3)
    state0 = create_train_state(model, opt, seed=0)

    dp = make_device_dp_train_step(model, opt, mesh, 32, keep_prob=0.8,
                                   chunk=2, donate=False)
    s_dp, _ = dp(replicate_state(mesh, state0), data)
    s_dp, _ = dp(s_dp, data)

    z = make_zero_device_train_step(model, opt, mesh, level, 32,
                                    keep_prob=0.8, chunk=2, donate=False)
    s_z = shard_state_zero(state0, mesh, level)
    s_z, _ = z(s_z, data)
    s_z, _ = z(s_z, data)
    hz = fetch_state_zero(s_z, model, level)
    hd = jax.device_get(s_dp)
    assert int(hz.step) == 4
    _assert_trees_equal(hd.params, hz.params)
    _assert_trees_equal(hd.opt_state, hz.opt_state)


# ---------------------------------------------- cross-topology ckpts


def _ckpt_template(model, opt):
    return create_train_state(model, opt, seed=9)


@pytest.mark.parametrize("level", [1, 3])
def test_checkpoint_zero_to_replicated_and_back(tmp_path, level):
    """Checkpoints are STANDARD-layout whatever --zero level wrote them:
    save mid-run under ZeRO -> restore replicated (and the reverse),
    both through restore_with_fallback, and finish bit-identical to an
    uninterrupted replicated run."""
    from distributed_tensorflow_tpu.checkpoint import (
        restore_with_fallback,
        save_checkpoint,
    )

    mesh = make_mesh()
    model = DeepCNN()
    opt = adam(1e-3)
    base = create_train_state(model, opt, seed=3)
    batches = [shard_batch(mesh, _batch(seed=s)) for s in (10, 11)]

    dp = make_dp_train_step(model, opt, mesh, keep_prob=0.8, donate=False)
    z = make_zero_train_step(model, opt, mesh, level, keep_prob=0.8,
                             donate=False)

    # uninterrupted replicated reference over both batches
    ref = replicate_state(mesh, base)
    for b in batches:
        ref, _ = dp(ref, b)
    ref = jax.device_get(ref)

    # zero writes step 1 -> replicated resumes
    s_z, _ = z(shard_state_zero(base, mesh, level), batches[0])
    d1 = str(tmp_path / f"z{level}_to_dp")
    save_checkpoint(d1, fetch_state_zero(s_z, model, level), step=1)
    got, step, report = restore_with_fallback(d1, _ckpt_template(model, opt))
    assert step == 1 and report.fallback_depth == 0
    done, _ = dp(replicate_state(mesh, got), batches[1])
    _assert_trees_equal(ref.params, jax.device_get(done).params)

    # replicated writes step 1 -> zero resumes
    s_dp, _ = dp(replicate_state(mesh, base), batches[0])
    d2 = str(tmp_path / f"dp_to_z{level}")
    save_checkpoint(d2, jax.device_get(s_dp), step=1)
    got, step, report = restore_with_fallback(d2, _ckpt_template(model, opt))
    assert step == 1 and report.fallback_depth == 0
    s_z, _ = z(shard_state_zero(got, mesh, level), batches[1])
    done = fetch_state_zero(s_z, model, level)
    _assert_trees_equal(ref.params, done.params)
    _assert_trees_equal(ref.opt_state, done.opt_state)


def test_corrupt_newest_zero_checkpoint_rides_the_ladder(tmp_path):
    """A ZeRO-written set torn mid-file (the machine-crash signature)
    quarantines and the ladder restores the older complete set — same
    guarantees as replicated-written checkpoints (it IS the same
    format)."""
    from distributed_tensorflow_tpu.checkpoint import (
        restore_with_fallback,
        save_checkpoint,
    )

    mesh = make_mesh()
    model = DeepCNN()
    opt = adam(1e-3)
    z = make_zero_train_step(model, opt, mesh, 1, donate=False)
    s_z = shard_state_zero(create_train_state(model, opt, seed=3), mesh, 1)
    d = str(tmp_path)
    batch = shard_batch(mesh, _batch())
    s_z, _ = z(s_z, batch)
    save_checkpoint(d, fetch_state_zero(s_z, model, 1), step=1)
    keep = fetch_state_zero(s_z, model, 1)
    s_z, _ = z(s_z, batch)
    save_checkpoint(d, fetch_state_zero(s_z, model, 1), step=2)
    p = os.path.join(d, "ckpt-2.npz")
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)

    got, step, report = restore_with_fallback(d, _ckpt_template(model, opt))
    assert step == 1 and report.fallback_depth == 1
    assert report.quarantined  # the torn set is out of selection for good
    _assert_trees_equal(keep.params, got.params)
    # the restored standard-layout state re-shards cleanly
    back = shard_state_zero(got, mesh, 1)
    assert int(back.step) == 1


def _parse(flags, args):
    flags.FLAGS._reset()
    flags.FLAGS._parse(args)
    return flags.FLAGS


def test_device_zero_mid_chunk_resume_matches_replicated(tmp_path):
    """--zero 1 --device_data through the production CLI: stop at a step
    that is NOT a chunk boundary, resume, and land bit-identical to an
    uninterrupted REPLICATED --device_data run — mid-chunk resume and
    cross-topology equivalence in one pass."""
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.checkpoint import restore_latest
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()

    def args_for(logdir, iters, zero):
        return [f"--logdir={logdir}", f"--data_dir={tmp_path}/none",
                f"--zero={zero}", "--batch_size=32", "--optimizer=adam",
                f"--training_iter={iters}", "--display_step=3",
                "--device_data", "--device_chunk=3",
                "--test_eval=false"]

    try:
        # interrupted zero run: 5 steps (chunks 3 + 2), resume to 9
        res = train(_parse(flags, args_for(f"{tmp_path}/a", 5, 1)),
                    mode="sync")
        assert res.final_step == 5
        res = train(_parse(flags, args_for(f"{tmp_path}/a", 9, 1)),
                    mode="sync")
        assert res.final_step == 9
        # uninterrupted replicated run: straight to 9
        res_b = train(_parse(flags, args_for(f"{tmp_path}/b", 9, 0)),
                      mode="sync")
        assert res_b.final_step == 9
    finally:
        flags.FLAGS._reset()

    model = DeepCNN()
    opt = get_optimizer("adam", 0.001)
    tmpl = lambda: create_train_state(model, opt, seed=9)
    got_a, step_a = restore_latest(f"{tmp_path}/a", tmpl())
    got_b, step_b = restore_latest(f"{tmp_path}/b", tmpl())
    assert step_a == step_b == 9
    _assert_trees_equal(got_b.params, got_a.params)
    _assert_trees_equal(got_b.opt_state, got_a.opt_state)


# ---------------------------------------------- guard rails


def test_replicate_state_refuses_zero_sharded_layout():
    """The satellite fix: silently re-replicating a ZeRO (flat padded
    chunk) layout would train on garbage — replicate_state must refuse
    loudly, and keep accepting host-built and replicated states."""
    mesh = make_mesh()
    model = DeepCNN()
    state = create_train_state(model, adam(1e-3), seed=0)
    z = shard_state_zero(state, mesh, 1)
    with pytest.raises(ValueError, match="already"):
        replicate_state(mesh, z)
    # host state and an already-replicated state still place fine
    r = replicate_state(mesh, state)
    r2 = replicate_state(mesh, r)
    assert jax.tree.leaves(r2.params)[0].is_fully_replicated


def test_zero_level_check():
    from distributed_tensorflow_tpu.parallel.zero import _check_level

    assert _check_level(1) == 1 and _check_level(3) == 3
    for bad in (0, 2, 4):
        with pytest.raises(ValueError, match="zero level"):
            _check_level(bad)


def test_zero_rejects_model_axis_strategies_in_loop(tmp_path):
    """The library-layer re-check: non-CLI callers that hand train() a
    colliding config still get the loud error, mid-setup not mid-trace."""
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()
    try:
        F = _parse(flags, [f"--logdir={tmp_path}/x",
                           f"--data_dir={tmp_path}/none", "--batch_size=32",
                           "--training_iter=2", "--test_eval=false"])
        # bypass the parse-time validator by mutating post-parse
        F.zero = 1
        F.expert_parallel = True
        with pytest.raises(ValueError, match="model-axis"):
            train(F, mode="sync")
    finally:
        flags.FLAGS._reset()


def test_zero_flag_validation():
    """Parse-time --zero validation: every unsupported composition names
    the flags at the command line, not mid-trace."""
    from distributed_tensorflow_tpu import flags

    flags.define_reference_flags()
    cases = [
        (["--zero=2"], "level 2"),
        (["--zero=5"], "must be 0"),
        (["--zero=1", "--pipeline", "--model_axis=2", "--num_blocks=4"],
         "pipeline"),
        (["--zero=3", "--expert_parallel"], "model axis"),
        (["--zero=1", "--seq_parallel"], "token axis"),
        (["--zero=1", "--model_axis=2"], "tensor parallelism"),
        (["--zero=1", "--mode=ps"], "SYNCHRONOUS"),
        (["--zero=1", "--ps_hosts=a:1,b:2"], "SYNCHRONOUS"),
        (["--zero=1", "--mode=local"], "no mesh"),
    ]
    try:
        for args, match in cases:
            flags.FLAGS._reset()
            with pytest.raises(ValueError, match=match):
                flags.FLAGS._parse(args)
        # the supported surface parses clean
        for ok in (["--zero=0"], ["--zero=1"], ["--zero=3"],
                   ["--zero=1", "--device_data", "--clip_norm=1.0",
                    "--accum_steps=2"]):
            flags.FLAGS._reset()
            flags.FLAGS._parse(ok)
            assert flags.FLAGS.zero == int(ok[0].split("=")[1])
    finally:
        flags.FLAGS._reset()


# ---------------------------------------------- memory budget


@pytest.mark.parametrize("model_cls", [MLP, DeepCNN])
def test_zero_memory_budget_reductions(model_cls):
    """The acceptance pin: >= D-fold optimizer-state reduction at ZeRO-1
    and >= D-fold param reduction at ZeRO-3 on the flagship models
    (their leaves dwarf the padding and the replicated scalar ``t``)."""
    d = 8
    b = zero_memory_budget(model_cls(), adam(1e-3), d)
    assert b["opt_reduction"] >= d * 0.99
    assert b["param_reduction"] >= d * 0.99
    per = b["per_chip"]
    # replicated holds everything; zero1 keeps full params; zero3 chunks
    assert per["zero1"]["params"] == per["replicated"]["params"]
    assert per["zero1"]["opt"] < per["replicated"]["opt"]
    assert per["zero3"]["params"] < per["replicated"]["params"]
    # transient grad bytes are mode-independent (full backward output)
    assert (per["replicated"]["grads"] == per["zero1"]["grads"]
            == per["zero3"]["grads"] == b["param_bytes"])
    for r in b["rows"]:
        if r["chunked"]:
            # padding never loses bytes: D chunks cover the leaf
            assert r["sharded_bytes"] * d >= r["bytes"]
        else:
            assert r["sharded_bytes"] == r["bytes"]
    # scalar slots (adam's t) replicate — never chunked
    t_rows = [r for r in b["rows"] if r["leaf"] == "t"]
    assert t_rows and not t_rows[0]["chunked"]


def test_trace_ops_mem_mode():
    """tools/trace_ops.py --mem prints the per-leaf table and the D-fold
    reductions without a chip (the auditable-anywhere satellite)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "trace_ops.py"),
         "--mem", "deep_cnn", "8"],
        capture_output=True, text=True, timeout=300, cwd=root, env=env)
    assert p.returncode == 0, p.stderr
    assert "replicated" in p.stdout and "zero1" in p.stdout
    assert "zero3" in p.stdout
    assert "8.00x" in p.stdout  # both reductions on the flagship CNN
    assert "weights/wd1" in p.stdout  # the per-leaf table
    assert "reduce-scatter+all-gather" in p.stdout


# ---------------------------------------------- comm/compute overlap


def _run_overlap_pair(mesh, model, opt, level, *, steps=3,
                      keep_prob=0.8, clip=None, bucket_mb=0.05):
    """Run the serial and --zero_overlap steps side by side on the same
    batches; the tiny bucket forces MULTI-bucket collectives so the
    concat/split machinery is actually exercised."""
    state0 = create_train_state(model, opt, seed=0)
    batch = shard_batch(mesh, _batch())
    outs = {}
    for overlap in (False, True):
        fn = make_zero_train_step(
            model, opt, mesh, level, keep_prob=keep_prob, donate=False,
            grad_transform=zero_clip_transform(clip) if clip else None,
            overlap=overlap, bucket_mb=bucket_mb)
        st = shard_state_zero(state0, mesh, level)
        for _ in range(steps):
            st, m = fn(st, batch)
        outs[overlap] = (fetch_state_zero(st, model, level),
                         float(m["loss"]))
    return outs


@pytest.mark.parametrize("level", [1, 3])
def test_zero_overlap_bitmatches_serial(mesh, level):
    """THE r14 acceptance pin: --zero_overlap trajectories are
    BIT-IDENTICAL to the serial ZeRO path at levels 1 and 3, dropout
    on — bucketed scatters own the same chunks, the level-3 prefetched
    gather is the same data movement, and the explicit reduce-scatter
    equals the serial gather transpose."""
    outs = _run_overlap_pair(mesh, DeepCNN(), adam(1e-3), level)
    assert outs[False][1] == outs[True][1]
    _assert_trees_equal(outs[False][0].params, outs[True][0].params)
    _assert_trees_equal(outs[False][0].opt_state, outs[True][0].opt_state)


@pytest.mark.parametrize("level", [1, 3])
def test_zero_overlap_clipped_bitmatches_serial(mesh, level):
    """--clip_norm composes: the axis-aware transform sees the same
    scattered chunks either way, so even the CLIPPED trajectory stays
    bitwise equal between overlap and serial (this is overlap-vs-serial
    at the SAME level — not the cross-level float-tolerance case)."""
    outs = _run_overlap_pair(mesh, DeepCNN(), adam(1e-3), level,
                             clip=0.05)
    _assert_trees_equal(outs[False][0].params, outs[True][0].params)


@pytest.mark.parametrize("level", [1, 3])
def test_zero_overlap_device_step_bitmatches_serial(mesh, level):
    """The --device_data composition: the overlap chunk scan (level 3:
    warmup gather + double-buffered prefetch carried across scan
    iterations) lands on bit-identical params vs the serial chunked
    step — the prefetched full params are the same values the serial
    step would re-gather."""
    from distributed_tensorflow_tpu.data import read_data_sets
    from distributed_tensorflow_tpu.data.device_data import (
        put_device_data,
    )
    from distributed_tensorflow_tpu.training.device_step import (
        make_zero_device_train_step,
    )

    model = DeepCNN()
    opt = adam(1e-3)
    state0 = create_train_state(model, opt, seed=0)
    ds = read_data_sets("/tmp/mnist-data", one_hot=True)
    data = put_device_data(ds.train, mesh)
    outs = {}
    for overlap in (False, True):
        fn = make_zero_device_train_step(
            model, opt, mesh, level, 32, keep_prob=0.8, chunk=3,
            donate=False, overlap=overlap, bucket_mb=0.05)
        st = shard_state_zero(state0, mesh, level)
        st, m = fn(st, data)
        outs[overlap] = (fetch_state_zero(st, model, level),
                         float(m["loss"]))
    assert outs[False][1] == outs[True][1]
    _assert_trees_equal(outs[False][0].params, outs[True][0].params)
    _assert_trees_equal(outs[False][0].opt_state,
                        outs[True][0].opt_state)


def test_bucketed_collectives_match_per_leaf(mesh):
    """The mechanism pin under the trajectory pins: a bucketed
    reduce-scatter owns EXACTLY the per-leaf scatters' chunks (same
    padding, same [D, c] row ownership, same elementwise sums), and
    the bucketed gather reassembles exactly what the per-leaf gathers
    would — on a ragged tree whose leaves straddle bucket boundaries."""
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_tpu.parallel.zero import (
        _bucket_plan,
        _gather_bucketed,
        _gather_params,
        _scatter_bucketed,
        _scatter_leaf,
    )

    tree = {
        "a": jax.random.normal(jax.random.key(0), (13,)),
        "b": jax.random.normal(jax.random.key(1), (3, 5)),
        "c": jax.random.normal(jax.random.key(2), (100,)),
        "d": jnp.float32(2.5),  # scalar leaf pads to one chunk each
    }
    meta = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.float32), tree)
    # 3 tiny buckets out of 4 leaves: the plan actually groups
    plan = _bucket_plan(jax.tree.leaves(meta), 8, 60 * 4)
    assert len(plan) == 3

    def pair(x):
        per = jax.tree.map(_scatter_leaf, x)
        buck = _scatter_bucketed(x, 8, 60 * 4)
        gper = _gather_params(per, meta)
        gbuck = _gather_bucketed(per, meta, 8, 60 * 4)
        return per, buck, gper, gbuck

    per, buck, gper, gbuck = jax.jit(jax.shard_map(
        pair, mesh=mesh, in_specs=(P(),),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P()),
        check_vma=False))(tree)
    for a, b in zip(jax.tree.leaves(per), jax.tree.leaves(buck)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(gper), jax.tree.leaves(gbuck)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero_comm_rows_overlap_exposure():
    """The ledger's overlap pricing: serial rows expose everything;
    overlap exposes one bucket per collective and prices the
    prefetched gather at zero exposure. Level-3 wire volume is
    |G| + |P| in BOTH schedules (r18, dttcheck-proven: the serial
    path's checkpointed gather output is itself the saved residual —
    no backward re-gather ever reaches the wire); overlap's win is
    the EXPOSED column, not the volume."""
    from distributed_tensorflow_tpu.parallel.zero import (
        zero_comm_rows,
        zero_exposed_comm_bytes,
    )

    G = 10 * 2 ** 20
    bucket = 1.0  # MB
    serial3 = zero_comm_rows(G, G, 3, 8)
    assert sum(r["bytes"] for r in serial3) == 2 * G
    assert {r["collective"] for r in serial3} == {
        "reduce_scatter(grad transpose)", "all_gather(params, forward)"}
    assert all(r["exposed_bytes"] == r["bytes"] for r in serial3)
    over3 = zero_comm_rows(G, G, 3, 8, overlap=True, bucket_mb=bucket)
    assert sum(r["bytes"] for r in over3) == 2 * G  # same volume
    gather = [r for r in over3 if "prefetched" in r["collective"]]
    assert gather and gather[0]["exposed_bytes"] == 0
    assert zero_exposed_comm_bytes(G, G, 3, 8, True, bucket) == 2 ** 20
    over1 = zero_comm_rows(G, G, 1, 8, overlap=True, bucket_mb=bucket)
    assert sum(r["bytes"] for r in over1) == 2 * G
    assert zero_exposed_comm_bytes(G, G, 1, 8, True, bucket) == 2 * 2 ** 20
    # a 1-way data axis still moves nothing
    assert zero_comm_rows(G, G, 3, 1, overlap=True) == []


def test_zero_overlap_flag_validation():
    """Parse-time --zero_overlap/--zero_bucket_mb validation: the
    overlap flag needs its parent mode, the bucket size needs the
    overlap flag and sane bounds — named at the command line."""
    from distributed_tensorflow_tpu import flags

    flags.define_reference_flags()
    cases = [
        (["--zero_overlap"], "only applies to --zero"),
        (["--zero=1", "--zero_overlap", "--zero_bucket_mb=0"],
         "must be in"),
        (["--zero=1", "--zero_overlap", "--zero_bucket_mb=2048"],
         "must be in"),
        (["--zero=1", "--zero_bucket_mb=8"],
         "only applies with"),
    ]
    try:
        for args, want in cases:
            flags.FLAGS._reset()
            with pytest.raises(ValueError, match=want):
                flags.FLAGS._parse(args)
        flags.FLAGS._reset()
        flags.FLAGS._parse(["--zero=3", "--zero_overlap",
                            "--zero_bucket_mb=8"])
        assert flags.FLAGS.zero_overlap is True
        assert flags.FLAGS.zero_bucket_mb == 8.0
    finally:
        flags.FLAGS._reset()


def test_trace_ops_comm_overlap_mode():
    """tools/trace_ops.py --comm ... --zero_overlap prints the exposed
    column and the prefetched-gather row — no chip."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "trace_ops.py"),
         "--comm", "deep_cnn", "8", "--zero_overlap", "--bucket_mb", "1"],
        capture_output=True, text=True, timeout=300, cwd=root, env=env)
    assert p.returncode == 0, p.stderr
    assert "exposed" in p.stdout
    assert "all_gather(params, prefetched)" in p.stdout
    assert "bucketed reduce-scatter" in p.stdout
