from distributed_tensorflow_tpu.models.cnn import DeepCNN
from distributed_tensorflow_tpu.models.registry import get_model, register_model

__all__ = ["DeepCNN", "get_model", "register_model"]
