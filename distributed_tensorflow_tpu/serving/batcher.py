"""Dynamic microbatch assembly with bounded admission — the serving
front door.

TPU inference wants large, shape-stable batches (one compiled executable
per bucket); traffic arrives as single requests at arbitrary times. The
batcher bridges the two the way Orca/TF-Serving-style systems do: a
bounded request queue, a worker that closes a microbatch when either
``max_batch`` requests are waiting or the oldest has waited
``max_delay_ms``, and power-of-two batch padding so the engine's jitted
executable cache stays small.

Admission is deadline-aware and NEVER hangs the client:

- a full queue rejects immediately (``RejectedError`` with the reason —
  backpressure the caller can see, retry, or shed),
- a request whose deadline expires before its batch executes completes
  with a deadline ``RejectedError`` instead of burning chip time on an
  answer nobody is waiting for,
- a dead worker (a batch raising ``BaseException``, e.g. an injected
  crash) fails every pending future and marks the batcher closed —
  subsequent submits reject; nothing blocks forever. Per-batch
  ``Exception``s fail only that batch's futures; the worker keeps
  serving.

Requests carry a ``group`` key (the engine uses the decode bucket —
prompt length/opts) so only shape-compatible requests assemble into one
microbatch; groups are served FIFO by their oldest request.

Fault points (utils/faults.py): ``serve_admit`` fires inside submit
after the admission checks, ``serve_batch`` after a microbatch is
assembled — ``--fault_spec serve_batch:mode=error`` proves the
reject-with-reason path under deterministic failure.

Request plane (r19, serving/reqtrace.py): every submission mints (or
echoes) a ``request_id`` and — when the plane is configured — owns a
phase timeline (admit/queue_wait/batch_assembly/prefill/decode/respond)
with a terminal disposition. EVERY exit records one: completions "ok",
a full queue "rejected_full", a closed batcher "rejected_closed", an
injected admission fault "rejected_fault", a deadline "expired", a
failed batch or dead worker "failed" — rejections no longer vanish from
the per-request story, and ``RejectedError.request_id`` carries the id
to the wire.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from distributed_tensorflow_tpu.serving import reqtrace
from distributed_tensorflow_tpu.utils import telemetry
from distributed_tensorflow_tpu.utils.faults import fault_point
from distributed_tensorflow_tpu.utils.telemetry import trace_span


class RejectedError(RuntimeError):
    """A request the serving stack declined to run, with the reason
    (queue full, deadline exceeded, batcher closed, injected fault).
    Backpressure is a VISIBLE contract: callers get this immediately,
    never a hang. ``request_id`` names the rejected request so the
    refusal is correlatable with the audit ring and span sink."""

    def __init__(self, reason: str, request_id: str | None = None):
        super().__init__(reason)
        self.reason = reason
        self.request_id = request_id


class Future:
    """Single-assignment result slot for one request. ``request_id``
    is set at submit; ``meta`` (the request-plane summary: disposition,
    phase breakdown) is set — before the result — when the plane is
    configured."""

    __slots__ = ("_event", "_value", "_error", "request_id", "meta")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: BaseException | None = None
        self.request_id: str | None = None
        self.meta: dict | None = None

    def set_result(self, value) -> None:
        self._value = value
        self._event.set()

    def set_error(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError("request still pending")
        if self._error is not None:
            raise self._error
        return self._value


@dataclass
class _Request:
    payload: Any
    opts: dict
    group: Any
    future: Future
    t_submit: float
    deadline: float
    request_id: str = ""
    trace: Any = None  # reqtrace.RequestTrace | None


def pow2_bucket(n: int, cap: int) -> int:
    """The smallest power of two >= n, clamped to ``cap`` — the batch
    padding policy (one compiled executable per bucket instead of one
    per observed batch size). The rounding rule itself is shared with
    the request plane's shape buckets (``reqtrace.pow2_ceil``)."""
    if n < 1:
        raise ValueError(f"bucket of {n} requests")
    return min(reqtrace.pow2_ceil(n), cap)


@dataclass
class BatcherStats:
    admitted: int = 0
    completed: int = 0
    rejected_full: int = 0
    rejected_closed: int = 0
    rejected_deadline: int = 0
    rejected_fault: int = 0
    failed: int = 0
    batches: int = 0
    batched_requests: int = 0
    queue_depth: int = 0
    lock: threading.Lock = field(default_factory=threading.Lock,
                                 repr=False)

    def as_dict(self) -> dict:
        with self.lock:
            d = {k: getattr(self, k) for k in (
                "admitted", "completed", "rejected_full",
                "rejected_closed", "rejected_deadline",
                "rejected_fault", "failed",
                "batches", "batched_requests", "queue_depth")}
        d["mean_batch_size"] = (d["batched_requests"] / d["batches"]
                                if d["batches"] else 0.0)
        return d


class DynamicBatcher:
    """Bounded queue + one worker thread assembling microbatches.

    ``runner(payloads, opts_list) -> results`` executes one assembled
    microbatch (same-length lists; the engine pads/stacks inside).
    ``group_key(payload, opts)`` partitions requests into
    shape-compatible groups (None = everything batches together).
    ``latency`` (a ``StreamingHistogram``) records per-request
    end-to-end milliseconds; ``on_batch(stats)`` runs after every batch
    (the metrics-emission and profiling hooks).
    """

    def __init__(self, runner: Callable, *, max_batch: int = 8,
                 max_delay_ms: float = 5.0, queue_depth: int = 64,
                 default_timeout_ms: float = 1000.0,
                 group_key: Callable | None = None,
                 latency=None, on_batch: Callable | None = None,
                 name: str = "serve"):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_depth < max_batch:
            raise ValueError(f"queue_depth ({queue_depth}) must hold at "
                             f"least one full batch ({max_batch})")
        self._runner = runner
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.queue_depth = int(queue_depth)
        self.default_timeout_s = float(default_timeout_ms) / 1000.0
        self._group_key = group_key
        self.latency = latency
        self._on_batch = on_batch
        self._route = name  # the request plane's route key
        self.stats = BatcherStats()
        self._queue: list[_Request] = []
        self._cv = threading.Condition()
        self._closed = False
        self._worker = threading.Thread(
            target=self._worker_loop, name=f"{name}-batcher", daemon=True)
        self._worker.start()
        # deadlines must fire even while the worker is busy inside a
        # long batch (otherwise an expired request waits for the batch
        # to finish before learning it was never going to run)
        self._expirer = threading.Thread(
            target=self._expiry_loop, name=f"{name}-expiry", daemon=True)
        self._expirer.start()

    # ------------------------------------------------------- admission

    def submit(self, payload, timeout_ms: float | None = None,
               request_id: str | None = None, **opts) -> Future:
        """Admit one request; returns its Future. Raises
        ``RejectedError`` IMMEDIATELY on a full queue, a closed batcher,
        or an armed ``serve_admit`` fault — admission never blocks.
        ``request_id`` (client-supplied) is echoed everywhere the
        request appears; omitted, one is minted — either way the Future
        (and any RejectedError) carries it."""
        now = time.monotonic()
        rid = str(request_id) if request_id else reqtrace.new_request_id()
        plane = reqtrace.get_plane()
        tr = (plane.begin(rid, self._route, payload)
              if plane is not None else None)
        timeout_s = (self.default_timeout_s if timeout_ms is None
                     else float(timeout_ms) / 1000.0)
        group = (self._group_key(payload, opts)
                 if self._group_key is not None else None)
        req = _Request(payload=payload, opts=opts, group=group,
                       future=Future(), t_submit=now,
                       deadline=now + timeout_s, request_id=rid,
                       trace=tr)
        req.future.request_id = rid
        with self._cv:
            if self._closed:
                # distinct counter: a closed batcher needs a restart, a
                # full queue needs shedding — an operator must be able
                # to tell which from the stats
                with self.stats.lock:
                    self.stats.rejected_closed += 1
                reqtrace.finish(tr, "rejected_closed",
                                reason="batcher closed")
                raise RejectedError("batcher closed", request_id=rid)
            if len(self._queue) >= self.queue_depth:
                with self.stats.lock:
                    self.stats.rejected_full += 1
                reason = (f"queue full (depth={self.queue_depth}); "
                          f"retry later")
                reqtrace.finish(tr, "rejected_full", reason=reason)
                raise RejectedError(reason, request_id=rid)
            with self.stats.lock:
                admit_count = self.stats.admitted + 1
            try:
                fault_point("serve_admit", count=admit_count)
            except Exception as e:
                with self.stats.lock:
                    self.stats.rejected_fault += 1
                reqtrace.finish(tr, "rejected_fault",
                                reason=f"admission fault: {e}")
                raise RejectedError(f"admission fault: {e}",
                                    request_id=rid) from e
            self._queue.append(req)
            if tr is not None:
                tr.admitted()
            with self.stats.lock:
                self.stats.admitted += 1
                self.stats.queue_depth = len(self._queue)
            self._cv.notify_all()
        return req.future

    # ---------------------------------------------------------- worker

    def _take_batch(self) -> list[_Request] | None:
        """Block until a batch is ready (or the batcher closes); expire
        overdue requests while waiting. Returns None only at close."""
        with self._cv:
            while True:
                if self._closed and not self._queue:
                    return None
                self._expire_locked()
                if self._queue:
                    oldest = self._queue[0]
                    ready_at = oldest.t_submit + self.max_delay_s
                    same = [r for r in self._queue
                            if r.group == oldest.group]
                    if (len(same) >= self.max_batch or self._closed
                            or time.monotonic() >= ready_at):
                        batch = same[:self.max_batch]
                        taken = set(map(id, batch))
                        self._queue = [r for r in self._queue
                                       if id(r) not in taken]
                        for r in batch:
                            if r.trace is not None:
                                r.trace.taken()
                        with self.stats.lock:
                            self.stats.queue_depth = len(self._queue)
                        return batch
                    self._cv.wait(max(ready_at - time.monotonic(), 0.0))
                else:
                    self._cv.wait(0.1)

    def _expire_locked(self) -> None:
        now = time.monotonic()
        keep = []
        for r in self._queue:
            if r.deadline <= now:
                with self.stats.lock:
                    self.stats.rejected_deadline += 1
                r.future.meta = reqtrace.finish(
                    r.trace, "expired",
                    reason="deadline exceeded before execution")
                r.future.set_error(RejectedError(
                    "deadline exceeded before execution",
                    request_id=r.request_id))
            else:
                keep.append(r)
        if len(keep) != len(self._queue):
            self._queue = keep
            with self.stats.lock:
                self.stats.queue_depth = len(self._queue)

    def _expiry_loop(self) -> None:
        """Fail overdue queued requests on their deadline, independent of
        the worker — a worker stuck inside a long batch must not delay
        'your deadline passed' for everything behind it."""
        while True:
            with self._cv:
                if self._closed and not self._queue:
                    return
                self._expire_locked()
                if self._queue:
                    wake = min(r.deadline for r in self._queue)
                    self._cv.wait(
                        max(wake - time.monotonic(), 0.0) + 1e-3)
                else:
                    self._cv.wait(0.05)

    def _worker_loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                with self.stats.lock:
                    self.stats.batches += 1
                    self.stats.batched_requests += len(batch)
                    n_batch = self.stats.batches
                fault_point("serve_batch", count=n_batch,
                            size=len(batch))
                with trace_span("serve_batch", count=n_batch,
                                size=len(batch)), \
                        telemetry.armed("serve_batch", count=n_batch,
                                        size=len(batch)), \
                        reqtrace.batch_context(
                            [r.trace for r in batch]):
                    results = self._runner([r.payload for r in batch],
                                           [r.opts for r in batch])
                if len(results) != len(batch):
                    raise RuntimeError(
                        f"runner returned {len(results)} results for "
                        f"{len(batch)} requests")
                now = time.monotonic()
                for r, res in zip(batch, results):
                    if self.latency is not None:
                        self.latency.record((now - r.t_submit) * 1e3)
                    # meta BEFORE the result: a client reading the
                    # future right after result() must see the summary
                    r.future.meta = reqtrace.finish(r.trace, "ok")
                    r.future.set_result(res)
                with self.stats.lock:
                    self.stats.completed += len(batch)
                if self._on_batch is not None:
                    try:
                        self._on_batch(self)
                    except Exception as e:  # hooks never kill serving
                        print(f"serving on_batch hook failed: {e}")
            except Exception as e:
                # one bad batch (including an injected serve_batch
                # fault): fail ITS futures, keep serving
                with self.stats.lock:
                    self.stats.failed += len(batch)
                for r in batch:
                    if not r.future.done():
                        r.future.meta = reqtrace.finish(
                            r.trace, "failed",
                            reason=f"{type(e).__name__}: {e}")
                        r.future.set_error(e)
            except BaseException as e:
                # worker death (SystemExit and friends): fail the batch
                # AND everything pending, close — no client ever hangs
                for r in batch:
                    if not r.future.done():
                        r.future.meta = reqtrace.finish(
                            r.trace, "failed",
                            reason=f"worker died: {type(e).__name__}: "
                                   f"{e}")
                        r.future.set_error(e)
                self._die(e)
                return

    def _die(self, error: BaseException) -> None:
        with self._cv:
            self._closed = True
            pending, self._queue = self._queue, []
            with self.stats.lock:
                self.stats.queue_depth = 0
                self.stats.failed += len(pending)
            self._cv.notify_all()
        for r in pending:
            if not r.future.done():
                r.future.meta = reqtrace.finish(
                    r.trace, "failed",
                    reason=f"batcher worker died: {error}")
                r.future.set_error(RejectedError(
                    f"batcher worker died: {error}",
                    request_id=r.request_id))
        print(f"serving batcher worker died: {type(error).__name__}: "
              f"{error}")

    # ----------------------------------------------------------- admin

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self, drain: bool = True) -> None:
        """Stop the worker. ``drain=True`` serves what is queued first;
        False rejects the queue."""
        with self._cv:
            self._closed = True
            if not drain:
                pending, self._queue = self._queue, []
                for r in pending:
                    r.future.meta = reqtrace.finish(
                        r.trace, "rejected_closed",
                        reason="batcher closed")
                    r.future.set_error(RejectedError(
                        "batcher closed", request_id=r.request_id))
                with self.stats.lock:
                    self.stats.queue_depth = 0
            self._cv.notify_all()
        self._worker.join(timeout=30)
