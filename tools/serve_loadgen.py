#!/usr/bin/env python
"""Load generator for the serving stack — open- and closed-loop.

Closed loop (``run_closed_loop``): N workers each keep exactly one
request in flight — measures the system at its natural concurrency
(latency under a fixed multiprogramming level). Open loop
(``run_open_loop``): requests FIRE at a target rate whatever the
responses do — the honest way to measure tail latency under offered
load, since a closed loop's arrival process slows down with the server
and hides queueing collapse. Both return the same report dict
(p50/p90/p99 latency ms, achieved rps, ok/rejected/error counts), both
drive either the in-process client or a JSON-over-HTTP endpoint.

Request plane (r19): every offered request carries a CLIENT-side
``request_id``; the response must echo it (a mismatch counts as an
error — ``id_echo_failures`` in the report, never silent). When the
server's request plane is armed, responses carry the per-request phase
breakdown, and the summary grows phase-attributed latency columns
(``phase_ms``: client-observed p50/p99 per server phase) plus SLO
compliance (``slo_compliant_pct`` against ``--slo_p99_ms``).

CLI (HTTP mode):

    python tools/serve_loadgen.py --url http://127.0.0.1:8000 \
        --mode open --rate 200 --duration 10 --kind generate \
        --prompt_len 8 --max_new_tokens 16

bench.py's serving phase imports the loop runners directly against an
in-process client (no sockets on the timed path).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

# sys.path[0] is tools/ when run as a script; the package root is one up
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from distributed_tensorflow_tpu.serving.batcher import RejectedError
from distributed_tensorflow_tpu.utils.metrics import StreamingHistogram


class EchoMismatchError(RuntimeError):
    """The response's request_id is not the one this client sent — the
    id round-trip contract is broken (counted separately: a miswired
    plane must not hide inside the generic error count)."""


class _Counters:
    def __init__(self):
        self.lock = threading.Lock()
        self.ok = 0
        self.rejected = 0
        self.errors = 0
        self.id_echo_failures = 0
        self.slo_compliant = 0
        self.phase_hists: dict[str, StreamingHistogram] = {}
        # per-replica attribution (r22): keyed on the router's
        # X-DTT-Replica response header (or the direct target URL) —
        # {name: {"ok": n, "rejected": n, "hist": StreamingHistogram}}
        self.replica_stats: dict[str, dict] = {}

    def add(self, kind: str):
        with self.lock:
            setattr(self, kind, getattr(self, kind) + 1)

    def phases(self, phases_ms: dict):
        with self.lock:
            for phase, ms in phases_ms.items():
                h = self.phase_hists.get(phase)
                if h is None:
                    h = self.phase_hists[phase] = StreamingHistogram()
                h.record(float(ms))

    def replica(self, name: str, kind: str,
                latency_ms: float | None = None):
        with self.lock:
            entry = self.replica_stats.get(name)
            if entry is None:
                entry = self.replica_stats[name] = {
                    "ok": 0, "rejected": 0,
                    "hist": StreamingHistogram()}
            entry[kind] += 1
            if latency_ms is not None:
                entry["hist"].record(latency_ms)


def _report(hist: StreamingHistogram, c: _Counters, elapsed_s: float,
            slo_p99_ms: float | None = None) -> dict:
    out = dict(hist.summary("latency_ms_"))
    out.update({
        "ok": c.ok,
        "rejected": c.rejected,
        "errors": c.errors,
        "id_echo_failures": c.id_echo_failures,
        "elapsed_s": round(elapsed_s, 3),
        "achieved_rps": round(c.ok / elapsed_s, 2)
        if elapsed_s > 0 else 0.0,
    })
    # phase-attributed latency: the server's per-request breakdown
    # aggregated client-side (only present when the replica's request
    # plane is armed and echoing phases)
    with c.lock:
        out["phase_ms"] = {
            phase: {"p50": round(h.quantile(0.5), 3),
                    "p99": round(h.quantile(0.99), 3),
                    "mean": round(h.mean, 3)}
            for phase, h in sorted(c.phase_hists.items())} or None
        # r22: which replica served what — present when responses carry
        # the router's X-DTT-Replica header (or --targets fanned out)
        out["per_replica"] = {
            name: {"ok": entry["ok"], "rejected": entry["rejected"],
                   "p50_ms": round(entry["hist"].quantile(0.5), 3),
                   "p99_ms": round(entry["hist"].quantile(0.99), 3)}
            for name, entry in sorted(c.replica_stats.items())} or None
    if slo_p99_ms and slo_p99_ms > 0:
        out["slo_p99_ms"] = slo_p99_ms
        total = c.ok + c.rejected + c.errors
        out["slo_compliant_pct"] = (
            round(100.0 * c.slo_compliant / total, 4) if total else None)
    return out


def _call_and_record(request_fn, hist: StreamingHistogram, c: _Counters,
                     slo_p99_ms: float | None = None) -> None:
    t0 = time.monotonic()
    try:
        meta = request_fn()
        latency_ms = (time.monotonic() - t0) * 1e3
        hist.record(latency_ms)
        c.add("ok")
        if slo_p99_ms and latency_ms <= slo_p99_ms:
            c.add("slo_compliant")
        if isinstance(meta, dict):
            if meta.get("phases_ms"):
                c.phases(meta["phases_ms"])
            if meta.get("replica"):
                c.replica(meta["replica"], "ok", latency_ms)
    except EchoMismatchError:
        c.add("id_echo_failures")
        c.add("errors")
    except RejectedError as e:
        c.add("rejected")
        name = getattr(e, "replica", None)
        if name:
            c.replica(name, "rejected")
    except Exception:  # noqa: BLE001 — the loadgen reports, not raises
        c.add("errors")


def run_closed_loop(request_fn, *, n_requests: int = 200,
                    concurrency: int = 4,
                    slo_p99_ms: float | None = None) -> dict:
    """``concurrency`` workers, one request in flight each, until
    ``n_requests`` total have been attempted. ``request_fn`` may return
    a meta dict (``request_id``/``phases_ms``) to feed the
    phase-attributed columns; ``slo_p99_ms`` adds client-judged SLO
    compliance."""
    hist = StreamingHistogram()
    c = _Counters()
    issued = [0]
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                if issued[0] >= n_requests:
                    return
                issued[0] += 1
            _call_and_record(request_fn, hist, c, slo_p99_ms)

    t0 = time.monotonic()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return _report(hist, c, time.monotonic() - t0, slo_p99_ms)


def run_open_loop(request_fn, *, rate_rps: float, duration_s: float,
                  max_inflight: int = 256,
                  slo_p99_ms: float | None = None) -> dict:
    """Fire at ``rate_rps`` (uniform arrivals) for ``duration_s``; each
    request runs on its own thread so a slow server cannot throttle the
    arrival process (that's the point of open loop). ``max_inflight``
    bounds the thread population — beyond it arrivals count as errors
    (client saturation, reported, not hidden)."""
    hist = StreamingHistogram()
    c = _Counters()
    inflight = threading.Semaphore(max_inflight)
    threads: list[threading.Thread] = []
    interval = 1.0 / rate_rps
    t0 = time.monotonic()
    next_fire = t0

    def one():
        try:
            _call_and_record(request_fn, hist, c, slo_p99_ms)
        finally:
            inflight.release()

    while time.monotonic() - t0 < duration_s:
        now = time.monotonic()
        if now < next_fire:
            time.sleep(next_fire - now)
        next_fire += interval
        if not inflight.acquire(blocking=False):
            c.add("errors")
            continue
        th = threading.Thread(target=one, daemon=True)
        th.start()
        threads.append(th)
    # throughput is ok/OFFERED-window: folding the post-window drain
    # (joins below, up to 30 s under backlog) into the denominator would
    # deflate achieved_rps exactly when the server is saturated — the
    # condition the open loop exists to measure honestly
    t_offered = time.monotonic() - t0
    for th in threads:
        th.join(timeout=30)
    out = _report(hist, c, t_offered, slo_p99_ms)
    out["drain_s"] = round(time.monotonic() - t0 - t_offered, 3)
    out["offered_rps"] = rate_rps
    return out


def long_tail_fn(short_fn, long_fn, *, long_every: int = 10):
    """The long-generation adversary: a bimodal mix where every
    ``long_every``-th request is the long closure (default 10 → 90%
    short / 10% long). A whole-batch scheduler pays the long request's
    full decode on every batch that contains one — head-of-line
    blocking the iteration-level scheduler exists to remove. The
    counter is lock-guarded so open-loop firing threads can't skew the
    mix."""
    if long_every < 2:
        raise ValueError(f"long_every must be >= 2, got {long_every}")
    lock = threading.Lock()
    count = [0]

    def call():
        with lock:
            count[0] += 1
            is_long = count[0] % long_every == 0
        return (long_fn if is_long else short_fn)()

    return call


def knee_throughput(request_fn, rates, *, duration_s: float = 2.0,
                    min_goodput: float = 0.95,
                    slo_p99_ms: float | None = None) -> dict:
    """Ascending open-loop rate sweep; the knee is the highest offered
    rate the system SUSTAINS — zero drops (rejected + errors == 0) and
    achieved ≥ ``min_goodput`` × offered. Stops one rate past the first
    failure (the collapse row stays in the sweep: the report shows the
    knee AND what falls off it). Each row carries queue_wait p99 from
    the server's phase breakdown when the request plane is armed —
    that's the column the continuous-vs-whole-batch A/B argues with."""
    sweep = []
    knee = 0.0
    for rate in sorted(float(r) for r in rates):
        rep = run_open_loop(request_fn, rate_rps=rate,
                            duration_s=duration_s, slo_p99_ms=slo_p99_ms)
        dropped = rep["rejected"] + rep["errors"]
        sustained = (dropped == 0
                     and rep["achieved_rps"] >= min_goodput * rate)
        qw = (rep.get("phase_ms") or {}).get("queue_wait")
        sweep.append({
            "offered_rps": rate,
            "achieved_rps": rep["achieved_rps"],
            "ok": rep["ok"],
            "rejected": rep["rejected"],
            "errors": rep["errors"],
            "latency_ms_p99": rep.get("latency_ms_p99"),
            "queue_wait_p99_ms": qw["p99"] if qw else None,
            "sustained": sustained,
        })
        if sustained:
            knee = rate
        else:
            break
    return {"knee_rps": knee, "min_goodput": min_goodput,
            "duration_s": duration_s, "sweep": sweep}


def http_request_fn(url: str, kind: str, *, prompt_len: int = 8,
                    vocab_size: int = 64, input_dim: int = 784,
                    max_new_tokens: int = 16):
    """A request closure against the HTTP front end. Raises
    ``RejectedError`` on 429 so backpressure is counted, not miscounted
    as an error. Every call tags its payload with a fresh client-side
    ``request_id`` and verifies the response echoes it
    (``EchoMismatchError`` otherwise); returns the response's meta
    (request_id + phases_ms when the server's request plane is armed)
    for the phase-attributed summary columns."""
    from distributed_tensorflow_tpu.serving.reqtrace import (
        new_request_id,
    )

    if kind == "generate":
        payload = {"prompt": [i % vocab_size for i in range(prompt_len)],
                   "max_new_tokens": max_new_tokens}
        path = "/v1/generate"
    else:
        payload = {"inputs": [0.5] * input_dim}
        path = "/v1/predict"

    def call():
        rid = new_request_id()
        body = json.dumps({**payload, "request_id": rid}).encode()
        req = urllib.request.Request(
            url.rstrip("/") + path, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
                replica = resp.headers.get("X-DTT-Replica")
        except urllib.error.HTTPError as e:
            if e.code == 429:
                err = RejectedError(f"HTTP 429: {e.read()[:200]}",
                                    request_id=rid)
                # the router stamps attribution on rejections too
                err.replica = e.headers.get("X-DTT-Replica")
                raise err from e
            raise
        echoed = out.get("request_id")
        if echoed != rid:
            raise EchoMismatchError(
                f"sent request_id {rid!r}, response echoed {echoed!r}")
        return {"request_id": echoed,
                "phases_ms": out.get("phases_ms"),
                "replica": replica}

    return call


def multi_target_fn(urls, kind: str, **kw):
    """Round-robin fan-out over several direct replica URLs — the
    router-less baseline for per-replica attribution (each response is
    attributed to the target that served it, standing in for the
    X-DTT-Replica header a router would stamp)."""
    fns = []
    for u in urls:
        if "://" not in u:
            u = "http://" + u
        inner = http_request_fn(u, kind, **kw)
        fns.append((u, inner))
    lock = threading.Lock()
    count = [0]

    def call():
        with lock:
            i = count[0] % len(fns)
            count[0] += 1
        name, inner = fns[i]
        try:
            meta = inner()
        except RejectedError as e:
            if not getattr(e, "replica", None):
                e.replica = name
            raise
        if isinstance(meta, dict) and not meta.get("replica"):
            meta["replica"] = name
        return meta

    return call


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--url", default="",
                    help="serving (or router) endpoint, e.g. "
                         "http://127.0.0.1:8000 — responses carrying "
                         "the router's X-DTT-Replica header populate "
                         "the per_replica columns")
    ap.add_argument("--targets", default="",
                    help="comma-separated host:port replica list to "
                         "round-robin directly (router-less fan-out); "
                         "mutually exclusive with --url")
    ap.add_argument("--mode", choices=("open", "closed"), default="closed")
    ap.add_argument("--kind", choices=("predict", "generate"),
                    default="predict")
    ap.add_argument("--requests", type=int, default=200,
                    help="closed loop: total requests")
    ap.add_argument("--concurrency", type=int, default=4,
                    help="closed loop: in-flight requests")
    ap.add_argument("--rate", type=float, default=100.0,
                    help="open loop: offered requests/sec")
    ap.add_argument("--duration", type=float, default=10.0,
                    help="open loop: seconds")
    ap.add_argument("--prompt_len", type=int, default=8)
    ap.add_argument("--vocab_size", type=int, default=64)
    ap.add_argument("--input_dim", type=int, default=784)
    ap.add_argument("--max_new_tokens", type=int, default=16)
    ap.add_argument("--mix", choices=("uniform", "long_tail"),
                    default="uniform",
                    help="long_tail: every --long_every-th generate "
                         "request asks for --long_tokens new tokens "
                         "(default 8x --max_new_tokens) — the "
                         "long-generation adversary")
    ap.add_argument("--long_every", type=int, default=10,
                    help="long_tail: 1-in-N requests are long")
    ap.add_argument("--long_tokens", type=int, default=0,
                    help="long_tail: long-request generation length "
                         "(0 = 8x --max_new_tokens)")
    ap.add_argument("--knee_rates", type=str, default="",
                    help="comma-separated offered rps ladder; when set, "
                         "runs the ascending knee-throughput sweep "
                         "instead of --mode")
    ap.add_argument("--slo_p99_ms", type=float, default=0.0,
                    help="if > 0, add client-judged SLO compliance "
                         "(slo_compliant_pct) to the summary")
    args = ap.parse_args()

    targets = [t.strip() for t in args.targets.split(",") if t.strip()]
    if bool(args.url) == bool(targets):
        ap.error("exactly one of --url or --targets is required")
    kw = dict(prompt_len=args.prompt_len, vocab_size=args.vocab_size,
              input_dim=args.input_dim,
              max_new_tokens=args.max_new_tokens)
    if targets:
        fn = multi_target_fn(targets, args.kind, **kw)
    else:
        fn = http_request_fn(args.url, args.kind, **kw)
    if args.mix == "long_tail":
        if args.kind != "generate":
            ap.error("--mix long_tail requires --kind generate")
        long_n = args.long_tokens or 8 * args.max_new_tokens
        long_kw = {**kw, "max_new_tokens": long_n}
        long = (multi_target_fn(targets, args.kind, **long_kw)
                if targets else
                http_request_fn(args.url, args.kind, **long_kw))
        fn = long_tail_fn(fn, long, long_every=args.long_every)
    slo = args.slo_p99_ms if args.slo_p99_ms > 0 else None
    if args.knee_rates:
        rates = [float(r) for r in args.knee_rates.split(",") if r]
        rep = knee_throughput(fn, rates, duration_s=args.duration,
                              slo_p99_ms=slo)
    elif args.mode == "closed":
        rep = run_closed_loop(fn, n_requests=args.requests,
                              concurrency=args.concurrency,
                              slo_p99_ms=slo)
    else:
        rep = run_open_loop(fn, rate_rps=args.rate,
                            duration_s=args.duration, slo_p99_ms=slo)
    rep["mix"] = args.mix
    print(json.dumps(rep))


if __name__ == "__main__":
    main()
