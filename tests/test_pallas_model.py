"""DeepCNN with the Pallas FC path (interpret mode on CPU): parity + training."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.models import DeepCNN
from distributed_tensorflow_tpu.training import adam, create_train_state, make_train_step


def test_pallas_model_forward_matches_xla():
    ref = DeepCNN()
    pal = DeepCNN(use_pallas=True)
    params = ref.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.key(1), (8, 784)) * 0.5
    a = ref.apply(params, x)
    b = pal.apply(params, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_pallas_model_trains():
    model = DeepCNN(use_pallas=True)
    opt = adam(1e-3)
    state = create_train_state(model, opt, seed=0)
    step_fn = make_train_step(model, opt, donate=False)
    from distributed_tensorflow_tpu.data.synthetic import synthetic_digits

    xs, labels = synthetic_digits(32, seed=0)
    batch = (jnp.asarray(xs), jax.nn.one_hot(jnp.asarray(labels), 10))
    losses = []
    for _ in range(8):
        state, m = step_fn(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
