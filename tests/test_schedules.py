"""Learning-rate schedules: shapes of the curves, optimizer integration
(the schedule compiles into the step and is evaluated on the optimizer's
own count), checkpoint roundtrips of the scheduled opt_state, and the
reference-parity guarantee that UNscheduled optimizers keep their exact
opt_state layouts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.models import DeepCNN
from distributed_tensorflow_tpu.training import (
    create_train_state,
    get_optimizer,
    get_schedule,
    make_train_step,
    sgd,
)
from distributed_tensorflow_tpu.training.schedules import (
    constant,
    cosine_decay,
    exponential_decay,
    linear_decay,
    schedule_from_flags,
    with_warmup,
)


def _at(schedule, step):
    return float(schedule(jnp.asarray(step, jnp.int32)))


def test_constant():
    s = constant(0.1)
    assert _at(s, 0) == pytest.approx(0.1)
    assert _at(s, 10_000) == pytest.approx(0.1)


def test_cosine_endpoints_and_midpoint():
    s = cosine_decay(1.0, decay_steps=100)
    assert _at(s, 0) == pytest.approx(1.0)
    assert _at(s, 50) == pytest.approx(0.5, abs=1e-6)
    assert _at(s, 100) == pytest.approx(0.0, abs=1e-6)
    assert _at(s, 500) == pytest.approx(0.0, abs=1e-6)  # clamped, not negative


def test_cosine_alpha_floor():
    s = cosine_decay(1.0, decay_steps=10, alpha=0.1)
    assert _at(s, 10) == pytest.approx(0.1, abs=1e-6)


def test_linear():
    s = linear_decay(2.0, decay_steps=4)
    assert [_at(s, i) for i in range(6)] == pytest.approx(
        [2.0, 1.5, 1.0, 0.5, 0.0, 0.0])


def test_exponential_continuous_vs_staircase():
    s = exponential_decay(1.0, decay_steps=10, decay_rate=0.5)
    assert _at(s, 10) == pytest.approx(0.5)
    assert _at(s, 5) == pytest.approx(0.5**0.5)
    st = exponential_decay(1.0, decay_steps=10, decay_rate=0.5, staircase=True)
    assert _at(st, 5) == pytest.approx(1.0)
    assert _at(st, 19) == pytest.approx(0.5)


def test_warmup_ramps_then_hands_off():
    s = with_warmup(cosine_decay(1.0, decay_steps=100), warmup_steps=10)
    # linear ramp to the base rate...
    assert _at(s, 0) == pytest.approx(0.1)
    assert _at(s, 4) == pytest.approx(0.5)
    assert _at(s, 9) == pytest.approx(1.0)
    # ...then the base schedule evaluated on the post-warmup step
    assert _at(s, 10) == pytest.approx(1.0)
    assert _at(s, 60) == pytest.approx(0.5, abs=1e-6)  # cosine midpoint


def test_get_schedule_constant_returns_plain_float():
    # the no-schedule case must stay a float so sgd keeps its stateless
    # reference-parity opt_state
    lr = get_schedule("constant", 0.01, 100)
    assert isinstance(lr, float) and lr == 0.01
    assert callable(get_schedule("constant", 0.01, 100, warmup_steps=5))
    assert callable(get_schedule("cosine", 0.01, 100))


def test_get_schedule_unknown_name():
    with pytest.raises(ValueError, match="unknown lr_schedule"):
        get_schedule("sawtooth", 0.1, 10)


def test_layouts_independent_of_schedule():
    """The opt_state layout must NOT depend on whether a schedule is set
    (schedules read TrainState.step), so checkpoints stay compatible
    across --lr_schedule toggles: sgd stays (), momentum stays the bare
    velocity tree."""
    params = {"w": jnp.ones((3,))}
    sched = cosine_decay(0.1, 10)
    assert sgd(0.1).init(params) == () == sgd(sched).init(params)
    mom_plain = get_optimizer("momentum", 0.1).init(params)
    mom_sched = get_optimizer("momentum", sched).init(params)
    assert jax.tree.structure(mom_plain) == jax.tree.structure(mom_sched)


def test_scheduled_update_without_step_is_loud():
    opt = sgd(cosine_decay(0.1, 10))
    params = {"w": jnp.ones((3,))}
    with pytest.raises(ValueError, match="needs the global step"):
        opt.update({"w": jnp.ones((3,))}, opt.init(params), params)


def test_scheduled_sgd_trajectory():
    """A scheduled sgd update must apply exactly lr(step) at each step."""
    sched = linear_decay(1.0, decay_steps=4)
    opt = sgd(sched)
    params = {"w": jnp.zeros((2,))}
    st = opt.init(params)
    grads = {"w": jnp.ones((2,))}
    expected = 0.0
    for t in range(4):
        updates, st = opt.update(grads, st, params, jnp.asarray(t, jnp.int32))
        expected -= 1.0 - t / 4
        params = jax.tree.map(lambda p, u: p + u, params, updates)
    np.testing.assert_allclose(np.asarray(params["w"]), expected, rtol=1e-6)


@pytest.mark.parametrize("name", ["sgd", "momentum", "adam"])
def test_scheduled_optimizer_compiles_into_step(name):
    """End-to-end: a scheduled optimizer inside the jitted train step — the
    schedule traces once, reads the advancing global step, loss stays
    finite."""
    model = DeepCNN()
    opt = get_optimizer(name, get_schedule("cosine", 1e-3, 50, warmup_steps=5))
    state = create_train_state(model, opt, seed=0)
    step_fn = make_train_step(model, opt, keep_prob=1.0, donate=False)
    x = jnp.ones((4, 784), jnp.float32)
    y = jax.nn.one_hot(jnp.arange(4) % 10, 10)
    for _ in range(3):
        state, m = step_fn(state, (x, y))
    assert np.isfinite(float(m["loss"]))
    assert int(state.step) == 3


def test_schedule_decays_within_jitted_run():
    """The effective rate must actually change across steps of one compiled
    function: with lr so large that an unscheduled run would move far, a
    fully-decayed schedule (step past the horizon) must apply ~0."""
    sched = linear_decay(1.0, decay_steps=2)
    opt = sgd(sched)
    params = {"w": jnp.zeros((2,))}
    st = opt.init(params)
    grads = {"w": jnp.ones((2,))}
    upd_hot, _ = opt.update(grads, st, params, jnp.asarray(0, jnp.int32))
    upd_cold, _ = opt.update(grads, st, params, jnp.asarray(100, jnp.int32))
    assert abs(float(upd_hot["w"][0])) == pytest.approx(1.0)
    assert abs(float(upd_cold["w"][0])) == pytest.approx(0.0, abs=1e-7)


def test_checkpoint_roundtrip_across_schedule_toggle(tmp_path):
    """Both toggle directions restore cleanly (same opt_state layout), and
    the schedule picks up at the RESTORED global step — not from the top
    of the warmup ramp."""
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        restore_latest,
        save_checkpoint,
    )

    model = DeepCNN()
    plain_opt = sgd(0.1)
    sched_opt = sgd(get_schedule("linear", 0.1, 10))

    # write with the PLAIN optimizer, restore into a SCHEDULED template
    state = create_train_state(model, plain_opt, seed=0)
    step_fn = make_train_step(model, plain_opt, keep_prob=1.0, donate=False)
    x = jnp.ones((2, 784), jnp.float32)
    y = jax.nn.one_hot(jnp.arange(2) % 10, 10)
    for _ in range(5):
        state, _ = step_fn(state, (x, y))
    save_checkpoint(str(tmp_path), state, int(state.step))

    restored, step = restore_latest(
        str(tmp_path), create_train_state(model, sched_opt, seed=1))
    assert step == 5 and int(restored.step) == 5
    # the scheduled step function continues from step 5: lr = 0.1*(1-5/10)
    sched_step = make_train_step(model, sched_opt, keep_prob=1.0, donate=False)
    before = np.asarray(restored.params["biases"]["out"])
    g_state, _ = sched_step(restored, (x, y))
    assert int(g_state.step) == 6
    # and the reverse direction restores too
    save_checkpoint(str(tmp_path), g_state, 6)
    back, step6 = restore_latest(
        str(tmp_path), create_train_state(model, plain_opt, seed=2))
    assert step6 == 6 and int(back.step) == 6
    assert before.shape == np.asarray(back.params["biases"]["out"]).shape


def test_schedule_from_flags_defaults_to_training_iter():
    class F:
        lr_schedule = "cosine"
        warmup_steps = 0
        decay_steps = 0
        decay_rate = 0.96
        learning_rate = 1.0
        training_iter = 200

    s = schedule_from_flags(F)
    assert _at(s, 100) == pytest.approx(0.5, abs=1e-6)
    F.lr_schedule = "constant"
    assert schedule_from_flags(F) == 1.0


def test_schedule_from_flags_warmup_fits_horizon():
    """With warmup and the default decay horizon, the schedule reaches its
    floor exactly at --training_iter (warmup comes out of the horizon)."""

    class F:
        lr_schedule = "linear"
        warmup_steps = 50
        decay_steps = 0
        decay_rate = 0.96
        learning_rate = 1.0
        training_iter = 200

    s = schedule_from_flags(F)
    assert _at(s, 49) == pytest.approx(1.0)  # top of the ramp
    assert _at(s, 200) == pytest.approx(0.0, abs=1e-6)  # floor at the end


@pytest.mark.parametrize("name", ["sgd", "momentum", "adam"])
def test_scheduled_optimizer_under_tensor_parallelism(name):
    """A scheduled optimizer under TP: the structural opt-state sharding
    rule places every layout, the GSPMD step runs, slots keep their
    param's split."""
    from distributed_tensorflow_tpu.parallel import MeshSpec, make_mesh
    from distributed_tensorflow_tpu.parallel.tensor_parallel import (
        make_tp_train_step,
        shard_state_tp,
        stage_batch_tp,
    )

    mesh = make_mesh(MeshSpec(data=4, model=2))
    model = DeepCNN()
    opt = get_optimizer(name, get_schedule("cosine", 1e-3, 50))
    state = shard_state_tp(create_train_state(model, opt, seed=0), mesh)
    step_fn = make_tp_train_step(model, opt, mesh, keep_prob=1.0, donate=False)
    x = jnp.ones((8, 784), jnp.float32)
    y = jax.nn.one_hot(jnp.arange(8) % 10, 10)
    state, m = step_fn(state, stage_batch_tp(mesh, (x, y)))
    assert np.isfinite(float(m["loss"]))
    assert int(state.step) == 1
    if name in ("momentum", "adam"):
        slot = (state.opt_state if name == "momentum"
                else state.opt_state["m"])["weights"]["wd1"]
        # the slot follows its param's TP split
        assert slot.addressable_shards[0].data.shape[1] == slot.shape[1] // 2


def test_ps_mode_rejects_schedules():
    from distributed_tensorflow_tpu.parallel.ps_emulation import run_worker

    class F:
        lr_schedule = "cosine"
        warmup_steps = 0

    with pytest.raises(ValueError, match="not supported in ps mode"):
        run_worker(None, F)
