"""TensorBoard event-file writer — pure Python, no TensorFlow dependency.

The reference wires a summary op into its Supervisor
(``tf.merge_all_summaries`` -> event files, ``MNISTDist.py:155,162``); this
is the equivalent sink for this framework's scalars. Files are standard
``events.out.tfevents.*`` logs TensorBoard reads directly:

  TFRecord framing: u64 length | u32 masked_crc32c(length) | payload
                    | u32 masked_crc32c(payload)
  payload: a tensorflow.Event proto — hand-encoded here (the subset used:
  wall_time=1 double, step=2 int64, file_version=3 string,
  summary=5 { repeated Value { tag=1 string, simple_value=2 float } })

Only scalar summaries are emitted, which is exactly what the reference's
training produces (its summary op merges nothing beyond Supervisor
defaults — SURVEY.md §5).
"""

from __future__ import annotations

import os
import socket
import struct
import time

# ------------------------------------------------------------- crc32c

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------- protobuf

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _len_delimited(field: int, payload: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _scalar_value(tag: str, value: float) -> bytes:
    body = _len_delimited(1, tag.encode())  # Value.tag = 1
    body += _varint((2 << 3) | 5) + struct.pack("<f", float(value))  # simple_value = 2
    return body


def _event(wall_time: float, step: int, *, file_version: str | None = None,
           scalars: dict | None = None) -> bytes:
    body = _varint((1 << 3) | 1) + struct.pack("<d", wall_time)  # wall_time = 1
    body += _varint(2 << 3) + _varint(int(step))  # step = 2 (varint)
    if file_version is not None:
        body += _len_delimited(3, file_version.encode())  # file_version = 3
    if scalars:
        summary = b"".join(
            _len_delimited(1, _scalar_value(tag, v))  # Summary.value = 1
            for tag, v in sorted(scalars.items())
        )
        body += _len_delimited(5, summary)  # Event.summary = 5
    return body


# ------------------------------------------------------------- writer

class EventFileWriter:
    """Append-only TensorBoard scalar log for one run directory."""

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        name = f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
        self.path = os.path.join(logdir, name)
        self._file = open(self.path, "ab")
        self._write(_event(time.time(), 0, file_version="brain.Event:2"))

    def _write(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._file.write(header)
        self._file.write(struct.pack("<I", _masked_crc(header)))
        self._file.write(payload)
        self._file.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalars(self, step: int, scalars: dict) -> None:
        clean = {k: float(v) for k, v in scalars.items()
                 if isinstance(v, (int, float))}
        if clean:
            self._write(_event(time.time(), step, scalars=clean))
            self._file.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
