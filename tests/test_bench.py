"""bench.py phases exercised on the 8-device virtual mesh (weak spot from
round 1: the multi-chip branch only ran when real hardware had >1 chip).
Constants are shrunk via monkeypatch; the point is that every branch —
mesh build, sharded prefetch staging, dp eval on the device-resident test
set, the feed-dict baseline — compiles and executes, not the numbers."""

import jax
import numpy as np
import pytest

_PRNG_BEFORE_BENCH_IMPORT = jax.config.jax_default_prng_impl

import bench  # noqa: E402 — the capture above must precede this import
from distributed_tensorflow_tpu.data import read_data_sets


@pytest.fixture(scope="module")
def ds(tmp_path_factory):
    # synthetic (no IDX files in the tmp dir); 2000-example test split is
    # divisible by 8 so convergence_phase takes the dp-eval branch
    return read_data_sets(str(tmp_path_factory.mktemp("no-data")), one_hot=True)


@pytest.mark.parametrize("n_chips", [1, 8])
def test_throughput_phase_runs(monkeypatch, ds, n_chips):
    monkeypatch.setattr(bench, "PER_CHIP_BATCH", 16)
    monkeypatch.setattr(bench, "WIRE_TIMED_STEPS", 4)
    rate = bench.throughput_phase(ds, n_chips)
    assert rate > 0 and np.isfinite(rate)


@pytest.mark.parametrize("n_chips", [1, 8])
def test_device_resident_phase_runs(monkeypatch, ds, n_chips):
    monkeypatch.setattr(bench, "PER_CHIP_BATCH", 16)
    monkeypatch.setattr(bench, "CHUNK", 3)
    monkeypatch.setattr(bench, "TIMED_CHUNKS", 2)
    rate = bench.device_resident_phase(ds, n_chips)
    assert rate > 0 and np.isfinite(rate)


@pytest.mark.parametrize("n_chips", [1, 8])
def test_convergence_phase_runs(monkeypatch, ds, n_chips):
    monkeypatch.setattr(bench, "CONVERGE_BATCH", 16)
    monkeypatch.setattr(bench, "CONVERGE_MAX_STEPS", 12)
    monkeypatch.setattr(bench, "CONVERGE_EVAL_EVERY", 6)
    out = bench.convergence_phase(ds, n_chips)
    assert 0.0 <= out["test_accuracy"] <= 1.0
    assert out["target_accuracy"] == bench.TARGET_ACC
    # 12 tiny steps will not reach 99%; the fields must say so honestly
    if out["seconds_to_target"] is None:
        assert out["steps_to_target"] is None


def test_resnet_phase_runs(monkeypatch, tmp_path):
    monkeypatch.setattr(bench, "RESNET_PER_CHIP_BATCH", 4)
    monkeypatch.setattr(bench, "RESNET_TIMED_CHUNKS", 1)
    monkeypatch.setattr(bench, "RESNET_CHUNK", 2)
    # hermetic: an empty data_dir pins the synthetic CIFAR fallback
    rate, source = bench.resnet_phase(8, data_dir=str(tmp_path / "no-cifar"))
    assert rate > 0 and np.isfinite(rate)
    assert source == "synthetic"


def test_ps_emulation_phase_runs(monkeypatch, ds):
    monkeypatch.setattr(bench, "PS_BATCH", 16)
    monkeypatch.setattr(bench, "PS_STEPS", 3)
    rate = bench.ps_emulation_phase(ds)
    assert rate > 0 and np.isfinite(rate)


def test_feeddict_baseline_runs(monkeypatch, ds):
    monkeypatch.setattr(bench, "FEEDDICT_BATCH", 16)
    monkeypatch.setattr(bench, "FEEDDICT_STEPS", 3)
    rate = bench.feeddict_baseline_phase(ds, 8)
    assert rate > 0 and np.isfinite(rate)


def test_sync_every_matches_backend():
    assert bench._sync_every(1) == 0
    expected = 16 if jax.default_backend() == "cpu" else 0
    assert bench._sync_every(8) == expected


def test_bench_import_does_not_flip_global_prng():
    """Regression: bench.py selects the rbg PRNG inside main() (scoped),
    not at import time — this module imports bench, and a module-level
    config flip leaked rbg into every test module collected afterwards
    (changing init distributions under other tests' seeds). Assert the
    import left the impl exactly as it found it."""
    assert jax.config.jax_default_prng_impl == _PRNG_BEFORE_BENCH_IMPORT


def test_convergence_phase_fashion_target(monkeypatch, ds):
    """The fashion phase reuses convergence_phase with its own target and
    budget; the reported target_accuracy must follow the parameter."""
    monkeypatch.setattr(bench, "CONVERGE_EVAL_EVERY", 5)
    out = bench.convergence_phase(ds, 1, target_acc=0.5, max_steps=20)
    assert out["target_accuracy"] == 0.5
    assert out["steps_to_target"] is None or out["steps_to_target"] <= 20


def test_lm_longctx_phase_runs(monkeypatch):
    monkeypatch.setattr(bench, "LM_SEQ_LEN", 64)
    monkeypatch.setattr(bench, "LM_BATCH", 4)
    monkeypatch.setattr(bench, "LM_D_MODEL", 32)
    monkeypatch.setattr(bench, "LM_ATTN_BLOCK", 16)
    monkeypatch.setattr(bench, "LM_TIMED_STEPS", 2)
    out = bench.lm_longctx_phase()
    assert out["lm_4k_tokens_per_sec_per_chip"] > 0
    assert out["lm_seq_len"] == 64
