"""DTT003 violating fixture: a loop variant that forgets the scalar
contract and the elastic poll."""


def _train_broken(FLAGS, ds, sv, logger, meter):
    for step in range(10):
        logger.scalars(step, {"images_per_sec": meter.images_per_sec})
