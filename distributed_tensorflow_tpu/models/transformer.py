"""MiniTransformer: an attention model family for the long-context path.

The reference framework has no attention model — this is the build's
extension exercising the sequence-parallel machinery
(ops/attention.ring_attention + parallel/sequence_parallel) on the same
datasets: an image is read as a SEQUENCE of rows (MNIST: 28 tokens of 28
pixels; CIFAR-10: 32 tokens of 96), embedded, run through pre-LN
transformer blocks, mean-pooled and classified. Pure pytree-of-arrays +
``apply`` like every model here — jits, shards, grads as a function.

Sequence parallelism: constructed with ``seq_axis="model"`` the model is
SPMD-aware — called inside shard_map with the token dimension sharded
over that mesh axis it slices its own positional embeddings by
``lax.axis_index``, runs RING attention over the axis, and mean-pools
with a ``psum``. Everything before the pool is per-token compute whose
parameter gradients arrive as P-scaled partials per shard while the
post-pool head's arrive replicated — one uniform pmean over the
sequence axis reduces both exactly (see
parallel/sequence_parallel.py for the derivation).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_tpu.models.cnn import truncated_normal_init
from distributed_tensorflow_tpu.models.registry import register_model
from distributed_tensorflow_tpu.ops import nn
from distributed_tensorflow_tpu.ops.attention import (
    blockwise_attention,
    multi_head_attention,
    ring_attention,
)


def _layernorm(x, gain, bias, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = xf.var(axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * gain + bias).astype(x.dtype)


def _attn_half_params(w, d, h, dh, dtype):
    """The attention half's parameters — ONE constructor for the dense
    and MoE block forms (like _attn_half on the compute side), so the
    layouts cannot diverge."""
    return {
        "ln1_g": jnp.ones((d,), dtype),
        "ln1_b": jnp.zeros((d,), dtype),
        "qkv": w((d, 3, h, dh)),
        "proj": w((h * dh, d)),
        "ln2_g": jnp.ones((d,), dtype),
        "ln2_b": jnp.zeros((d,), dtype),
    }


def _block_params(w, d, h, dh, mlp_dim, dtype):
    """One pre-LN block's parameter dict (shared by both transformer
    families so their checkpoints stay structurally interchangeable)."""
    return {
        **_attn_half_params(w, d, h, dh, dtype),
        "mlp_in": {"w": w((d, mlp_dim)), "b": jnp.zeros((mlp_dim,), dtype)},
        "mlp_out": {"w": w((mlp_dim, d)), "b": jnp.zeros((d,), dtype)},
    }


def _transformer_block(h, blk, attn_fn, cd):
    """One pre-LN transformer block: LN -> attention -> residual ->
    LN -> MLP -> residual. ``attn_fn(q, k, v)`` supplies the attention
    flavor (dense / blockwise / ring, causal or not) so the block is the
    ONE implementation both model families and every parallelism mode
    run."""
    return _mlp_half(_attn_half(h, blk, attn_fn, cd), blk, cd)


def _mlp_half(h, blk, cd):
    """LN -> relu MLP -> residual — the dense block's second half,
    shared with serving/decode.py's incremental step so the two code
    paths cannot diverge (the KV-cache bitwise-parity contract rides on
    this being the one implementation)."""
    y = _layernorm(h, blk["ln2_g"], blk["ln2_b"])
    y = jax.nn.relu(nn.dense(y, blk["mlp_in"]["w"], blk["mlp_in"]["b"],
                             compute_dtype=cd))
    return h + nn.dense(y, blk["mlp_out"]["w"], blk["mlp_out"]["b"],
                        compute_dtype=cd)


def _attn_half(h, blk, attn_fn, cd):
    """LN -> attention -> residual (shared by the dense-MLP and MoE
    block forms)."""
    return _attn_half_kv(h, blk, attn_fn, cd)[0]


def _attn_half_kv(h, blk, attn_fn, cd):
    """``_attn_half`` that also hands back this block's (k, v) — the
    serving prefill captures them into the decode cache, computed by the
    SAME projection the training forward runs (returns
    ``(h_out, k, v)``; k/v are (B, S, H, Dh) in the attention input
    dtype)."""
    y = _layernorm(h, blk["ln1_g"], blk["ln1_b"])
    qkv = jnp.einsum("bsd,dthe->tbshe", y, blk["qkv"].astype(y.dtype))
    a = attn_fn(qkv[0], qkv[1], qkv[2])
    a = a.reshape(*a.shape[:2], -1)  # (B, S, H*Dh)
    return h + nn.dense(a, blk["proj"], compute_dtype=cd), qkv[1], qkv[2]


def _moe_block_params(w, d, h, dh, mlp_dim, num_experts, dtype):
    """MoE block: same attention half as _block_params; the MLP becomes
    E experts behind a top-1 router (ops/moe.py)."""
    return {
        **_attn_half_params(w, d, h, dh, dtype),
        "moe": {
            "router": w((d, num_experts)),
            "w1": w((num_experts, d, mlp_dim)),
            "b1": jnp.zeros((num_experts, mlp_dim), dtype),
            "w2": w((num_experts, mlp_dim, d)),
            "b2": jnp.zeros((num_experts, d), dtype),
        },
    }


def _transformer_block_moe(h, blk, attn_fn, cd, capacity_factor,
                           moe_axis):
    """MoE block form: returns (h, load_balance_loss)."""
    from distributed_tensorflow_tpu.ops.moe import switch_moe

    h = _attn_half(h, blk, attn_fn, cd)
    y = _layernorm(h, blk["ln2_g"], blk["ln2_b"])
    y, aux = switch_moe(y, blk["moe"], capacity_factor=capacity_factor,
                        axis_name=moe_axis, compute_dtype=cd)
    return h + y, aux["lb_loss"]


@register_model("transformer")
class MiniTransformer:
    """Row-sequence transformer classifier.

    ``seq_axis=None`` (default): dense attention, runs anywhere a
    DeepCNN runs. ``seq_axis="model"``: ring attention + sharded
    positional slices + psum pooling — must then be applied inside
    shard_map with tokens sharded over that axis (the sequence-parallel
    step builder does this).
    """

    stateful = False

    def __init__(
        self,
        image_size: int = 28,
        channels: int = 1,
        num_classes: int = 10,
        d_model: int = 128,
        num_heads: int = 4,
        num_blocks: int = 2,
        mlp_ratio: int = 4,
        compute_dtype: Any = None,
        seq_axis: str | None = None,
        remat: bool = False,
        **_unused,  # registry passes hidden_units etc. to every model
    ):
        if d_model % num_heads:
            raise ValueError(f"d_model={d_model} % num_heads={num_heads} != 0")
        self.remat = remat
        self.image_size = image_size
        self.channels = channels
        self.num_classes = num_classes
        self.d_model = d_model
        self.num_heads = num_heads
        self.num_blocks = num_blocks
        self.mlp_dim = mlp_ratio * d_model
        self.compute_dtype = compute_dtype
        self.seq_axis = seq_axis
        self.seq_len = image_size           # one token per image row
        self.token_dim = image_size * channels

    def init(self, key, dtype=jnp.float32):
        d, h = self.d_model, self.num_heads
        dh = d // h
        keys = iter(jax.random.split(key, 4 + 7 * self.num_blocks))

        def w(shape, stddev=0.02):
            return truncated_normal_init(next(keys), shape, stddev, dtype)

        params = {
            "embed": {"w": w((self.token_dim, d)), "b": jnp.zeros((d,), dtype)},
            "pos": w((self.seq_len, d)),
            "blocks": [],
            "ln_f": {"g": jnp.ones((d,), dtype), "b": jnp.zeros((d,), dtype)},
            "head": {
                "w": w((d, self.num_classes)),
                "b": jnp.zeros((self.num_classes,), dtype),
            },
        }
        for _ in range(self.num_blocks):
            params["blocks"].append(
                _block_params(w, d, h, dh, self.mlp_dim, dtype))
        return params

    # ---- forward -------------------------------------------------------
    def apply(self, params, x, *, keep_prob=1.0, rng=None, train: bool = False):
        cd = self.compute_dtype
        x = nn.normalize_if_u8(x, cd)
        # (B, 784[*C]) or (B, S, token): accept both layouts. In SP mode
        # x is the LOCAL token block (B, S/P, token) handed in by the
        # shard_map step.
        if x.ndim == 2:
            x = x.reshape(-1, self.seq_len, self.token_dim)
        if cd is not None:
            x = x.astype(cd)

        d = self.d_model
        h = nn.dense(x, params["embed"]["w"], params["embed"]["b"],
                     compute_dtype=cd)
        pos = params["pos"]
        if self.seq_axis is not None:
            # my shard's slice of the positional table
            s_local = x.shape[1]
            start = lax.axis_index(self.seq_axis) * s_local
            pos = lax.dynamic_slice_in_dim(pos, start, s_local, axis=0)
        h = h + pos.astype(h.dtype)

        if self.seq_axis is not None:
            attn = lambda q, k, v: ring_attention(q, k, v, self.seq_axis)
        else:
            attn = multi_head_attention
        blk_fn = _transformer_block
        if self.remat:
            blk_fn = jax.checkpoint(_transformer_block,
                                    static_argnums=(2, 3))
        for blk in params["blocks"]:
            h = blk_fn(h, blk, attn, cd)

        h = _layernorm(h, params["ln_f"]["g"], params["ln_f"]["b"])
        # mean-pool over the FULL sequence: local sum, psum across the
        # sequence shards, divide by the global length
        pooled = h.sum(axis=1)
        if self.seq_axis is not None:
            pooled = lax.psum(pooled, self.seq_axis)
        pooled = pooled / jnp.asarray(self.seq_len, pooled.dtype)
        pooled = nn.dropout(pooled, keep_prob, rng, deterministic=not train)
        logits = nn.dense(pooled, params["head"]["w"], params["head"]["b"],
                          compute_dtype=cd)
        return logits.astype(jnp.float32)

    def num_params(self, params=None):
        if params is None:
            params = jax.eval_shape(lambda: self.init(jax.random.key(0)))
        return sum(int(jnp.size(p)) for p in jax.tree.leaves(params))


@register_model("lm")
class TransformerLM:
    """Causal (next-token) transformer language model — the long-context
    flagship. The reference framework is images-only (MNISTDist.py:68);
    this is the build's beyond-parity extension, and the end-to-end
    consumer of the causal attention forms.

    Input: integer token ids (B, S); output: per-token logits (B, S, V).
    The per-token cross-entropy and accuracy come from the SAME loss ops
    the classifiers use — ``ops.nn.softmax_cross_entropy`` and
    ``accuracy`` already handle labels.ndim == logits.ndim - 1, so the
    whole train-state/step/loop stack runs unchanged on (B, S) integer
    targets.

    Attention flavors (all causal):
    - ``seq_axis=None, attn_block=None``: dense triangle — fine to a few
      thousand tokens, O(S^2) memory.
    - ``attn_block=N``: single-device blockwise streaming softmax —
      O(S*N) peak memory, the one-chip long-context path.
    - ``seq_axis="model"``: RING attention over the mesh axis; tokens
      sharded, k/v blocks rotating on ICI — the multi-chip long-context
      path (must run inside the SP shard_map step).
    ``remat=True`` wraps each block in ``jax.checkpoint`` — activation
    memory drops from O(num_blocks * S * d) to O(S * d) + one block's
    recompute, the standard trade for long sequences.

    ``ce_block=N`` streams the LOSS head the same way ``attn_block``
    streams attention: the train/eval steps route through
    ``loss_with_metrics`` (ops.nn.streamed_softmax_ce_head), which
    never materializes the (B, S, V) f32 logits — O(N * V) peak in
    both passes. The other memory wall of large-vocab long context
    (the flash VJPs removed the O(S^2) one). ``apply`` still exists
    and still returns full logits (generation/inspection); training
    simply never calls it when ``ce_block`` is set.
    """

    stateful = False

    def __init__(
        self,
        vocab_size: int = 64,
        seq_len: int = 256,
        d_model: int = 128,
        num_heads: int = 4,
        num_blocks: int = 2,
        mlp_ratio: int = 4,
        compute_dtype: Any = None,
        seq_axis: str | None = None,
        attn_block: int | None = None,
        remat: bool = False,
        ce_block: int | None = None,
        moe_experts: int = 0,
        moe_capacity: float = 1.25,
        moe_aux: float = 0.01,
        moe_axis: str | None = None,
        **_unused,
    ):
        if d_model % num_heads:
            raise ValueError(f"d_model={d_model} % num_heads={num_heads} != 0")
        if seq_axis is not None and attn_block is not None:
            raise ValueError("seq_axis (ring) and attn_block (local "
                             "blockwise) are mutually exclusive attention "
                             "flavors")
        if moe_axis is not None and not moe_experts:
            raise ValueError("moe_axis (expert parallelism) needs "
                             "moe_experts > 0")
        if moe_axis is not None and seq_axis is not None:
            raise ValueError("moe_axis and seq_axis both claim the mesh's "
                             "model axis — pick one")
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.d_model = d_model
        self.num_heads = num_heads
        self.num_blocks = num_blocks
        self.mlp_dim = mlp_ratio * d_model
        self.compute_dtype = compute_dtype
        self.seq_axis = seq_axis
        self.attn_block = attn_block
        self.remat = remat
        self.ce_block = ce_block
        self.moe_experts = int(moe_experts)
        self.moe_capacity = float(moe_capacity)
        self.moe_aux = float(moe_aux)
        self.moe_axis = moe_axis

    def init(self, key, dtype=jnp.float32):
        d, h = self.d_model, self.num_heads
        dh = d // h
        keys = iter(jax.random.split(key, 4 + 8 * self.num_blocks))

        def w(shape, stddev=0.02):
            return truncated_normal_init(next(keys), shape, stddev, dtype)

        params = {
            "tok": w((self.vocab_size, d)),
            "pos": w((self.seq_len, d)),
            "blocks": [],
            "ln_f": {"g": jnp.ones((d,), dtype), "b": jnp.zeros((d,), dtype)},
            "head": {
                "w": w((d, self.vocab_size)),
                "b": jnp.zeros((self.vocab_size,), dtype),
            },
        }
        for _ in range(self.num_blocks):
            if self.moe_experts:
                params["blocks"].append(_moe_block_params(
                    w, d, h, dh, self.mlp_dim, self.moe_experts, dtype))
            else:
                params["blocks"].append(
                    _block_params(w, d, h, dh, self.mlp_dim, dtype))
        return params

    def apply_hidden(self, params, x, *, keep_prob=1.0, rng=None,
                     train: bool = False):
        """Everything up to (but not including) the vocab head: final
        hidden states (B, S, d) after ln_f + dropout. The streamed-CE
        path consumes this directly so the (B, S, V) logits never
        materialize; ``apply`` adds the head on top."""
        return self._hidden_and_aux(params, x, keep_prob=keep_prob,
                                    rng=rng, train=train)[0]

    def _hidden_and_aux(self, params, x, *, keep_prob=1.0, rng=None,
                        train: bool = False):
        """(hidden, moe load-balance loss total) — the aux term is 0.0
        for dense-MLP models; loss_with_metrics adds it to the training
        loss scaled by ``moe_aux``."""
        cd = self.compute_dtype
        # x: integer ids (B, S) — or the LOCAL token block (B, S/P) when
        # called inside the SP shard_map step
        h = jnp.take(params["tok"], x, axis=0)
        pos = params["pos"]
        if self.seq_axis is not None:
            s_local = x.shape[1]
            start = lax.axis_index(self.seq_axis) * s_local
            pos = lax.dynamic_slice_in_dim(pos, start, s_local, axis=0)
        h = h + pos.astype(h.dtype)
        if cd is not None:
            h = h.astype(cd)

        if self.seq_axis is not None:
            attn = lambda q, k, v: ring_attention(
                q, k, v, self.seq_axis, causal=True)
        elif self.attn_block is not None:
            attn = lambda q, k, v: blockwise_attention(
                q, k, v, self.attn_block, causal=True)
        else:
            attn = lambda q, k, v: multi_head_attention(q, k, v, causal=True)

        lb_total = jnp.float32(0.0)
        if self.moe_experts:
            moe_fn = _transformer_block_moe
            if self.remat:
                moe_fn = jax.checkpoint(_transformer_block_moe,
                                        static_argnums=(2, 3, 4, 5))
            for blk in params["blocks"]:
                h, lb = moe_fn(h, blk, attn, cd, self.moe_capacity,
                               self.moe_axis)
                lb_total = lb_total + lb
        else:
            blk_fn = _transformer_block
            if self.remat:
                blk_fn = jax.checkpoint(_transformer_block,
                                        static_argnums=(2, 3))
            for blk in params["blocks"]:
                h = blk_fn(h, blk, attn, cd)

        h = _layernorm(h, params["ln_f"]["g"], params["ln_f"]["b"])
        if rng is not None and self.seq_axis is not None:
            # per-token dropout: decorrelate the mask across sequence
            # shards (each shard holds DIFFERENT tokens — unlike the
            # classifier's post-pool dropout, which must be identical)
            rng = jax.random.fold_in(rng, lax.axis_index(self.seq_axis))
        return (nn.dropout(h, keep_prob, rng, deterministic=not train),
                lb_total)

    def apply(self, params, x, *, keep_prob=1.0, rng=None, train: bool = False):
        h = self.apply_hidden(params, x, keep_prob=keep_prob, rng=rng,
                              train=train)
        logits = nn.dense(h, params["head"]["w"], params["head"]["b"],
                          compute_dtype=self.compute_dtype)
        return logits.astype(jnp.float32)

    @property
    def wants_loss_hook(self) -> bool:
        """True when training/eval must route through
        ``loss_with_metrics`` (training.loss_and_metrics checks this):
        the streamed CE head and/or the MoE auxiliary loss."""
        return bool(self.ce_block or self.moe_experts)

    def loss_with_metrics(self, params, x, y, *, keep_prob=1.0, rng=None,
                          train: bool = False):
        """(loss, metrics) — the train/eval hook. With ``ce_block`` the
        CE is the streamed head (values/grads match apply +
        softmax_cross_entropy to fp tolerance, tests/test_lm.py); with
        ``moe_experts`` the TRAINING loss adds ``moe_aux`` times the
        Switch load-balance term (metrics report it either way; eval
        loss stays the plain CE)."""
        h, lb = self._hidden_and_aux(params, x, keep_prob=keep_prob,
                                     rng=rng, train=train)
        if self.ce_block:
            ce, acc = nn.streamed_softmax_ce_head(
                h, params["head"]["w"], params["head"]["b"], y,
                block=self.ce_block, compute_dtype=self.compute_dtype)
        else:
            logits = nn.dense(h, params["head"]["w"], params["head"]["b"],
                              compute_dtype=self.compute_dtype)
            logits = logits.astype(jnp.float32)
            ce = nn.softmax_cross_entropy(logits, y)
            acc = nn.accuracy(logits, y)
        metrics = {"loss": ce, "accuracy": acc}
        loss = ce
        if self.moe_experts:
            metrics["moe_lb"] = lb
            if train:
                loss = ce + self.moe_aux * lb
        return loss, metrics

    def num_params(self, params=None):
        if params is None:
            params = jax.eval_shape(lambda: self.init(jax.random.key(0)))
        return sum(int(jnp.size(p)) for p in jax.tree.leaves(params))
