"""DTT011 good fixture: every public phase is fact-covered or
exempted with a stated reason."""


def covered_phase() -> dict:
    return {"covered_total": 1}


def uncovered_phase() -> dict:
    return {"uncovered_rate": 2.0}


def bare_exempt_phase() -> dict:
    return {"bare_rate": 3.0}
