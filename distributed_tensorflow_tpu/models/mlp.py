"""Single-hidden-layer MLP — the model the reference's dead flag was for.

The reference defines ``--hidden_units=100`` ("Number of units in the
hidden layer of the NN", ``/root/reference/.idea/MNISTDist.py:26``) and
never reads it — the flag survives from the MLP this script evolved from.
``--model mlp`` makes it live: flatten → dense(hidden_units) + relu →
dropout → dense(num_classes), with the same init family as the CNN
(truncated normal σ=0.1, bias 0.1, ``MNISTDist.py:42-49``).

Same functional contract as the other models (pytree params + pure
``apply``), so every mode — sync DP, device-resident sampling, PS
emulation, checkpointing — works unchanged. No tensor-parallel sharding
rule is registered (a 100-unit hidden layer has nothing worth splitting);
``--model_axis>1`` is rejected loudly by the existing ``has_tp_specs``
gate.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.cnn import (
    constant_init,
    truncated_normal_init,
)
from distributed_tensorflow_tpu.models.registry import register_model
from distributed_tensorflow_tpu.ops import nn


@register_model("mlp")
class MLP:
    def __init__(
        self,
        image_size: int = 28,
        channels: int = 1,
        num_classes: int = 10,
        hidden_units: int = 100,
        compute_dtype: Any = None,
    ):
        self.image_size = image_size
        self.channels = channels
        self.num_classes = num_classes
        self.hidden_units = hidden_units
        self.compute_dtype = compute_dtype
        self.flat_dim = image_size * image_size * channels

    def init(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        return {
            "weights": {
                "h1": truncated_normal_init(
                    k1, (self.flat_dim, self.hidden_units), dtype=dtype),
                "out": truncated_normal_init(
                    k2, (self.hidden_units, self.num_classes), dtype=dtype),
            },
            "biases": {
                "h1": constant_init((self.hidden_units,), dtype=dtype),
                "out": constant_init((self.num_classes,), dtype=dtype),
            },
        }

    def apply(self, params, x, *, keep_prob=1.0, rng=None, train: bool = False):
        w, b = params["weights"], params["biases"]
        cd = self.compute_dtype
        x = nn.normalize_if_u8(x, cd)
        x = x.reshape(-1, self.flat_dim)
        x = jax.nn.relu(nn.dense(x, w["h1"], b["h1"], compute_dtype=cd))
        x = nn.dropout(x, keep_prob, rng, deterministic=not train)
        return nn.dense(x, w["out"], b["out"], compute_dtype=cd)

    def num_params(self, params=None):
        if params is None:
            params = jax.eval_shape(lambda: self.init(jax.random.key(0)))
        return sum(int(jnp.size(p)) for p in jax.tree.leaves(params))
