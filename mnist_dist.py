#!/usr/bin/env python
"""Distributed MNIST training — CLI-compatible TPU-native rebuild.

Drop-in entry point with the reference's exact flag surface
(``/root/reference/.idea/MNISTDist.py:13-31``) and role semantics
(``:93-107``): launch once per task with ``--job_name``/``--task_index``;
``--ps_hosts``/``--worker_hosts`` describe the cluster. What runs underneath
is a TPU-native stack:

  default (no ps_hosts, single worker): synchronous training over all local
    TPU chips — params replicated in HBM, batch split over the "data" mesh
    axis, psum over ICI. One chip degrades gracefully to single-device.
  --ps_hosts set: the reference's asynchronous parameter-server topology,
    emulated with a host-side parameter service — ps tasks serve params
    (the server.join() role, MNISTDist.py:105-106), workers train against
    them with stale-gradient SGD.

Examples:
  python mnist_dist.py                          # sync over local devices
  python mnist_dist.py --training_iter 1000 --optimizer adam
  python mnist_dist.py --job_name=ps --task_index=0 \
      --ps_hosts=localhost:2222 --worker_hosts=localhost:2223,localhost:2224
  python mnist_dist.py --job_name=worker --task_index=0 \
      --ps_hosts=localhost:2222 --worker_hosts=localhost:2223,localhost:2224
"""

import sys

from distributed_tensorflow_tpu import flags
from distributed_tensorflow_tpu.cluster import ClusterSpec, resolve_mode

flags.define_reference_flags()
FLAGS = flags.FLAGS


def main(_):
    from distributed_tensorflow_tpu.utils import faults

    # arm deterministic fault injection (no-op with no --fault_spec /
    # DTT_FAULT_SPEC) before any path that carries injection points runs
    faults.configure_from_flags(FLAGS)
    if FLAGS.eval_only:
        # restore-and-measure, no training, any checkpoint layout — runs
        # before role dispatch so it works regardless of cluster flags
        from distributed_tensorflow_tpu.training.loop import evaluate_only

        evaluate_only(FLAGS)
        return 0
    if FLAGS.prng != "threefry":
        # must land before any PRNG key is created; affects dropout masks
        # and --device_data's on-device batch sampling
        import jax

        jax.config.update("jax_default_prng_impl", FLAGS.prng)
    mode = resolve_mode(FLAGS)

    if mode == "ps":
        cluster = ClusterSpec.from_flags(FLAGS)
        if FLAGS.job_name not in ("ps", "worker"):
            print(f"--job_name must be 'ps' or 'worker' when --ps_hosts is "
                  f"set (got {FLAGS.job_name!r})", file=sys.stderr)
            return 2
        from distributed_tensorflow_tpu.parallel import ps_emulation

        # fail EVERY role fast at dispatch — the run_worker guard alone
        # would leave ps processes blocked in serve_forever() while the
        # workers die at startup
        err = ps_emulation.ps_unsupported_flag_error(FLAGS)
        if err is not None:
            print(err, file=sys.stderr)
            return 2

        if FLAGS.job_name == "ps":
            # reference: server.join() — serve parameters until killed
            ps_emulation.run_parameter_server(cluster, FLAGS)
            return 0
        return ps_emulation.run_worker(cluster, FLAGS)

    from distributed_tensorflow_tpu.cluster import maybe_initialize_distributed
    from distributed_tensorflow_tpu.training.loop import train

    if mode == "sync":
        # multi-host sync DP: join the coordination service BEFORE any jax
        # device use, so every host sees the global mesh. The retry knobs
        # are the crash-restart recovery path: a relaunched worker waits
        # (bounded) for the coordinator to come back instead of dying on
        # the first connection refusal.
        cluster = ClusterSpec.from_flags(FLAGS)
        maybe_initialize_distributed(
            cluster, FLAGS.task_index,
            init_retries=FLAGS.init_retries,
            init_backoff_s=FLAGS.init_backoff_s,
            init_timeout_s=FLAGS.init_timeout_s)

    import jax

    if FLAGS.mode == "auto" and mode == "local" and len(jax.devices()) > 1:
        mode = "sync"  # auto-upgrade: use every local chip
    train(FLAGS, mode=("sync" if mode == "sync" else "local"))
    return 0


if __name__ == "__main__":
    flags.run(main)
