"""Sequence/context parallelism: shard the TOKEN axis over the mesh.

The reference framework predates attention; this is the build's
long-context machinery (meta-goal: sequence parallelism as a first-class
mode). Layout: batch over the "data" axis, sequence over the "model"
axis of the standard ("data", "model") mesh. Params replicate; inside
``shard_map`` every device holds one (batch-slice, token-block) tile,
attention runs as a RING over the sequence axis (one ppermute hop per
step, k/v blocks rotating while queries stay — ops/attention), and the
model mean-pools with a psum so the classifier head sees the full
sequence. Peak per-device activation memory is one token block
regardless of total sequence length — the property that makes long
contexts fit at all.

Gradient reduction is the subtle half: each sequence shard
differentiates its own replicated copy of the loss and the pooled
psum's transpose is itself a psum, so per-token parameter gradients
arrive as their true partials scaled by the axis size P, while the
post-pool head's gradients arrive bitwise-replicated — ONE uniform
pmean over the sequence axis reduces both exactly (mean of P-scaled
partials = the total; mean of replicas = identity). Then pmean over
"data" as in ordinary sync DP, and every device applies the identical
update so the replicated state stays in sync. Exactness vs the dense
single-device step is pinned by tests/test_attention.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from distributed_tensorflow_tpu.training.train_state import (
    TrainState,
    apply_updates,
    compute_grads,
    loss_and_metrics,
)


def stage_batch_sp(mesh, batch):
    """(x, y) host batch -> device arrays with x (B, S, token) tiled
    (batch over "data", tokens over "model") and labels batch-sharded.

    Multi-process: ``batch`` is this process's LOCAL slice of the global
    batch with the FULL token axis (the "model"/sequence axis must stay
    within each host — the loop guards this); slices assemble into one
    global-mesh array via ``make_array_from_process_local_data``, each
    host uploading only to its own chips, exactly like DP/TP staging."""
    from distributed_tensorflow_tpu.parallel.mesh import put_global

    x, y = batch
    return put_global(
        (NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS)),
         NamedSharding(mesh, P(DATA_AXIS))),
        (x, y),
    )


def reshape_for_sp(model, x):
    """Flat (B, F) pixels -> (B, S, token) BEFORE staging, so the token
    axis exists to shard. A host-side numpy view — staging does the one
    upload (a jnp reshape here would bounce the batch host->device->host
    on the hot input path)."""
    import numpy as np

    return np.asarray(x).reshape(-1, model.seq_len, model.token_dim)


def make_sp_train_step(model, optimizer, mesh, keep_prob: float = 1.0,
                       donate: bool = True):
    """Compiled sequence-parallel train step: (state, staged batch) ->
    (state, metrics).

    ``model`` must be constructed with ``seq_axis=MODEL_AXIS`` (it then
    ring-attends and psum-pools over that axis). State (params + opt
    slots) replicates.
    """
    if getattr(model, "seq_axis", None) != MODEL_AXIS:
        raise ValueError(
            f"model.seq_axis must be {MODEL_AXIS!r} for the SP step "
            f"(got {getattr(model, 'seq_axis', None)!r})")

    def per_shard(state: TrainState, batch):
        rng, sub = jax.random.split(state.rng)
        # dropout runs on the REPLICATED post-pool path: the mask must be
        # identical across sequence shards (distinct only per data shard)
        # or the replicated head computation diverges between shards
        sub = jax.random.fold_in(sub, lax.axis_index(DATA_AXIS))

        grads, shard_metrics, model_state = compute_grads(
            model, state.params, batch, keep_prob=keep_prob, rng=sub,
            model_state=state.model_state,
        )
        # ONE uniform pmean over the sequence axis is exact for EVERY
        # parameter: per-token params (embeddings, block weights) carry
        # their true partial contribution scaled by P — each of the P
        # sequence shards differentiates its own replicated copy of the
        # loss, and the pooled psum's transpose is itself a psum,
        # multiplying every pre-pool cotangent by P — so
        # pmean = (1/P) * sum(P * partial_i) = the exact total. Post-pool
        # (head) params see the replicated pooled vector and identical
        # labels/dropout, so their grads are already bitwise-replicated
        # across sequence shards and pmean is the identity.
        # tests/test_attention.py pins the trajectory equivalence.
        grads = lax.pmean(grads, MODEL_AXIS)
        grads = lax.pmean(grads, DATA_AXIS)
        metrics = lax.pmean(shard_metrics, DATA_AXIS)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params, state.step)
        params = apply_updates(state.params, updates)
        return (TrainState(params, opt_state, state.step + 1, rng,
                           model_state), metrics)

    sharded = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), (P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS))),
        out_specs=(P(), P()),
        check_vma=False,  # rng ops + replicated-out pattern
    )
    if donate:
        return jax.jit(sharded, donate_argnums=(0,))
    return jax.jit(sharded)


def make_sp_eval_step(model, mesh):
    """Dropout-off metrics over the SP layout, pmean'd over "data".

    Accepts (and ignores) a trailing ``model_state`` so the training
    loop can call every mode's eval step with one signature (the
    transformer is stateless)."""
    def per_shard(params, batch):
        _, aux = loss_and_metrics(model, params, batch, train=False)
        return lax.pmean(aux["metrics"], DATA_AXIS)

    sharded = jax.jit(jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), (P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS))),
        out_specs=P(),
        check_vma=False,
    ))

    def eval_step(params, batch, model_state=()):
        return sharded(params, batch)

    return eval_step
