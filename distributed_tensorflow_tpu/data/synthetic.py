"""Procedural offline datasets for egress-free environments.

When ``--data_dir`` holds no IDX files (the reference would try to download,
``MNISTDist.py:167``; this build cannot assume network access), we fall back
to a deterministic, *learnable* procedural digit dataset: digits rendered
from a 5×7 bitmap font at random sub-pixel offsets with noise and contrast
jitter. A small CNN reaches >99% on it quickly, which keeps convergence
tests, demos and benchmarks meaningful without network access. Every array
is a pure function of the seed.
"""

from __future__ import annotations

import numpy as np

# 5x7 bitmap font, digits 0-9 (rows of 5 bits, MSB = leftmost pixel)
_FONT = {
    0: ["01110", "10001", "10011", "10101", "11001", "10001", "01110"],
    1: ["00100", "01100", "00100", "00100", "00100", "00100", "01110"],
    2: ["01110", "10001", "00001", "00010", "00100", "01000", "11111"],
    3: ["11111", "00010", "00100", "00010", "00001", "10001", "01110"],
    4: ["00010", "00110", "01010", "10010", "11111", "00010", "00010"],
    5: ["11111", "10000", "11110", "00001", "00001", "10001", "01110"],
    6: ["00110", "01000", "10000", "11110", "10001", "10001", "01110"],
    7: ["11111", "00001", "00010", "00100", "01000", "01000", "01000"],
    8: ["01110", "10001", "10001", "01110", "10001", "10001", "01110"],
    9: ["01110", "10001", "10001", "01111", "00001", "00010", "01100"],
}


def _glyph(digit: int) -> np.ndarray:
    rows = _FONT[digit]
    return np.array([[int(c) for c in r] for r in rows], dtype=np.float32)


def _render(digit: int, rng: np.random.Generator, size: int = 28) -> np.ndarray:
    """Render one digit: upscale glyph ~3x, random placement, blur-ish noise."""
    g = _glyph(digit)
    scale = rng.integers(2, 4)  # 2x or 3x upscaling
    g = np.kron(g, np.ones((scale, scale), dtype=np.float32))
    h, w = g.shape
    img = np.zeros((size, size), dtype=np.float32)
    oy = rng.integers(0, size - h + 1)
    ox = rng.integers(0, size - w + 1)
    img[oy : oy + h, ox : ox + w] = g
    # cheap separable blur for stroke softness
    k = np.array([0.25, 0.5, 0.25], dtype=np.float32)
    img = np.apply_along_axis(lambda r: np.convolve(r, k, mode="same"), 1, img)
    img = np.apply_along_axis(lambda c: np.convolve(c, k, mode="same"), 0, img)
    contrast = 0.7 + 0.3 * rng.random()
    img = np.clip(img * contrast + rng.normal(0, 0.05, img.shape), 0.0, 1.0)
    return img


def synthetic_digits(
    num: int, seed: int = 0, size: int = 28, num_classes: int = 10
) -> tuple[np.ndarray, np.ndarray]:
    """Returns (images [num, size*size] float32 in [0,1], labels [num] int64)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=num)
    images = np.stack([_render(int(d) % 10, rng, size) for d in labels])
    return images.reshape(num, size * size).astype(np.float32), labels.astype(np.int64)


def synthetic_cifar(
    num: int, seed: int = 0, size: int = 32, num_classes: int = 10
) -> tuple[np.ndarray, np.ndarray]:
    """Class-conditional colored texture dataset, [num, size, size, 3] in [0,1].

    Each class is a fixed random 4×4×3 texture tiled up with noise — enough
    structure for a ResNet to learn, fully offline and deterministic.
    """
    rng = np.random.default_rng(seed)
    tex_rng = np.random.default_rng(12345)  # class textures independent of split seed
    textures = tex_rng.random((num_classes, 4, 4, 3)).astype(np.float32)
    labels = rng.integers(0, num_classes, size=num)
    reps = size // 4
    imgs = np.empty((num, size, size, 3), dtype=np.float32)
    for i, lab in enumerate(labels):
        base = np.tile(textures[lab], (reps, reps, 1))
        shift = rng.integers(0, 4, size=2)
        base = np.roll(base, tuple(shift), axis=(0, 1))
        imgs[i] = np.clip(base + rng.normal(0, 0.15, base.shape), 0.0, 1.0)
    return imgs, labels.astype(np.int64)
