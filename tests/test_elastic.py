"""Elastic, preemption-tolerant training (r15).

The tentpole under test: a membership change — a spot preemption
modeled by the ``preempt`` fault point — becomes a planned, accounted,
bitwise-safe resize. The rescale matrix pins post-resize trajectories
BITWISE against a fresh run restored at the target shape (resize IS a
cross-topology restore); the chaos test kills and re-adds a worker
mid-run and pins final params against an un-preempted reference; the
accounting tests pin the ``resize_s`` goodput charge and the
``membership_change``/``resize`` spans end to end.
"""

import glob
import json
import os
import sys

import jax
import numpy as np
import pytest

from distributed_tensorflow_tpu import cluster, flags
from distributed_tensorflow_tpu.checkpoint import (
    latest_checkpoint,
    restore_latest,
)
from distributed_tensorflow_tpu.checkpoint.checkpoint import save_checkpoint
from distributed_tensorflow_tpu.models import get_model
from distributed_tensorflow_tpu.training import (
    create_train_state,
    get_optimizer,
)
from distributed_tensorflow_tpu.training import elastic
from distributed_tensorflow_tpu.training.loop import train
from distributed_tensorflow_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_slate():
    """Every test starts and ends with no fault rules, the full world
    at epoch 0, and no pending elastic state — nothing leaks between
    tests (or into other files' tests)."""
    faults.reset()
    cluster.reset_membership()
    elastic._PENDING["resize"] = None
    elastic._PENDING["joins"] = []
    elastic._PENDING["handled"] = set()
    yield
    faults.reset()
    cluster.reset_membership()
    elastic._PENDING["resize"] = None
    elastic._PENDING["joins"] = []
    elastic._PENDING["handled"] = set()
    flags.FLAGS._reset()


# --------------------------------------------------- preempt fault point


def test_preempt_spec_parses_the_documented_forms():
    rules = faults.parse_fault_spec(
        "preempt:at_step=60:mode=notice:notice_s=30:host=3,"
        "preempt:mode=immediate:host=2:rejoin_steps=40")
    assert rules[0].mode == "notice" and rules[0].at_step == 60
    assert rules[0].notice_s == 30.0 and rules[0].host == 3
    assert rules[1].mode == "immediate" and rules[1].rejoin_steps == 40


@pytest.mark.parametrize("bad,match", [
    ("restore:notice_s=3", "only applies to the preempt point"),
    ("ckpt_write:host=1", "only applies to the preempt point"),
    ("restore:mode=notice", "only applies to the preempt point"),
    ("preempt:mode=torn_file", "names no file"),
    ("preempt:notice_s=-1", "must be >= 0"),
    ("preempt:rejoin_steps=-2", "must be >= 0"),
    ("preempt:host=-1", "must be >= 0"),
])
def test_preempt_grammar_mistakes_are_named(bad, match):
    with pytest.raises(faults.FaultSpecError, match=match):
        faults.parse_fault_spec(bad)


def test_preempt_point_registered_and_described():
    assert "preempt" in faults.INJECTION_POINTS
    text = faults.describe_points()
    assert "preempt" in text and "rejoin_steps" in text


def test_preempt_mode_raises_typed_signal():
    faults.configure("preempt:at_step=5:mode=notice:notice_s=7:host=2")
    faults.fault_point("preempt", step=4)  # filter: no fire
    with pytest.raises(faults.Preempted) as ei:
        faults.fault_point("preempt", step=5)
    assert ei.value.host == 2 and ei.value.notice_s == 7.0
    assert not ei.value.immediate


def test_armed_points_sees_env_rules(monkeypatch):
    monkeypatch.setenv("DTT_FAULT_SPEC", "preempt:mode=immediate")
    faults.reset()
    assert "preempt" in faults.armed_points()


# ------------------------------------------------------ flag validation


@pytest.mark.parametrize("argv,match", [
    (["--world_size=-1"], "--world_size"),
    (["--elastic", "--ps_hosts=a:1,b:2"], "ps"),
    (["--fault_spec=preempt:mode=notice", "--mode=ps"], "ps"),
    (["--fault_spec=preempt:frequency=2"], "--fault_spec"),
])
def test_elastic_flag_validation(argv, match):
    flags.define_reference_flags()
    flags.FLAGS._reset()
    with pytest.raises(ValueError, match=match):
        flags.FLAGS._parse(argv)


def test_elastic_flag_surface_parses_clean():
    flags.define_reference_flags()
    for ok in (["--elastic"], ["--world_size=4"],
               ["--fault_spec=preempt:at_step=9:mode=notice:host=1"
                ":rejoin_steps=5"]):
        flags.FLAGS._reset()
        flags.FLAGS._parse(ok)


# -------------------------------------------------- cluster membership


def test_set_world_filters_active_devices():
    assert len(cluster.active_devices()) == 8  # full world by default
    cluster.set_world((0, 2, 5), epoch=0)
    devs = cluster.active_devices()
    assert [d.id for d in devs] == [0, 2, 5]
    cluster.reset_membership()
    assert len(cluster.active_devices()) == 8


def test_world_size_beyond_host_is_loud():
    cluster.set_world(range(16), epoch=0)
    with pytest.raises(ValueError, match="exceed"):
        cluster.active_devices()


def test_empty_world_refused():
    with pytest.raises(ValueError, match="empty the world"):
        cluster.set_world(())


def test_epoch_advances_by_default():
    cluster.set_world((0, 1), epoch=0)
    assert cluster.membership_epoch() == 0
    assert cluster.set_world((0,)) == 1
    assert cluster.membership_epoch() == 1


def test_make_mesh_covers_the_elastic_world():
    from distributed_tensorflow_tpu.parallel import make_mesh

    cluster.set_world((0, 1, 2, 3), epoch=0)
    mesh = make_mesh()
    assert mesh.devices.size == 4
    cluster.reset_membership()
    assert make_mesh().devices.size == 8


def test_epoch_coordinator_namespaces_the_port():
    assert cluster._epoch_coordinator("10.0.0.1:1234", 0) == \
        "10.0.0.1:1234"
    assert cluster._epoch_coordinator("10.0.0.1:1234", 3) == \
        "10.0.0.1:1237"


def test_init_retry_messages_name_the_epoch(capsys):
    """The satellite: re-initialization after a resize cannot race a
    stale peer (the coordinator is epoch-namespaced) and the retry/
    backoff lines name the epoch."""
    from distributed_tensorflow_tpu.cluster import (
        ClusterSpec,
        maybe_initialize_distributed,
    )

    faults.configure("init:mode=refuse:times=0")  # never let it connect
    spec = ClusterSpec({"ps": [], "worker": ["127.0.0.1:3000",
                                             "127.0.0.1:3001"]})
    with pytest.raises(faults.InjectedFault):
        maybe_initialize_distributed(spec, 0, init_retries=1,
                                     init_backoff_s=0.0,
                                     membership_epoch=2)
    out = capsys.readouterr().out
    assert "[membership epoch 2]" in out
    assert "127.0.0.1:3002" in out  # port 3000 + epoch 2


# ------------------------------------------------- drain via managed()


def _tiny_state():
    model = get_model("mlp", image_size=28, channels=1, num_classes=10,
                      hidden_units=16)
    return create_train_state(model, get_optimizer("sgd", 0.01), seed=0)


def _change(lost=False):
    return elastic.MembershipChange(kind="depart", hosts=(1,), step=5,
                                    epoch=1, lost_step=lost)


def test_resize_drain_is_the_managed_exit_save(tmp_path):
    """A ResizeRequired unwinding through managed() is a CLEAN exit:
    the final save IS the drain checkpoint, at the agreed step."""
    from distributed_tensorflow_tpu.training.supervisor import Supervisor

    sv = Supervisor(is_chief=True, logdir=str(tmp_path),
                    save_model_secs=10**6)
    state = _tiny_state()
    with pytest.raises(elastic.ResizeRequired):
        with sv.managed(state) as box:
            box.update(state, 5)
            raise elastic.ResizeRequired(_change(), (0, 1), (0,), 5)
    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 5


def test_lost_step_preemption_skips_the_drain_save(tmp_path):
    """mode=immediate: the step died with the capacity — NO drain save;
    the re-form restores the newest cadenced checkpoint instead."""
    from distributed_tensorflow_tpu.training.supervisor import Supervisor

    sv = Supervisor(is_chief=True, logdir=str(tmp_path),
                    save_model_secs=10**6)
    state = _tiny_state()
    with pytest.raises(elastic.ResizeRequired):
        with sv.managed(state) as box:
            box.update(state, 5)
            raise elastic.ResizeRequired(_change(lost=True), (0, 1),
                                         (0,), 5)
    assert latest_checkpoint(str(tmp_path)) is None


def test_adopt_sentinel_snapshot(tmp_path):
    d = str(tmp_path)
    state = {"params": {"w": np.arange(8.0, dtype=np.float32)},
             "step": np.int64(0)}
    # nothing to adopt without a sentinel dir
    assert elastic.adopt_sentinel_snapshot(d) is None
    save_checkpoint(d, dict(state, step=np.int64(8)), 8)
    save_checkpoint(os.path.join(d, "sentinel"),
                    dict(state, step=np.int64(10)), 10)
    assert elastic.adopt_sentinel_snapshot(d) == 10
    assert latest_checkpoint(d)[1] == 10
    # idempotent: the main dir is now at least as new
    assert elastic.adopt_sentinel_snapshot(d) is None
    # an OLDER sentinel is never adopted
    save_checkpoint(d, dict(state, step=np.int64(20)), 20)
    assert elastic.adopt_sentinel_snapshot(d) is None


# -------------------------------------------------- goodput accounting


def test_resize_s_scalar_always_present():
    from distributed_tensorflow_tpu.utils.efficiency import GoodputMeter

    g = GoodputMeter()
    assert g.scalars()["resize_s"] == 0.0
    g.charge(2.5, "resize")
    g.charge(0.5, "ckpt")
    assert g.scalars()["resize_s"] == 2.5


# ------------------------------------------- multi-host vote agreement


def _mh_supervisor(proc, n=2):
    es = elastic.ElasticSupervisor()
    es._n_procs = n
    es._proc = proc
    es._default_world = n
    return es


def test_vote_departure_bit_and_agreement():
    """The departing process announces via its bit; on_vote installs
    the SAME change on every process — the survivor resizes, the
    departed process leaves."""
    faults.configure("preempt:mode=notice")
    dep = _mh_supervisor(1)
    assert dep.poll(10) is False  # announced, not yet agreed
    assert dep.local_departure_bit() == 1
    srv = _mh_supervisor(0)
    assert srv.local_departure_bit() == 0
    bits = [0, 1]  # the gathered column, identical everywhere
    for es in (srv, dep):
        es.on_vote(bits, 10)
        assert es.poll(10) is True
    with pytest.raises(elastic.ResizeRequired) as ei:
        srv.maybe_resize(10)
    assert ei.value.new_world == (0,)
    assert ei.value.change.epoch == 1
    with pytest.raises(elastic.Departed):
        dep.maybe_resize(10)


def test_vote_code_carries_lost_step_and_rejoin():
    """An immediate preemption with a re-join schedule survives the
    vote: the departure code encodes both, so every survivor installs
    the change with the detecting process's full semantics."""
    faults.configure("preempt:mode=immediate:rejoin_steps=5")
    dep = _mh_supervisor(1)
    assert dep.poll(10) is False
    code = dep.local_departure_bit()
    assert code & 1 and code & 2 and code >> 2 == 5
    srv = _mh_supervisor(0)
    srv.on_vote([0, code], 10)
    with pytest.raises(elastic.ResizeRequired) as ei:
        srv.maybe_resize(10)
    ch = ei.value.change
    assert ch.lost_step is True
    assert ch.rejoins == ((1, 5),)


def test_vote_ranks_map_to_member_ids_after_a_resize():
    """Vote rows are CURRENT process ranks; after a resize they must
    map through the sorted world to stable member ids — rank 1 of a
    (0, 2) world is member 2, not member 1."""
    cluster.set_world((0, 2), epoch=1)
    srv = _mh_supervisor(0, n=2)
    srv.on_vote([0, 1], 20)
    with pytest.raises(elastic.ResizeRequired) as ei:
        srv.maybe_resize(20)
    assert ei.value.change.hosts == (2,)
    assert ei.value.new_world == (0,)
    assert ei.value.change.epoch == 2


def test_each_preempt_rule_departs_once_per_run():
    """Loop re-entry re-arms the fault rules (their fired counters
    reset); the handled-departure registry keeps a no-at_step rule
    with rejoin_steps from re-firing after its host re-joins — one
    kill-and-re-add cycle, not endless churn."""
    cluster.set_world((0, 1, 2, 3), epoch=0)
    spec = "preempt:mode=notice:host=2:rejoin_steps=4"
    faults.configure(spec)
    es = elastic.ElasticSupervisor()
    assert es.poll(5) is True
    with pytest.raises(elastic.ResizeRequired) as ei:
        es.maybe_resize(5)
    cluster.set_world(ei.value.new_world, epoch=1)
    elastic._PENDING["joins"] = [(9, 2)]
    faults.configure(spec)  # the resize re-entry re-arms the rule
    es = elastic.ElasticSupervisor()
    assert es.poll(9) is True  # the scheduled re-join, NOT a re-fire
    with pytest.raises(elastic.ResizeRequired) as ei:
        es.maybe_resize(9)
    assert ei.value.change.kind == "join"
    cluster.set_world(ei.value.new_world, epoch=2)
    faults.configure(spec)  # the join re-entry re-arms it again
    es = elastic.ElasticSupervisor()
    # host 2 is back in the world, but this rule identity already ran
    assert es.poll(10) is False
    assert cluster.world_hosts(4) == (0, 1, 2, 3)


def test_departed_is_a_clean_managed_exit(tmp_path):
    """The preempted process leaves at the AGREED boundary: its exit
    must count as clean (chief-side: the final save still lands), or
    cross-host-sharded survivors would vote the drain save away."""
    from distributed_tensorflow_tpu.training.supervisor import Supervisor

    sv = Supervisor(is_chief=True, logdir=str(tmp_path),
                    save_model_secs=10**6)
    state = _tiny_state()
    with pytest.raises(elastic.Departed):
        with sv.managed(state) as box:
            box.update(state, 7)
            raise elastic.Departed(7)
    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 7


# --------------------------------------------------- the rescale matrix


def _assert_trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _parse(args):
    flags.define_reference_flags()
    flags.FLAGS._reset()
    flags.FLAGS._parse(args)
    return flags.FLAGS


def _args(tmp, logdir, iters, world, zero, extra=()):
    return [f"--logdir={logdir}", f"--data_dir={tmp}/none",
            "--model=mlp", "--batch_size=24", f"--training_iter={iters}",
            "--display_step=3", "--device_data", "--device_chunk=3",
            "--test_eval=false", f"--world_size={world}",
            f"--zero={zero}", "--save_model_secs=100000",
            "--optimizer=adam", *extra]


def _final_state(logdir, step_want):
    model = get_model("mlp", image_size=28, channels=1, num_classes=10,
                      hidden_units=100)
    tmpl = create_train_state(model, get_optimizer("adam", 0.001), seed=0)
    got, step = restore_latest(logdir, tmpl)
    assert step == step_want
    return got


# tier-1 time budget: the suite is killed at 870 s, so only ONE matrix
# cell runs fast (zero=1 — it exercises the ZeRO loop AND the elastic
# path; the plain-DP loop is covered fast by the immediate test below);
# the other cells and the grow/chaos scenarios ride the slow lane
@pytest.mark.parametrize("zero", [
    pytest.param(0, marks=pytest.mark.slow),
    1,
    pytest.param(3, marks=pytest.mark.slow),
])
def test_rescale_matrix_shrink_bitwise(tmp_path, zero):
    """D=4 -> 2 at a drained boundary, zero in {0,1,3}: the post-resize
    trajectory is BITWISE the one a fresh run restored at the target
    shape takes — resize is a cross-topology restore, not a migration.
    (--device_data makes the trajectory a pure function of the
    checkpointed state, so bitwise equality is well-defined.)"""
    tmp = str(tmp_path)
    spec = ("preempt:at_step=6:mode=notice:notice_s=5:host=3,"
            "preempt:at_step=6:mode=notice:host=2")
    res = train(_parse(_args(tmp, f"{tmp}/a", 12, 4, zero,
                             (f"--fault_spec={spec}",))), mode="sync")
    assert res.final_step == 12 and res.n_chips == 2
    faults.reset()
    # the un-preempted reference: a clean run at D=4 to the drain step,
    # then a clean run RESTORED at the target shape to the end
    res = train(_parse(_args(tmp, f"{tmp}/b", 6, 4, zero)), mode="sync")
    assert res.final_step == 6 and res.n_chips == 4
    res = train(_parse(_args(tmp, f"{tmp}/b", 12, 2, zero)), mode="sync")
    assert res.final_step == 12 and res.n_chips == 2

    got_a = _final_state(f"{tmp}/a", 12)
    got_b = _final_state(f"{tmp}/b", 12)
    _assert_trees_equal(got_b.params, got_a.params)
    _assert_trees_equal(got_b.opt_state, got_a.opt_state)


def test_join_change_grows_the_world_unit():
    """The join half of poll/maybe_resize without a training run: a
    scheduled re-join becomes a due change at its step and the resize
    grows the world (the trained twin is the slow grow test below)."""
    cluster.set_world((0, 1), epoch=1)
    elastic._PENDING["joins"] = [(9, 2), (9, 3), (20, 4)]
    es = elastic.ElasticSupervisor()
    assert es.poll(8) is False
    assert es.poll(9) is True
    with pytest.raises(elastic.ResizeRequired) as ei:
        es.maybe_resize(9)
    assert ei.value.change.kind == "join"
    assert ei.value.new_world == (0, 1, 2, 3)
    assert ei.value.change.epoch == 2
    assert elastic._PENDING["joins"] == [(20, 4)]  # not yet due


@pytest.mark.slow
def test_rescale_grow_via_rejoin_bitwise(tmp_path):
    """D=2 -> 4: the re-add direction. The world starts at 2 members
    of a 4-slot launch, two preempted hosts re-join mid-run, and the
    grown trajectory pins bitwise against a fresh run restored at 4."""
    tmp = str(tmp_path)
    # depart hosts 2,3 at step 3, both re-join 3 steps after the drain:
    # world 4 (0..3), 2 (3..6), 4 (6..12)
    spec = ("preempt:at_step=3:mode=notice:host=3:rejoin_steps=3,"
            "preempt:at_step=3:mode=notice:host=2:rejoin_steps=3")
    res = train(_parse(_args(tmp, f"{tmp}/a", 12, 4, 0,
                             (f"--fault_spec={spec}",))), mode="sync")
    assert res.final_step == 12 and res.n_chips == 4
    faults.reset()
    res = train(_parse(_args(tmp, f"{tmp}/b", 3, 4, 0)), mode="sync")
    assert res.final_step == 3
    res = train(_parse(_args(tmp, f"{tmp}/b", 6, 2, 0)), mode="sync")
    assert res.final_step == 6
    res = train(_parse(_args(tmp, f"{tmp}/b", 12, 4, 0)), mode="sync")
    assert res.final_step == 12

    got_a = _final_state(f"{tmp}/a", 12)
    got_b = _final_state(f"{tmp}/b", 12)
    _assert_trees_equal(got_b.params, got_a.params)
    _assert_trees_equal(got_b.opt_state, got_a.opt_state)


def test_immediate_preemption_loses_the_step_and_recovers(tmp_path):
    """mode=immediate with no checkpoint on disk: the in-flight
    progress is genuinely lost — the re-formed world starts from
    scratch at the new size and lands bitwise on a clean run at that
    shape (the honest lost-step semantics, end to end)."""
    tmp = str(tmp_path)
    spec = "preempt:at_step=6:mode=immediate:host=1"
    res = train(_parse(_args(tmp, f"{tmp}/a", 9, 2, 0,
                             (f"--fault_spec={spec}",))), mode="sync")
    assert res.final_step == 9 and res.n_chips == 1
    faults.reset()
    res = train(_parse(_args(tmp, f"{tmp}/b", 9, 1, 0)), mode="sync")
    assert res.final_step == 9

    got_a = _final_state(f"{tmp}/a", 9)
    got_b = _final_state(f"{tmp}/b", 9)
    _assert_trees_equal(got_b.params, got_a.params)


# ----------------------------------------------------- the chaos test


@pytest.mark.slow
def test_chaos_kill_and_readd_worker_bitwise_with_accounting(tmp_path):
    """THE acceptance scenario: a run preempted at D=4 drains at the
    next boundary, re-forms at D=2, later re-adds the lost capacity
    back to D=4, and its final params are bitwise equal to an
    un-preempted reference; the resize downtime lands as a named
    resize_s charge in the goodput ledger, and membership_change/
    resize spans ride the span sink AND the flight recorder."""
    from distributed_tensorflow_tpu.utils import telemetry

    tmp = str(tmp_path)
    spec = ("preempt:at_step=4:mode=notice:notice_s=30:host=3"
            ":rejoin_steps=4,"
            "preempt:at_step=4:mode=notice:host=2:rejoin_steps=4")
    extra = (f"--fault_spec={spec}", "--display_step=2",
             "--device_chunk=2")
    res = train(_parse(_args(tmp, f"{tmp}/a", 16, 4, 0, extra)),
                mode="sync")
    assert res.final_step == 16 and res.n_chips == 4
    # the flight recorder's ring holds the membership story; a dump
    # (what any crash/watchdog/atexit path writes) must surface it
    fr_path = telemetry.flight_recorder().dump("chaos-test")
    faults.reset()

    # un-preempted reference: the same world schedule as three clean
    # runs (4 to the drain, 2 to the re-join, 4 to the end)
    res = train(_parse(_args(tmp, f"{tmp}/b", 4, 4, 0,
                             ("--display_step=2", "--device_chunk=2"))),
                mode="sync")
    assert res.final_step == 4
    res = train(_parse(_args(tmp, f"{tmp}/b", 8, 2, 0,
                             ("--display_step=2", "--device_chunk=2"))),
                mode="sync")
    assert res.final_step == 8
    res = train(_parse(_args(tmp, f"{tmp}/b", 16, 4, 0,
                             ("--display_step=2", "--device_chunk=2"))),
                mode="sync")
    assert res.final_step == 16

    got_a = _final_state(f"{tmp}/a", 16)
    got_b = _final_state(f"{tmp}/b", 16)
    _assert_trees_equal(got_b.params, got_a.params)
    _assert_trees_equal(got_b.opt_state, got_a.opt_state)

    # --- accounting: the named resize_s charge in the goodput ledger
    lines = [json.loads(l) for l in open(f"{tmp}/a/metrics.jsonl")]
    resize_vals = [l["resize_s"] for l in lines if "resize_s" in l]
    assert resize_vals and max(resize_vals) > 0.0
    epochs = [l["membership_epoch"] for l in lines
              if "membership_epoch" in l]
    assert epochs and max(epochs) == 2.0  # depart epoch 1, re-join 2

    # --- the spans: membership_change at each change, resize on each
    # re-formed loop's first boundary
    span_file = glob.glob(f"{tmp}/a/spans-*.jsonl")[0]
    recs = [json.loads(l) for l in open(span_file)]
    changes = [r for r in recs if r.get("name") == "membership_change"]
    assert {c["change"] for c in changes} == {"depart", "join"}
    resizes = [r for r in recs if r.get("name") == "resize"]
    assert len(resizes) == 2
    assert all(r["resize_s"] > 0 for r in resizes)

    # --- the flight recorder holds the membership_change span too
    assert fr_path is not None
    fr = open(fr_path).read()
    assert "membership_change" in fr


# ------------------------------------------------------- fleet report


def test_fleet_report_surfaces_resize_column(tmp_path):
    sys.path.insert(0, REPO)
    from tools.fleet_report import analyze, print_report

    p = tmp_path / "spans-worker-0.jsonl"
    recs = [
        {"name": "train_step", "ts": 1.0, "dur_s": 0.01, "step": 1,
         "host": "worker-0"},
        {"name": "membership_change", "ts": 2.0, "dur_s": 0.0,
         "change": "depart", "epoch": 1, "host": "worker-0"},
        {"name": "resize", "ts": 3.0, "dur_s": 0.0, "resize_s": 1.25,
         "epoch": 1, "host": "worker-0"},
        {"name": "resize", "ts": 9.0, "dur_s": 0.0, "resize_s": 0.75,
         "epoch": 2, "host": "worker-0"},
    ]
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    report = analyze([str(p)])
    h = report["hosts"]["worker-0"]
    assert h["resize_s"] == 2.0
    assert h["membership_changes"] == 1
    import io

    buf = io.StringIO()
    print_report(report, out=buf)
    assert "resize_s" in buf.getvalue()
    assert "2.00" in buf.getvalue()


# ------------------------------------------------------- bench fields


def test_bench_elastic_phase_nonnull():
    import bench

    out = bench.elastic_phase()
    assert out.get("elastic_error") is None, out
    assert out["elastic_world"] == "2->1"
    assert out["elastic_epoch"] == 1
    assert out["elastic_drain_steps"] == 2
    # the adopted sentinel snapshot (step 10) landed torn, so the
    # ladder walked back to the last cadenced checkpoint (step 8)
    assert out["elastic_restore_step"] == 8
    assert out["elastic_restore_fallback_depth"] == 1
    assert out["elastic_resize_s"] is not None


def test_bench_degraded_record_keeps_elastic_fields():
    import bench

    rec = bench.degraded_record("forced outage", {"attempts": 1},
                                cpu_smoke=False)
    assert rec["elastic_world"] == "2->1"
    assert rec["elastic_restore_fallback_depth"] == 1
    assert rec["elastic_resize_s"] is not None
