"""Tiny model registry so `--model` can select architectures by name."""

from __future__ import annotations

_REGISTRY: dict[str, type] = {}


def register_model(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        return cls

    return deco


def get_model(name: str, **kwargs):
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(_REGISTRY)}"
        ) from None
    return cls(**kwargs)


def available_models():
    return sorted(_REGISTRY)
