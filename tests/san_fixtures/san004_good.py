"""SAN004 good fixture: the same shapes done right — a fresh stop
Event per start(), a maxlen-bounded ring, a daemon thread."""
import threading
from collections import deque


class Restartable:
    def __init__(self):
        self._stop = threading.Event()
        self._ring: deque = deque(maxlen=256)
        self._thread = None
        self._lock = threading.Lock()

    def start(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop = threading.Event()  # fresh per start
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()

    def _loop(self):
        while not self._stop.wait(0.1):
            with self._lock:
                self._ring.append(1)

    def close(self):
        self._stop.set()


def launch(job):
    t = threading.Thread(target=job_runner, daemon=True)
    t.start()


def job_runner():
    pass
