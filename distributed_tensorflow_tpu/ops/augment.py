"""On-device data augmentation, compiled into the train step.

The reference has no augmentation (raw MNIST batches straight into the
feed_dict, ``MNISTDist.py:178-188``); host-side augmentation is also the
classic input-pipeline bottleneck. The TPU-native design runs it INSIDE
the compiled step — a PRNG key in, pure array ops out, fused by XLA with
the first conv — so it is free of host cost, works identically in the
host-fed and device-resident (``--device_data``) modes, and each data
shard draws independent augmentations from its own key stream.

The transform is the standard CIFAR recipe: zero-pad by ``pad``, random
crop back to the original size, random horizontal flip — applied
per-example via one gather (no ``vmap`` of ``dynamic_slice``, which XLA
would turn into a serial loop on TPU).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def random_crop_flip(images, rng, *, pad: int = 4, flip: bool = True):
    """Per-example random crop (after zero-padding) + horizontal flip.

    ``images``: [B, H, W, C], any real dtype (uint8 passes through
    unchanged in dtype). Returns the same shape/dtype.
    """
    b, h, w, c = images.shape
    kc, kf = jax.random.split(rng)
    padded = jnp.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)))

    # per-example crop offsets in [0, 2*pad]
    off = jax.random.randint(kc, (b, 2), 0, 2 * pad + 1)
    rows = off[:, 0, None] + jnp.arange(h)[None, :]  # [B, H]
    cols = off[:, 1, None] + jnp.arange(w)[None, :]  # [B, W]
    # advanced-index gather: out[b,i,j,:] = padded[b, rows[b,i], cols[b,j], :]
    bidx = jnp.arange(b)[:, None, None]
    out = padded[bidx, rows[:, :, None], cols[:, None, :]]

    if flip:
        do = jax.random.bernoulli(kf, 0.5, (b,))
        out = jnp.where(do[:, None, None, None], out[:, :, ::-1, :], out)
    return out


def make_augment(meta: dict, *, pad: int = 4, flip: bool = True):
    """(flat_or_nhwc_batch_images, rng) -> augmented, same layout.

    Models in this framework take flattened [B, H*W*C] pixels
    (``MNISTDist.py:68`` reshapes on entry); the augmenter restores the
    image geometry from the dataset ``meta``, transforms, and re-flattens
    so it drops in front of any model unchanged."""
    h = w = meta["image_size"]
    c = meta["channels"]

    def augment(x, rng):
        flat = x.ndim == 2
        imgs = x.reshape(-1, h, w, c) if flat else x
        imgs = random_crop_flip(imgs, rng, pad=pad, flip=flip)
        return imgs.reshape(x.shape[0], -1) if flat else imgs

    return augment
