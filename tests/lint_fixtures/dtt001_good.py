"""DTT001 conforming fixture: mesh constants and forwarded parameters."""

from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_tensorflow_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def step(x):
    return lax.psum(x, DATA_AXIS)


def fwd(x, axis_name):
    return lax.psum(x, axis_name)  # forwarded parameter


def specs(mesh, arr):
    return P(DATA_AXIS, None), Mesh(arr, (DATA_AXIS, MODEL_AXIS))
