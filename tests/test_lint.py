"""dttlint — the AST invariant linter (tools/dttlint/).

Three layers: (1) per-rule fixture pairs — one minimal violating
snippet, one conforming — under tests/lint_fixtures/; (2) the
REPO-WIDE run: zero non-baselined findings with the checked-in
baseline, and stale suppressions fail loudly; (3) the CLI surface
(--json, exit codes, the DTT001 --fix rewrite)."""

import json
import os
import shutil
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.dttlint import run_lint  # noqa: E402
from tools.dttlint.__main__ import apply_dtt001_fixes  # noqa: E402
from tools.dttlint.rules import (  # noqa: E402
    ALL_RULES,
    rule_collective_axis,
    rule_donation_safety,
    rule_fault_registry,
    rule_flag_validator,
    rule_inventory_coverage,
    rule_ledger_coverage,
    rule_perf_coverage,
    rule_scalar_contract,
    rule_span_taxonomy,
    rule_trace_purity,
    rule_traced_coverage,
)

FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")


_EMPTY_BASELINE = os.path.join(FIXTURES, "empty_baseline.json")


def _lint(rule, root, *targets):
    return run_lint(os.path.join(FIXTURES, root) if root else FIXTURES,
                    baseline_path=_EMPTY_BASELINE, rules=[rule],
                    targets=targets)


@pytest.fixture(scope="module", autouse=True)
def empty_baseline():
    with open(_EMPTY_BASELINE, "w") as f:
        json.dump({"version": 1, "entries": []}, f)
    yield
    os.remove(_EMPTY_BASELINE)


# ---------------------------------------------------- per-rule fixtures

# (rule, fixture root under lint_fixtures/ or "" for flat, bad targets,
#  good targets, expected rule id, minimum bad findings)
FIXTURE_MATRIX = [
    (rule_collective_axis, "", ("dtt001_bad.py",), ("dtt001_good.py",),
     "DTT001", 4),
    (rule_ledger_coverage, "dtt002", ("parallel/bad_mod.py",),
     ("parallel/good_mod.py",), "DTT002", 1),
    (rule_scalar_contract, "", ("dtt003_bad.py",), ("dtt003_good.py",),
     "DTT003", 3),
    (rule_fault_registry, "", ("dtt004_bad.py",), ("dtt004_good.py",),
     "DTT004", 2),
    (rule_span_taxonomy, "dtt005_bad", ("code.py",), None, "DTT005", 2),
    (rule_flag_validator, "dtt006_bad", ("flags.py",), None, "DTT006", 1),
    (rule_trace_purity, "", ("dtt007_bad.py",), ("dtt007_good.py",),
     "DTT007", 5),
    (rule_donation_safety, "", ("dtt008_bad.py",), ("dtt008_good.py",),
     "DTT008", 1),
    (rule_traced_coverage, "dtt009_bad",
     ("parallel/mod.py", "tools/dttcheck/refs.py"), None, "DTT009", 1),
    (rule_inventory_coverage, "dtt010_bad",
     ("code.py", "tools/dttsan/stub.py"), None, "DTT010", 2),
    (rule_perf_coverage, "dtt011_bad",
     ("bench.py", "tools/dttperf/records.py"), None, "DTT011", 2),
]


@pytest.mark.parametrize(
    "rule,root,bad,good,rule_id,min_bad",
    FIXTURE_MATRIX, ids=[m[4] for m in FIXTURE_MATRIX])
def test_rule_fixture_pair(rule, root, bad, good, rule_id, min_bad):
    res = _lint(rule, root, *bad)
    assert len(res.findings) >= min_bad, \
        f"{rule_id} bad fixture: {[f.format() for f in res.findings]}"
    assert all(f.rule == rule_id for f in res.findings)
    if good is None:  # table-paired rules carry their own good dir
        root = root.replace("_bad", "_good")
        good = bad
    res_good = _lint(rule, root, *good)
    assert res_good.findings == [], \
        f"{rule_id} good fixture not clean: " \
        f"{[f.format() for f in res_good.findings]}"


def test_dtt001_flags_every_literal_kind():
    """The bad fixture exercises all three literal shapes: collective
    axis arg, axis_name kwarg, PartitionSpec/Mesh tuples."""
    res = _lint(rule_collective_axis, "", "dtt001_bad.py")
    msgs = "\n".join(f.message for f in res.findings)
    assert "psum()" in msgs and "psum_scatter()" in msgs
    assert "P()" in msgs and "Mesh()" in msgs


def test_dtt004_names_both_directions():
    res = _lint(rule_fault_registry, "", "dtt004_bad.py")
    msgs = "\n".join(f.message for f in res.findings)
    assert "unknown_point" in msgs and "UNREGISTERED" in msgs
    assert "orphan" in msgs and "never fired" in msgs


def test_dtt005_flags_both_directions():
    res = _lint(rule_span_taxonomy, "dtt005_bad", "code.py")
    msgs = "\n".join(f.message for f in res.findings)
    assert "rogue_span" in msgs  # code -> docs drift
    assert "ghost_span" in msgs  # docs -> code drift


def test_dtt007_names_each_impurity():
    res = _lint(rule_trace_purity, "", "dtt007_bad.py")
    msgs = "\n".join(f.message for f in res.findings)
    for needle in ("print", "time.time", "np.random.rand",
                   "branches on traced argument 'x'"):
        assert needle in msgs, f"missing {needle!r} in:\n{msgs}"


# ------------------------------------------------------- repo-wide run


def test_repo_lints_clean_with_checked_in_baseline():
    """THE gate: the whole walk set (package + tools + bench +
    entry points) has zero non-baselined findings and zero stale
    suppressions, inside the <10s acceptance budget — and every
    baseline entry still matches a real finding (the suppressed set
    is exactly the baseline, which can only shrink)."""
    t0 = time.perf_counter()
    res = run_lint()
    dt = time.perf_counter() - t0
    assert res.findings == [], \
        "new findings:\n" + "\n".join(f.format() for f in res.findings)
    assert res.stale == [], res.stale
    assert len(res.rules) == 11
    assert dt < 10.0, f"lint took {dt:.1f}s (>10s acceptance budget)"
    assert res.baselined, "baseline is empty — update this test if " \
                          "the tree went fully clean"
    keys = {(f.rule, f.key) for f in res.baselined}
    from tools.dttlint import load_baseline

    assert keys == {(e["rule"], e["key"]) for e in load_baseline()}


def test_stale_suppression_fails_loudly(tmp_path):
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "DTT001", "key": "no/such/file.py::gone::psum:data",
         "reason": "left over from deleted code"},
    ]}))
    res = run_lint(baseline_path=str(base))
    assert not res.ok
    assert any("no/such/file.py" in s for s in res.stale)


def test_finding_keys_are_line_number_free():
    """Baseline stability: keys must survive unrelated edits, so no
    key may embed a line number."""
    import re

    res = _lint(rule_collective_axis, "", "dtt001_bad.py")
    for f in res.findings:
        assert not re.search(r":\d+$", f.key.replace(":2", "")), f.key


# ------------------------------------------------------------ CLI + fix


def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.dttlint", *args],
        capture_output=True, text=True, cwd=cwd)


def test_cli_exits_zero_and_emits_json():
    p = _cli("--json")
    assert p.returncode == 0, p.stdout + p.stderr
    out = json.loads(p.stdout)
    assert out["ok"] and out["findings"] == []
    assert len(out["rules"]) == 11


def test_cli_exits_nonzero_on_new_violation(tmp_path):
    """Introduce a fixture violation into a scratch tree — the exit
    code must flip (the tier-1 hook's contract)."""
    shutil.copy(os.path.join(FIXTURES, "dtt001_bad.py"),
                tmp_path / "bench.py")  # bench.py is in the walk set
    (tmp_path / "docs").mkdir()
    base = tmp_path / "empty.json"
    base.write_text(json.dumps({"version": 1, "entries": []}))
    p = _cli("--root", str(tmp_path), "--baseline", str(base))
    assert p.returncode == 1
    assert "DTT001" in p.stdout


def test_fix_rewrites_axis_literals(tmp_path):
    """The --fix stub: DTT001 "data"/"model" literals become the mesh
    constants (import added), and the rewritten file lints clean."""
    target = tmp_path / "code.py"
    shutil.copy(os.path.join(FIXTURES, "dtt001_bad.py"), target)
    res = run_lint(str(tmp_path), baseline_path=_EMPTY_BASELINE,
                   rules=[rule_collective_axis], targets=("code.py",))
    assert res.findings
    n = apply_dtt001_fixes(res.findings, str(tmp_path))
    assert n >= 4
    src = target.read_text()
    assert '"data"' not in src and '"model"' not in src
    assert "from distributed_tensorflow_tpu.parallel.mesh import" in src
    res2 = run_lint(str(tmp_path), baseline_path=_EMPTY_BASELINE,
                    rules=[rule_collective_axis], targets=("code.py",))
    assert res2.findings == []


# ------------------------------------------- the rules watch the tree


def test_scalar_contract_sees_all_loop_variants():
    """The DTT003 surface: all six _train_* variants in loop.py are in
    scope (a new variant automatically joins)."""
    from tools.dttlint import RepoIndex
    import ast

    index = RepoIndex()
    tree = index.trees["distributed_tensorflow_tpu/training/loop.py"]
    variants = [n.name for n in tree.body
                if isinstance(n, ast.FunctionDef)
                and n.name.startswith("_train_")]
    assert len(variants) >= 6, variants
    assert rule_scalar_contract(index) == []


def test_all_rules_registered():
    assert [r.rule_id for r in ALL_RULES] == [
        f"DTT00{i}" for i in range(1, 10)] + ["DTT010", "DTT011"]


def test_dtt009_names_the_orphan_and_guards_self_disable():
    """The orphan site is NAMED; and a walk set with parallel/
    collectives but no tools/dttcheck sources is itself a finding
    (the rule must not silently self-disable)."""
    res = _lint(rule_traced_coverage, "dtt009_bad",
                "parallel/mod.py", "tools/dttcheck/refs.py")
    assert [f.key for f in res.findings] == [
        "parallel/mod.py::orphan_collective_path"]
    assert "machine-unproven" in res.findings[0].message
    res2 = _lint(rule_traced_coverage, "dtt009_bad", "parallel/mod.py")
    assert [f.rule for f in res2.findings] == ["DTT009"]
    assert "self-disable" in res2.findings[0].message


def test_dtt010_names_the_unresolvable_and_guards_self_disable():
    """DTT010 (r20): the Thread/Timer whose target is an arbitrary
    callable value is NAMED (the self-method one is inventory-covered
    and stays quiet); a walk set with Thread sites but no tools/dttsan
    sources is itself a finding."""
    res = _lint(rule_inventory_coverage, "dtt010_bad",
                "code.py", "tools/dttsan/stub.py")
    assert [f.key for f in res.findings] == [
        "code.py::launch:Thread", "code.py::launch:Timer"]
    assert all("inventory" in f.message for f in res.findings)
    res2 = _lint(rule_inventory_coverage, "dtt010_bad", "code.py")
    assert [f.rule for f in res2.findings] == ["DTT010"]
    assert "self-disable" in res2.findings[0].message


def test_dtt011_names_the_hole_and_guards_self_disable():
    """DTT011 (r23): the phase in neither table is NAMED, the
    bare-reason exemption is rejected with its own message, the
    fact-covered phase stays quiet; a walk set with bench phases but
    no tools/dttperf sources is itself a finding."""
    res = _lint(rule_perf_coverage, "dtt011_bad",
                "bench.py", "tools/dttperf/records.py")
    assert sorted(f.key for f in res.findings) == [
        "bench.py::bare_exempt_phase", "bench.py::uncovered_phase"]
    by_key = {f.key: f.message for f in res.findings}
    assert "unexplained exemption" in by_key["bench.py::bare_exempt_phase"]
    assert "neither PHASE_FACTS nor PHASE_EXEMPT" in \
        by_key["bench.py::uncovered_phase"]
    res2 = _lint(rule_perf_coverage, "dtt011_bad", "bench.py")
    assert [f.rule for f in res2.findings] == ["DTT011"]
    assert "self-disable" in res2.findings[0].message
