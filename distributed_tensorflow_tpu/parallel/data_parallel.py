"""Synchronous data parallelism — the TPU-idiomatic mode.

The reference's only strategy is *asynchronous* PS data-parallelism
(params on ps tasks, independent worker updates, ``MNISTDist.py:110-111,
174-176,188``); its own comment defers synchronous training to
``SyncReplicasOptimizer`` (``:174-176``). On TPU, synchronous DP is the
native design: params replicated in HBM on every chip, the global batch
split over the "data" mesh axis, and ONE collective — ``lax.pmean`` over
ICI — replaces the entire worker↔ps parameter round-trip per step.

Implementation: ``jax.shard_map`` over the mesh so the collective is
explicit in the program (and visible in tests via a virtual 8-device CPU
mesh), then ``jit`` compiles the whole step — forward, backward, pmean,
update — into one XLA executable per chip.
"""

from __future__ import annotations


import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.parallel.mesh import DATA_AXIS, batch_sharding, replicated_sharding
from distributed_tensorflow_tpu.training.train_state import (
    TrainState,
    apply_augment,
    apply_updates,
    compute_grads,
    loss_and_metrics,
)


def shard_batch(mesh, batch):
    """Lay a host batch out across the mesh's data axis.

    Single-process: one device_put of the full global batch with a
    NamedSharding (the input-side half of DP). Multi-process (multi-host
    SPMD, the reference's one-process-per-machine topology,
    ``MNISTDist.py:101-103``): ``batch`` is this process's LOCAL slice of
    the global batch; the slices are assembled into one global-mesh array
    via ``jax.make_array_from_process_local_data`` — each host uploads only
    to its own chips, no cross-host data movement.
    """
    from distributed_tensorflow_tpu.parallel.mesh import put_global

    x, y = batch
    return put_global(
        (batch_sharding(mesh, x.ndim), batch_sharding(mesh, y.ndim)),
        (x, y),
    )


def local_batch_size(global_batch_size: int) -> int:
    """This process's share of the global batch (multi-host sync DP feeds
    each host ``global/process_count`` examples per step)."""
    n = jax.process_count()
    if global_batch_size % n:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by "
            f"{n} processes"
        )
    return global_batch_size // n


def make_dp_train_step(model, optimizer, mesh, keep_prob: float = 1.0, donate: bool = True,
                       grad_transform=None, accum_steps: int = 1,
                       augment_fn=None):
    """Compiled sync-DP train step: (state, sharded batch) -> (state, metrics).

    Per-shard: forward+backward on the local batch slice with a
    device-distinct dropout rng; then ``pmean`` of grads *and* metrics over
    the data axis; then an identical optimizer update on every device, so
    replicated state stays bitwise in sync (the property the reference
    gives up by going async). ``grad_transform`` (e.g. global-norm clip)
    runs on the aggregated grads, identically on every shard.
    ``accum_steps`` accumulates gradients over that many microbatches of
    the shard's slice before the one pmean+update
    (``train_state.compute_grads``).
    """
    def per_shard(state: TrainState, batch):
        rng, sub = jax.random.split(state.rng)
        # distinct dropout mask per data shard, same key evolution everywhere
        sub = jax.random.fold_in(sub, lax.axis_index(DATA_AXIS))
        batch = apply_augment(augment_fn, batch, state.rng,
                              shard_index=lax.axis_index(DATA_AXIS))

        grads, shard_metrics, model_state = compute_grads(
            model, state.params, batch, keep_prob=keep_prob, rng=sub,
            model_state=state.model_state, accum_steps=accum_steps,
        )
        grads = lax.pmean(grads, DATA_AXIS)
        if grad_transform is not None:
            grads = grad_transform(grads)
        metrics = lax.pmean(shard_metrics, DATA_AXIS)
        # cross-replica batch-norm stats: average the per-shard EMAs so the
        # replicated state stays identical on every device
        if model_state:
            model_state = lax.pmean(model_state, DATA_AXIS)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params, state.step)
        params = apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1, rng, model_state), metrics

    state_spec = P()  # replicated
    batch_spec = (P(DATA_AXIS), P(DATA_AXIS))
    sharded = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(state_spec, batch_spec),
        out_specs=(state_spec, P()),
        check_vma=False,  # rng ops + replicated-out pattern
    )
    if donate:
        return jax.jit(sharded, donate_argnums=(0,))
    return jax.jit(sharded)


def make_dp_eval_step(model, mesh):
    """Sharded full-batch eval: metrics pmean'd over the data axis."""

    def per_shard(params, batch, model_state):
        _, aux = loss_and_metrics(model, params, batch, train=False,
                                  model_state=model_state)
        return lax.pmean(aux["metrics"], DATA_AXIS)

    return jax.jit(
        jax.shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(P(), (P(DATA_AXIS), P(DATA_AXIS)), P()),
            out_specs=P(),
            check_vma=False,
        )
    )


def replicate_state(mesh, state: TrainState) -> TrainState:
    """Place a host-built TrainState replicated over the mesh.

    Refuses a state whose leaves are ALREADY device-sharded (a ZeRO /
    PP / TP layout from a prior placement): silently re-replicating
    would bake the sharded representation — for ZeRO, flat PADDED
    chunk vectors — onto every device as if it were the standard
    layout, and training would consume garbage. Fetch the standard
    layout first (``parallel.zero.fetch_state_zero`` /
    ``fetch_state_pp``) and replicate that."""
    from distributed_tensorflow_tpu.utils.pytree import path_key

    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        if (isinstance(leaf, jax.Array)
                and len(leaf.sharding.device_set) > 1
                and not leaf.is_fully_replicated):
            raise ValueError(
                f"replicate_state: leaf {path_key(path)!r} is already "
                f"sharded over {len(leaf.sharding.device_set)} devices "
                f"(a ZeRO/PP/TP placement) — re-replicating would "
                f"silently treat the sharded (padded) layout as the "
                f"standard one. Fetch the standard layout first "
                f"(e.g. parallel.zero.fetch_state_zero) and replicate "
                f"that.")
    return jax.device_put(state, replicated_sharding(mesh))


def dp_comm_rows(grad_bytes: int, d: int) -> list[dict]:
    """Static per-step collective wire bytes for plain replicated DP —
    this module's ONE collective, the grad ``pmean`` (a ring all-reduce,
    ~2|G| on the wire over the data axis). Delegates to the ZeRO level-0
    row so the all-reduce convention has exactly one formula
    (``parallel/zero.zero_comm_rows`` generalizes this pattern over the
    sharding levels); ``utils/resources.comm_ledger`` composes it."""
    from distributed_tensorflow_tpu.parallel.zero import zero_comm_rows

    return zero_comm_rows(grad_bytes, 0, 0, d)
