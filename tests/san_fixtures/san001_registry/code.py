"""SAN001 fixture: one thread root — registered or not depending on
which registry the test hands dttsan."""
import threading


class Poller:
    def __init__(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        pass
