"""Procedural language-model dataset: in-context associative recall.

The reference framework is images-only (MNISTDist.py:68); this split
feeds the build's causal-LM extension. Each sequence follows a FRESH
per-sequence random permutation of the vocabulary: x[t+1] = perm(x[t]),
with perm drawn independently per sequence. Because no transition is
shared across sequences, the weights CANNOT memorize a bigram table —
the only way to predict x[t+1] is to find the earlier occurrence of
x[t] in this sequence's own context and copy what followed it (the
induction-head solution). That makes next-token accuracy here a direct
measurement of working long-range attention:

- a bigram/MLP model is stuck near 1/vocab_size,
- a causal transformer approaches the RECALL CEILING: a permutation
  step enters one of the permutation's cycles immediately, so once the
  cycle has been traversed every later token has an in-context
  antecedent. The achievable accuracy is the mean fraction of positions
  whose token already appeared — measured per split and exposed as
  ``recall_ceiling`` (for vocab 64 and seq 256 it is ~0.87).

Deterministic per (seed, split sizes); the whole split materializes as
uint8/uint16 tokens (vocab-dependent) so evaluation is a fixed set.
"""

from __future__ import annotations

import numpy as np


def _gen_sequences(n: int, seq_len: int, vocab_size: int,
                   rng: np.random.Generator) -> np.ndarray:
    """(n, seq_len+1) token ids: per-row random permutation walks."""
    # one fresh permutation per sequence: argsort of uniform noise
    perms = np.argsort(rng.random((n, vocab_size)), axis=1)
    toks = np.empty((n, seq_len + 1), dtype=np.int64)
    toks[:, 0] = rng.integers(0, vocab_size, n)
    rows = np.arange(n)
    for t in range(seq_len):
        toks[:, t + 1] = perms[rows, toks[:, t]]
    return toks


def recall_ceiling(tokens: np.ndarray) -> float:
    """Mean fraction of predictable positions: target y[t] = x[t+1] is
    predictable by in-context recall iff x[t] occurred earlier in the
    sequence (its successor was then observed). Computed exactly from
    the split's tokens."""
    x = tokens[:, :-1]
    n, s = x.shape
    seen = np.zeros((n, tokens.max() + 1), dtype=bool)
    rows = np.arange(n)
    predictable = np.zeros((n, s), dtype=bool)
    for t in range(s):
        predictable[:, t] = seen[rows, x[:, t]]
        seen[rows, x[:, t]] = True
    return float(predictable.mean())


class LMDataSet:
    """One LM split with the tutorial ``next_batch`` surface.

    ``next_batch(B)`` -> (x int32 (B, S), y int32 (B, S)) with
    y = x shifted one token left (next-token targets — every position
    has a target, so the token axis shards uniformly in SP mode).
    Storage is u8/u16 by vocab size; shuffled-epoch index stream like
    the image DataSet. ``images``/``labels`` expose the full split for
    the shared ``evaluate`` path (the names are the tutorial API's)."""

    def __init__(self, n: int, seq_len: int, vocab_size: int = 64,
                 seed: int = 0):
        if vocab_size < 2 or vocab_size > 65535:
            raise ValueError(f"vocab_size={vocab_size} not in [2, 65535]")
        rng = np.random.default_rng(seed)
        toks = _gen_sequences(n, seq_len, vocab_size, rng)
        store = np.uint8 if vocab_size <= 256 else np.uint16
        self._tokens = toks.astype(store)
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self._rng = np.random.default_rng(seed + 1)
        self._order = self._rng.permutation(n)
        self._pos = 0
        self.epochs_completed = 0

    @property
    def num_examples(self) -> int:
        return len(self._tokens)

    @property
    def images(self) -> np.ndarray:
        """Full split inputs (N, S) int32 — evaluate()'s batch source."""
        return self._tokens[:, :-1].astype(np.int32)

    @property
    def labels(self) -> np.ndarray:
        """Full split next-token targets (N, S) int32."""
        return self._tokens[:, 1:].astype(np.int32)

    def recall_ceiling(self) -> float:
        return recall_ceiling(self._tokens.astype(np.int64))

    def _next_indices(self, batch_size: int) -> np.ndarray:
        idx = np.empty(batch_size, dtype=np.int64)
        filled = 0
        while filled < batch_size:
            take = min(batch_size - filled, len(self._order) - self._pos)
            idx[filled:filled + take] = (
                self._order[self._pos:self._pos + take])
            self._pos += take
            filled += take
            if self._pos >= len(self._order):
                self._order = self._rng.permutation(self.num_examples)
                self._pos = 0
                self.epochs_completed += 1
        return idx

    def next_batch(self, batch_size: int) -> tuple[np.ndarray, np.ndarray]:
        idx = self._next_indices(batch_size)
        t = self._tokens[idx]
        return t[:, :-1].astype(np.int32), t[:, 1:].astype(np.int32)

    # token ids are already the thin-wire format — one batch surface
    next_batch_raw = next_batch

    def shard(self, index: int, count: int) -> "LMDataSet":
        """Disjoint contiguous shard (multi-host DP feeding)."""
        out = object.__new__(LMDataSet)
        sl = slice(index * self.num_examples // count,
                   (index + 1) * self.num_examples // count)
        out._tokens = self._tokens[sl]
        out.seq_len = self.seq_len
        out.vocab_size = self.vocab_size
        out._rng = np.random.default_rng(index)
        out._order = out._rng.permutation(len(out._tokens))
        out._pos = 0
        out.epochs_completed = 0
        return out
