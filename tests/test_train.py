"""Single-device training: step semantics, global step, convergence smoke.

Reference semantics: SGD minimize with global_step increment
(MNISTDist.py:147-149), hot loop (:172-188).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.data import read_data_sets
from distributed_tensorflow_tpu.models import DeepCNN
from distributed_tensorflow_tpu.training import (
    adam,
    create_train_state,
    get_optimizer,
    make_train_step,
    sgd,
)
from distributed_tensorflow_tpu.training.train_state import evaluate


def test_sgd_update_rule():
    opt = sgd(0.1)
    params = {"w": jnp.array([1.0, 2.0])}
    grads = {"w": jnp.array([10.0, -10.0])}
    updates, _ = opt.update(grads, opt.init(params), params)
    new = jax.tree.map(lambda p, u: p + u, params, updates)
    np.testing.assert_allclose(np.asarray(new["w"]), [0.0, 3.0], rtol=1e-6)


def test_get_optimizer_unknown():
    with pytest.raises(ValueError):
        get_optimizer("nope", 0.1)


def test_train_step_increments_global_step():
    model = DeepCNN()
    state = create_train_state(model, sgd(0.001), seed=0)
    step_fn = make_train_step(model, sgd(0.001), donate=False)
    batch = (jnp.ones((8, 784)), jax.nn.one_hot(jnp.zeros(8, jnp.int32), 10))
    assert int(state.step) == 0
    state, metrics = step_fn(state, batch)
    assert int(state.step) == 1
    assert "loss" in metrics and "accuracy" in metrics
    state, _ = step_fn(state, batch)
    assert int(state.step) == 2


def test_train_step_changes_params():
    model = DeepCNN()
    opt = sgd(0.01)
    state = create_train_state(model, opt, seed=0)
    step_fn = make_train_step(model, opt, donate=False)
    x = jax.random.normal(jax.random.key(0), (8, 784))
    y = jax.nn.one_hot(jnp.arange(8) % 10, 10)
    before = np.asarray(state.params["weights"]["out"]).copy()
    state, _ = step_fn(state, (x, y))
    after = np.asarray(state.params["weights"]["out"])
    assert not np.allclose(before, after)


def test_convergence_smoke():
    """Loss decreases and accuracy climbs on the synthetic digit set."""
    model = DeepCNN()
    opt = adam(1e-3)
    state = create_train_state(model, opt, seed=0)
    step_fn = make_train_step(model, opt, keep_prob=0.75)
    ds = read_data_sets("/nonexistent", one_hot=True)
    first_loss = None
    for i in range(60):
        batch = ds.train.next_batch(64)
        state, metrics = step_fn(state, batch)
        if first_loss is None:
            first_loss = float(metrics["loss"])
    last_loss = float(metrics["loss"])
    assert last_loss < first_loss * 0.7, (first_loss, last_loss)
    res = evaluate(model, state.params, ds.test, batch_size=500)
    assert res["accuracy"] > 0.5  # 60 steps is plenty on the procedural set
