"""SAN003 good fixture: consistent acquisition order, wait inside a
while predicate holding only its own condition, notify while holding,
no blocking work under any lock."""
import time
import threading


class Orderly:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._cv = threading.Condition()
        self.items = []
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            with self._a:       # only ever A -> B
                with self._b:
                    pass

    def forwards(self):
        with self._a:
            with self._b:
                pass

    def consume(self):
        with self._cv:
            while not self.items:
                self._cv.wait()
            return self.items.pop()

    def produce(self, x):
        with self._cv:
            self.items.append(x)
            self._cv.notify_all()

    def slow_then_lock(self):
        time.sleep(0.01)        # the sleep happens OUTSIDE the lock
        with self._a:
            pass
