"""Throughput metering + the collective in-flight cap.

The reference has no tracing or profiling at all (``import time`` at
MNISTDist.py:8 is dead — SURVEY.md §5). The build needs metering for the
BASELINE metric (images/sec/chip); jax.profiler tracing is driven directly
by the training loop via ``--profile_dir`` (training/loop.py).
"""

from __future__ import annotations

import threading
import time

import jax


class Throughput:
    """images/sec (and per-chip) meter over a training window."""

    def __init__(self, batch_size: int, n_chips: int = 1):
        self.batch_size = batch_size
        self.n_chips = n_chips
        self.reset()

    def reset(self):
        self._start = time.perf_counter()
        self._images = 0

    def step(self, n: int | None = None):
        self._images += n if n is not None else self.batch_size

    @property
    def images_per_sec(self) -> float:
        dt = time.perf_counter() - self._start
        return self._images / dt if dt > 0 else 0.0

    @property
    def images_per_sec_per_chip(self) -> float:
        return self.images_per_sec / max(self.n_chips, 1)


class ServeTraceCapture:
    """``--serve_profile_batches N``: capture ONE jax.profiler trace
    window around N served microbatches and report the artifact path.

    Installed as the serving metrics hook's profiler: the first
    ``on_batch`` call starts the trace, the Nth stops it — so the window
    brackets real traffic (steady-state batching, reload blips included
    if one lands inside), not a synthetic loop. One-shot by design: a
    profile is an investigation artifact, not a steady-state cost.
    ``path`` (and the returned value of the closing ``on_batch``) is the
    trace directory for ``tensorboard --logdir`` / Perfetto."""

    def __init__(self, profile_dir: str, n_batches: int):
        if n_batches < 1:
            raise ValueError(f"n_batches must be >= 1, got {n_batches}")
        self.profile_dir = profile_dir
        self.n_batches = int(n_batches)
        self._seen = 0
        self._active = False
        self._done = False
        # shared across every batcher's worker thread: start/stop of the
        # singleton jax profiler must be check-then-act under one lock
        self._lock = threading.Lock()
        self.path: str | None = None

    def on_batch(self) -> str | None:
        """Call once per served microbatch (any worker thread). Returns
        the artifact path on the call that closes the window, else
        None."""
        with self._lock:
            if self._done:
                return None
            if not self._active:
                import os

                os.makedirs(self.profile_dir, exist_ok=True)
                jax.profiler.start_trace(self.profile_dir)
                self._active = True
            self._seen += 1
            if self._seen >= self.n_batches:
                jax.profiler.stop_trace()
                self._active = False
                self._done = True
                self.path = self.profile_dir
                print(f"serving profile: traced {self._seen} batches "
                      f"into {self.profile_dir}")
                return self.path
            return None

    def close(self) -> None:
        """Stop a still-open window (server shutdown before N batches)."""
        with self._lock:
            if self._active:
                jax.profiler.stop_trace()
                self._active = False
                self._done = True
                self.path = self.profile_dir


def collective_sync_cadence(multi_device: bool) -> int:
    """How often (in steps) a multi-device training loop must
    ``block_until_ready`` to bound in-flight collective programs; 0 = never.

    XLA:CPU runs each virtual device on a pool thread and collective
    programs rendezvous across all of them; dozens of concurrently enqueued
    mesh programs can interleave across device threads and deadlock the
    rendezvous (observed at ~60 deep on an 8-device host — PERF.md). TPU
    streams execute strictly in enqueue order per chip, so no cap there.

    MULTI-PROCESS CPU (the gloo test topology) is stricter still: two
    in-flight cross-host programs can interleave their gloo sends on one
    TCP pair and crash the transport with a preamble/size mismatch
    (``op.preamble.length <= op.nbytes`` abort, observed r8) — so at most
    ONE collective program may be in flight: cadence 1.
    """
    if not multi_device:
        return 0
    if jax.default_backend() == "cpu":
        return 1 if jax.process_count() > 1 else 16
    return 0
