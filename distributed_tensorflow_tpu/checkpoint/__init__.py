from distributed_tensorflow_tpu.checkpoint.checkpoint import (
    Checkpointer,
    background_save_from_flags,
    max_to_keep_from_flags,
    save_checkpoint,
    save_checkpoint_sharded,
    load_flat_sharded,
    restore_latest,
    latest_checkpoint,
)

__all__ = [
    "Checkpointer",
    "background_save_from_flags",
    "max_to_keep_from_flags",
    "save_checkpoint",
    "save_checkpoint_sharded",
    "load_flat_sharded",
    "restore_latest",
    "latest_checkpoint",
]
