"""The dttcheck scenario matrix: one traceable step function per
(parallel-mode x model) cell, built over an abstract 8-device CPU mesh.

Each scenario instantiates the REAL builder the training loop uses
(``make_dp_train_step`` / ``make_zero_train_step`` /
``make_pp_train_step`` / ``make_tp_train_step`` / ``make_ep_train_step``
/ ``make_sp_train_step`` / ``ps_emulation.make_grad_fn`` and the eval
twins) on a small-but-structurally-faithful model, so what dttcheck
proves is the program the loops dispatch — not a reimplementation.
Models are kept tiny (tracing cost is Python time, and the repo-wide
pytest gate carries a <15s chip-free budget); every byte formula under
proof is size-generic, so small shapes prove the same algebra the
flagship shapes run.

``build_from_config`` is the generic (model, optimizer, batch, layout)
-> traceable-target assembly — the same entry the
``utils/resources.comm_ledger(verify=True)`` hook uses, so a ledger
can be machine-proven for ANY model the caller prices, not just the
canonical matrix below.

Scenario fields drive the four passes:

- ``ledger_kwargs`` — the ``utils/resources.comm_ledger`` layout this
  step corresponds to (None = the scenario skips the ledger proof:
  clip-transform variants add real clip-norm collectives the ledger
  deliberately does not price, and eval steps have no training ledger).
- ``plan`` — the declared :class:`ParallelismPlan` facts: expected
  mesh axes per flattened argument leaf (from the mode's OWN spec
  builder — ``zero_state_specs`` / ``pp_state_specs`` /
  ``ep_state_specs``), the replication-drift pass's ground truth.
- ``donate`` — whether the builder promises buffer donation (the
  donation-audit pass verifies the jaxpr can actually alias it).
- ``hlo`` — proof source: GSPMD modes (TP) lower their collectives in
  the SPMD partitioner, so their inventory comes from compiled CPU HLO
  instead of the jaxpr (see inventory.hlo_inventory).

The clip variants exist for two reasons: they prove the axis-aware
clip transforms deadlock-free (identical collective signatures on
every rank) and they keep every collective call site in ``parallel/``
reachable from a traced step — the dttlint DTT009 closure.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable

#: the virtual mesh every scenario assumes (tests force the same one)
N_DEVICES = 8


def ensure_cpu_mesh() -> None:
    """Force the 8-device virtual CPU mesh BEFORE jax initializes —
    the conftest strategy, callable from the CLI and bench subprocess.
    A no-op when jax is already up with >= 8 devices."""
    import sys

    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                        f"{N_DEVICES}").strip()
    import jax

    if len(jax.devices()) < N_DEVICES:
        raise RuntimeError(
            f"dttcheck needs a {N_DEVICES}-device mesh and jax is "
            f"already initialized with {len(jax.devices())} device(s) — "
            f"run in a fresh process (python -m tools.dttcheck) or "
            f"under the test conftest")


@dataclass
class TraceTarget:
    """Everything the passes need for one scenario, fully built."""

    name: str
    mode: str
    model_name: str
    step_fn: Callable
    args: tuple
    mesh: Any
    model: Any
    optimizer: Any
    batch_size: int
    ledger_kwargs: dict | None = None
    plan: list | None = None          # expected axes per flat arg leaf
    donate: bool = False
    hlo: bool = False
    notes: str = ""


@dataclass
class Scenario:
    name: str
    mode: str
    model_name: str
    build: Callable[[], TraceTarget]


def _models():
    from distributed_tensorflow_tpu.models.cnn import DeepCNN
    from distributed_tensorflow_tpu.models.mlp import MLP
    from distributed_tensorflow_tpu.models.transformer import TransformerLM
    from distributed_tensorflow_tpu.parallel.mesh import MODEL_AXIS

    return {
        "deep_cnn": lambda **kw: DeepCNN(image_size=8, channels=1,
                                         num_classes=10,
                                         hidden_units=128, **kw),
        "mlp": lambda **kw: MLP(image_size=8, channels=1, num_classes=10,
                                hidden_units=64, **kw),
        "lm": lambda **kw: TransformerLM(
            vocab_size=64, seq_len=8, d_model=16, num_heads=2,
            num_blocks=4, **kw),
        "lm_moe": lambda **kw: TransformerLM(
            vocab_size=64, seq_len=8, d_model=16, num_heads=2,
            num_blocks=2, moe_experts=2, moe_axis=MODEL_AXIS, **kw),
    }


def make_batch(model, batch: int) -> tuple:
    """A host batch with the model family's training shapes (zeros —
    tracing reads avals only)."""
    import numpy as np

    if hasattr(model, "vocab_size"):  # the causal-LM family
        return (np.zeros((batch, model.seq_len), np.int32),
                np.zeros((batch, model.seq_len), np.int32))
    flat = model.image_size * model.image_size * model.channels
    return (np.zeros((batch, flat), np.float32),
            np.zeros((batch, model.num_classes), np.float32))


def _flat_axes(tree) -> list:
    """Spec pytree -> expected mesh-axis tuple per flattened leaf."""
    import jax
    from jax.sharding import PartitionSpec as P

    out = []
    for spec in jax.tree.leaves(tree, is_leaf=lambda v: isinstance(v, P)):
        axes = []
        for entry in spec:
            if entry is None:
                continue
            axes.extend(entry if isinstance(entry, tuple) else (entry,))
        out.append(tuple(axes))
    return out


def _replicated_specs(tree):
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(lambda _: P(), tree)


def _mesh(data: int, model: int):
    from distributed_tensorflow_tpu.parallel.mesh import MeshSpec, make_mesh

    return make_mesh(MeshSpec(data, model))


def _opt():
    from distributed_tensorflow_tpu.training.train_state import get_optimizer

    return get_optimizer("sgd", 0.01)


def _state(model, opt):
    from distributed_tensorflow_tpu.training.train_state import (
        create_train_state,
    )

    return create_train_state(model, opt, seed=0)


# -------------------------------------------------- the generic builder


def ledger_config(mode: str = "dp", *, data_ways: int = 1,
                  model_axis: int = 1, zero_level: int = 0,
                  virtual_stages: int = 1, microbatches: int = 0,
                  pp_schedule: str = "auto", zero_overlap: bool = False,
                  zero_bucket_mb: float = 4.0, **_ignored) -> dict:
    """Normalize a parallel-layout config to the canonical
    ``utils/resources.comm_ledger`` kwargs — the ONE normalization
    (clamping, ``zeroN`` -> level) shared by the scenario builders here
    and the ``tools/dttperf`` step-time predictor, so the layout the
    predictor prices is byte-identical to the one the builders trace."""
    data_ways = max(1, int(data_ways))
    model_axis = max(1, int(model_axis))
    if mode.startswith("zero"):
        zero_level = zero_level or int(mode[4:] or 0)
    return dict(mode=mode, data_ways=data_ways, model_axis=model_axis,
                zero_level=int(zero_level),
                virtual_stages=max(1, int(virtual_stages)),
                microbatches=int(microbatches), pp_schedule=pp_schedule,
                zero_overlap=bool(zero_overlap),
                zero_bucket_mb=float(zero_bucket_mb or 4.0))


def build_from_config(model, optimizer, batch_size: int, *,
                      mode: str = "dp", data_ways: int = 1,
                      model_axis: int = 1, zero_level: int = 0,
                      virtual_stages: int = 1, microbatches: int = 0,
                      pp_schedule: str = "auto",
                      zero_overlap: bool = False,
                      zero_bucket_mb: float = 4.0,
                      grad_transform=None, name: str | None = None,
                      model_name: str | None = None,
                      **_ignored) -> TraceTarget:
    """(model, optimizer, layout config) -> a traceable TraceTarget for
    that mode's REAL train-step builder. The config keys mirror
    ``utils/resources.parallel_config_from_flags`` exactly, so the
    ``comm_ledger(verify=True)`` hook can forward its own kwargs
    verbatim. ``grad_transform`` (the clip variants) disables the
    ledger proof — clip collectives are deliberately unpriced."""
    from jax.sharding import PartitionSpec as P  # noqa: F401

    from distributed_tensorflow_tpu.parallel.mesh import (
        DATA_AXIS,
        MODEL_AXIS,
    )

    lcfg = ledger_config(
        mode, data_ways=data_ways, model_axis=model_axis,
        zero_level=zero_level, virtual_stages=virtual_stages,
        microbatches=microbatches, pp_schedule=pp_schedule,
        zero_overlap=zero_overlap, zero_bucket_mb=zero_bucket_mb)
    data_ways = lcfg["data_ways"]
    model_axis = lcfg["model_axis"]
    zero_level = lcfg["zero_level"]
    virtual_stages = lcfg["virtual_stages"]
    zero_overlap = lcfg["zero_overlap"]
    zero_bucket_mb = lcfg["zero_bucket_mb"]
    model_name = model_name or type(model).__name__
    name = name or f"{mode}/{model_name}"
    batch = make_batch(model, int(batch_size))
    batch_axes = [(DATA_AXIS,), (DATA_AXIS,)]
    ledger_kwargs = None if grad_transform is not None else dict(lcfg)
    common = dict(model=model, optimizer=optimizer, mode=mode,
                  model_name=model_name, batch_size=int(batch_size),
                  ledger_kwargs=ledger_kwargs, name=name)

    if mode == "ps":
        import jax

        from distributed_tensorflow_tpu.parallel.ps_emulation import (
            make_grad_fn,
        )

        grad_fn = make_grad_fn(model, keep_prob=1.0,
                               devices=[jax.devices()[0]])
        return TraceTarget(
            step_fn=grad_fn,
            args=(_state(model, optimizer).params, batch,
                  jax.random.PRNGKey(0)),
            mesh=None, plan=None, donate=False,
            notes="host-wire topology: the device program must be "
                  "collective-free (the pull/push rows ride TCP)",
            **common)

    mesh = _mesh(data_ways, model_axis)

    if mode in ("zero1", "zero3"):
        from distributed_tensorflow_tpu.parallel.zero import (
            make_zero_train_step,
            shard_state_zero,
            zero_state_specs,
        )

        zstate = shard_state_zero(_state(model, optimizer), mesh,
                                  zero_level)
        step = make_zero_train_step(
            model, optimizer, mesh, zero_level,
            grad_transform=grad_transform, overlap=zero_overlap,
            bucket_mb=zero_bucket_mb)
        plan = _flat_axes(zero_state_specs(zstate, zero_level)) \
            + batch_axes
        return TraceTarget(step_fn=step, args=(zstate, batch), mesh=mesh,
                           plan=plan, donate=True, **common)

    if mode == "pp":
        from distributed_tensorflow_tpu.parallel.pipeline_parallel import (
            make_pp_train_step,
            pp_state_specs,
            shard_state_pp,
        )

        micro = int(microbatches) or model_axis
        pstate = shard_state_pp(_state(model, optimizer), mesh,
                                virtual_stages=virtual_stages)
        step = make_pp_train_step(
            model, optimizer, mesh, microbatches=micro,
            grad_transform=grad_transform,
            virtual_stages=virtual_stages, schedule=pp_schedule)
        plan = _flat_axes(pp_state_specs(pstate)) + batch_axes
        return TraceTarget(step_fn=step, args=(pstate, batch), mesh=mesh,
                           plan=plan, donate=True, **common)

    if mode == "tp":
        from distributed_tensorflow_tpu.parallel.tensor_parallel import (
            make_tp_train_step,
            shard_state_tp,
            stage_batch_tp,
        )

        state = shard_state_tp(_state(model, optimizer), mesh)
        step = make_tp_train_step(model, optimizer, mesh,
                                  grad_transform=grad_transform)
        staged = stage_batch_tp(mesh, batch)
        return TraceTarget(
            step_fn=step, args=(state, staged), mesh=mesh, plan=None,
            donate=True, hlo=True,
            notes="GSPMD: inventory from compiled CPU HLO", **common)

    if mode == "ep":
        from distributed_tensorflow_tpu.parallel.expert_parallel import (
            ep_state_specs,
            make_ep_train_step,
            shard_state_ep,
        )

        estate = shard_state_ep(_state(model, optimizer), mesh)
        step = make_ep_train_step(model, optimizer, mesh,
                                  grad_transform=grad_transform)
        plan = _flat_axes(ep_state_specs(estate)) + batch_axes
        return TraceTarget(step_fn=step, args=(estate, batch), mesh=mesh,
                           plan=plan, donate=True, **common)

    if mode == "sp":
        from distributed_tensorflow_tpu.parallel.sequence_parallel import (
            make_sp_train_step,
        )

        state = _state(model, optimizer)
        step = make_sp_train_step(model, optimizer, mesh,
                                  grad_transform=grad_transform,
                                  per_token_targets=True)
        plan = _flat_axes(_replicated_specs(state)) \
            + [(DATA_AXIS, MODEL_AXIS), (DATA_AXIS, MODEL_AXIS)]
        return TraceTarget(step_fn=step, args=(state, batch), mesh=mesh,
                           plan=plan, donate=True, **common)

    # dp (and the degenerate 1-chip local layout)
    from distributed_tensorflow_tpu.parallel.data_parallel import (
        make_dp_train_step,
        replicate_state,
    )

    state = replicate_state(mesh, _state(model, optimizer))
    step = make_dp_train_step(model, optimizer, mesh,
                              grad_transform=grad_transform)
    plan = _flat_axes(_replicated_specs(state)) + batch_axes
    return TraceTarget(step_fn=step, args=(state, batch), mesh=mesh,
                       plan=plan, donate=True, **common)


# ------------------------------------------- eval / clip variant builders


def _build_eval(mode: str, model_name: str) -> TraceTarget:
    from distributed_tensorflow_tpu.parallel.mesh import (
        DATA_AXIS,
        MODEL_AXIS,
    )

    opt = _opt()
    if mode == "dp":
        from distributed_tensorflow_tpu.parallel.data_parallel import (
            make_dp_eval_step,
        )

        model = _models()[model_name]()
        mesh = _mesh(N_DEVICES, 1)
        state = _state(model, opt)
        step = make_dp_eval_step(model, mesh)
        args = (state.params, make_batch(model, 8 * N_DEVICES),
                state.model_state)
        plan = _flat_axes(_replicated_specs(state.params)) \
            + [(DATA_AXIS,), (DATA_AXIS,)] \
            + _flat_axes(_replicated_specs(state.model_state))
    elif mode == "zero3":
        from distributed_tensorflow_tpu.parallel.zero import (
            make_zero_eval_step,
            shard_state_zero,
        )

        model = _models()[model_name]()
        mesh = _mesh(N_DEVICES, 1)
        zstate = shard_state_zero(_state(model, opt), mesh, 3)
        step = make_zero_eval_step(model, mesh, 3)
        args = (zstate.params, make_batch(model, 8 * N_DEVICES), ())
        plan = [(DATA_AXIS,)] * len(_flat_axes(
            _replicated_specs(zstate.params))) \
            + [(DATA_AXIS,), (DATA_AXIS,)]
    elif mode == "ep":
        from distributed_tensorflow_tpu.parallel.expert_parallel import (
            ep_state_specs,
            make_ep_eval_step,
            shard_state_ep,
        )

        model = _models()[model_name]()
        mesh = _mesh(N_DEVICES // 2, 2)
        estate = shard_state_ep(_state(model, opt), mesh)
        step = make_ep_eval_step(model, mesh)
        args = (estate.params, make_batch(model, 8 * (N_DEVICES // 2)))
        plan = _flat_axes(ep_state_specs(estate).params) \
            + [(DATA_AXIS,), (DATA_AXIS,)]
    else:  # sp
        from distributed_tensorflow_tpu.parallel.sequence_parallel import (
            make_sp_eval_step,
        )

        model = _models()[model_name](seq_axis=MODEL_AXIS)
        mesh = _mesh(N_DEVICES // 2, 2)
        state = _state(model, opt)
        step = make_sp_eval_step(model, mesh, per_token_targets=True)
        args = (state.params, make_batch(model, 8 * (N_DEVICES // 2)), ())
        plan = _flat_axes(_replicated_specs(state.params)) \
            + [(DATA_AXIS, MODEL_AXIS), (DATA_AXIS, MODEL_AXIS)]
    return TraceTarget(
        name=f"{mode}_eval/{model_name}", mode=mode,
        model_name=model_name, step_fn=step, args=args, mesh=mesh,
        model=model, optimizer=opt, batch_size=int(args[1][0].shape[0]),
        ledger_kwargs=None, plan=plan, donate=False)


def _clip_transform(mode: str, virtual_stages: int = 1):
    if mode == "pp":
        from distributed_tensorflow_tpu.parallel.pipeline_parallel import (
            pp_clip_transform,
        )

        return pp_clip_transform(1.0, virtual_stages)
    if mode == "ep":
        from distributed_tensorflow_tpu.parallel.expert_parallel import (
            ep_clip_transform,
        )

        return ep_clip_transform(1.0)
    from distributed_tensorflow_tpu.parallel.zero import (
        zero_clip_transform,
    )

    return zero_clip_transform(1.0)


def _canonical(mode: str, model_name: str, *, clip: bool = False,
               **cfg) -> TraceTarget:
    model = _models()[model_name]()
    if mode == "sp":
        from distributed_tensorflow_tpu.parallel.mesh import MODEL_AXIS

        model = _models()[model_name](seq_axis=MODEL_AXIS)
    data = cfg.pop("data_ways", N_DEVICES // cfg.get("model_axis", 1))
    name = cfg.pop("name", None)
    if clip and name is None:
        name = f"{mode}_clip/{model_name}"
    return build_from_config(
        model, _opt(), cfg.pop("batch_size", 8 * data),
        mode=mode, data_ways=data, name=name, model_name=model_name,
        grad_transform=_clip_transform(
            mode, cfg.get("virtual_stages", 1)) if clip else None,
        **cfg)


#: the canonical (mode x model x layout) matrix as pure DATA — the one
#: cell table both proof planes consume: ``SCENARIOS`` below builds a
#: real TraceTarget per cell (spatial proofs, needs the CPU mesh), and
#: ``tools/dttperf`` prices the same train cells chip-free (temporal
#: predictions; eval cells have no training ledger and clip cells are
#: deliberately unpriced, so dttperf skips both). Names are stable
#: finding-key material for BOTH analyzers.
CANONICAL_CELLS: tuple = (
    dict(name="dp/deep_cnn", mode="dp", model_name="deep_cnn"),
    dict(name="dp/mlp", mode="dp", model_name="mlp"),
    dict(name="dp_eval/deep_cnn", mode="dp", model_name="deep_cnn",
         kind="eval"),
    dict(name="zero1/deep_cnn", mode="zero1", model_name="deep_cnn",
         cfg=dict(zero_level=1)),
    dict(name="zero1_overlap/deep_cnn", mode="zero1",
         model_name="deep_cnn",
         cfg=dict(zero_level=1, zero_overlap=True, zero_bucket_mb=0.25)),
    dict(name="zero3/deep_cnn", mode="zero3", model_name="deep_cnn",
         cfg=dict(zero_level=3)),
    dict(name="zero3_overlap/deep_cnn", mode="zero3",
         model_name="deep_cnn",
         cfg=dict(zero_level=3, zero_overlap=True, zero_bucket_mb=0.25)),
    dict(name="zero1_clip/deep_cnn", mode="zero1", model_name="deep_cnn",
         clip=True, cfg=dict(zero_level=1)),
    dict(name="zero3_eval/deep_cnn", mode="zero3",
         model_name="deep_cnn", kind="eval"),
    dict(name="pp_gpipe/lm", mode="pp", model_name="lm",
         cfg=dict(model_axis=2, microbatches=4, pp_schedule="gpipe")),
    dict(name="pp_interleaved/lm", mode="pp", model_name="lm",
         cfg=dict(model_axis=2, microbatches=4, virtual_stages=2,
                  pp_schedule="interleaved")),
    dict(name="pp_zb/lm", mode="pp", model_name="lm",
         cfg=dict(model_axis=2, microbatches=4, pp_schedule="zb")),
    dict(name="pp_clip/lm", mode="pp", model_name="lm", clip=True,
         cfg=dict(model_axis=2, microbatches=4, pp_schedule="gpipe")),
    dict(name="tp/deep_cnn", mode="tp", model_name="deep_cnn",
         cfg=dict(model_axis=2)),
    dict(name="ep/lm_moe", mode="ep", model_name="lm_moe",
         cfg=dict(model_axis=2)),
    dict(name="ep_clip/lm_moe", mode="ep", model_name="lm_moe",
         clip=True, cfg=dict(model_axis=2)),
    dict(name="ep_eval/lm_moe", mode="ep", model_name="lm_moe",
         kind="eval"),
    dict(name="sp/lm", mode="sp", model_name="lm",
         cfg=dict(model_axis=2)),
    dict(name="sp_eval/lm", mode="sp", model_name="lm", kind="eval"),
    dict(name="ps/deep_cnn", mode="ps", model_name="deep_cnn",
         cfg=dict(data_ways=1, batch_size=32)),
)


def cell_layout(cell: dict, n_devices: int = N_DEVICES) -> dict:
    """The fully-resolved ledger/layout kwargs for one TRAIN cell —
    exactly what ``_canonical`` hands ``build_from_config``, computed
    WITHOUT building anything (chip-free; the dttperf predictor prices
    these). Resolves the same defaults: ``data_ways`` fills the mesh
    left over by ``model_axis``."""
    cfg = dict(cell.get("cfg") or {})
    cfg.pop("batch_size", None)
    data = cfg.pop("data_ways", n_devices // cfg.get("model_axis", 1))
    return ledger_config(cell["mode"], data_ways=data, **cfg)


def _build_cell(cell: dict) -> TraceTarget:
    if cell.get("kind") == "eval":
        return _build_eval(cell["mode"], cell["model_name"])
    return _canonical(cell["mode"], cell["model_name"],
                      clip=bool(cell.get("clip")), name=cell["name"],
                      **dict(cell.get("cfg") or {}))


#: the matrix. One Scenario per canonical cell; the full run is the
#: repo gate, --mode/--model filter for bring-up.
SCENARIOS: tuple = tuple(
    Scenario(c["name"], c["mode"], c["model_name"],
             (lambda c=c: _build_cell(c)))
    for c in CANONICAL_CELLS)
