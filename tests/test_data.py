"""Data layer: IDX parsing, next_batch semantics, synthetic fallback, sharding."""

import gzip
import struct

import numpy as np

from distributed_tensorflow_tpu.data import DataSet, read_data_sets
from distributed_tensorflow_tpu.data.idx import read_idx
from distributed_tensorflow_tpu.data.synthetic import synthetic_cifar, synthetic_digits


def _write_idx(path, arr: np.ndarray, gz=False):
    dtype_code = 0x08  # ubyte
    header = bytes([0, 0, dtype_code, arr.ndim]) + struct.pack(
        f">{arr.ndim}i", *arr.shape
    )
    payload = header + arr.astype(np.uint8).tobytes()
    if gz:
        with gzip.open(path, "wb") as f:
            f.write(payload)
    else:
        with open(path, "wb") as f:
            f.write(payload)


def test_idx_roundtrip(tmp_path):
    arr = np.arange(2 * 3 * 4, dtype=np.uint8).reshape(2, 3, 4)
    p = str(tmp_path / "x-idx3-ubyte")
    _write_idx(p, arr)
    np.testing.assert_array_equal(read_idx(p), arr)


def test_idx_gzip(tmp_path):
    arr = np.arange(10, dtype=np.uint8)
    p = str(tmp_path / "y-idx1-ubyte.gz")
    _write_idx(p, arr, gz=True)
    np.testing.assert_array_equal(read_idx(p), arr)


def test_read_data_sets_from_idx(tmp_path):
    # fabricate a tiny mnist-shaped dataset on disk
    rng = np.random.default_rng(0)
    tri = rng.integers(0, 255, (50, 28, 28), dtype=np.uint8)
    trl = rng.integers(0, 10, 50, dtype=np.uint8)
    tei = rng.integers(0, 255, (20, 28, 28), dtype=np.uint8)
    tel = rng.integers(0, 10, 20, dtype=np.uint8)
    _write_idx(str(tmp_path / "train-images-idx3-ubyte.gz"), tri, gz=True)
    _write_idx(str(tmp_path / "train-labels-idx1-ubyte.gz"), trl, gz=True)
    _write_idx(str(tmp_path / "t10k-images-idx3-ubyte.gz"), tei, gz=True)
    _write_idx(str(tmp_path / "t10k-labels-idx1-ubyte.gz"), tel, gz=True)
    ds = read_data_sets(str(tmp_path), one_hot=True)
    assert ds.source == "idx"
    assert ds.train.num_examples == 50
    assert ds.test.num_examples == 20
    assert ds.train.images.shape == (50, 784)
    assert ds.train.images.dtype == np.float32
    assert ds.train.images.max() <= 1.0


def test_synthetic_fallback(tmp_path):
    ds = read_data_sets(str(tmp_path / "empty"), one_hot=True)
    assert ds.source == "synthetic"
    assert ds.train.images.shape[1] == 784
    assert set(np.unique(ds.train.labels_int)) <= set(range(10))


def test_next_batch_one_hot_and_epoch():
    imgs = np.arange(10, dtype=np.float32).reshape(10, 1)
    labels = np.arange(10) % 10
    ds = DataSet(imgs, labels, one_hot=True, seed=0)
    xs, ys = ds.next_batch(4)
    assert xs.shape == (4, 1) and ys.shape == (4, 10)
    np.testing.assert_allclose(ys.sum(axis=1), 1.0)
    # epoch wrap: 3 more batches of 4 crosses the boundary and reshuffles
    for _ in range(3):
        ds.next_batch(4)
    assert ds.epochs_completed >= 1


def test_next_batch_covers_epoch_without_repeat():
    imgs = np.arange(8, dtype=np.float32).reshape(8, 1)
    ds = DataSet(imgs, np.zeros(8, dtype=np.int64), one_hot=False, seed=1)
    seen = np.concatenate([ds.next_batch(4)[0].ravel() for _ in range(2)])
    assert sorted(seen.tolist()) == list(range(8))


def test_shuffle_stream_deterministic_per_seed():
    """Epoch shuffles come from the native C++ permutation (NumPy fallback);
    either way the index stream is a function of the DataSet seed."""
    imgs = np.arange(32, dtype=np.float32).reshape(32, 1)
    labels = np.arange(32) % 10

    def stream(seed):
        ds = DataSet(imgs, labels, one_hot=False, seed=seed)
        return np.concatenate([ds.next_batch(8)[0].ravel() for _ in range(8)])

    np.testing.assert_array_equal(stream(5), stream(5))
    assert not np.array_equal(stream(5), stream(6))


def test_shuffle_reshuffles_between_epochs():
    imgs = np.arange(64, dtype=np.float32).reshape(64, 1)
    ds = DataSet(imgs, np.zeros(64, dtype=np.int64), one_hot=False, seed=0)
    epoch1 = np.concatenate([ds.next_batch(32)[0].ravel() for _ in range(2)])
    epoch2 = np.concatenate([ds.next_batch(32)[0].ravel() for _ in range(2)])
    assert sorted(epoch1.tolist()) == sorted(epoch2.tolist())
    assert not np.array_equal(epoch1, epoch2)


def test_shard_disjoint():
    imgs = np.arange(10, dtype=np.float32).reshape(10, 1)
    ds = DataSet(imgs, np.zeros(10, dtype=np.int64))
    parts = [ds.shard(i, 2) for i in range(2)]
    all_vals = np.concatenate([p.images.ravel() for p in parts])
    assert sorted(all_vals.tolist()) == list(range(10))


def test_synthetic_digits_deterministic():
    a, la = synthetic_digits(16, seed=3)
    b, lb = synthetic_digits(16, seed=3)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(la, lb)


def test_synthetic_cifar_shapes():
    x, y = synthetic_cifar(8, seed=0)
    assert x.shape == (8, 32, 32, 3)
    assert x.min() >= 0.0 and x.max() <= 1.0


def test_out_of_range_labels_fail_loudly():
    """A corrupt loader's invalid class id must raise at DataSet
    construction — the TPU-form CE one-hots integer labels, and an
    out-of-range id would otherwise silently drop the example from the
    loss (all-zero one-hot row, ADVICE r3)."""
    import pytest

    from distributed_tensorflow_tpu.data.datasets import DataSet

    imgs = np.zeros((4, 784), np.float32)
    with pytest.raises(ValueError, match=r"labels\[2\] = 10"):
        DataSet(imgs, np.array([0, 1, 10, 3]), num_classes=10)
    with pytest.raises(ValueError, match="not in"):
        DataSet(imgs, np.array([0, -1, 2, 3]), num_classes=10)
