"""Prefetch pipeline: ordering, error propagation, clean shutdown."""

import threading
import time

import numpy as np
import pytest

from distributed_tensorflow_tpu.data.pipeline import batch_iterator, prefetch_to_device
from distributed_tensorflow_tpu.data.datasets import DataSet


def test_prefetch_preserves_order_and_values():
    batches = [(np.full((2, 2), i), np.array([i])) for i in range(5)]
    out = list(prefetch_to_device(iter(batches)))
    assert len(out) == 5
    for i, (x, y) in enumerate(out):
        np.testing.assert_allclose(np.asarray(x), i)


def test_prefetch_propagates_worker_exception():
    def gen():
        yield (np.zeros(1), np.zeros(1))
        raise RuntimeError("boom in loader")

    it = prefetch_to_device(gen())
    next(it)
    with pytest.raises(RuntimeError, match="boom in loader"):
        next(it)


def test_prefetch_close_terminates_worker():
    before = threading.active_count()

    def infinite():
        i = 0
        while True:
            yield (np.full(4, i), np.zeros(1))
            i += 1

    it = prefetch_to_device(infinite(), size=2)
    next(it)
    it.close()
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.02)
    assert threading.active_count() <= before


def test_prefetch_worker_death_mid_epoch_reaches_consumer():
    """r8 worker-death semantics: an exception raised by the staging
    thread MID-epoch (buffered items already queued) reaches the consumer
    as that exception AFTER the buffered items — not a hang and not a
    silent short epoch — and the consumer's finally-drain leaves no stuck
    thread."""
    from distributed_tensorflow_tpu.utils import faults

    before = threading.active_count()
    batches = [(np.full(4, i), np.zeros(1)) for i in range(10)]
    faults.configure("prefetch:at_count=3:mode=error")
    try:
        it = prefetch_to_device(iter(batches), size=2)
        got = []
        with pytest.raises(faults.InjectedFault):
            for x, _ in it:
                got.append(int(np.asarray(x)[0]))
        # every batch staged before the death was delivered, in order
        assert got == [0, 1, 2]
    finally:
        faults.reset()
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.02)
    assert threading.active_count() <= before, "staging thread leaked"


def test_prefetch_worker_death_with_full_queue_no_hang():
    """The death lands while the queue is FULL and the consumer is slow:
    the exception must still arrive (the worker's bounded _send loop keeps
    offering it), and closing without draining must not leak the
    thread."""
    from distributed_tensorflow_tpu.utils import faults

    before = threading.active_count()

    def gen():
        i = 0
        while True:
            yield (np.full(4, i), np.zeros(1))
            i += 1

    faults.configure("prefetch:at_count=4:mode=error")
    try:
        it = prefetch_to_device(gen(), size=2)
        next(it)
        time.sleep(0.2)  # let the worker fill the queue and hit the fault
        with pytest.raises(faults.InjectedFault):
            for _ in range(10):
                next(it)
        it.close()
    finally:
        faults.reset()
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.02)
    assert threading.active_count() <= before, "staging thread leaked"


def test_empty_dataset_next_batch_raises():
    ds = DataSet(np.zeros((0, 4), np.float32), np.zeros(0, np.int64))
    with pytest.raises(ValueError, match="empty"):
        ds.next_batch(4)


def test_batch_iterator_shapes():
    ds = DataSet(np.arange(20, dtype=np.float32).reshape(10, 2),
                 np.zeros(10, np.int64), one_hot=True)
    it = batch_iterator(ds, 4)
    x, y = next(it)
    assert x.shape == (4, 2) and y.shape == (4, 10)
