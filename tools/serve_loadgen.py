#!/usr/bin/env python
"""Load generator for the serving stack — open- and closed-loop.

Closed loop (``run_closed_loop``): N workers each keep exactly one
request in flight — measures the system at its natural concurrency
(latency under a fixed multiprogramming level). Open loop
(``run_open_loop``): requests FIRE at a target rate whatever the
responses do — the honest way to measure tail latency under offered
load, since a closed loop's arrival process slows down with the server
and hides queueing collapse. Both return the same report dict
(p50/p90/p99 latency ms, achieved rps, ok/rejected/error counts), both
drive either the in-process client or a JSON-over-HTTP endpoint.

CLI (HTTP mode):

    python tools/serve_loadgen.py --url http://127.0.0.1:8000 \
        --mode open --rate 200 --duration 10 --kind generate \
        --prompt_len 8 --max_new_tokens 16

bench.py's serving phase imports the loop runners directly against an
in-process client (no sockets on the timed path).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

# sys.path[0] is tools/ when run as a script; the package root is one up
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from distributed_tensorflow_tpu.serving.batcher import RejectedError
from distributed_tensorflow_tpu.utils.metrics import StreamingHistogram


def _report(hist: StreamingHistogram, ok: int, rejected: int,
            errors: int, elapsed_s: float) -> dict:
    out = dict(hist.summary("latency_ms_"))
    out.update({
        "ok": ok,
        "rejected": rejected,
        "errors": errors,
        "elapsed_s": round(elapsed_s, 3),
        "achieved_rps": round(ok / elapsed_s, 2) if elapsed_s > 0 else 0.0,
    })
    return out


class _Counters:
    def __init__(self):
        self.lock = threading.Lock()
        self.ok = 0
        self.rejected = 0
        self.errors = 0

    def add(self, kind: str):
        with self.lock:
            setattr(self, kind, getattr(self, kind) + 1)


def _call_and_record(request_fn, hist: StreamingHistogram,
                     c: _Counters) -> None:
    t0 = time.monotonic()
    try:
        request_fn()
        hist.record((time.monotonic() - t0) * 1e3)
        c.add("ok")
    except RejectedError:
        c.add("rejected")
    except Exception:  # noqa: BLE001 — the loadgen reports, not raises
        c.add("errors")


def run_closed_loop(request_fn, *, n_requests: int = 200,
                    concurrency: int = 4) -> dict:
    """``concurrency`` workers, one request in flight each, until
    ``n_requests`` total have been attempted."""
    hist = StreamingHistogram()
    c = _Counters()
    issued = [0]
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                if issued[0] >= n_requests:
                    return
                issued[0] += 1
            _call_and_record(request_fn, hist, c)

    t0 = time.monotonic()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return _report(hist, c.ok, c.rejected, c.errors,
                   time.monotonic() - t0)


def run_open_loop(request_fn, *, rate_rps: float, duration_s: float,
                  max_inflight: int = 256) -> dict:
    """Fire at ``rate_rps`` (uniform arrivals) for ``duration_s``; each
    request runs on its own thread so a slow server cannot throttle the
    arrival process (that's the point of open loop). ``max_inflight``
    bounds the thread population — beyond it arrivals count as errors
    (client saturation, reported, not hidden)."""
    hist = StreamingHistogram()
    c = _Counters()
    inflight = threading.Semaphore(max_inflight)
    threads: list[threading.Thread] = []
    interval = 1.0 / rate_rps
    t0 = time.monotonic()
    next_fire = t0

    def one():
        try:
            _call_and_record(request_fn, hist, c)
        finally:
            inflight.release()

    while time.monotonic() - t0 < duration_s:
        now = time.monotonic()
        if now < next_fire:
            time.sleep(next_fire - now)
        next_fire += interval
        if not inflight.acquire(blocking=False):
            c.add("errors")
            continue
        th = threading.Thread(target=one, daemon=True)
        th.start()
        threads.append(th)
    # throughput is ok/OFFERED-window: folding the post-window drain
    # (joins below, up to 30 s under backlog) into the denominator would
    # deflate achieved_rps exactly when the server is saturated — the
    # condition the open loop exists to measure honestly
    t_offered = time.monotonic() - t0
    for th in threads:
        th.join(timeout=30)
    out = _report(hist, c.ok, c.rejected, c.errors, t_offered)
    out["drain_s"] = round(time.monotonic() - t0 - t_offered, 3)
    out["offered_rps"] = rate_rps
    return out


def http_request_fn(url: str, kind: str, *, prompt_len: int = 8,
                    vocab_size: int = 64, input_dim: int = 784,
                    max_new_tokens: int = 16):
    """A request closure against the HTTP front end. Raises
    ``RejectedError`` on 429 so backpressure is counted, not miscounted
    as an error."""

    if kind == "generate":
        body = json.dumps({
            "prompt": [i % vocab_size for i in range(prompt_len)],
            "max_new_tokens": max_new_tokens}).encode()
        path = "/v1/generate"
    else:
        body = json.dumps(
            {"inputs": [0.5] * input_dim}).encode()
        path = "/v1/predict"

    def call():
        req = urllib.request.Request(
            url.rstrip("/") + path, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 429:
                raise RejectedError(f"HTTP 429: {e.read()[:200]}") from e
            raise

    return call


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--url", required=True,
                    help="serving endpoint, e.g. http://127.0.0.1:8000")
    ap.add_argument("--mode", choices=("open", "closed"), default="closed")
    ap.add_argument("--kind", choices=("predict", "generate"),
                    default="predict")
    ap.add_argument("--requests", type=int, default=200,
                    help="closed loop: total requests")
    ap.add_argument("--concurrency", type=int, default=4,
                    help="closed loop: in-flight requests")
    ap.add_argument("--rate", type=float, default=100.0,
                    help="open loop: offered requests/sec")
    ap.add_argument("--duration", type=float, default=10.0,
                    help="open loop: seconds")
    ap.add_argument("--prompt_len", type=int, default=8)
    ap.add_argument("--vocab_size", type=int, default=64)
    ap.add_argument("--input_dim", type=int, default=784)
    ap.add_argument("--max_new_tokens", type=int, default=16)
    args = ap.parse_args()

    fn = http_request_fn(args.url, args.kind, prompt_len=args.prompt_len,
                         vocab_size=args.vocab_size,
                         input_dim=args.input_dim,
                         max_new_tokens=args.max_new_tokens)
    if args.mode == "closed":
        rep = run_closed_loop(fn, n_requests=args.requests,
                              concurrency=args.concurrency)
    else:
        rep = run_open_loop(fn, rate_rps=args.rate,
                            duration_s=args.duration)
    print(json.dumps(rep))


if __name__ == "__main__":
    main()
